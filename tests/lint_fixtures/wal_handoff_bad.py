"""FIXTURE (never imported): KV-handoff journal violations.

- ``handoff_returns_unresolved``: a return after a ``_journal_handoff``
  begin with no ``_journal_resolve`` — the entry outlives the handoff,
  and every later delivery of this id would be treated as a crash
  re-delivery forever.
- ``handoff_swallows_transfer_failure``: a broad handler eats the
  transfer failure without resolving (or re-raising) — the mover
  reports fallback while the journal still says the handoff is live.
"""


def handoff_returns_unresolved(ckpt, peer, key, base):
    seq = _journal_handoff(ckpt, key, dict(base, phase="export"))  # noqa: F821
    if seq is None:
        return "degraded"
    peer.deliver(key[1], base)
    return "delivered"  # WRONG: begun entry left pending on a live path


def handoff_swallows_transfer_failure(ckpt, peer, key, base):
    outcome = "delivered"
    try:
        _journal_handoff(ckpt, key, dict(base, phase="transfer"))  # noqa: F821
        raise RuntimeError("transfer path down")  # the dead-peer path
    except Exception:
        outcome = "fallback"  # WRONG: swallowed without resolving
    return outcome
