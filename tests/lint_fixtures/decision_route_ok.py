"""Fixture: the fleet router's decision-emission shapes (verbs
``fleet_route`` / ``fleet_shed``) — none of these may be flagged by the
``decision-outcome`` rule.

The real router (serving/router.py) funnels every outcome — affinity
hit, balanced fallback, overflow queueing, shed — through a single emit
before its single return; these fixtures pin the shapes the rule must
keep accepting.
"""


class _Log:
    def emit(self, *a, **k):
        pass


DECISIONS = _Log()


def ok_route_single_exit(rid, candidates, pick):
    """The router's funnel shape: decide outcome, one emit, one return."""
    if not candidates:
        outcome, engine = "no_replicas", ""
    else:
        outcome, engine = pick(candidates)
    DECISIONS.emit(f"req/{rid}", "fleet_route", outcome=outcome, node=engine)
    return engine or None


def ok_shed_branch_emits(rid, severity, tier):
    """Both the shed branch and the admit branch leave a 'why' record."""
    if severity == "page" and tier == "best_effort":
        DECISIONS.emit(f"req/{rid}", "fleet_shed", outcome="shed",
                       reason="burn-rate page")
        return None
    DECISIONS.emit(f"req/{rid}", "fleet_route", outcome="balanced")
    return rid
