"""Fixture: the canonical decision-emission shapes — none of these may
be flagged by the ``decision-outcome`` rule."""


class _Log:
    def emit(self, *a, **k):
        pass


class _Failure(RuntimeError):
    pass


DECISIONS = _Log()


def _decide(x):
    if x < 0:
        raise _Failure("no fit")
    return x


def ok_emit_then_return(x):
    """The simple linear verb: decide, emit, return."""
    y = _decide(x)
    DECISIONS.emit("ns/p", "verb")
    return y


def ok_error_emit_and_reraise(x):
    """The canonical failure shape: emit outcome=error, then propagate
    (propagation itself is legal, as in wal-protocol)."""
    try:
        y = _decide(x)
    except _Failure as e:
        DECISIONS.emit("ns/p", "verb", outcome="error", reason=str(e))
        raise
    DECISIONS.emit("ns/p", "verb")
    return y


def ok_branches_both_emit(x):
    if x:
        DECISIONS.emit("ns/p", "verb", outcome="error")
        return None
    DECISIONS.emit("ns/p", "verb")
    return x
