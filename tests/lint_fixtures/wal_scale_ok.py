"""FIXTURE (never imported): the fleet scale-down journal shapes — all
legal.

The scale executor's real shape (serving/router.py): each drain phase
(cordon → drain → migrate → release) journals a fresh ``_journal_scale``
begin for the scale key, the stale-claim path resolves INLINE with
``_journal_resolve("abort")`` before re-raising, the happy path commits
after release, and unhandled exceptions propagate — the pending entry is
the crash-safety story (the reconciler rolls it forward past the migrate
commit point or back before it).
"""


class _Stale(RuntimeError):
    pass


def execute_scale(ckpt, engine, key, base, cordon, drain, migrate, release):
    seq = _journal_scale(ckpt, key, dict(base, phase="cordon"))  # noqa: F821
    cordon(engine)
    seq = _journal_scale(ckpt, key, dict(base, phase="drain"))  # noqa: F821
    snapshot = drain(engine)
    seq = _journal_scale(  # noqa: F821
        ckpt, key, dict(base, phase="migrate", snapshot=snapshot)
    )
    try:
        moved = migrate(snapshot)
    except _Stale:
        _journal_resolve(ckpt, "abort", key, seq)  # noqa: F821
        raise
    seq = _journal_scale(ckpt, key, dict(base, phase="release"))  # noqa: F821
    release(engine)
    _journal_resolve(ckpt, "commit", key, seq)  # noqa: F821
    return moved


def resolve_scale_after_crash(ckpt, key, data, deliver):
    seq = data.get("_seq")
    try:
        deliver(key[1], dict(data))
    except Exception:
        raise  # entry stays pending for the next pass, by design
    _journal_resolve(ckpt, "commit", key, seq)  # noqa: F821
    return "rollforward"
