"""FIXTURE (never imported): a lock created outside the ranked factory —
invisible to both the static rule set and the runtime witness."""

import threading


class Rogue:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._cond = threading.Condition()
