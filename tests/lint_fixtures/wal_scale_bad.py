"""FIXTURE (never imported): fleet scale-down journal violations.

- ``scale_returns_unresolved``: a return after a ``_journal_scale``
  begin with no ``_journal_resolve`` — the scale entry outlives the
  drain, and every reconciler pass would re-deliver the migrated
  snapshot forever.
- ``scale_swallows_migrate_failure``: a broad handler eats the migrate
  failure without resolving (or re-raising) — the executor reports
  success while the journal still says the drain is live.
"""


def scale_returns_unresolved(ckpt, engine, key, base, drain):
    seq = _journal_scale(ckpt, key, dict(base, phase="drain"))  # noqa: F821
    if seq is None:
        return "degraded"
    drain(engine)
    return "drained"  # WRONG: begun entry left pending on a live path


def scale_swallows_migrate_failure(ckpt, key, base):
    outcome = "scaled"
    try:
        _journal_scale(ckpt, key, dict(base, phase="migrate"))  # noqa: F821
        raise RuntimeError("no survivor with headroom")
    except Exception:
        outcome = "failed"  # WRONG: swallowed without resolving
    return outcome
