"""FIXTURE (never imported): the PR 6 gang double-booking shape.

The real bug: gang usage was spread into ONE of the usage ledger's
aggregates by a helper outside the ledger module, so the sibling
aggregate (and the informer index) kept counting the gang on a single
chip — a concurrent admission storm double-booked the other members.
The ledger-encapsulation rule must flag every direct reach into the
protected internals; the only legal route is the locked methods."""


def spread_gang_usage(usage, index, assume, chips, per_chip, node):
    for idx in chips:
        # WRONG: mutates one aggregate of NodeChipUsage directly, missing
        # _core_refs and the lock — the double-booking shape
        usage._mem_used[idx] = usage._mem_used.get(idx, 0) + per_chip
    # WRONG: pokes ClusterUsageIndex internals (and skips the generation
    # bump, so the extender's view cache serves stale state forever)
    index._nodes[node]["frac"]["tpu-mem"] = dict.fromkeys(chips, per_chip)
    # WRONG: reads the in-flight gang ledger without its lock (torn read)
    return list(assume._gang.values())
