"""FIXTURE (never imported; fed to the annotations rule under an
allocator/ path): public surface with missing annotations."""


def place(pod, units: int):  # WRONG: pod + return unannotated
    return units


def watch(cb: Callable[[], None]) -> Iterator[int]:  # WRONG: neither name
    yield 0  # is imported — `from __future__ import annotations` hides it


class Ledger:
    def __init__(self, ttl):  # WRONG: ttl + return unannotated
        self._ttl = ttl

    def reserve(self, key: str) -> bool:
        return True
