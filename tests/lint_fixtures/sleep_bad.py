"""FIXTURE (never imported; fed to the hygiene rule under a tests/ path):
a long blind sleep where a deadline poll belongs."""

import time


def test_settles_eventually(daemon):
    daemon.kick()
    time.sleep(2.0)  # WRONG: blind 2s wait
    assert daemon.settled
