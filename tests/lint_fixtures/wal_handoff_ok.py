"""FIXTURE (never imported): the KV-handoff journal shapes — all legal.

The handoff mover's real shape (serving/handoffproto.py): each protocol
phase journals a fresh ``_journal_handoff`` begin for the handoff key,
every degraded path resolves INLINE with ``_journal_resolve("abort")``,
the happy path commits, and unhandled exceptions propagate — the pending
entry is the crash-safety story (the reconciler rolls it forward or
back).
"""


def execute_handoff(ckpt, peer, fallback, key, base, pages):
    seq = _journal_handoff(ckpt, key, dict(base, phase="export"))  # noqa: F821
    blobs = list(pages)
    seq = _journal_handoff(ckpt, key, dict(base, phase="transfer"))  # noqa: F821
    try:
        for i, blob in enumerate(blobs):
            peer.put_page(key[1], i, blob, 0)
    except ValueError:
        fallback(key[1], dict(base))
        _journal_resolve(ckpt, "abort", key, seq)  # noqa: F821
        return "fallback"
    seq = _journal_handoff(ckpt, key, dict(base, phase="import"))  # noqa: F821
    peer.deliver(key[1], base)
    seq = _journal_handoff(ckpt, key, dict(base, phase="commit"))  # noqa: F821
    _journal_resolve(ckpt, "commit", key, seq)  # noqa: F821
    return "delivered"


def resolve_after_crash(ckpt, key, data, deliver):
    seq = data.get("_seq")
    try:
        deliver(key[1], dict(data))
    except Exception:
        raise  # entry stays pending for the next pass, by design
    _journal_resolve(ckpt, "commit", key, seq)  # noqa: F821
    return "rollforward"
