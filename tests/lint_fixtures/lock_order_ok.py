"""FIXTURE (never imported): the same shapes as lock_order_bad.py with
the nesting the ranking declares — must produce zero findings."""

from gpushare_device_plugin_tpu.utils.lockrank import make_lock, make_rlock


class Ledger:
    def __init__(self) -> None:
        self._lock = make_rlock("allocator.ledger")

    def overlay(self, cache: "Cache") -> None:
        with self._lock:
            with self._lock:  # rlock re-entry is legal
                cache.get("k")


class Cache:
    def __init__(self) -> None:
        self._lock = make_lock("informer.cache")

    def get(self, key: str) -> None:
        with self._lock:
            pass
