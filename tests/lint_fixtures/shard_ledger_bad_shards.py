"""FIXTURE (never imported): shard code reaching past the 2PC reserve
API into the AssumeCache's other surfaces — each marked line must be
flagged by the ledger-encapsulation rule when this file is loaded under
a path ending in shards.py."""


class BadShard:
    def __init__(self, ledger):
        self._ledger = ledger

    def sneaky_single_chip(self, key):
        # single-chip reservation family: bypasses the all-or-nothing
        # gang entry — a crash here strands a partial cross-shard gang
        self._ledger.reserve_mem(key, 0, 4)  # FLAG

    def sneaky_snapshot(self):
        return self._ledger.snapshot()  # FLAG

    def sneaky_transaction(self, key):
        with self._ledger.transaction():  # FLAG
            self._ledger.reserve_core(key, [0, 1])  # FLAG

    def sneaky_reconciler_surface(self, key):
        return self._ledger.release_if_unclaimed(key)  # FLAG
