"""FIXTURE (never imported): daemon-hygiene violations — a broad
except-pass in a supervised loop and an unbounded queue."""

import queue


def supervise(watch_fn):
    q = queue.Queue()  # WRONG: unbounded
    q2 = queue.Queue(0)  # WRONG: maxsize<=0 is unbounded too
    while True:
        try:
            q.put(watch_fn())
            q2.put(watch_fn())
        except Exception:  # WRONG: silently eaten
            pass
