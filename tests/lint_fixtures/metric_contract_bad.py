"""metric-contract fixture: every shape the rule must flag."""

from gpushare_device_plugin_tpu.utils.metric_catalog import (
    CHECKPOINT_FENCED,
    GANG2PC_TOTAL,
)
from gpushare_device_plugin_tpu.utils.metrics import REGISTRY

# finding 1: a family name inlined outside the catalog module
ROGUE = "tpushare_rogue_total"


def emit_everything_wrong() -> None:
    # finding 2: inline literal at the call site (and 3: undeclared family)
    REGISTRY.counter_inc("tpushare_rogue_total", "help")
    # finding 4: counter_inc on a family declared as a gauge
    REGISTRY.counter_inc(CHECKPOINT_FENCED, "help")
    # finding 5: label outside the declared set (phase/outcome)
    REGISTRY.counter_inc(GANG2PC_TOTAL, "help", shard="shard-0")
