"""metric-contract fixture: the canonical shapes the rule accepts."""

from gpushare_device_plugin_tpu.utils.metric_catalog import (
    ALLOCATE_SECONDS,
    DEFRAG_STRANDED_PCT,
    GANG2PC_TOTAL,
)
from gpushare_device_plugin_tpu.utils.metrics import REGISTRY


def emit_by_the_book(pod_labels: dict) -> None:
    REGISTRY.counter_inc(GANG2PC_TOTAL, "help", phase="prepare", outcome="ok")
    REGISTRY.observe(ALLOCATE_SECONDS, 0.001, "help", resource="mem")
    REGISTRY.gauge_set(DEFRAG_STRANDED_PCT, 1.0, "help")
    # dynamic label pass-through is trusted (documented by the catalog)
    REGISTRY.gauge_set(DEFRAG_STRANDED_PCT, 1.0, "help", **pod_labels)


def read_by_the_book() -> float:
    return REGISTRY.counter_value(GANG2PC_TOTAL, phase="prepare", outcome="ok")
