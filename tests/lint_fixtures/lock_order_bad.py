"""FIXTURE (never imported): lock-order inversion — acquires the
allocator ledger (rank 30) while holding the informer cache lock
(rank 50). tests/test_lint.py feeds this through the lock-order rule
with a package-scoped path and expects a finding."""

from gpushare_device_plugin_tpu.utils.lockrank import make_lock, make_rlock


class Ledger:
    def __init__(self) -> None:
        self._lock = make_rlock("allocator.ledger")

    def claim(self, key: str) -> bool:
        with self._lock:
            return True


class Cache:
    def __init__(self, assume: Ledger) -> None:
        self._lock = make_lock("informer.cache")
        self._assume = assume

    def apply(self, key: str) -> None:
        with self._lock:
            # WRONG: cache (50) held while taking the ledger (30)
            self._assume.claim(key)
