"""FIXTURE (never imported): a gang2pc journal begin whose returned
(key, seq) handle is discarded — flagged by the wal-protocol rule (the
seq is the only handle a later commit/abort can seq-guard with)."""


class BadTwoPhase:
    def __init__(self, ckpt):
        self._ckpt = ckpt

    def _journal_2pc(self, key, data):
        data = dict(data)
        data["kind"] = "gang2pc"
        return self._ckpt.begin(key, data)

    def prepare(self, key):
        self._journal_2pc(key, {"phase": "prepare"})  # FLAG: seq discarded
        return True
