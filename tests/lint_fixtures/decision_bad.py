"""Fixture: decision emission NOT dominated on all outcome paths —
every function here must be flagged by the ``decision-outcome`` rule."""


class _Log:
    def emit(self, *a, **k):
        pass


DECISIONS = _Log()


def bad_return_without_emit(x):
    """A rejection branch returns before any emit: the refused pod has
    no 'why' record."""
    if x < 0:
        return None
    DECISIONS.emit("ns/p", "verb")
    return x


def bad_fallthrough(x):
    """Only one branch emits; the other completes normally silent."""
    if x:
        DECISIONS.emit("ns/p", "verb")


def bad_swallowing_handler(x):
    """The handler swallows the failure and returns without an
    error-outcome emit."""
    try:
        y = int(x)
    except ValueError:
        return None
    DECISIONS.emit("ns/p", "verb")
    return y
