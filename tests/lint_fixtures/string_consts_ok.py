"""string-consts fixture: schema strings referenced through const.py.

A docstring may NAME a key like tpushare.aliyun.com/gang-shape in prose
— docstrings are never findings (this one is the regression test).
"""

from gpushare_device_plugin_tpu import const


def read_gang(pod: dict) -> tuple[str, str]:
    """Reads ALIYUN_COM_TPU_MEM_IDX through the const, as required."""
    ann = pod.get("metadata", {}).get("annotations", {})
    shape = ann.get(const.ANN_GANG_SHAPE, "")
    idx = ann.get(const.ENV_MEM_IDX, "")
    return shape, idx
