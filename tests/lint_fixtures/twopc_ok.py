"""FIXTURE (never imported): gang2pc begins whose seqs are kept —
assigned for a later seq-guarded resolve, or returned to the caller.
Zero wal-protocol findings expected (a 2PC prepare legitimately leaves
its entry pending across the process boundary)."""


class OkTwoPhase:
    def __init__(self, ckpt):
        self._ckpt = ckpt
        self._seqs = {}

    def _journal_2pc(self, key, data):
        data = dict(data)
        data["kind"] = "gang2pc"
        return self._ckpt.begin(key, data)

    def prepare(self, key):
        seq = self._journal_2pc(key, {"phase": "prepare"})
        self._seqs[key] = seq
        return True

    def decide(self, key):
        return self._journal_2pc(key, {"phase": "decision"})

    def commit(self, key):
        self._ckpt.commit(key, seq=self._seqs.pop(key, None))
