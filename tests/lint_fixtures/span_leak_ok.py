"""span-leak fixtures: canonical safe shapes the rule must accept."""

from gpushare_device_plugin_tpu.utils.tracing import TRACER


def context_manager() -> None:
    # the structurally-safe form: exit always ends
    with TRACER.span("safe") as sp:
        sp.set_attribute("k", "v")


def try_finally() -> None:
    sp = TRACER.start_span("safe")
    try:
        sp.set_attribute("k", "v")
    finally:
        sp.end()


def start_inside_try(flag: bool) -> int:
    # the shape the rule's message recommends: start INSIDE the try,
    # end in its finally — every exit (return/raise included) resolves
    try:
        sp = TRACER.start_span("safe")
        sp.set_attribute("k", "v")
        if flag:
            return 1
        raise RuntimeError("boom")
    finally:
        sp.end()


def branch_both_end(flag: bool) -> None:
    sp = TRACER.start_span("safe")
    if flag:
        sp.end("error")
    else:
        sp.end()


def end_before_raise(flag: bool) -> None:
    sp = TRACER.start_span("safe")
    if flag:
        sp.end("error")
        raise RuntimeError("boom")
    sp.end()
