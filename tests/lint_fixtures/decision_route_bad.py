"""Fixture: fleet-router decision emission NOT dominated on all outcome
paths — every function here must be flagged by the ``decision-outcome``
rule. These are the provenance holes the router refactor must never
reintroduce: a request refused (shed) or silently queued with no record
saying why.
"""


class _Log:
    def emit(self, *a, **k):
        pass


DECISIONS = _Log()


def bad_shed_without_record(rid, severity, tier):
    """The shed branch returns before any emit: the dropped request has
    no 'why' record."""
    if severity == "page" and tier == "best_effort":
        return None  # WRONG: shed with no fleet_shed record
    DECISIONS.emit(f"req/{rid}", "fleet_route", outcome="balanced")
    return rid


def bad_no_replicas_fallthrough(rid, candidates):
    """Only the routed branch emits; the empty-fleet path completes
    normally silent."""
    if candidates:
        DECISIONS.emit(f"req/{rid}", "fleet_route", outcome="affinity")
