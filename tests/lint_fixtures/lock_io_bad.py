"""FIXTURE (never imported): blocking I/O under an in-memory-only lock —
the exact shape of the real pre-PR-7 extender defect: the decision lock
held across a journal abort (which waits on the WAL writer's fsync) and
across an apiserver LIST."""

from gpushare_device_plugin_tpu.utils.lockrank import make_rlock


class Core:
    def __init__(self, api, ckpt) -> None:
        self._lock = make_rlock("extender.core")
        self._api = api
        self._ckpt = ckpt

    def bind(self, ns: str, name: str) -> None:
        with self._lock:
            # WRONG: a full cluster LIST under the decision lock
            self._api.list_pods()
            # WRONG: abort blocks until its record is durable (fsync)
            self._ckpt.abort((ns, name))
