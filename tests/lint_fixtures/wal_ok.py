"""FIXTURE (never imported): the canonical WAL shapes — all legal.

- ``admit``: the allocator's shape — begin, try persist/commit, abort on
  handled failures, unhandled exceptions propagate (restart replay +
  reconciler resolve the pending entry by design).
- ``admit_finally``: try/finally resolution.
- ``admit_loop``: begin/resolve per loop iteration (the retry shape).
"""


def admit(ckpt, api, key, data, patch):
    ckpt.begin(key, data)
    try:
        api.patch_pod(key[0], key[1], patch)
        ckpt.commit(key)
    except ValueError:
        ckpt.abort(key)
        raise


def admit_finally(ckpt, api, key, data, patch):
    ckpt.begin(key, data)
    try:
        api.patch_pod(key[0], key[1], patch)
    finally:
        ckpt.commit(key)


def admit_loop(ckpt, api, key, data, patch):
    for _attempt in (0, 1):
        ckpt.begin(key, data)
        try:
            api.patch_pod(key[0], key[1], patch)
            ckpt.commit(key)
            break
        except ValueError:
            ckpt.abort(key)
