"""bench_mfu.py --spec-smoke: speculative decoding inside the paged
engine must be bit-identical, retrace-free, and honestly budgeted.

Tier-1 (not slow): the CPU spec smoke is the acceptance gate for the
draft/verify pipeline — the spec engine and the plain paged engine are
both sized by ``paged_plan_for_slice`` against the SAME byte budget
(the draft's weights and KV pages come out of that budget), run the
same decode-dominated shared-prefix trace, and must produce identical
tokens with zero retraces, a nonempty acceptance histogram, and fewer
total ticks. Those gates are additionally hard-asserted inside the
bench itself (a non-zero exit fails this test with stderr).
"""

import json
import os
import subprocess
import sys
from pathlib import Path


def _run_smoke(repo):
    proc = subprocess.run(
        [sys.executable, str(repo / "bench_mfu.py"), "--spec-smoke"],
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        capture_output=True, text=True, timeout=600, cwd=str(repo),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    report = json.loads(proc.stdout.strip().splitlines()[-1])
    assert report["sections"] == ["serve_spec"]
    return report["serve_spec"]


def test_bench_spec_smoke_parity_budget_and_acceptance_row():
    repo = Path(__file__).resolve().parent.parent
    row = _run_smoke(repo)

    # Bit-identity and zero-retrace are hard-asserted inside the bench;
    # the report must reflect them, and the spec engine must have
    # compiled exactly the five speculative programs.
    assert row["retraces"] == 0
    assert set(row["spec"]["trace_counts"]) == {
        "prefill", "extend", "decode", "draft", "verify",
    }
    assert all(v == 1 for v in row["spec"]["trace_counts"].values())

    # The speculative path actually ran and accepted (self-draft means
    # ceiling acceptance: the mean acceptance length is exactly k).
    assert row["draft_steps"] >= 1
    assert row["spec_accept_len_mean"] == row["spec_k"]

    # Equal-HBM accounting: the spec plan paid for its draft slab out
    # of the same budget, so it holds strictly fewer pages than the
    # plain plan, and the draft slab's size is reported.
    assert row["spec_plan"]["pages"] < row["plain_plan"]["pages"]
    assert row["spec_plan"]["draft_page_bytes"] > 0
    assert row["spec_plan"]["draft_bytes"] > 0

    # The throughput rows bench.py hoists for its 25% trend guards are
    # present and sane (the wall-clock improvement bar is gated on the
    # full TPU run, not at CPU smoke sizes — but report them always).
    assert row["spec_tokens_per_s"] > 0
    assert row["plain_tokens_per_s"] > 0
    assert row["tick_speedup"] > 1.0
