"""Group-commit WAL + coalesced admission pipeline suite (ISSUE 4).

Covers the tentpole's three stages and their failure boundaries:

- **WAL group commit** (`allocator/checkpoint.py` + `utils/batch.py`):
  batch/always mode replay parity (the tier-1 smoke bit), a 200-seed
  multi-threaded interleaving property test (like
  ``tests/test_index_property.py`` but over journal ops), compaction
  racing a queued batch, a torn tail landing mid-batch (only the fsync'd
  prefix replays), and the two new ``crash_after`` boundaries:
  ``checkpoint.wal_queue`` (queued, never fsync'd -> replays as absent)
  and ``checkpoint.batch_fsync`` (durable, callers dead -> replays as
  present).
- **PATCH coalescing** (`cluster/apiserver.py`): the pipelined pod-PATCH
  dispatcher (batching, per-item ApiError mapping, dead-connection
  fallback) and the merging node-PATCH coalescer.
- **Informer batch apply** (`cluster/informer.py`): a watch burst applied
  under one cache-lock acquisition with exact index maintenance.

``make chaos-restart`` runs this file alongside the restart-recovery
suite; everything here is tier-1 ('not slow').
"""

from __future__ import annotations

import json
import os
import random
import threading
import time

import pytest

from gpushare_device_plugin_tpu.allocator import checkpoint as ckpt_mod
from gpushare_device_plugin_tpu.allocator.assume import AssumeCache
from gpushare_device_plugin_tpu.allocator.checkpoint import (
    AllocationCheckpoint,
    replay_checkpoint,
)
from gpushare_device_plugin_tpu.cluster import apiserver as api_mod
from gpushare_device_plugin_tpu.cluster.apiserver import (
    ApiError,
    ApiServerClient,
    PodPatchPipeline,
)
from gpushare_device_plugin_tpu.utils.metrics import REGISTRY
from gpushare_device_plugin_tpu.utils.faults import FAULTS, SimulatedCrash

from fake_apiserver import FakeApiServer
from k8s_fixtures import make_pod

NODE = "node-wal"


@pytest.fixture(autouse=True)
def _clean_faults():
    FAULTS.clear()
    yield
    FAULTS.clear()


def _fsync_count(mode: str) -> int:
    return REGISTRY.histogram_stats(ckpt_mod.FSYNC_SECONDS, mode=mode)[0]


def _canonical_state(ckpt: AllocationCheckpoint) -> str:
    """Replay state, canonically serialized — 'byte-identical replay
    state' means these strings match across durability modes."""
    return json.dumps(
        sorted((list(k), v) for k, v in ckpt.pending().items()),
        sort_keys=True,
    )


# --- mode parity ------------------------------------------------------------


def test_batch_and_always_replay_byte_identical(tmp_path):
    """Tier-1 smoke bit: the same admission sequence journaled in batch
    and always mode must reload to byte-identical replay state."""
    seq = []
    for i in range(40):
        key = ("default", f"p{i % 13}")
        seq.append(("begin", key, {"kind": "mem", "idx": i % 4, "units": 2}))
        if i % 3 == 0:
            seq.append(("commit", key, None))
        elif i % 3 == 1:
            seq.append(("abort", key, None))
        # i % 3 == 2: left unresolved -> must replay

    states = {}
    for mode in ("always", "batch"):
        path = str(tmp_path / f"{mode}.ckpt")
        ckpt = AllocationCheckpoint(path, fsync=mode, batch_window_s=0.001)
        for op, key, data in seq:
            if op == "begin":
                ckpt.begin(key, dict(data))
            elif op == "commit":
                ckpt.commit(key)
            else:
                ckpt.abort(key)
        ckpt.close()
        reopened = AllocationCheckpoint(path, fsync=mode)
        states[mode] = _canonical_state(reopened)
        reopened.close()
    assert states["batch"] == states["always"]
    assert states["batch"] != "[]"  # the sequence leaves live entries


def test_interleaving_property_200_seeds(tmp_path):
    """Threading stress for the group-commit writer: per seed, 4 threads
    journal begin/commit/abort over disjoint key spaces with a randomized
    gather window; after close + reopen the replay set must equal exactly
    the keys each thread deliberately left unresolved. 200 seeds — any
    ordering bug between the writer thread, compaction, and the callers
    has to survive thousands of interleavings to land."""
    failures = []
    for seed in range(200):
        rng = random.Random(seed)
        path = str(tmp_path / f"s{seed}.ckpt")
        window = rng.choice([0.0, 0.0002, 0.001])
        ckpt = AllocationCheckpoint(path, fsync="batch", batch_window_s=window)
        # pre-decide every key's fate so the expected replay set is exact
        plans = []
        expected = set()
        for t in range(4):
            plan = []
            for k in range(5):
                key = (f"ns{t}", f"p{k}")
                fate = rng.choice(["leave", "commit", "abort"])
                plan.append((key, fate))
                if fate == "leave":
                    expected.add(key)
            plans.append(plan)

        def worker(plan):
            for key, fate in plan:
                ckpt.begin(key, {"kind": "mem", "idx": 1, "units": 1})
                if fate == "commit":
                    ckpt.commit(key)
                elif fate == "abort":
                    ckpt.abort(key)

        threads = [
            threading.Thread(target=worker, args=(p,), daemon=True)
            for p in plans
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        ckpt.close()
        reopened = AllocationCheckpoint(path, fsync="batch")
        got = set(reopened.pending())
        reopened.close()
        if got != expected:
            failures.append((seed, sorted(expected - got), sorted(got - expected)))
    assert not failures, (
        f"{len(failures)}/200 seeds diverged; first (seed, missing, extra): "
        f"{failures[0]}"
    )


# --- compaction vs the writer ----------------------------------------------


def test_compaction_races_queued_batch(tmp_path):
    """Compact while a batch is still queued in the writer: the compacted
    snapshot plus the late-appended records must replay to the same state,
    and every surviving line must parse."""
    path = str(tmp_path / "race.ckpt")
    ckpt = AllocationCheckpoint(path, fsync="batch", batch_window_s=0.2)
    keys = [("default", f"p{i}") for i in range(5)]
    threads = [
        threading.Thread(
            target=ckpt.begin,
            args=(k, {"kind": "mem", "idx": i, "units": 1}),
            daemon=True,
        )
        for i, k in enumerate(keys)
    ]
    for t in threads:
        t.start()
    time.sleep(0.02)  # inside the 0.2s gather window: the batch is queued
    ckpt.compact()  # swaps the file under the queued batch
    for t in threads:
        t.join(timeout=10)
    ckpt.close()
    with open(path) as f:
        for line in f:
            if line.strip():
                json.loads(line)  # no torn/corrupt lines
    reopened = AllocationCheckpoint(path, fsync="batch")
    assert set(reopened.pending()) == set(keys)
    reopened.close()


def test_compact_every_bounds_file_under_groupcommit(tmp_path, monkeypatch):
    """The resolve-triggered compaction still bounds the journal when the
    records ride the group-commit writer."""
    monkeypatch.setattr(ckpt_mod, "COMPACT_EVERY", 8)
    path = str(tmp_path / "bounded.ckpt")
    ckpt = AllocationCheckpoint(path, fsync="batch", batch_window_s=0.0005)
    ckpt.begin(("default", "keeper"), {"kind": "mem", "idx": 3, "units": 1})
    for i in range(40):
        key = ("default", f"p{i}")
        ckpt.begin(key, {"kind": "mem", "idx": 0, "units": 1})
        ckpt.commit(key)
    ckpt.flush()
    with open(path) as f:
        lines = [ln for ln in f if ln.strip()]
    assert len(lines) < 40  # compaction ran; the file is not append-only
    ckpt.close()
    reopened = AllocationCheckpoint(path, fsync="batch")
    assert set(reopened.pending()) == {("default", "keeper")}
    reopened.close()


# --- torn tail mid-batch ----------------------------------------------------


def test_torn_tail_mid_batch_replays_fsynced_prefix(tmp_path):
    """One fsync covered the whole batch; a crash tearing the batch's last
    record must replay exactly the intact prefix."""
    path = str(tmp_path / "torn.ckpt")
    before = _fsync_count("batch")
    ckpt = AllocationCheckpoint(path, fsync="batch", batch_window_s=0.2)
    keys = [("default", f"p{i}") for i in range(3)]
    threads = [
        threading.Thread(
            target=ckpt.begin,
            args=(k, {"kind": "mem", "idx": i, "units": 1}),
            daemon=True,
        )
        for i, k in enumerate(keys)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10)
    # all three records rode ONE flush+fsync
    assert _fsync_count("batch") - before == 1
    ckpt.close()
    size = os.path.getsize(path)
    with open(path, "rb") as f:
        data = f.read()
    assert data.count(b"\n") == 4  # header + 3 begins
    with open(path, "r+b") as f:
        f.truncate(size - 10)  # tear into the batch's final record
    reopened = AllocationCheckpoint(path, fsync="batch")
    pending = set(reopened.pending())
    assert len(pending) == 2 and pending < set(keys)
    reopened.close()


# --- crash_after at the new batch boundaries --------------------------------


def test_wal_queue_crash_never_fsynced_replays_absent(tmp_path):
    """crash_after:checkpoint.wal_queue — the record is queued for group
    commit but the process dies before the batch fsyncs: a restart must
    see NO trace of it (the caller never proceeded past begin, so nothing
    was promised)."""
    path = str(tmp_path / "queue-crash.ckpt")
    # huge window: the queued batch provably cannot flush before "death"
    ckpt = AllocationCheckpoint(path, fsync="batch", batch_window_s=60.0)
    FAULTS.inject("checkpoint.wal_queue", mode="crash", times=1)
    with pytest.raises(SimulatedCrash):
        ckpt.begin(("default", "ghost"), {"kind": "mem", "idx": 0, "units": 2})
    ckpt.abandon()  # SIGKILL semantics: the queue dies with the process
    survivor = AllocationCheckpoint(path, fsync="batch")
    assert survivor.pending() == {}
    assert replay_checkpoint(survivor, AssumeCache()) == 0
    survivor.close()


def test_batch_fsync_crash_durable_replays_present(tmp_path):
    """crash_after:checkpoint.batch_fsync — the batch IS durable when the
    crash kills its callers: a restart must replay every record of it."""
    path = str(tmp_path / "fsync-crash.ckpt")
    ckpt = AllocationCheckpoint(path, fsync="batch", batch_window_s=0.001)
    FAULTS.inject("checkpoint.batch_fsync", mode="crash", times=1)
    with pytest.raises(SimulatedCrash):
        ckpt.begin(("default", "durable"), {"kind": "mem", "idx": 1, "units": 4})
    ckpt.abandon()
    survivor = AllocationCheckpoint(path, fsync="batch")
    assert set(survivor.pending()) == {("default", "durable")}
    assume = AssumeCache()
    assert replay_checkpoint(survivor, assume) == 1
    mem_used, _held = assume.overlaid_state(lambda: ({}, set()))
    assert mem_used == {1: 4}
    survivor.close()


@pytest.mark.parametrize("mode", ["always", "batch"])
def test_begin_crash_semantics_identical_across_modes(tmp_path, mode):
    """The restart suite's checkpoint.begin boundary, in BOTH durability
    modes: the record is durable before the fault fires, whichever path
    wrote it."""
    path = str(tmp_path / f"{mode}.ckpt")
    ckpt = AllocationCheckpoint(path, fsync=mode, batch_window_s=0.001)
    FAULTS.inject("checkpoint.begin", mode="crash", times=1)
    with pytest.raises(SimulatedCrash):
        ckpt.begin(("default", "p"), {"kind": "mem", "idx": 0, "units": 2})
    survivor = AllocationCheckpoint(path, fsync=mode)
    assert set(survivor.pending()) == {("default", "p")}
    survivor.close()
    ckpt.abandon()


def test_flush_is_the_single_durability_barrier(tmp_path):
    """The old side-channel flush path is gone: ``flush()`` drains the
    group-commit writer itself, so a record sitting in a long gather
    window becomes durable the moment anyone needs the barrier."""
    path = str(tmp_path / "barrier.ckpt")
    ckpt = AllocationCheckpoint(path, fsync="batch", batch_window_s=60.0)
    done = threading.Event()

    def begin():
        ckpt.begin(("default", "slow"), {"kind": "mem", "idx": 0, "units": 1})
        done.set()

    t = threading.Thread(target=begin, daemon=True)
    t.start()
    time.sleep(0.05)
    assert not done.is_set()  # still gathering: not yet durable
    ckpt.flush()  # the barrier forces the batch out
    assert done.wait(5.0)
    reader = AllocationCheckpoint(path, fsync="batch")
    assert set(reader.pending()) == {("default", "slow")}
    reader.close()
    ckpt.close()


# --- coalesced pod-PATCH pipeline -------------------------------------------


@pytest.fixture
def api():
    srv = FakeApiServer()
    srv.add_node(NODE)
    srv.start()
    yield srv
    srv.stop()


def _patch_batches() -> tuple[int, float]:
    return REGISTRY.histogram_stats(api_mod.PATCH_BATCH_RECORDS, kind="pod")


def test_pipeline_coalesces_concurrent_pod_patches(api):
    client = ApiServerClient(api.url)
    pipeline = PodPatchPipeline(client, window_s=0.05)
    n = 8
    for i in range(n):
        api.add_pod(make_pod(f"pp{i}", 2, node=NODE))
    batches_before, patches_before = _patch_batches()
    results: dict[int, dict] = {}
    errors: list = []

    def patch(i):
        try:
            results[i] = pipeline.patch_pod(
                "default", f"pp{i}",
                {"metadata": {"annotations": {"wal-test": str(i)}}},
            )
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [
        threading.Thread(target=patch, args=(i,), daemon=True) for i in range(n)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10)
    pipeline.stop()
    assert not errors
    assert len(results) == n
    for i, pod in results.items():
        # each caller got ITS pod's post-PATCH copy, annotation applied
        assert pod["metadata"]["name"] == f"pp{i}"
        assert pod["metadata"]["annotations"]["wal-test"] == str(i)
        assert api.pods[("default", f"pp{i}")]["metadata"]["annotations"][
            "wal-test"
        ] == str(i)
    batches_after, patches_after = _patch_batches()
    assert patches_after - patches_before == n
    # coalesced: strictly fewer dispatch batches than patches
    assert batches_after - batches_before < n


def test_pipeline_maps_api_errors_per_item(api):
    """404/409 surface as the same ApiError a direct patch_pod raises —
    including on the pipelined (multi-item) path."""
    client = ApiServerClient(api.url)
    pipeline = PodPatchPipeline(client, window_s=0.05)
    api.add_pod(make_pod("real", 2, node=NODE))
    outcome: dict[str, object] = {}

    def patch(name):
        try:
            outcome[name] = pipeline.patch_pod(
                "default", name, {"metadata": {"annotations": {"a": "1"}}}
            )
        except Exception as e:  # noqa: BLE001
            outcome[name] = e

    threads = [
        threading.Thread(target=patch, args=(n,), daemon=True)
        for n in ("real", "missing")
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10)
    assert isinstance(outcome["missing"], ApiError)
    assert outcome["missing"].status == 404
    assert isinstance(outcome["real"], dict)

    # conflict injection takes the single-item (sequential) path
    api.conflicts_to_inject = 1
    with pytest.raises(ApiError) as ei:
        pipeline.patch_pod(
            "default", "real", {"metadata": {"annotations": {"b": "2"}}}
        )
    assert ei.value.status == 409
    pipeline.stop()


def test_pipeline_falls_back_when_pipe_connection_dies(api):
    """A dead pipelined connection must degrade to per-item sequential
    PATCHes, not fail the batch."""
    client = ApiServerClient(api.url)
    pipeline = PodPatchPipeline(client, window_s=0.05, fanout=1)
    for i in range(4):
        api.add_pod(make_pod(f"fb{i}", 2, node=NODE))

    def storm(tag):
        outcome = {}

        def patch(i):
            outcome[i] = pipeline.patch_pod(
                "default", f"fb{i}", {"metadata": {"annotations": {tag: "y"}}}
            )

        threads = [
            threading.Thread(target=patch, args=(i,), daemon=True)
            for i in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10)
        return outcome

    assert len(storm("warm")) == 4  # establishes the pipe
    # sever the pipelined connection behind the dispatcher's back
    for pipe in pipeline._pipes:
        if pipe is not None:
            pipe[0].sock.close()
    outcome = storm("after")
    assert len(outcome) == 4
    for i in range(4):
        assert api.pods[("default", f"fb{i}")]["metadata"]["annotations"]["after"] == "y"
    pipeline.stop()


def test_node_patch_coalescer_merges_same_node(api):
    """N concurrent annotation updates to one node collapse into fewer
    PATCH requests whose merge carries every key."""
    client = ApiServerClient(api.url)
    n = 6
    before = len([p for p, _ in api.patch_log if f"/nodes/{NODE}" in p])
    results: list = []

    def patch(i):
        results.append(
            client.patch_node_merged(
                NODE, {"metadata": {"annotations": {f"k{i}": str(i)}}}
            )
        )

    threads = [
        threading.Thread(target=patch, args=(i,), daemon=True) for i in range(n)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10)
    assert len(results) == n
    ann = api.nodes[NODE]["metadata"]["annotations"]
    for i in range(n):
        assert ann[f"k{i}"] == str(i)
    sent = len([p for p, _ in api.patch_log if f"/nodes/{NODE}" in p]) - before
    assert sent < n  # at least one merge happened


# --- informer batched apply -------------------------------------------------


def test_informer_apply_batch_single_lock_pass():
    from gpushare_device_plugin_tpu.cluster import informer as inf_mod
    from gpushare_device_plugin_tpu.cluster.informer import PodInformer

    inf = PodInformer(client=None, node_name=NODE)
    inf._synced.set()
    count_before = REGISTRY.histogram_stats(
        inf_mod.APPLY_BATCH, scope=NODE
    )[0]
    events = []
    for i in range(10):
        pod = make_pod(f"b{i}", 2, node=NODE)
        pod["metadata"]["resourceVersion"] = str(100 + i)
        events.append(("ADDED", pod))
    rv, err = inf.apply_batch(events)
    assert err is None
    assert rv == "109"
    assert len(inf.pending_pods()) == 10
    mem_used, _ = inf.chip_state()
    assert mem_used == {}  # pending pods don't count toward usage
    # the whole burst was ONE observed batch (one lock acquisition)
    assert REGISTRY.histogram_stats(inf_mod.APPLY_BATCH, scope=NODE)[0] == (
        count_before + 1
    )
    # an ERROR event stops the batch and surfaces for relist
    rv2, err2 = inf.apply_batch([("ERROR", {"code": 410})])
    assert rv2 is None and err2 == {"code": 410}


def test_idle_exit_hands_off_restart_duty():
    """Idle-exit/submit race: the worker must clear ``_thread`` UNDER THE
    LOCK before dying, so a submit() racing the exit restarts a fresh
    worker instead of enqueueing behind a thread that has already made
    its final queue check (a ticket that would hang until some unrelated
    later submit)."""
    from gpushare_device_plugin_tpu.utils.batch import GroupBatcher

    b = GroupBatcher(lambda items: None, window_s=0.0, idle_exit_s=0.05)
    assert b.submit("a").wait(1.0) is None
    deadline = time.monotonic() + 2.0
    while time.monotonic() < deadline:
        with b._cond:
            if b._thread is None:
                break
        time.sleep(0.005)
    with b._cond:
        assert b._thread is None, "idle exit left a dead thread installed"
    # a post-idle submit restarts cleanly and resolves
    assert b.submit("b").wait(1.0) is None
    b.stop()
