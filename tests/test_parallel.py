"""Pod-side parallel runtime: env consumption, mesh building, ring attention.

Runs on the virtual 8-device CPU mesh (conftest.py sets
xla_force_host_platform_device_count=8).
"""

import pytest

pytestmark = pytest.mark.slow

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from gpushare_device_plugin_tpu import const
from gpushare_device_plugin_tpu.parallel import (
    MeshSpec,
    PodTpuEnv,
    configure_jax_from_env,
    make_mesh,
    ring_attention,
)
from gpushare_device_plugin_tpu.parallel.mesh import local_batch_size
from gpushare_device_plugin_tpu.parallel.ring import full_attention


# --- podenv -----------------------------------------------------------------

def injected_env(chips="1", container=8, dev=32, bounds=""):
    env = {
        const.ENV_TPU_VISIBLE_CHIPS: chips,
        const.ENV_MEM_IDX: chips.split(",")[0] if chips else "-1",
        const.ENV_MEM_CONTAINER: str(container),
        const.ENV_MEM_DEV: str(dev),
    }
    if bounds:
        env[const.ENV_TPU_PROCESS_BOUNDS] = bounds
    return env


def test_podenv_parses_fractional_grant():
    pod = PodTpuEnv.from_env(injected_env(chips="2", container=8, dev=32))
    assert pod.visible_chips == (2,)
    assert pod.chip_index == 2
    assert pod.hbm_fraction == pytest.approx(0.25)
    assert not pod.exclusive


def test_podenv_explicit_fraction_is_upper_bound():
    # Explicit env caps the derived fraction but can never raise it — a
    # stale pod-level value must not let one container grab the pod's total.
    env = injected_env(container=8, dev=32)
    env[const.ENV_XLA_MEM_FRACTION] = "0.5"
    assert PodTpuEnv.from_env(env).hbm_fraction == pytest.approx(0.25)
    env[const.ENV_XLA_MEM_FRACTION] = "0.125"
    assert PodTpuEnv.from_env(env).hbm_fraction == pytest.approx(0.125)


def test_podenv_whole_chip_is_exclusive():
    pod = PodTpuEnv.from_env(injected_env(container=32, dev=32))
    assert pod.exclusive


def test_podenv_string_envs_share_one_parser():
    # Regression pin for the consolidated annotation→env string parsing:
    # gang shape, topology bounds, workload class, and the LoRA adapter id
    # must all read absent/blank values as their defaults and strip
    # whitespace the same way — a new env var cannot drift from the
    # gang/class/mem precedents.
    env = injected_env()
    env[const.ENV_TPU_PROCESS_BOUNDS] = "  2,2,1  "
    env[const.ENV_GANG_SHAPE] = " 2x2 "
    env[const.ENV_WORKLOAD_CLASS] = f"  {const.WORKLOAD_BEST_EFFORT}  "
    env[const.ENV_LORA_ADAPTER] = "  tenant-a  "
    pod = PodTpuEnv.from_env(env)
    assert pod.process_bounds == "2,2,1"
    assert pod.gang_shape == (2, 2)
    assert pod.workload_class == const.WORKLOAD_BEST_EFFORT
    assert pod.lora_adapter == "tenant-a"


def test_podenv_string_envs_default_when_absent_or_garbled():
    pod = PodTpuEnv.from_env(injected_env())
    assert pod.process_bounds == ""
    assert pod.gang_shape == ()
    assert pod.workload_class == const.WORKLOAD_LATENCY_CRITICAL
    assert pod.lora_adapter == ""
    # A garbled class falls back to the protective default, never raises —
    # same rule as cluster.pods.workload_class on the annotation side.
    env = injected_env()
    env[const.ENV_WORKLOAD_CLASS] = "turbo"
    env[const.ENV_LORA_ADAPTER] = "   "
    pod = PodTpuEnv.from_env(env)
    assert pod.workload_class == const.WORKLOAD_LATENCY_CRITICAL
    assert pod.lora_adapter == ""


def test_configure_jax_sets_mem_fraction(monkeypatch):
    monkeypatch.delenv("XLA_PYTHON_CLIENT_MEM_FRACTION", raising=False)
    settings = configure_jax_from_env(injected_env(container=8, dev=32))
    # 0.25 * 0.95 headroom
    assert float(settings["XLA_PYTHON_CLIENT_MEM_FRACTION"]) == pytest.approx(0.2375, abs=1e-3)
    assert settings["XLA_PYTHON_CLIENT_PREALLOCATE"] == "true"


def test_configure_jax_exclusive_no_cap(monkeypatch):
    monkeypatch.delenv("XLA_PYTHON_CLIENT_MEM_FRACTION", raising=False)
    settings = configure_jax_from_env(
        injected_env(chips="0,1,2,3", container=32, dev=32, bounds="2,2,1")
    )
    assert "XLA_PYTHON_CLIENT_MEM_FRACTION" not in settings
    assert settings[const.ENV_TPU_PROCESS_BOUNDS] == "2,2,1"
    assert settings[const.ENV_TPU_VISIBLE_CHIPS] == "0,1,2,3"


# --- mesh -------------------------------------------------------------------

def test_mesh_spec_auto_factors():
    spec = MeshSpec.auto(8)
    assert spec.size == 8
    assert spec.tp == 4  # tp takes the small power of two first
    spec_sp = MeshSpec.auto(8, want_sp=True)
    assert spec_sp.size == 8 and spec_sp.sp == 2


def test_make_mesh_and_batch_math():
    mesh = make_mesh(MeshSpec(dp=2, fsdp=2, tp=2))
    assert mesh.shape == {"dp": 2, "fsdp": 2, "tp": 2, "sp": 1}
    assert local_batch_size(16, mesh) == 4
    with pytest.raises(ValueError):
        local_batch_size(6, mesh)


def test_make_mesh_size_mismatch():
    with pytest.raises(ValueError):
        make_mesh(MeshSpec(dp=3, fsdp=1, tp=1), devices=jax.devices()[:2])


# --- ring attention ---------------------------------------------------------

@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_full(causal):
    devs = np.array(jax.devices()).reshape(8)
    mesh = Mesh(devs, ("sp",))
    B, S, H, D = 2, 32, 4, 8
    rng = jax.random.key(0)
    kq, kk, kv = jax.random.split(rng, 3)
    q = jax.random.normal(kq, (B, S, H, D), dtype=jnp.float32)
    k = jax.random.normal(kk, (B, S, H, D), dtype=jnp.float32)
    v = jax.random.normal(kv, (B, S, H, D), dtype=jnp.float32)

    expected = full_attention(q, k, v, causal=causal)
    got = ring_attention(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-5)


def test_ring_attention_with_tp_heads():
    devs = np.array(jax.devices()).reshape(2, 2, 2)
    mesh = Mesh(devs, ("dp", "tp", "sp"))
    B, S, H, D = 2, 16, 4, 8
    rng = jax.random.key(1)
    kq, kk, kv = jax.random.split(rng, 3)
    q = jax.random.normal(kq, (B, S, H, D))
    k = jax.random.normal(kk, (B, S, H, D))
    v = jax.random.normal(kv, (B, S, H, D))
    expected = full_attention(q, k, v, causal=True)
    got = ring_attention(
        q, k, v, mesh, causal=True, batch_axes=("dp",), head_axes="tp"
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_gqa_grouped(causal):
    """GQA-native ring: circulating Hkv < H heads must match the grouped
    oracle (the ring moves 1/g the ICI bytes; numerics identical)."""
    from gpushare_device_plugin_tpu.workloads.attention import (
        grouped_full_attention,
    )

    devs = np.array(jax.devices()).reshape(8)
    mesh = Mesh(devs, ("sp",))
    B, S, H, Hkv, D = 2, 32, 8, 2, 8
    kq, kk, kv = jax.random.split(jax.random.key(3), 3)
    q = jax.random.normal(kq, (B, S, H, D), dtype=jnp.float32)
    k = jax.random.normal(kk, (B, S, Hkv, D), dtype=jnp.float32)
    v = jax.random.normal(kv, (B, S, Hkv, D), dtype=jnp.float32)
    expected = grouped_full_attention(q, k, v, causal=causal)
    got = ring_attention(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-5)


def test_ring_attention_gqa_with_tp():
    """Grouped ring composes with tensor parallelism: tp shards Hkv, and
    each (tp, sp) shard's query group stays aligned with its KV heads."""
    from gpushare_device_plugin_tpu.workloads.attention import (
        grouped_full_attention,
    )

    devs = np.array(jax.devices()).reshape(2, 2, 2)
    mesh = Mesh(devs, ("dp", "tp", "sp"))
    B, S, H, Hkv, D = 2, 16, 8, 2, 8
    kq, kk, kv = jax.random.split(jax.random.key(4), 3)
    q = jax.random.normal(kq, (B, S, H, D))
    k = jax.random.normal(kk, (B, S, Hkv, D))
    v = jax.random.normal(kv, (B, S, Hkv, D))
    expected = grouped_full_attention(q, k, v, causal=True)
    got = ring_attention(
        q, k, v, mesh, causal=True, batch_axes=("dp",), head_axes="tp"
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-5)


def test_ring_attention_gqa_grad():
    """Training path: gradients flow through the grouped ring."""
    devs = np.array(jax.devices()).reshape(8)
    mesh = Mesh(devs, ("sp",))
    B, S, H, Hkv, D = 1, 16, 4, 2, 4
    kq, kk = jax.random.split(jax.random.key(5))
    q = jax.random.normal(kq, (B, S, H, D))
    kv = jax.random.normal(kk, (B, S, Hkv, D))

    def loss(q, kv):
        return jnp.sum(ring_attention(q, kv, kv, mesh) ** 2)

    gq, gkv = jax.jit(jax.grad(loss, argnums=(0, 1)))(q, kv)
    assert gq.shape == q.shape and gkv.shape == kv.shape
    assert bool(jnp.isfinite(gq).all()) and bool(jnp.isfinite(gkv).all())
    assert float(jnp.abs(gkv).sum()) > 0


def test_ring_attention_causal_skips_masked_hops():
    """The causal ring must guard each hop's score/update behind a
    conditional on block visibility (fully-future K/V blocks are skipped —
    ~half the MXU work at sp > 1), while the non-causal ring has no such
    branch. Oracle equality for both is covered above; here we pin the
    structure so a refactor cannot silently reintroduce the wasted work."""
    devs = np.array(jax.devices()).reshape(8)
    mesh = Mesh(devs, ("sp",))
    B, S, H, D = 1, 32, 2, 8
    q = jax.random.normal(jax.random.key(0), (B, S, H, D))

    causal_jaxpr = str(jax.make_jaxpr(
        lambda q: ring_attention(q, q, q, mesh, causal=True)
    )(q))
    plain_jaxpr = str(jax.make_jaxpr(
        lambda q: ring_attention(q, q, q, mesh, causal=False)
    )(q))
    assert "cond" in causal_jaxpr
    assert "cond" not in plain_jaxpr


def test_ring_attention_single_device_axis():
    """n=1 ring (sp axis of size 1): the rotate loop has zero trips and the
    one block is consumed in place — no ppermute at all in the graph."""
    devs = np.array(jax.devices()[:1]).reshape(1)
    mesh = Mesh(devs, ("sp",))
    B, S, H, D = 2, 16, 4, 8
    kq, kk, kv = jax.random.split(jax.random.key(7), 3)
    q = jax.random.normal(kq, (B, S, H, D))
    k = jax.random.normal(kk, (B, S, H, D))
    v = jax.random.normal(kv, (B, S, H, D))
    expected = full_attention(q, k, v, causal=True)
    got = ring_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-5)
    jaxpr = str(jax.make_jaxpr(
        lambda q: ring_attention(q, q, q, mesh, causal=True)
    )(q))
    assert "ppermute" not in jaxpr


def test_ring_attention_jit_grad():
    """Ring attention must be differentiable under jit (training path)."""
    devs = np.array(jax.devices()).reshape(8)
    mesh = Mesh(devs, ("sp",))
    B, S, H, D = 1, 16, 2, 4
    rng = jax.random.key(2)
    q = jax.random.normal(rng, (B, S, H, D))

    def loss(q):
        return jnp.sum(ring_attention(q, q, q, mesh) ** 2)

    g = jax.jit(jax.grad(loss))(q)
    assert g.shape == q.shape
    assert bool(jnp.all(jnp.isfinite(g)))


def test_multihost_spec_from_explicit_env():
    from gpushare_device_plugin_tpu.parallel import multihost_spec

    spec = multihost_spec({
        const.ENV_COORDINATOR_ADDRESS: "llama3-fsdp-0.llama3-fsdp:8476",
        const.ENV_NUM_PROCESSES: "4",
        const.ENV_PROCESS_ID: "3",
    })
    assert spec.is_multihost
    assert spec.process_id == 3
    assert spec.num_processes == 4


def test_multihost_spec_ordinal_from_hostname():
    from gpushare_device_plugin_tpu.parallel import multihost_spec

    spec = multihost_spec({
        const.ENV_COORDINATOR_ADDRESS: "llama3-fsdp-0.llama3-fsdp:8476",
        const.ENV_NUM_PROCESSES: "4",
        "HOSTNAME": "llama3-fsdp-2",
    })
    assert spec.process_id == 2


def test_multihost_spec_single_host_default():
    from gpushare_device_plugin_tpu.parallel import (
        initialize_multihost,
        multihost_spec,
    )

    spec = multihost_spec({})
    assert not spec.is_multihost
    # no coordinator -> no jax.distributed.initialize, plain return
    assert initialize_multihost({}) == spec


def test_initialize_multihost_calls_jax_distributed(monkeypatch):
    import jax

    from gpushare_device_plugin_tpu.parallel import initialize_multihost

    calls = {}
    monkeypatch.setattr(
        jax.distributed, "initialize", lambda **kw: calls.update(kw)
    )
    initialize_multihost({
        const.ENV_COORDINATOR_ADDRESS: "c:1234",
        const.ENV_NUM_PROCESSES: "2",
        const.ENV_PROCESS_ID: "1",
    })
    assert calls == {
        "coordinator_address": "c:1234",
        "num_processes": 2,
        "process_id": 1,
    }


def test_prune_unshardable_axes():
    from gpushare_device_plugin_tpu.parallel.mesh import prune_unshardable

    mesh = make_mesh(MeshSpec(dp=1, fsdp=2, tp=4))
    specs = {
        "kernel": jax.sharding.PartitionSpec("fsdp", "tp"),
        "bias": jax.sharding.PartitionSpec("tp"),
        "big": jax.sharding.PartitionSpec(("dp", "fsdp"), "tp"),
    }
    abstract = {
        "kernel": jax.ShapeDtypeStruct((16, 10), jnp.float32),  # 10 % 4 != 0
        "bias": jax.ShapeDtypeStruct((10,), jnp.float32),
        "big": jax.ShapeDtypeStruct((8, 8), jnp.float32),
    }
    pruned = prune_unshardable(specs, abstract, mesh)
    assert pruned["kernel"] == jax.sharding.PartitionSpec("fsdp", None)
    assert pruned["bias"] == jax.sharding.PartitionSpec(None)
    assert pruned["big"] == jax.sharding.PartitionSpec(("dp", "fsdp"), "tp")


@pytest.mark.parametrize("causal", [True, False])
def test_ring_flash_hops_match_oracle(causal):
    """hop_attention="flash": the Pallas kernel runs per hop and the
    per-hop (o, lse) merge must be exact vs the grouped oracle — the ring
    gets kernel-grade attention without materialized score blocks."""
    from gpushare_device_plugin_tpu.parallel.ring import grouped_attention

    devs = np.array(jax.devices()).reshape(8)
    mesh = Mesh(devs, ("sp",))
    B, S, H, Hkv, D = 2, 64, 4, 2, 16
    kq, kk, kv = jax.random.split(jax.random.key(11), 3)
    q = jax.random.normal(kq, (B, S, H, D), jnp.float32)
    k = jax.random.normal(kk, (B, S, Hkv, D), jnp.float32)
    v = jax.random.normal(kv, (B, S, Hkv, D), jnp.float32)
    ref = grouped_attention(q, k, v, causal=causal)
    got = ring_attention(q, k, v, mesh, causal=causal, hop_attention="flash")
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-5)


def test_ring_flash_hops_grad_matches_plain_ring():
    """Training path: flash-hop ring gradients equal the plain-hop ring's
    (the dlse term of the pair-vjp is exercised by the cross-hop merge)."""
    devs = np.array(jax.devices()).reshape(8)
    mesh = Mesh(devs, ("sp",))
    B, S, H, D = 1, 64, 2, 8
    q = jax.random.normal(jax.random.key(12), (B, S, H, D))

    def loss_flash(q):
        return jnp.sum(
            ring_attention(q, q, q, mesh, hop_attention="flash") ** 2
        )

    def loss_plain(q):
        return jnp.sum(
            ring_attention(q, q, q, mesh, hop_attention="plain") ** 2
        )

    gf = jax.jit(jax.grad(loss_flash))(q)
    gp = jax.jit(jax.grad(loss_plain))(q)
    np.testing.assert_allclose(np.asarray(gf), np.asarray(gp), atol=1e-4)


def test_ring_hop_attention_validation():
    devs = np.array(jax.devices()).reshape(8)
    mesh = Mesh(devs, ("sp",))
    q = jnp.zeros((1, 16, 2, 4))
    with pytest.raises(ValueError, match="hop_attention"):
        ring_attention(q, q, q, mesh, hop_attention="bogus")


def test_ring_auto_stays_plain_off_tpu():
    """auto on CPU keeps the einsum path (the interpreter kernel would be
    pathologically slow in a training loop) — pinned via the jaxpr: no
    pallas custom call in the auto trace off-TPU."""
    devs = np.array(jax.devices()).reshape(8)
    mesh = Mesh(devs, ("sp",))
    q = jax.random.normal(jax.random.key(13), (1, 64, 2, 8))
    auto_jaxpr = str(jax.make_jaxpr(
        lambda q: ring_attention(q, q, q, mesh, hop_attention="auto")
    )(q))
    flash_jaxpr = str(jax.make_jaxpr(
        lambda q: ring_attention(q, q, q, mesh, hop_attention="flash")
    )(q))
    assert "pallas" not in auto_jaxpr
    assert "pallas" in flash_jaxpr
