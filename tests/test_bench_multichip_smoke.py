"""bench_mfu.py --multichip-smoke: tensor-parallel gang serving must be
token-identical to the single-chip engine (ISSUE 6 satellite).

Tier-1 (not slow): the CPU multi-chip smoke is the acceptance gate for
the topology subsystem's workload half — the TP SlotEngine over a
simulated granted gang (8 forced virtual devices) must emit tokens
BIT-IDENTICAL to the single-chip engine on the same trace with zero
retraces, and the per-chip gang sizing must admit a larger pool than one
chip's identical slice. Subprocess on purpose, like the other bench
smokes: the bench must work as shipped (env forcing, argv handling, the
JSON contract the driver parses)."""

import json
import os
import subprocess
import sys
from pathlib import Path


def test_bench_multichip_smoke_tp_engine_token_identical():
    repo = Path(__file__).resolve().parent.parent
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    # the bench forces its own virtual device count; an inherited
    # XLA_FLAGS from the test session must not mask that path
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [sys.executable, str(repo / "bench_mfu.py"), "--multichip-smoke"],
        env=env, capture_output=True, text=True, timeout=600, cwd=str(repo),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    report = json.loads(proc.stdout.strip().splitlines()[-1])
    assert report["sections"] == ["serve_tp"]
    row = report["serve_tp"]

    # the virtual mesh came up and the gang spanned multiple chips
    assert row["devices"] >= 2
    assert row["tp"] >= 2
    assert not row.get("skipped")

    # THE acceptance gates (also hard-asserted inside the bench):
    # bit-identical tokens and zero retraces across slot churn
    assert row["tokens_identical"] is True
    assert row["retraces"] == 0
    assert row["tp_engine"]["trace_counts"] == {
        "prefill": 1, "extend": 1, "decode": 1,
    }

    # same trace served to completion on both engines
    assert row["tp_engine"]["requests"] == row["single"]["requests"]
    assert row["tp_engine"]["tokens"] == row["single"]["tokens"]
    assert row["tp_goodput_ratio"] is not None

    # the capacity story: per-chip gang sizing beats one chip's slice
    assert row["slots_gang"] > row["slots_single_slice"]

    # the MULTICHIP_r0*.json dry-run capture is folded into the report
    dry = row["multichip_dryrun"]
    assert dry["found"] is True
    assert dry["ok"] is True
    assert dry["n_devices"] >= 2
