"""North-star benchmark: pod Allocate() latency + throughput through the
full stack, plus the compute-path numbers (flash-attention speedup,
train-step MFU) when a real TPU chip is attached.

Control-plane half, three sections:

- **Serial** (the historical headline): drives the complete admission path
  on one simulated 4-chip x 32 GiB host (BASELINE.md config 1/3 shape):
  in-process fake kubelet grants fake-device IDs over **real gRPC on a
  unix socket** to the real plugin server, whose ClusterAllocator matches
  the pod off the informer cache, first-fit binpacks the chip, and
  persists annotations with a strategic-merge PATCH — the reference's hot
  path (``allocate.go:27-134``) end to end, nothing mocked below the wire.
  Three independent trials; the reported p50/p99 are the medians of
  per-trial quantiles.
- **Concurrent** (``--workers N``, default 8): N parallel fake-kubelet
  admission workers storm the same real gRPC socket with same-size pods.
  The lock-sharded allocator overlaps their apiserver PATCHes; the section
  verifies zero double-assignments / no chip over-commit after every storm
  and reports aggregate pods/s plus the speedup over this run's serial
  throughput. The storm runs with the crash-safe WAL **on** (group-commit
  ``batch`` mode by default; ``--wal-fsync`` picks ``always``/``off``) and
  the coalesced PATCH pipeline wired in, and reports
  ``wal_fsyncs_per_admission``, the fsync p99, and the PATCH-coalescing
  ratio. The serial section stays WAL-free — its p50 is the long-lived
  trend-guard series and must compare like-for-like with the committed
  history. ``--wal-bench`` runs ONLY the storm, once per WAL mode
  (``always`` then ``batch``), and emits a comparison record
  (``make bench-wal``).
- **Extender**: a multi-node scoring benchmark — cluster-wide informer
  over hundreds of placed pods, batched filter+prioritize over the node
  list, p50 per scheduling decision (index + NodeView cache hot).

Compute half: delegates to ``bench_mfu.py`` in a subprocess (so this script
stays importable without jax) and folds its JSON into the ``compute`` key.
Skipped cleanly off-TPU.

Prints ONE JSON line:
    {"metric": "allocate_p50_latency", "value": <ms>, "unit": "ms",
     "vs_baseline": <x>, "concurrent": {...}, "extender": {...}, ...}

``vs_baseline`` is 100 ms / p50 (the reference's own allocate-path retry
tick, its only latency anchor; higher is better).

Trend guards: exits nonzero (after printing the JSON line) when the
measured p50 regresses >20% — or the p99 >25% (tail regressions must not
land silently either) — against the newest committed ``BENCH_r*.json``.
``--no-trend-guard`` disables both. ``--smoke`` runs a 3-pod quick pass
with all guards and the compute bench off (CI bit-rot insurance, see
``make bench-smoke``).
"""

from __future__ import annotations

import argparse
import json
import os
import re
import shutil
import statistics
import subprocess
import sys
import tempfile
import threading
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent / "tests"))

NODE = "bench-node"
CHIPS = 4
HBM_GIB = 32
ROUNDS = 10
TRIALS = 3
# Pod sizes per fill round: [16,8,4,2,2] fills one 32-unit chip exactly;
# four repetitions pack the host 128/128 (first-fit lands them chip by chip).
POD_SIZES = [16, 8, 4, 2, 2] * CHIPS
TREND_GUARD_PCT = 20.0
P99_GUARD_PCT = 25.0
DEFAULT_WORKERS = 8
CONCURRENT_ROUNDS = 4  # first round is warmup, like the serial trial
CONCURRENT_POD_UNITS = 2
# Tracing-overhead hard gate (--trace-bench): the traced storm's p99 may
# inflate at most this much over the --no-trace storm. docs/observability.md.
TRACE_OVERHEAD_PCT = 5.0
# Decision-provenance hard gate (--decisions-bench): the decisions-on
# storm's admission p99 may inflate at most this much over decisions-off.
DECISIONS_OVERHEAD_PCT = 5.0
# Sharded-extender scale bench (--scale-bench): the 8-shard router must
# clear this much admission throughput over the single-shard baseline at
# the largest node count — the work-reduction the sharding exists for.
SCALE_SPEEDUP_MIN = 3.0
SCALE_NODE_COUNTS = [32, 256, 1000]
SCALE_SHARD_COUNTS = [1, 8]
SCALE_STORM_EVENTS = 100_000


def run_allocate_trial(
    rounds: int = ROUNDS, pod_sizes: list[int] | None = None
) -> tuple[list[float], float, float]:
    """One full fill/drain cycle; returns (latencies_ms, wall_s, peak_util%)."""
    from gpushare_device_plugin_tpu import const
    from gpushare_device_plugin_tpu.allocator.cluster import ClusterAllocator
    from gpushare_device_plugin_tpu.cluster.apiserver import ApiServerClient
    from gpushare_device_plugin_tpu.cluster.informer import PodInformer
    from gpushare_device_plugin_tpu.device import DeviceInventory
    from gpushare_device_plugin_tpu.discovery import MockBackend
    from gpushare_device_plugin_tpu.plugin import PluginConfig, TpuSharePlugin

    from fake_apiserver import FakeApiServer
    from fake_kubelet import FakeKubelet
    from k8s_fixtures import make_pod

    pod_sizes = pod_sizes if pod_sizes is not None else POD_SIZES
    tmp = tempfile.mkdtemp(prefix="tpushare-bench-")
    api = FakeApiServer()
    api.add_node(NODE)
    api.start()
    kubelet = FakeKubelet(tmp)
    kubelet.start()

    client = ApiServerClient(api.url)
    inv = DeviceInventory(MockBackend(num_chips=CHIPS, hbm_bytes=HBM_GIB << 30).chips())
    # The daemon's default pod source: watch-backed informer cache (one
    # PATCH is then the only HTTP round-trip on the Allocate hot path).
    informer = PodInformer(client, NODE).start()
    allocator = ClusterAllocator(inv, client, informer, NODE)
    plugin = TpuSharePlugin(
        inv, allocate_fn=allocator.allocate, config=PluginConfig(plugin_dir=tmp)
    )
    plugin.serve()
    reg = kubelet.wait_for_registration()
    assert reg.resource_name == const.RESOURCE_MEM

    latencies: list[float] = []
    total_units = sum(inv.units_by_index().values())
    peak_used = 0
    pod_seq = 0
    fill_wall = 0.0
    for rnd in range(rounds):
        t_fill0 = time.perf_counter()
        running: list[str] = []
        used = 0
        for size in pod_sizes:
            name = f"bench-{pod_seq}"
            pod_seq += 1
            api.add_pod(make_pod(name, size, node=NODE))
            t0 = time.perf_counter()
            resp = kubelet.allocate(reg.endpoint, [[f"g{i}" for i in range(size)]])
            # Round 0 is warmup (first-call connection setup, code paths
            # still cold) — run it fully but keep it out of the stats.
            if rnd > 0:
                latencies.append((time.perf_counter() - t0) * 1e3)
            assert resp.container_responses[0].envs[const.ENV_TPU_VISIBLE_CHIPS]
            # kubelet starts the container: phase Running, so the next
            # allocation's usage accounting sees this pod. Wait (untimed)
            # for the watch to deliver the transition — usage accounting is
            # Running-only (reference parity, podmanager.go:102-115), and we
            # are benching allocate latency, not watch propagation. The poll
            # is an O(1) keyed read so it does not contend with the
            # delivery thread the way a full-cache scan would.
            api.set_pod_phase("default", name, "Running")
            deadline = time.perf_counter() + 2.0
            while time.perf_counter() < deadline:
                cached = informer.get_pod("default", name)
                if cached is not None and cached.get("status", {}).get("phase") == "Running":
                    break
                time.sleep(0.0005)
            running.append(name)
            used += size
        if rnd > 0:
            fill_wall += time.perf_counter() - t_fill0
        peak_used = max(peak_used, used)
        # Fill round complete: workload pods finish, host drains. Wait
        # (untimed) for the DELETED events to clear the informer before the
        # next fill round — otherwise the delete storm's watch processing
        # lands inside the next round's timed windows and the bench measures
        # delete propagation, not allocate latency.
        for name in running:
            api.delete_pod("default", name)
        deadline = time.perf_counter() + 2.0
        while time.perf_counter() < deadline:
            if all(informer.get_pod("default", n) is None for n in running):
                break
            time.sleep(0.0005)

    plugin.stop()
    kubelet.stop()
    informer.stop()
    api.stop()
    return latencies, fill_wall, 100.0 * peak_used / total_units


def _wal_metrics_snapshot(wal_mode: str) -> dict:
    """Cumulative WAL/PATCH instrumentation counters from the process-wide
    registry; the storm reports deltas across its run."""
    from gpushare_device_plugin_tpu.allocator import checkpoint as ckpt_mod
    from gpushare_device_plugin_tpu.cluster import apiserver as api_mod
    from gpushare_device_plugin_tpu.utils.metrics import REGISTRY

    fsyncs, _ = REGISTRY.histogram_stats(ckpt_mod.FSYNC_SECONDS, mode=wal_mode)
    _batches, records = REGISTRY.histogram_stats(
        ckpt_mod.BATCH_RECORDS, mode=wal_mode
    )
    patch_batches, patches = REGISTRY.histogram_stats(
        api_mod.PATCH_BATCH_RECORDS, kind="pod"
    )
    return {
        "fsyncs": fsyncs,
        "wal_records": records,
        "patch_batches": patch_batches,
        "patches": patches,
    }


def run_concurrent_trial(
    workers: int,
    rounds: int = CONCURRENT_ROUNDS,
    pod_units: int = CONCURRENT_POD_UNITS,
    pods_per_round: int | None = None,
    wal_mode: str = "batch",
    wal_window_s: float = 0.002,
) -> dict:
    """Concurrent-admission storm: ``workers`` threads drive Allocate()
    through the real gRPC socket against a shared pool of same-size
    pending pods (the hardest case for the match semantics — every worker
    competes for the same candidates). Per round the host is packed
    exactly full, then every assignment is audited: each pod annotated
    exactly once, no chip over its capacity. Returns aggregate pods/s over
    the timed rounds (round 0 is warmup) plus the audit tallies."""
    from gpushare_device_plugin_tpu import const
    from gpushare_device_plugin_tpu.allocator.cluster import ClusterAllocator
    from gpushare_device_plugin_tpu.cluster.apiserver import ApiServerClient
    from gpushare_device_plugin_tpu.cluster.informer import PodInformer
    from gpushare_device_plugin_tpu.device import DeviceInventory
    from gpushare_device_plugin_tpu.discovery import MockBackend
    from gpushare_device_plugin_tpu.plugin import PluginConfig, TpuSharePlugin

    from fake_apiserver import FakeApiServer
    from fake_kubelet import FakeKubelet

    tmp = tempfile.mkdtemp(prefix="tpushare-cbench-")
    api = FakeApiServer()
    api.add_node(NODE)
    api.start()
    kubelet = FakeKubelet(tmp)
    kubelet.start()

    client = ApiServerClient(api.url)
    inv = DeviceInventory(MockBackend(num_chips=CHIPS, hbm_bytes=HBM_GIB << 30).chips())
    informer = PodInformer(client, NODE).start()
    # The storm runs the full crash-safe + coalesced write stack — the WAL
    # (group-commit or always-fsync per wal_mode) plus the pipelined PATCH
    # dispatcher — i.e. the configuration a production daemon ships with.
    # The serial section stays WAL-free for trend-guard parity.
    ckpt = None
    if wal_mode != "off":
        from gpushare_device_plugin_tpu.allocator.checkpoint import (
            AllocationCheckpoint,
        )

        ckpt = AllocationCheckpoint(
            os.path.join(tmp, "wal.ckpt"), fsync=wal_mode,
            batch_window_s=wal_window_s,
        )
    from gpushare_device_plugin_tpu.cluster.apiserver import PodPatchPipeline

    pipeline = PodPatchPipeline(client)
    metrics_before = _wal_metrics_snapshot(wal_mode)
    allocator = ClusterAllocator(
        inv, client, informer, NODE,
        checkpoint=ckpt, patcher=pipeline.patch_pod,
    )
    plugin = TpuSharePlugin(
        inv,
        allocate_fn=allocator.allocate,
        config=PluginConfig(plugin_dir=tmp, grpc_workers=max(8, workers + 4)),
    )
    plugin.serve()
    reg = kubelet.wait_for_registration()
    assert reg.resource_name == const.RESOURCE_MEM
    kubelet.stub_for(reg.endpoint)  # pre-dial before the threads race it

    units_by_index = inv.units_by_index()
    total_units = sum(units_by_index.values())
    if pods_per_round is None:
        pods_per_round = total_units // pod_units  # exact pack

    def wait_until(pred, timeout=10.0):
        deadline = time.perf_counter() + timeout
        while time.perf_counter() < deadline:
            if pred():
                return True
            time.sleep(0.001)
        return False

    try:
        timed_pods, timed_wall, latencies = _concurrent_rounds(
            api, kubelet, reg, informer, client, units_by_index,
            workers, rounds, pod_units, pods_per_round, wait_until,
        )
    finally:
        plugin.stop()
        kubelet.stop()
        informer.stop()
        pipeline.stop()
        if ckpt is not None:
            ckpt.close()
        api.stop()

    # WAL + PATCH-coalescing instrumentation over the whole storm (warmup
    # round included — the counters span every admission of this trial)
    after = _wal_metrics_snapshot(wal_mode)
    delta = {k: after[k] - metrics_before[k] for k in after}
    admissions = pods_per_round * rounds
    wal_stats: dict = {"wal_mode": wal_mode}
    if wal_mode != "off":
        wal_stats["wal_window_ms"] = round(wal_window_s * 1e3, 1)
    if wal_mode != "off" and admissions:
        from gpushare_device_plugin_tpu.allocator import checkpoint as ckpt_mod
        from gpushare_device_plugin_tpu.utils.metrics import REGISTRY

        p99_s = REGISTRY.histogram_quantile(
            ckpt_mod.FSYNC_SECONDS, 0.99, mode=wal_mode
        )
        wal_stats.update({
            "wal_fsyncs_per_admission": round(delta["fsyncs"] / admissions, 3),
            "wal_fsync_p99_ms": (
                round(p99_s * 1e3, 3) if p99_s is not None else None
            ),
            "wal_batch_mean": (
                round(delta["wal_records"] / delta["fsyncs"], 2)
                if delta["fsyncs"] else None
            ),
        })
    patch_coalesce_ratio = (
        round(1.0 - delta["patch_batches"] / delta["patches"], 3)
        if delta["patches"] else None
    )
    return {
        "workers": workers,
        **wal_stats,
        # fraction of pod PATCHes that shared a dispatch batch with at
        # least one other (1 - batches/patches; 0 = fully sequential)
        "patch_coalesce_ratio": patch_coalesce_ratio,
        # Thread concurrency buys wall-clock only where admission waits
        # (apiserver RTT) rather than computes; the speedup is therefore
        # core-count-bound on CPU-starved hosts. Recorded so a reader can
        # interpret speedup_vs_serial against the machine that produced it.
        "cpus": os.cpu_count(),
        "pods_per_round": pods_per_round,
        "pod_units": pod_units,
        "rounds_timed": rounds - 1,
        "throughput_pods_s": round(timed_pods / timed_wall, 1) if timed_wall else 0.0,
        "p50_ms": round(statistics.median(latencies), 3) if latencies else None,
        "p99_ms": (
            round(statistics.quantiles(latencies, n=100)[98], 3)
            if len(latencies) >= 100
            else None
        ),
        "double_assignments": 0,  # audited per round; any nonzero raises
    }


def _concurrent_rounds(
    api, kubelet, reg, informer, client, units_by_index,
    workers, rounds, pod_units, pods_per_round, wait_until,
) -> tuple[int, float, list[float]]:
    from gpushare_device_plugin_tpu import const

    from k8s_fixtures import make_pod

    timed_pods = 0
    timed_wall = 0.0
    latencies: list[float] = []
    errors: list[str] = []
    pod_seq = 0
    for rnd in range(rounds):
        names = []
        for _ in range(pods_per_round):
            name = f"cbench-{pod_seq}"
            pod_seq += 1
            api.add_pod(make_pod(name, pod_units, node=NODE))
            names.append(name)
        # the storm measures admission, not watch propagation: wait until
        # every pending pod is matchable from the cache before firing
        assert wait_until(
            lambda: len(informer.pending_pods()) >= pods_per_round
        ), "informer never saw the round's pending pods"

        jobs = list(range(pods_per_round))
        jobs_lock = threading.Lock()
        round_lat: list[list[float]] = [[] for _ in range(workers)]
        barrier = threading.Barrier(workers + 1)

        def worker(wi: int):
            barrier.wait()
            while True:
                with jobs_lock:
                    if not jobs:
                        return
                    jobs.pop()
                t0 = time.perf_counter()
                try:
                    kubelet.allocate(
                        reg.endpoint, [[f"g{i}" for i in range(pod_units)]]
                    )
                except Exception as e:  # noqa: BLE001 — audited below
                    errors.append(str(e))
                round_lat[wi].append((time.perf_counter() - t0) * 1e3)

        threads = [
            threading.Thread(target=worker, args=(wi,), daemon=True)
            for wi in range(workers)
        ]
        for t in threads:
            t.start()
        barrier.wait()
        t0 = time.perf_counter()
        for t in threads:
            t.join(timeout=60.0)
        wall = time.perf_counter() - t0
        hung = [t.name for t in threads if t.is_alive()]
        if hung:
            # a bogus 60s wall would inflate throughput and the audit
            # below would race the still-running workers — fail loudly
            raise AssertionError(f"storm workers hung past 60s: {hung}")
        if errors:
            raise AssertionError(f"concurrent Allocate errors: {errors[:3]}")

        # audit the round: every pod assigned exactly once, no chip over
        # capacity — the storm must not trade throughput for correctness
        used_by_chip: dict[int, int] = {}
        for name in names:
            pod = client.get_pod("default", name)
            ann = pod["metadata"].get("annotations", {})
            assert ann.get(const.ENV_ASSIGNED_FLAG) == "true", f"{name} unassigned"
            idx = int(ann[const.ENV_MEM_IDX])
            used_by_chip[idx] = used_by_chip.get(idx, 0) + pod_units
        over = {
            i: u for i, u in used_by_chip.items() if u > units_by_index.get(i, 0)
        }
        assert not over, f"chip over-commit after storm: {over}"

        if rnd > 0:
            timed_pods += pods_per_round
            timed_wall += wall
            for lats in round_lat:
                latencies.extend(lats)

        for name in names:
            api.delete_pod("default", name)
        assert wait_until(
            lambda: all(informer.get_pod("default", n) is None for n in names)
        ), "informer never drained the round's deleted pods"

    _assert_lock_order_clean("concurrent admission storm")
    return timed_pods, timed_wall, latencies


def _assert_lock_order_clean(context: str) -> None:
    """Hard gate: when the runtime lock-order witness is enabled
    (TPUSHARE_LOCK_WITNESS=1 / TPUSHARE_TEST_CHAOS=1), any inversion the
    storm drove against the declared ranking fails the bench — the
    deterministic complement to the double-assignment audits."""
    from gpushare_device_plugin_tpu.utils import lockrank

    lockrank.assert_clean(context)


def run_gang_storm(
    workers: int,
    rounds: int = 3,
    shape: str = "2x1x1",
    per_chip: int = 8,
) -> dict:
    """Gang-admission storm: ``workers`` threads storm Allocate() through
    the real gRPC socket with MULTI-CHIP gang pods against one node
    topology — the all-or-nothing claim protocol's hardest case (every
    worker races for overlapping sub-slices of the same grid). Per round
    the host packs exactly full with gangs; the audit then asserts the
    two invariants the gang ledger exists for:

    - **zero partial grants** — every pod is either fully granted (all
      member chips + per-chip share persisted in one annotation set) or
      untouched; a pod with SOME gang fields is a protocol violation;
    - **zero double assignments** — per-chip sums over all gang members
      never exceed chip capacity, and no two gangs share a chip beyond
      its capacity.

    Also reports mean ICI hops of the granted slices (the topology
    scorer's objective) and aggregate gangs/s."""
    from gpushare_device_plugin_tpu import const
    from gpushare_device_plugin_tpu.allocator.cluster import ClusterAllocator
    from gpushare_device_plugin_tpu.cluster.apiserver import ApiServerClient
    from gpushare_device_plugin_tpu.cluster.informer import PodInformer
    from gpushare_device_plugin_tpu.device import DeviceInventory
    from gpushare_device_plugin_tpu.discovery import MockBackend
    from gpushare_device_plugin_tpu.plugin import PluginConfig, TpuSharePlugin
    from gpushare_device_plugin_tpu.topology import ChipTopology, shape_size

    from fake_apiserver import FakeApiServer
    from fake_kubelet import FakeKubelet
    from k8s_fixtures import make_pod

    tmp = tempfile.mkdtemp(prefix="tpushare-gbench-")
    api = FakeApiServer()
    api.add_node(NODE)
    api.start()
    kubelet = FakeKubelet(tmp)
    kubelet.start()
    client = ApiServerClient(api.url)
    inv = DeviceInventory(MockBackend(num_chips=CHIPS, hbm_bytes=HBM_GIB << 30).chips())
    informer = PodInformer(client, NODE).start()
    allocator = ClusterAllocator(inv, client, informer, NODE)
    plugin = TpuSharePlugin(
        inv,
        allocate_fn=allocator.allocate,
        config=PluginConfig(plugin_dir=tmp, grpc_workers=max(8, workers + 4)),
    )
    plugin.serve()
    reg = kubelet.wait_for_registration()
    assert reg.resource_name == const.RESOURCE_MEM
    kubelet.stub_for(reg.endpoint)

    topo = ChipTopology.default_for(CHIPS)
    n_members = shape_size(shape)
    pod_units = per_chip * n_members
    units_by_index = inv.units_by_index()
    total_units = sum(units_by_index.values())
    gangs_per_round = total_units // pod_units  # exact pack

    def wait_until(pred, timeout=10.0):
        deadline = time.perf_counter() + timeout
        while time.perf_counter() < deadline:
            if pred():
                return True
            time.sleep(0.001)
        return False

    partial_grants = 0
    double_assignments = 0
    hops: list[int] = []
    timed_gangs = 0
    timed_wall = 0.0
    pod_seq = 0
    try:
        for rnd in range(rounds):
            names = []
            for _ in range(gangs_per_round):
                name = f"gbench-{pod_seq}"
                pod_seq += 1
                api.add_pod(make_pod(
                    name, pod_units, node=NODE,
                    annotations={const.ANN_GANG_SHAPE: shape},
                ))
                names.append(name)
            assert wait_until(
                lambda: len(informer.pending_pods()) >= gangs_per_round
            ), "informer never saw the round's pending gang pods"

            jobs = list(range(gangs_per_round))
            jobs_lock = threading.Lock()
            errors: list[str] = []
            barrier = threading.Barrier(workers + 1)

            def worker():
                barrier.wait()
                while True:
                    with jobs_lock:
                        if not jobs:
                            return
                        jobs.pop()
                    try:
                        kubelet.allocate(
                            reg.endpoint,
                            [[f"g{i}" for i in range(pod_units)]],
                        )
                    except Exception as e:  # noqa: BLE001 — audited below
                        errors.append(str(e))

            threads = [
                threading.Thread(target=worker, daemon=True)
                for _ in range(workers)
            ]
            for t in threads:
                t.start()
            barrier.wait()
            t0 = time.perf_counter()
            for t in threads:
                t.join(timeout=60.0)
            wall = time.perf_counter() - t0
            if any(t.is_alive() for t in threads):
                raise AssertionError("gang storm workers hung past 60s")
            if errors:
                raise AssertionError(f"gang Allocate errors: {errors[:3]}")

            # audit: all-or-nothing grants, per-chip capacity, hop stats
            used_by_chip: dict[int, int] = {}
            for name in names:
                pod = client.get_pod("default", name)
                ann = pod["metadata"].get("annotations", {})
                gang_keys = [
                    k for k in (
                        const.ENV_GANG_CHIPS,
                        const.ENV_GANG_PER_CHIP,
                        const.ENV_ASSIGNED_FLAG,
                    ) if ann.get(k) not in (None, "false")
                ]
                if len(gang_keys) not in (0, 3):
                    partial_grants += 1
                    continue
                if not gang_keys:
                    partial_grants += 1  # storm packs exactly: all must land
                    continue
                chips = [int(x) for x in ann[const.ENV_GANG_CHIPS].split(",")]
                per = int(ann[const.ENV_GANG_PER_CHIP])
                if len(chips) != n_members or len(set(chips)) != len(chips):
                    partial_grants += 1
                    continue
                hops.append(topo.slice_hops(chips))
                for c in chips:
                    used_by_chip[c] = used_by_chip.get(c, 0) + per
            for idx, used in used_by_chip.items():
                if used > units_by_index.get(idx, 0):
                    double_assignments += 1
            if rnd > 0:
                timed_gangs += gangs_per_round
                timed_wall += wall
            for name in names:
                api.delete_pod("default", name)
            assert wait_until(
                lambda: all(
                    informer.get_pod("default", n) is None for n in names
                )
            ), "informer never drained the round's gang pods"
    finally:
        plugin.stop()
        kubelet.stop()
        informer.stop()
        api.stop()

    _assert_lock_order_clean("gang-admission storm")
    return {
        "workers": workers,
        "shape": shape,
        "per_chip_units": per_chip,
        "gangs_per_round": gangs_per_round,
        "rounds_timed": rounds - 1,
        "throughput_gangs_s": (
            round(timed_gangs / timed_wall, 1) if timed_wall else 0.0
        ),
        "partial_grants": partial_grants,
        "double_assignments": double_assignments,
        "mean_ici_hops": round(sum(hops) / len(hops), 2) if hops else None,
    }


def run_extender_bench(
    n_nodes: int = 32, pods_per_node: int = 30, iters: int = 30
) -> dict:
    """Multi-node scheduler-extender scoring benchmark: a cluster-wide
    informer holds ``n_nodes * pods_per_node`` placed pods; one scheduling
    decision = batched filter+prioritize over all nodes. Reports the p50
    per decision with the incremental index + NodeView cache hot, and the
    legacy two-verb cost for comparison."""
    from gpushare_device_plugin_tpu.cluster.apiserver import ApiServerClient
    from gpushare_device_plugin_tpu.cluster.informer import PodInformer
    from gpushare_device_plugin_tpu.extender.server import ExtenderCore

    from fake_apiserver import FakeApiServer
    from k8s_fixtures import assigned_running_pod, make_pod

    api = FakeApiServer()
    api.start()
    nodes = []
    for j in range(n_nodes):
        name = f"xb{j}"
        cap = {"aliyun.com/tpu-mem": str(CHIPS * HBM_GIB), "aliyun.com/tpu-count": str(CHIPS)}
        node = {
            "metadata": {"name": name, "labels": {}, "resourceVersion": "1"},
            "status": {"capacity": dict(cap), "allocatable": dict(cap)},
        }
        api.nodes[name] = node
        nodes.append(node)
    for i in range(n_nodes * pods_per_node):
        api.add_pod(
            assigned_running_pod(
                f"xp{i}", 2, chip_idx=i % CHIPS, node=f"xb{i % n_nodes}"
            )
        )
    client = ApiServerClient(api.url)
    informer = PodInformer(client).start(sync_timeout_s=30)
    core = ExtenderCore(client, informer=informer)
    pending = make_pod("xbench-pod", 4, node="")
    args = {"pod": pending, "nodes": {"items": nodes}}
    try:
        assert core.batch(args)["nodenames"], "extender bench: nothing fits"
        batch_lat, pair_lat = [], []
        for _ in range(iters):
            t0 = time.perf_counter()
            core.batch(args)
            batch_lat.append((time.perf_counter() - t0) * 1e3)
        for _ in range(iters):
            t0 = time.perf_counter()
            core.filter(args)
            core.prioritize(args)
            pair_lat.append((time.perf_counter() - t0) * 1e3)
    finally:
        informer.stop()
        api.stop()
    return {
        "nodes": n_nodes,
        "pods": n_nodes * pods_per_node,
        "batch_p50_ms": round(statistics.median(batch_lat), 3),
        "filter_prioritize_p50_ms": round(statistics.median(pair_lat), 3),
    }


def run_defrag_bench(
    rounds: int = 6,
    seed: int = 20260803,
    defrag_passes: int = 4,
    churn_frac: float = 0.45,
) -> dict:
    """Churn-trace defragmentation bench (``allocator/defrag.py``).

    ``rounds`` of first-fit admissions followed by a seeded random
    ~``churn_frac`` of pods finishing leave the node's chips holding
    free-HBM slivers no pending pod fits — the stranded-HBM state
    long-running clusters converge to (ROADMAP open item 5). The bench
    then runs :class:`~gpushare_device_plugin_tpu.allocator.defrag.DefragLoop`
    passes (planner scan + journaled moves through the real WAL + ledger
    + fake apiserver) until the plan drains, and reports stranded-HBM%
    and binpack packing density (used units over occupied-chip capacity)
    before/after.

    Correctness is gated here, not just measured (``_defrag_gates``):
    stranded-HBM% must STRICTLY improve and packing density must not
    drop, no chip may end over capacity, and the journal/ledger must
    drain — a defragmenter that "finishes" with a pending move entry or
    an orphaned reservation has lost the crash-safety story the move
    protocol exists for."""
    from gpushare_device_plugin_tpu.allocator import defrag as D
    from gpushare_device_plugin_tpu.allocator.assume import AssumeCache
    from gpushare_device_plugin_tpu.allocator.checkpoint import AllocationCheckpoint
    from gpushare_device_plugin_tpu.cluster import pods as P
    from gpushare_device_plugin_tpu.cluster.apiserver import ApiServerClient
    from gpushare_device_plugin_tpu.cluster.podsource import ApiServerPodSource

    from fake_apiserver import FakeApiServer
    from k8s_fixtures import assigned_running_pod

    import random

    chip_units = HBM_GIB
    capacity = {i: chip_units for i in range(CHIPS)}
    rng = random.Random(seed)
    tmp = tempfile.mkdtemp(prefix="tpushare-dbench-")
    api = FakeApiServer()
    api.add_node(NODE)
    api.start()
    try:
        client = ApiServerClient(api.url)
        source = ApiServerPodSource(client, NODE)
        used = {i: 0 for i in range(CHIPS)}
        alive: dict[str, tuple[int, int]] = {}
        pod_seq = 0
        sizes = [12, 8, 6, 4, 2]  # mixed fractional classes, like POD_SIZES

        def admit(units: int) -> bool:
            nonlocal pod_seq
            for idx in range(CHIPS):  # first-fit, the allocator's order
                if capacity[idx] - used[idx] >= units:
                    name = f"churn-{pod_seq}"
                    pod_seq += 1
                    api.add_pod(
                        assigned_running_pod(name, units, chip_idx=idx, node=NODE)
                    )
                    used[idx] += units
                    alive[name] = (idx, units)
                    return True
            return False

        for _ in range(rounds):
            while admit(rng.choice(sizes)):
                pass  # fill runs the node to refusal
            for name in rng.sample(
                sorted(alive), k=max(1, int(churn_frac * len(alive)))
            ):
                idx, units = alive.pop(name)
                used[idx] -= units
                api.delete_pod("default", name)

        def binpack_pct(quantum: int) -> float:
            """Binpack utilization: the fraction of node capacity the
            allocator can actually deliver — units in use plus free
            units REACHABLE by quantum-sized requests (first-fit per
            chip: ``free // quantum`` whole requests). Stranded slivers
            are the gap between this and 100%; consolidating them is
            exactly what raises it."""
            placements = D.movable_placements(list(source.labeled_pods()))
            by_chip: dict[int, int] = {}
            for _key, (idx, units) in placements.items():
                by_chip[idx] = by_chip.get(idx, 0) + units
            total_cap = sum(capacity.values())
            in_use = sum(by_chip.values())
            admissible = sum(
                ((cap - by_chip.get(idx, 0)) // quantum) * quantum
                for idx, cap in capacity.items()
            ) if quantum > 0 else total_cap - in_use
            return 100.0 * (in_use + admissible) / total_cap

        planner = D.DefragPlanner(lambda: dict(capacity), source)
        ckpt = AllocationCheckpoint(os.path.join(tmp, "wal.ckpt"))
        assume = AssumeCache()
        mover = D.SliceMover(
            client, source, assume, ckpt, NODE, lambda: dict(capacity)
        )
        loop = D.DefragLoop(planner, mover, client, NODE, interval_s=3600.0)

        pre = planner.scan()
        binpack_before = binpack_pct(pre.quantum)
        t0 = time.perf_counter()
        reports = [loop.run_once()]
        while reports[-1].moves and len(reports) < defrag_passes:
            reports.append(loop.run_once())
        wall_ms = (time.perf_counter() - t0) * 1e3
        before, after = reports[0], planner.scan()
        # same quantum on both sides: the utilization comparison must be
        # like-for-like even if churn deletions shifted the auto-derived
        # largest-pod threshold mid-bench
        binpack_after = binpack_pct(pre.quantum)
        stats = mover.stats()

        # post-conditions the gates read: per-chip capacity + clean state
        double_booked = 0
        final_used: dict[int, int] = {}
        for pod in source.labeled_pods():
            if not P.is_active(pod) or not P.is_assigned(pod):
                continue
            idx = P.chip_idx_from_annotation(pod)
            final_used[idx] = final_used.get(idx, 0) + P.mem_units_of_pod(pod)
        for idx, n in final_used.items():
            if n > capacity.get(idx, 0):
                double_booked += 1
        claims, mem_res, core_res = assume.snapshot()
        journal_pending = len(ckpt.pending())
        ckpt.close()
    finally:
        api.stop()

    _assert_lock_order_clean("defrag churn bench")
    return {
        "rounds": rounds,
        "seed": seed,
        "churn_pods": pod_seq,
        "live_pods": len(alive),
        "quantum": before.quantum,
        "stranded_before_units": sum(before.stranded_by_chip.values()),
        "stranded_after_units": sum(after.stranded_by_chip.values()),
        "stranded_before_pct": round(before.stranded_pct, 2),
        "stranded_after_pct": round(after.stranded_pct, 2),
        "binpack_before_pct": round(binpack_before, 1),
        "binpack_after_pct": round(binpack_after, 1),
        "moves_completed": stats.completed,
        "moves_failed": stats.failed,
        "last_move_ms": stats.last_move_ms,
        "defrag_passes": len(reports),
        "defrag_wall_ms": round(wall_ms, 1),
        "double_booked_chips": double_booked,
        "journal_pending": journal_pending,
        "orphaned_reservations": len(claims) + len(mem_res) + len(core_res),
    }


def _scale_config(
    n_nodes: int,
    n_shards: int,
    events: int,
    workers: int = 8,
    fanout: int = 2,
    seed: int = 20260804,
    gang_every: int = 0,
    settle_s: float = 1.0,
) -> dict:
    """One sharded-cluster churn configuration, end to end: synthesize
    ``n_nodes`` heterogeneous nodes in a fake apiserver, stand up
    ``n_shards`` :class:`ShardExtender` instances (each with its own
    per-shard group-commit bind WAL and its own informer usage index)
    behind a :class:`ShardRouter`, and drive ``events`` Poisson churn
    events through ``router.admit`` (and, with ``gang_every``, cross-
    shard gang groups through the two-phase reserve). Returns the churn
    stats plus the post-run correctness audit: per-chip overcommit
    (cross-shard double-bookings), partial gang grants, and undrained
    gang2pc journal entries after a reconciler pass."""
    import tempfile as _tempfile

    from gpushare_device_plugin_tpu.allocator.checkpoint import (
        AllocationCheckpoint,
    )
    from gpushare_device_plugin_tpu.cluster.apiserver import ApiServerClient
    from gpushare_device_plugin_tpu.cluster.informer import PodInformer
    from gpushare_device_plugin_tpu.extender import simcluster as S
    from gpushare_device_plugin_tpu.extender.shards import (
        LeaderLease, ShardExtender, ShardRouter, resolve_gang2pc,
    )

    from fake_apiserver import FakeApiServer

    api = FakeApiServer(chaos=False)
    nodes = S.make_cluster(n_nodes, seed=seed)
    for n in nodes:
        api.nodes[n["metadata"]["name"]] = n
    api.start()
    tmp = _tempfile.mkdtemp(prefix="tpushare-scale-")
    client = ApiServerClient(api.url)
    informer = PodInformer(client).start(sync_timeout_s=60)
    try:
        shards = [
            ShardExtender(
                f"shard-{i}", client, informer=informer,
                checkpoint=AllocationCheckpoint(
                    os.path.join(tmp, f"shard-{i}.wal")
                ),
            )
            for i in range(n_shards)
        ]
        lease = LeaderLease()
        router = ShardRouter(shards, fanout=fanout, lease=lease)
        router.set_nodes(nodes)
        driver = S.ChurnDriver(
            create_pod_fn=api.add_pod,
            delete_pod_fn=api.delete_pod,
            admit_fn=router.admit,
            admit_gang_fn=router.admit_gang_group,
            seed=seed, gang_every=gang_every, workers=workers,
        )
        stats = driver.run(events)
        time.sleep(settle_s)  # let the watch catch up before auditing
        pods = client.list_pods()
        violations = S.audit_cluster(nodes, pods)
        resolve_counts = resolve_gang2pc(shards, client, lease=lease)
        twopc_left = sum(len(s.twopc_pending()) for s in shards)
        _assert_lock_order_clean(
            f"scale config nodes={n_nodes} shards={n_shards}"
        )
        return {
            "nodes": n_nodes,
            "shards": n_shards,
            "fanout": fanout,
            "workers": workers,
            "events": events,
            **S.summarize(stats),
            "violations": violations,
            "gang2pc_resolve": resolve_counts,
            "gang2pc_pending_after": twopc_left,
        }
    finally:
        informer.stop()
        api.stop()
        shutil.rmtree(tmp, ignore_errors=True)


def _scale_gates(record: dict, *, speedup_gate: bool) -> list[str]:
    """Correctness (always) + speedup (full mode) gates for the scale
    bench. Zero cross-shard double-bookings and zero partial gangs are
    HARD in every mode, smoke included."""
    failed = []
    for cfg in record.get("configs", []) + (
        [record["storm"]] if record.get("storm") else []
    ):
        tag = f"nodes={cfg['nodes']} shards={cfg['shards']}"
        if cfg["violations"]:
            failed.append(
                f"SCALE BENCH FAILED ({tag}): {len(cfg['violations'])} "
                f"audit violation(s), first: {cfg['violations'][0]}"
            )
        if cfg["gang2pc_pending_after"]:
            failed.append(
                f"SCALE BENCH FAILED ({tag}): "
                f"{cfg['gang2pc_pending_after']} undrained gang2pc "
                "journal entr(ies) after the reconciler pass"
            )
        if cfg["admitted"] <= 0:
            failed.append(
                f"SCALE BENCH FAILED ({tag}): zero admissions — every "
                "other gate is vacuous over an empty run"
            )
    if speedup_gate:
        speedup = record.get("speedup_max_nodes")
        if speedup is None:
            # a missing ratio is a FAILED gate, not a skipped one: a
            # baseline that admitted nothing must not exit 0
            failed.append(
                "SCALE BENCH FAILED: speedup unmeasurable (single-shard "
                "baseline recorded no throughput)"
            )
        elif speedup < SCALE_SPEEDUP_MIN:
            failed.append(
                f"SCALE BENCH FAILED: {max(record['node_counts'])}-node "
                f"8-shard speedup x{speedup} below "
                f"the x{SCALE_SPEEDUP_MIN} gate"
            )
    return failed


def run_scale_bench(
    node_counts: list[int],
    shard_counts: list[int],
    events_per_config: int,
    storm_events: int = 0,
    workers: int = 8,
    fanout: int = 2,
    gang_every_storm: int = 40,
) -> dict:
    """Admission throughput + p99 versus node count and shard count
    (ROADMAP item 2's scale story), plus — with ``storm_events`` — the
    big churn storm: the largest node count under the largest shard
    count with gang-group bursts riding the cross-shard two-phase
    reserve, audited for zero double-bookings and zero partial gangs.

    Throughput configs run WITHOUT gang bursts (single-pod admission
    throughput is the headline; the storm covers the 2PC path), with
    the same worker count, fanout, and seed across the whole matrix so
    the only variable is the sharding."""
    configs = []
    tput: dict[tuple[int, int], float] = {}
    for n_nodes in node_counts:
        for n_shards in shard_counts:
            cfg = _scale_config(
                n_nodes, n_shards, events_per_config,
                workers=workers, fanout=fanout,
            )
            configs.append(cfg)
            tput[(n_nodes, n_shards)] = cfg["admissions_per_s"]
            print(
                f"scale: nodes={n_nodes} shards={n_shards} "
                f"-> {cfg['admissions_per_s']:.1f} adm/s "
                f"p99={cfg['admit_p99_ms']:.1f}ms "
                f"violations={len(cfg['violations'])}",
                file=sys.stderr,
            )
    max_nodes = max(node_counts)
    lo, hi = min(shard_counts), max(shard_counts)
    speedup = None
    if lo != hi and tput.get((max_nodes, lo)):
        speedup = round(tput[(max_nodes, hi)] / tput[(max_nodes, lo)], 2)
    storm = None
    if storm_events:
        storm = _scale_config(
            max_nodes, hi, storm_events, workers=workers, fanout=fanout,
            gang_every=gang_every_storm, settle_s=2.0,
        )
        print(
            f"scale storm: nodes={max_nodes} shards={hi} "
            f"events={storm_events} -> admitted={storm['admitted']} "
            f"gangs={storm['gang_groups']} "
            f"violations={len(storm['violations'])} "
            f"gang2pc_left={storm['gang2pc_pending_after']}",
            file=sys.stderr,
        )
    best = configs and max(
        (c for c in configs if c["shards"] == hi and c["nodes"] == max_nodes),
        key=lambda c: c["admissions_per_s"],
    )
    return {
        "node_counts": node_counts,
        "shard_counts": shard_counts,
        "events_per_config": events_per_config,
        "configs": configs,
        "storm": storm,
        "speedup_max_nodes": speedup,
        "admissions_per_s": best["admissions_per_s"] if best else None,
        "admission_p99_ms": best["admit_p99_ms"] if best else None,
    }


def scale_throughput_guard(adm_s: float | None, repo: Path) -> str | None:
    """Failure message when sharded admission throughput dropped
    >P99_GUARD_PCT below the newest committed record carrying it."""
    return _pct_trend_guard(
        adm_s, repo, field="scale_admissions_per_s",
        label="scale admission throughput", fmt=".1f", unit=" adm/s",
        lower_is_worse=True,
    )


def scale_p99_guard(p99_ms: float | None, repo: Path) -> str | None:
    """Same budget for the sharded admission latency tail."""
    return _pct_trend_guard(
        p99_ms, repo, field="scale_admission_p99_ms",
        label="scale admission p99", unit="ms",
    )


def _defrag_gates(defrag: dict) -> list[str]:
    """Correctness gates on one ``run_defrag_bench`` result — shared by
    the full bench and ``--defrag-smoke`` so the acceptance bar cannot
    drift between the two entry points."""
    msgs: list[str] = []
    if defrag["stranded_before_pct"] <= 0:
        msgs.append(
            "DEFRAG BENCH BROKEN: the churn trace produced no stranded "
            "HBM — nothing to defragment means nothing was measured"
        )
    elif defrag["stranded_after_pct"] >= defrag["stranded_before_pct"]:
        msgs.append(
            f"DEFRAG FAILED: stranded-HBM% not strictly reduced "
            f"({defrag['stranded_before_pct']}% -> "
            f"{defrag['stranded_after_pct']}%)"
        )
    if defrag["binpack_after_pct"] < defrag["binpack_before_pct"]:
        msgs.append(
            f"DEFRAG FAILED: binpack density dropped "
            f"({defrag['binpack_before_pct']}% -> "
            f"{defrag['binpack_after_pct']}%)"
        )
    if defrag["moves_completed"] <= 0:
        msgs.append("DEFRAG FAILED: no move completed over the churn trace")
    if defrag["double_booked_chips"]:
        msgs.append(
            f"DEFRAG FAILED: {defrag['double_booked_chips']} chip(s) over "
            "capacity after the moves — double-booking"
        )
    if defrag["orphaned_reservations"]:
        msgs.append(
            f"DEFRAG FAILED: {defrag['orphaned_reservations']} ledger "
            "entries survived the moves — orphaned reservations"
        )
    if defrag["journal_pending"]:
        msgs.append(
            f"DEFRAG FAILED: {defrag['journal_pending']} move entries "
            "still pending in the WAL after the loop drained"
        )
    return msgs


def _iter_json_objects(text: str):
    """Top-level JSON objects from a possibly-concatenated stream (the
    driver appends one record per bench invocation to the same file)."""
    dec = json.JSONDecoder()
    i = 0
    while True:
        i = text.find("{", i)
        if i < 0:
            return
        try:
            obj, end = dec.raw_decode(text, i)
        except json.JSONDecodeError:
            i += 1
            continue
        yield obj
        i = end


def previous_metric(repo: Path, field: str) -> tuple[float, str] | None:
    """(value, filename) of ``field`` from the newest committed
    ``BENCH_r*.json`` that carries it, if any."""
    newest: tuple[int, float, str] | None = None
    for f in repo.glob("BENCH_r*.json"):
        m = re.match(r"BENCH_r(\d+)\.json", f.name)
        if not m:
            continue
        try:
            vals = [
                float(parsed[field])
                for obj in _iter_json_objects(f.read_text())
                if isinstance(parsed := (obj.get("parsed") if isinstance(obj, dict) else None), dict)
                and parsed.get("metric") == "allocate_p50_latency"
                and isinstance(parsed.get(field), (int, float))
            ]
            if not vals:
                continue
        except OSError:
            continue
        n = int(m.group(1))
        if newest is None or n > newest[0]:
            newest = (n, vals[-1], f.name)
    return (newest[1], newest[2]) if newest else None


def previous_p50(repo: Path) -> tuple[float, str] | None:
    """(p50_ms, filename) from the newest committed BENCH_r*.json, if any."""
    return previous_metric(repo, "value")


def _pct_trend_guard(
    value: float | None,
    repo: Path,
    *,
    field: str,
    label: str,
    pct: float = P99_GUARD_PCT,
    fmt: str = ".3f",
    unit: str = "",
    lower_is_worse: bool = False,
) -> str | None:
    """Shared core of every percentage trend guard: compare ``value``
    against the newest committed record carrying ``field`` and return a
    failure message when it moved >``pct``% in the worse direction
    (``lower_is_worse`` flips it for throughput-style metrics); None when
    within budget, improving, or without history. One implementation so a
    threshold or message change can never drift between metrics."""
    if value is None:
        return None
    prev = previous_metric(repo, field)
    if prev is None:
        return None
    prev_val, fname = prev
    if lower_is_worse:
        if value >= prev_val * (1 - pct / 100.0):
            return None
        verb = "dropped"
    else:
        if value <= prev_val * (1 + pct / 100.0):
            return None
        verb = "regressed"
    return (
        f"TREND GUARD: {label} {value:{fmt}}{unit} {verb} >{pct:.0f}% "
        f"vs {fname} ({prev_val:{fmt}}{unit})"
    )


def trend_guard(p50: float, repo: Path) -> str | None:
    """Failure message when ``p50`` regressed >TREND_GUARD_PCT vs the newest
    committed ``BENCH_r*.json``; None when within budget (or no history)."""
    return _pct_trend_guard(
        p50, repo, field="value", label="p50", pct=TREND_GUARD_PCT, unit="ms"
    )


def p99_guard(p99: float, repo: Path) -> str | None:
    """Failure message when ``p99`` regressed >P99_GUARD_PCT vs the newest
    committed record carrying a p99; None when within budget (or no
    history). The p50 guard alone let tail-latency regressions land
    silently — a hot path can keep its median while growing a lock-wait
    tail, which is exactly the failure mode a concurrency rework risks."""
    return _pct_trend_guard(p99, repo, field="p99_ms", label="p99", unit="ms")


def utilization_guard(util_pct: float, repo: Path) -> str | None:
    """Failure message when peak binpack utilization dropped below the
    newest committed record's (no tolerance: the fill schedule packs the
    host exactly, so any drop means pods the allocator used to place now
    fail); None when >= previous or no history."""
    prev = previous_metric(repo, "binpack_utilization_pct")
    if prev is None:
        return None
    prev_util, fname = prev
    if util_pct < prev_util:
        return (
            f"UTILIZATION GUARD: peak binpack utilization {util_pct:.1f}% "
            f"dropped below {fname} ({prev_util:.1f}%)"
        )
    return None


def wal_fsync_guard(fsyncs_per_admission: float | None, repo: Path) -> str | None:
    """Failure message when ``wal_fsyncs_per_admission`` regressed (grew)
    >P99_GUARD_PCT vs the newest committed record carrying it — group
    commit's amortization must not silently erode back toward
    one-fsync-per-record; None when within budget or no history."""
    return _pct_trend_guard(
        fsyncs_per_admission, repo, field="wal_fsyncs_per_admission",
        label="wal_fsyncs_per_admission",
    )


def wal_fsync_p99_guard(p99_ms: float | None, repo: Path) -> str | None:
    """Same budget for the fsync latency tail: a batch that grows cheap in
    count but expensive per sync is still a regression."""
    return _pct_trend_guard(
        p99_ms, repo, field="wal_fsync_p99_ms", label="wal_fsync_p99",
        unit="ms",
    )


def gang_storm_guard(gangs_s: float | None, repo: Path) -> str | None:
    """Failure message when gang-admission throughput dropped
    >P99_GUARD_PCT below the newest committed record carrying it; None
    when within budget or no history. Lower is worse (throughput)."""
    return _pct_trend_guard(
        gangs_s, repo, field="gang_throughput_gangs_s",
        label="gang storm throughput", fmt=".1f", unit=" gangs/s",
        lower_is_worse=True,
    )


def serve_goodput_guard(tokens_s: float | None, repo: Path) -> str | None:
    """Failure message when the continuous-batching engine's goodput
    dropped >P99_GUARD_PCT below the newest committed record carrying it
    (the serve bench's ``serve_goodput_tokens_per_s``); None when within
    budget or no history. Lower is worse here, unlike the latency guards."""
    return _pct_trend_guard(
        tokens_s, repo, field="serve_goodput_tokens_per_s",
        label="serve goodput", fmt=".1f", unit=" tokens/s",
        lower_is_worse=True,
    )


def serve_ttft_guard(p99_ms: float | None, repo: Path) -> str | None:
    """Same budget for the engine's TTFT tail (``serve_ttft_p99_ms``):
    admission latency is the metric continuous batching exists to fix, so
    a regression there must not land silently."""
    return _pct_trend_guard(
        p99_ms, repo, field="serve_ttft_p99_ms", label="serve ttft_p99",
        fmt=".2f", unit="ms",
    )


def serve_paged_goodput_guard(tokens_s: float | None, repo: Path) -> str | None:
    """Failure message when the PAGED engine's goodput dropped
    >P99_GUARD_PCT below the newest committed record carrying it (the
    paged bench's ``serve_paged_goodput_tokens_per_s``); None when within
    budget or no history. Lower is worse (throughput)."""
    return _pct_trend_guard(
        tokens_s, repo, field="serve_paged_goodput_tokens_per_s",
        label="serve paged goodput", fmt=".1f", unit=" tokens/s",
        lower_is_worse=True,
    )


def prefix_hit_guard(ratio: float | None, repo: Path) -> str | None:
    """Same budget for the radix prefix-cache hit ratio
    (``serve_prefix_hit_ratio``) on the bench's shared-prefix trace: a
    silent drop means requests re-prefill system prompts the cache used
    to serve — the capacity the paged pool exists to reclaim."""
    return _pct_trend_guard(
        ratio, repo, field="serve_prefix_hit_ratio",
        label="prefix hit ratio", fmt=".4f", lower_is_worse=True,
    )


def disagg_ttft_guard(p99_ms: float | None, repo: Path) -> str | None:
    """Failure message when the disaggregated plane's end-to-end TTFT
    p99 (``disagg_ttft_p99_ms``, the serve_disagg section) grew
    >P99_GUARD_PCT over the newest committed record carrying it; None
    when within budget or no history. The improvement-vs-unified bar is
    hard-gated inside bench_mfu on the full run; this guards the trend —
    a handoff change that still "wins" but ships first tokens later than
    it used to is a regression."""
    return _pct_trend_guard(
        p99_ms, repo, field="disagg_ttft_p99_ms",
        label="disagg ttft_p99", fmt=".2f", unit="ms",
    )


def disagg_tpot_guard(p99_ms: float | None, repo: Path) -> str | None:
    """Same budget for the decode tier's inter-token latency tail
    (``disagg_tpot_p99_ms``): the other half of the disaggregation
    contract — prefill stays off the decode tier's step clock."""
    return _pct_trend_guard(
        p99_ms, repo, field="disagg_tpot_p99_ms",
        label="disagg tpot_p99", fmt=".2f", unit="ms",
    )


def spec_tokens_guard(tokens_s: float | None, repo: Path) -> str | None:
    """Failure message when the speculative paged engine's throughput
    (``spec_tokens_per_s``, the serve_spec section) dropped
    >P99_GUARD_PCT below the newest committed record carrying it; None
    when within budget or no history. Lower is worse (throughput). The
    improvement-vs-plain bar is hard-gated inside bench_mfu on the full
    run; this guards the trend — a pipeline change that still "wins"
    but emits tokens slower than it used to is a regression."""
    return _pct_trend_guard(
        tokens_s, repo, field="spec_tokens_per_s",
        label="spec tokens/s", fmt=".1f", unit=" tokens/s",
        lower_is_worse=True,
    )


def spec_accept_guard(mean_len: float | None, repo: Path) -> str | None:
    """Same budget for the mean acceptance length
    (``spec_accept_len_mean``): the bench self-drafts, so this sits at
    the ceiling k — any drop means the verify/accept math started
    rejecting tokens the draft got right, which is a correctness smell
    even while the parity gate still passes (the correction token
    masks it)."""
    return _pct_trend_guard(
        mean_len, repo, field="spec_accept_len_mean",
        label="spec acceptance length", fmt=".3f", unit=" tokens",
        lower_is_worse=True,
    )


def fleet_goodput_guard(tokens_s: float | None, repo: Path) -> str | None:
    """Failure message when the fleet router's goodput
    (``fleet_goodput_tokens_per_s``, the serve_fleet section) dropped
    >P99_GUARD_PCT below the newest committed record carrying it; None
    when within budget or no history. Lower is worse (throughput). The
    zero-drop/parity/exactly-once invariants hard-gate inside bench_mfu
    itself; this guards the trend — a router change that still routes
    correctly but serves the fleet slower is a regression."""
    return _pct_trend_guard(
        tokens_s, repo, field="fleet_goodput_tokens_per_s",
        label="fleet goodput", fmt=".1f", unit=" tokens/s",
        lower_is_worse=True,
    )


def fleet_prefix_guard(ratio: float | None, repo: Path) -> str | None:
    """Same budget for the fleet-global prefix-hit ratio
    (``fleet_prefix_hit_ratio``): the affinity plane's whole point is
    concentrating shared prefixes on warm replicas — the beats-spread
    bar hard-gates inside bench_mfu, this guards the trend (a policy
    change that still "wins" but re-pays more shared prefill than it
    used to is a regression)."""
    return _pct_trend_guard(
        ratio, repo, field="fleet_prefix_hit_ratio",
        label="fleet prefix-hit ratio", fmt=".4f", unit="",
        lower_is_worse=True,
    )


def lora_goodput_guard(tokens_s: float | None, repo: Path) -> str | None:
    """Failure message when the multi-LoRA engine's N-adapter goodput
    (``lora_goodput_tokens_per_s``, the serve_lora section) dropped
    >P99_GUARD_PCT below the newest committed record carrying it; None
    when within budget or no history. Lower is worse (throughput). The
    bit-identity / zero-retrace / >=0.9x-of-one-adapter bars hard-gate
    inside bench_mfu itself; this guards the trend — a dispatch change
    that still passes parity but serves heterogeneous tenants slower
    than it used to is a regression."""
    return _pct_trend_guard(
        tokens_s, repo, field="lora_goodput_tokens_per_s",
        label="lora goodput", fmt=".1f", unit=" tokens/s",
        lower_is_worse=True,
    )


def adapter_hit_guard(ratio: float | None, repo: Path) -> str | None:
    """Same budget for the adapter admission hit ratio
    (``adapter_hit_ratio``): load-on-admission prefetch plus LRU
    residency exist to make repeat tenants hits — a cache-policy change
    that still serves correctly but re-loads adapters it used to keep
    resident is a regression even while every hard gate passes."""
    return _pct_trend_guard(
        ratio, repo, field="adapter_hit_ratio",
        label="adapter hit ratio", fmt=".4f", unit="",
        lower_is_worse=True,
    )


def interference_guard(pct: float | None, repo: Path) -> str | None:
    """Failure message when the interference bench's governor-OFF p99
    inflation (``interference_p99_inflation_pct``) DROPPED >25% vs the
    newest committed record carrying it; None when within budget or no
    history. Lower is worse here: the OFF episode is the scenario's
    signal source — a co-tenant that no longer measurably interferes
    means the whole governor acceptance run went vacuous (the >=25%
    absolute floor already hard-gated inside bench_mfu)."""
    return _pct_trend_guard(
        pct, repo, field="interference_p99_inflation_pct",
        label="interference OFF-phase p99 inflation", fmt=".1f", unit="%",
        lower_is_worse=True,
    )


def defrag_stranded_guard(pct: float | None, repo: Path) -> str | None:
    """Failure message when the post-defrag stranded-HBM% on the churn
    trace grew >P99_GUARD_PCT over the newest committed record carrying
    it; None when within budget or no history. The absolute
    before->after improvement is hard-gated per run (``_defrag_gates``);
    this guards the trend — a planner change that still "improves" but
    leaves more HBM stranded than it used to is a regression."""
    return _pct_trend_guard(
        pct, repo, field="defrag_stranded_after_pct",
        label="defrag stranded-HBM%", fmt=".2f", unit="%",
    )


def defrag_binpack_guard(pct: float | None, repo: Path) -> str | None:
    """Same budget for the post-defrag binpack packing density
    (``defrag_binpack_after_pct``, higher is better): the repack
    objective's other face — fewer stranded slivers must keep showing up
    as denser occupied chips."""
    return _pct_trend_guard(
        pct, repo, field="defrag_binpack_after_pct",
        label="defrag binpack density", fmt=".1f", unit="%",
        lower_is_worse=True,
    )


def run_compute_bench(repo: Path, backend_init_timeout: float = 60.0) -> dict:
    """bench_mfu.py in a subprocess; {} on any failure (never fatal here).

    bench_mfu re-prints its cumulative report after every section, so even
    a timeout (dead TPU tunnel mid-compile) salvages the sections that
    finished — the last parseable dict line wins. ``backend_init_timeout``
    rides through to bench_mfu's subprocess backend-init probe: a wedged
    TPU tunnel now costs that bound (with the reason + elapsed recorded in
    the report) instead of a fixed 300 s.
    """
    stdout, stderr, note = "", "", None
    try:
        proc = subprocess.run(
            [
                sys.executable, str(repo / "bench_mfu.py"),
                "--backend-init-timeout", str(backend_init_timeout),
            ],
            capture_output=True, text=True, timeout=1800,
        )
        stdout, stderr = proc.stdout, proc.stderr
        note = None if proc.returncode == 0 else f"rc={proc.returncode}"
    except subprocess.TimeoutExpired as e:
        # kill-at-timeout can truncate multi-byte sequences: never raise
        def _txt(v):
            return v.decode(errors="replace") if isinstance(v, bytes) else (v or "")

        stdout, stderr = _txt(e.stdout), _txt(e.stderr)
        note = "timeout"
    except OSError as e:
        print(f"compute bench failed to run: {e}", file=sys.stderr)
        return {"error": str(e)}
    sys.stderr.write(stderr)
    for line in reversed(stdout.strip().splitlines()):
        try:
            obj = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(obj, dict):
            if note:
                obj["partial"] = note
            return obj
    return {"error": f"no JSON output ({note or 'empty'})"}


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(prog="bench.py")
    p.add_argument("--workers", type=int, default=DEFAULT_WORKERS,
                   help="concurrent-admission worker count (0 = skip the "
                   "concurrent section)")
    p.add_argument("--smoke", action="store_true",
                   help="3-pod quick run: 1 trial, tiny rounds, guards and "
                   "compute bench off — exercises every section end to end "
                   "so the script itself cannot bit-rot (make bench-smoke)")
    p.add_argument("--no-mfu", action="store_true")
    p.add_argument("--no-trend-guard", action="store_true")
    p.add_argument("--no-util-guard", action="store_true")
    p.add_argument("--no-extender", action="store_true",
                   help="skip the multi-node extender scoring section")
    p.add_argument("--wal-fsync", default="batch",
                   choices=["batch", "always", "off"],
                   help="WAL mode for the concurrent storm: group-commit "
                   "batch (default), per-record always, or off (no "
                   "journal; the coalesced PATCH pipeline stays on in "
                   "every mode — 'off' isolates the WAL's cost, not "
                   "this round's whole write stack)")
    p.add_argument("--wal-bench", action="store_true",
                   help="run ONLY the concurrent storm, once per WAL mode "
                   "(always then batch), and emit a comparison record "
                   "(make bench-wal)")
    p.add_argument("--no-trace", action="store_true",
                   help="disable admission tracing for this run (sample "
                   "ratio 0 — the unsampled hot path is O(ns); the "
                   "baseline half of the --trace-bench A/B)")
    p.add_argument("--trace-bench", action="store_true",
                   help="run ONLY the concurrent storm, traced vs "
                   "--no-trace, and HARD-FAIL if tracing inflates the "
                   "admission p99 more than 5% (make bench-trace)")
    p.add_argument("--no-decisions", action="store_true",
                   help="disable decision-provenance emission for this "
                   "run (the baseline half of the --decisions-bench A/B)")
    p.add_argument("--decisions-bench", action="store_true",
                   help="run ONLY the concurrent storm, decisions-on vs "
                   "decisions-off, and HARD-FAIL if provenance inflates "
                   "the admission p99 more than 5% (make bench-decisions)")
    p.add_argument("--backend-init-timeout", type=float, default=60.0,
                   help="bound (seconds) on bench_mfu's subprocess "
                   "backend-init probe — a wedged TPU tunnel costs this "
                   "much, recorded in the report, instead of 300 s")
    p.add_argument("--defrag-smoke", action="store_true",
                   help="run ONLY the defrag churn section with a short "
                   "trace and emit its record — the correctness gates "
                   "(stranded-HBM strictly reduced, no double-booking, "
                   "journal/ledger drained) stay HARD even in smoke "
                   "(make bench-defrag-smoke)")
    p.add_argument("--no-defrag", action="store_true",
                   help="skip the defrag churn section")
    p.add_argument("--scale-bench", action="store_true",
                   help="run ONLY the sharded-extender scale bench, full "
                   "size: admission throughput + p99 over the "
                   "32/256/1000-node x 1/8-shard matrix, the 1k-node "
                   "100k-pod churn storm with cross-shard gang groups, "
                   "and the HARD >=3x 8-shard speedup gate. Long — "
                   "tens of minutes on a small box (make bench-scale "
                   "for the matrix alone via --scale-storm-events)")
    p.add_argument("--scale-smoke", action="store_true",
                   help="run ONLY a seconds-sized scale-bench pass (tiny "
                   "node/shard/event counts). The correctness gates — "
                   "zero cross-shard double-bookings, zero partial "
                   "gangs, gang2pc journal drained — stay HARD; the "
                   "speedup gate is full-size-only "
                   "(make bench-scale-smoke)")
    p.add_argument("--no-scale", action="store_true",
                   help="skip the scale section of the full bench")
    p.add_argument("--scale-storm-events", type=int,
                   default=SCALE_STORM_EVENTS,
                   help="churn events in the --scale-bench storm phase "
                   "(0 skips the storm and runs the matrix alone)")
    p.add_argument("--wal-window-ms", type=float, default=8.0,
                   help="group-commit gather window for the storm's WAL "
                   "(the --wal-batch-window-ms daemon tunable). The storm "
                   "default is wider than the daemon's 2 ms: a throughput "
                   "storm trades per-record latency for amortization, and "
                   "the window is invisible in wall clock because the "
                   "waits overlap across workers")
    return p.parse_args(argv)


def run_wal_bench(
    workers: int, rounds: int = CONCURRENT_ROUNDS,
    wal_window_s: float = 0.002,
) -> int:
    """A/B the group-commit WAL under an admission storm: same storm, WAL
    in ``always`` then ``batch`` mode. Emits one JSON line; nonzero only
    if a storm audit fails (those raise)."""
    record = {
        "metric": "wal_groupcommit", "workers": workers,
        "wal_window_ms": wal_window_s * 1e3,
    }
    for mode in ("always", "batch"):
        trial = run_concurrent_trial(
            workers, rounds=rounds, wal_mode=mode, wal_window_s=wal_window_s
        )
        record[mode] = trial
        print(
            f"wal={mode}: throughput={trial['throughput_pods_s']:.1f} pods/s "
            f"p50={trial['p50_ms']}ms "
            f"fsyncs/admission={trial.get('wal_fsyncs_per_admission')} "
            f"batch_mean={trial.get('wal_batch_mean')} "
            f"patch_coalesce_ratio={trial.get('patch_coalesce_ratio')}",
            file=sys.stderr,
        )
    always_tput = record["always"].get("throughput_pods_s") or 0
    batch_tput = record["batch"].get("throughput_pods_s") or 0
    if always_tput:
        record["batch_speedup_vs_always"] = round(batch_tput / always_tput, 2)
    print(json.dumps(record))
    return 0


def _run_overhead_ab(
    workers: int,
    rounds: int,
    trials: int,
    *,
    metric: str,
    label: str,
    off_label: str,
    on_label: str,
    set_mode,
    restore,
    gate_pct: float,
    mode_extra=None,
    record_extra=None,
) -> int:
    """Shared A/B overhead harness for feature-on vs feature-off under
    the concurrent-admission storm (tracing, decision provenance, ...).

    Methodology (one implementation, so a fix here covers every A/B):
    the storm runs WAL-off — the group-commit fsync waits dominate the
    journaled storm's tail with stalls that have nothing to do with the
    feature, and a QUIETER baseline makes the gate STRICTER (a fixed
    per-admission tax is a larger fraction of a smaller p99). Modes
    alternate per trial (off, on, off, ...) so box drift cannot
    masquerade as overhead, and each mode's figure is its BEST-of-N p99
    — the bench's convention for noisy wall numbers: a systematic tax
    shifts the minimum too, while GC/loopback noise only inflates it.
    HARD GATE: the on-mode p99 may not inflate more than ``gate_pct``
    over off.

    ``set_mode(enabled)`` flips the feature; ``restore()`` reinstates
    the production default; ``mode_extra(enabled) -> dict`` adds
    per-mode record fields; ``record_extra(record)`` adds run-level
    fields before the JSON line."""
    record: dict = {"metric": metric, "workers": workers, "trials": trials}
    results: dict = {
        off_label: {"p50": [], "p99": []},
        on_label: {"p50": [], "p99": []},
    }
    try:
        run_concurrent_trial(workers, rounds=rounds, wal_mode="off")  # warmup
        for _ in range(trials):
            for mode, enabled in ((off_label, False), (on_label, True)):
                set_mode(enabled)
                trial = run_concurrent_trial(
                    workers, rounds=rounds, wal_mode="off"
                )
                if trial["p50_ms"] is not None:
                    results[mode]["p50"].append(trial["p50_ms"])
                if trial["p99_ms"] is not None:
                    results[mode]["p99"].append(trial["p99_ms"])
    finally:
        restore()
    p99 = {}
    for mode, enabled in ((off_label, False), (on_label, True)):
        p50s, p99s = results[mode]["p50"], results[mode]["p99"]
        record[mode] = {
            **(mode_extra(enabled) if mode_extra else {}),
            "p50_ms": round(min(p50s), 3) if p50s else None,
            "p99_ms": round(min(p99s), 3) if p99s else None,
            "p99_ms_trials": p99s,
        }
        p99[mode] = record[mode]["p99_ms"]
        print(
            f"{label}={mode}: p50={record[mode]['p50_ms']}ms "
            f"p99={record[mode]['p99_ms']}ms (trials {p99s})",
            file=sys.stderr,
        )
    if record_extra:
        record_extra(record)
    if p99.get(off_label) and p99.get(on_label) is not None:
        overhead = (
            100.0 * (p99[on_label] - p99[off_label]) / p99[off_label]
        )
        record["p99_overhead_pct"] = round(overhead, 1)
    record["gate_pct"] = gate_pct
    print(json.dumps(record))
    overhead = record.get("p99_overhead_pct")
    if overhead is None:
        print(
            f"{label.upper()} BENCH: not enough samples for p99",
            file=sys.stderr,
        )
        return 1
    if overhead > gate_pct:
        print(
            f"{label.upper()} OVERHEAD GUARD FAILED: {on_label} p99 "
            f"{p99[on_label]:.3f}ms is {overhead:+.1f}% vs {off_label} "
            f"{p99[off_label]:.3f}ms (gate {gate_pct:.0f}%)",
            file=sys.stderr,
        )
        return 1
    print(
        f"{label} overhead: p99 {overhead:+.1f}% (gate {gate_pct:.0f}%)",
        file=sys.stderr,
    )
    return 0


def run_trace_bench(
    workers: int, rounds: int = CONCURRENT_ROUNDS, trials: int = 3
) -> int:
    """A/B the tracing layer under the concurrent-admission storm: the
    same storm with every admission traced (sample ratio 1.0, the daemon
    default) and with tracing off (``--no-trace``); methodology and the
    5% hard gate live in :func:`_run_overhead_ab` (``make
    bench-trace``)."""
    from gpushare_device_plugin_tpu.utils.tracing import STORE, TRACER

    return _run_overhead_ab(
        workers, rounds, trials,
        metric="trace_overhead", label="trace",
        off_label="untraced", on_label="traced",
        set_mode=lambda on: TRACER.configure(sample_ratio=1.0 if on else 0.0),
        restore=lambda: TRACER.configure(sample_ratio=1.0),
        gate_pct=TRACE_OVERHEAD_PCT,
        mode_extra=lambda on: {"sample_ratio": 1.0 if on else 0.0},
        record_extra=lambda record: record.update(
            traced_store_traces=len(STORE.trace_ids())
        ),
    )


def run_decisions_bench(
    workers: int, rounds: int = CONCURRENT_ROUNDS, trials: int = 3
) -> int:
    """A/B the decision-provenance layer under the concurrent-admission
    storm: the same storm with every admission's "why" recorded
    (``DECISIONS`` enabled, the daemon default) and with emission off
    (``--no-decisions``); methodology and the 5% hard gate live in
    :func:`_run_overhead_ab` (``make bench-decisions``). Tracing stays
    ON in both modes — the production configuration records both, and
    the A/B isolates the decisions delta."""
    from gpushare_device_plugin_tpu.utils.decisions import DECISIONS

    return _run_overhead_ab(
        workers, rounds, trials,
        metric="decisions_overhead", label="decisions",
        off_label="off", on_label="on",
        set_mode=lambda on: DECISIONS.configure(enabled=on),
        restore=lambda: DECISIONS.configure(enabled=True),
        gate_pct=DECISIONS_OVERHEAD_PCT,
        mode_extra=lambda on: {"enabled": on},
        record_extra=lambda record: record.update(
            ring_records=DECISIONS.size(), ring_dropped=DECISIONS.dropped()
        ),
    )


def main(argv=None) -> int:
    args = parse_args(argv)
    repo = Path(__file__).resolve().parent
    if args.no_trace:
        from gpushare_device_plugin_tpu.utils.tracing import TRACER

        TRACER.configure(sample_ratio=0.0)
    if args.no_decisions:
        from gpushare_device_plugin_tpu.utils.decisions import DECISIONS

        DECISIONS.configure(enabled=False)
    if args.trace_bench:
        return run_trace_bench(max(1, args.workers))
    if args.decisions_bench:
        return run_decisions_bench(max(1, args.workers))
    if args.defrag_smoke:
        defrag = run_defrag_bench(rounds=3)
        print(json.dumps({"metric": "defrag_churn", **defrag}))
        print(
            f"defrag churn (smoke): stranded "
            f"{defrag['stranded_before_pct']}% -> "
            f"{defrag['stranded_after_pct']}% "
            f"binpack {defrag['binpack_before_pct']}% -> "
            f"{defrag['binpack_after_pct']}% "
            f"moves={defrag['moves_completed']}",
            file=sys.stderr,
        )
        failed = _defrag_gates(defrag)
        for m in failed:
            print(m, file=sys.stderr)
        return 1 if failed else 0
    if args.scale_bench or args.scale_smoke:
        if args.scale_smoke:
            scale = run_scale_bench(
                node_counts=[16], shard_counts=[1, 2],
                events_per_config=80, storm_events=160,
                workers=4, gang_every_storm=12,
            )
        else:
            scale = run_scale_bench(
                node_counts=SCALE_NODE_COUNTS,
                shard_counts=SCALE_SHARD_COUNTS,
                events_per_config=600,
                storm_events=args.scale_storm_events,
                workers=max(1, args.workers),
            )
        print(json.dumps({
            "metric": "scale_bench",
            "smoke": args.scale_smoke,
            "scale_admissions_per_s": scale["admissions_per_s"],
            "scale_admission_p99_ms": scale["admission_p99_ms"],
            "scale_speedup": scale["speedup_max_nodes"],
            **{k: scale[k] for k in
               ("node_counts", "shard_counts", "configs", "storm")},
        }))
        failed = _scale_gates(scale, speedup_gate=not args.scale_smoke)
        for m in failed:
            print(m, file=sys.stderr)
        return 1 if failed else 0
    if args.wal_bench:
        return run_wal_bench(
            max(1, args.workers), wal_window_s=args.wal_window_ms / 1000.0
        )
    if args.smoke:
        args.no_mfu = True
        args.no_trend_guard = True
        args.no_util_guard = True
    trials = 1 if args.smoke else TRIALS
    rounds = 2 if args.smoke else ROUNDS
    pod_sizes = [16, 8, 4] if args.smoke else POD_SIZES  # smoke: 3 pods/round

    trial_p50s: list[float] = []
    trial_p99s: list[float] = []
    throughputs: list[float] = []
    utils: list[float] = []
    for i in range(trials):
        latencies, wall, util = run_allocate_trial(rounds=rounds, pod_sizes=pod_sizes)
        trial_p50s.append(statistics.median(latencies))
        trial_p99s.append(
            statistics.quantiles(latencies, n=100)[98]
            if len(latencies) >= 100
            else max(latencies)
        )
        throughputs.append(len(latencies) / wall)
        utils.append(util)
        print(
            f"trial {i + 1}/{trials}: pods={len(latencies)} "
            f"p50={trial_p50s[-1]:.3f}ms p99={trial_p99s[-1]:.3f}ms "
            f"throughput={throughputs[-1]:.1f} pods/s",
            file=sys.stderr,
        )

    p50 = statistics.median(trial_p50s)
    p99 = statistics.median(trial_p99s)
    serial_pods_s = statistics.median(throughputs)
    print(
        f"allocate: p50={p50:.3f}ms (spread {min(trial_p50s):.3f}-{max(trial_p50s):.3f}) "
        f"p99={p99:.3f}ms (spread {min(trial_p99s):.3f}-{max(trial_p99s):.3f}) "
        f"throughput={serial_pods_s:.1f} pods/s "
        f"peak_binpack_utilization={max(utils):.1f}%",
        file=sys.stderr,
    )

    concurrent = {}
    if args.workers > 0:
        concurrent = run_concurrent_trial(
            args.workers,
            rounds=2 if args.smoke else CONCURRENT_ROUNDS,
            pod_units=16 if args.smoke else CONCURRENT_POD_UNITS,
            wal_mode=args.wal_fsync,
            wal_window_s=args.wal_window_ms / 1000.0,
        )
        if serial_pods_s > 0 and concurrent.get("throughput_pods_s"):
            concurrent["speedup_vs_serial"] = round(
                concurrent["throughput_pods_s"] / serial_pods_s, 2
            )
        print(
            f"concurrent (workers={args.workers}, wal={args.wal_fsync}): "
            f"throughput={concurrent['throughput_pods_s']:.1f} pods/s "
            f"(x{concurrent.get('speedup_vs_serial', 0)} vs serial) "
            f"p50={concurrent['p50_ms']}ms "
            f"fsyncs/admission={concurrent.get('wal_fsyncs_per_admission')} "
            f"patch_coalesce_ratio={concurrent.get('patch_coalesce_ratio')} "
            f"double_assignments={concurrent['double_assignments']}",
            file=sys.stderr,
        )

    gang = {}
    if args.workers > 0:
        gang = run_gang_storm(
            args.workers,
            rounds=2 if args.smoke else 3,
        )
        print(
            f"gang storm (workers={args.workers}, shape={gang['shape']}): "
            f"throughput={gang['throughput_gangs_s']:.1f} gangs/s "
            f"partial_grants={gang['partial_grants']} "
            f"double_assignments={gang['double_assignments']} "
            f"mean_ici_hops={gang['mean_ici_hops']}",
            file=sys.stderr,
        )
        if gang["partial_grants"] or gang["double_assignments"]:
            # correctness, not performance: a partial gang or a double-
            # booked chip must fail the bench outright
            print(json.dumps({"metric": "gang_storm", **gang}))
            print(
                f"GANG STORM FAILED: partial_grants="
                f"{gang['partial_grants']} double_assignments="
                f"{gang['double_assignments']}",
                file=sys.stderr,
            )
            return 1

    defrag = {}
    if not args.no_defrag:
        defrag = run_defrag_bench(rounds=3 if args.smoke else 6)
        print(
            f"defrag churn ({defrag['churn_pods']} pods, "
            f"{defrag['rounds']} rounds): stranded "
            f"{defrag['stranded_before_pct']}% -> "
            f"{defrag['stranded_after_pct']}% "
            f"binpack {defrag['binpack_before_pct']}% -> "
            f"{defrag['binpack_after_pct']}% "
            f"moves={defrag['moves_completed']} "
            f"({defrag['defrag_wall_ms']}ms)",
            file=sys.stderr,
        )
        defrag_failed = _defrag_gates(defrag)
        if defrag_failed:
            # correctness, not performance — like the gang storm's
            # partial-grant gate, a non-improving or state-leaking
            # defrag pass fails the bench outright, smoke included
            print(json.dumps({"metric": "defrag_churn", **defrag}))
            for m in defrag_failed:
                print(m, file=sys.stderr)
            return 1

    extender = {}
    if not args.no_extender:
        extender = run_extender_bench(
            n_nodes=4 if args.smoke else 32,
            pods_per_node=5 if args.smoke else 30,
            iters=5 if args.smoke else 30,
        )
        print(
            f"extender ({extender['nodes']} nodes, {extender['pods']} pods): "
            f"batch_p50={extender['batch_p50_ms']}ms "
            f"filter+prioritize_p50={extender['filter_prioritize_p50_ms']}ms",
            file=sys.stderr,
        )

    scale = {}
    if not args.no_scale:
        # Bounded mid-size config for the trend-guard series (the full
        # 1k-node matrix + 100k-pod storm live behind --scale-bench):
        # one node count, 1 vs 8 shards, plus a short gang-burst storm
        # so every committed record exercises the two-phase reserve.
        scale = run_scale_bench(
            node_counts=[32] if args.smoke else [256],
            shard_counts=[1, 2] if args.smoke else [1, 8],
            events_per_config=60 if args.smoke else 400,
            storm_events=120 if args.smoke else 800,
            workers=4 if args.smoke else max(1, args.workers),
            gang_every_storm=12 if args.smoke else 40,
        )
        scale_failed = _scale_gates(scale, speedup_gate=False)
        if scale_failed:
            # correctness, not performance: a double-booked chip or an
            # undrained 2PC entry fails the bench outright, smoke included
            print(json.dumps({"metric": "scale_bench", **{
                k: scale[k] for k in ("configs", "storm")
            }}))
            for m in scale_failed:
                print(m, file=sys.stderr)
            return 1
        print(
            f"scale (nodes={scale['node_counts']}, "
            f"shards={scale['shard_counts']}): "
            f"sharded={scale['admissions_per_s']} adm/s "
            f"p99={scale['admission_p99_ms']}ms "
            f"speedup=x{scale['speedup_max_nodes']}",
            file=sys.stderr,
        )

    compute = {} if args.no_mfu else run_compute_bench(
        repo, backend_init_timeout=args.backend_init_timeout
    )
    if compute.get("train"):
        t = compute["train"]
        print(
            f"compute: mfu={t.get('mfu_pct')}% tokens/s={t.get('tokens_per_s')} "
            f"flash_speedups={[f['speedup'] for f in compute.get('flash', [])]}",
            file=sys.stderr,
        )

    record = {
        "metric": "allocate_p50_latency",
        "value": round(p50, 3),
        "unit": "ms",
        "vs_baseline": round(100.0 / p50, 1),
        "p50_spread_ms": [round(min(trial_p50s), 3), round(max(trial_p50s), 3)],
        "p99_ms": round(p99, 3),
        "throughput_pods_s": round(serial_pods_s, 1),
        # North star #2 (BASELINE.md, reference analog display.go:231-241):
        # peak TPU-HBM binpack utilization across trials — the fill rounds
        # pack the host completely, so anything under 100 is an allocator
        # regression.
        "binpack_utilization_pct": round(max(utils), 1),
        "trials": trials,
        # WAL group-commit numbers, hoisted top-level so previous_metric /
        # the trend guards can read them like every other headline field.
        "wal_fsyncs_per_admission": concurrent.get("wal_fsyncs_per_admission"),
        "wal_fsync_p99_ms": concurrent.get("wal_fsync_p99_ms"),
        "patch_coalesce_ratio": concurrent.get("patch_coalesce_ratio"),
        # Continuous-batching serve numbers, hoisted top-level like the
        # WAL fields so previous_metric / the trend guards can read them.
        "serve_goodput_tokens_per_s": compute.get("serve_engine", {})
        .get("engine", {}).get("goodput_tokens_per_s"),
        "serve_ttft_p99_ms": compute.get("serve_engine", {})
        .get("engine", {}).get("ttft_p99_ms"),
        # Paged-KV serve numbers (serve_paged section), hoisted for the
        # trend guards: paged goodput and the radix prefix-hit ratio on
        # the shared-prefix trace.
        "serve_paged_goodput_tokens_per_s": compute.get("serve_paged", {})
        .get("paged", {}).get("goodput_tokens_per_s"),
        "serve_prefix_hit_ratio": compute.get("serve_paged", {})
        .get("prefix_hit_ratio"),
        # Disaggregated-serving numbers (serve_disagg section), hoisted
        # for the trend guards: end-to-end TTFT p99 and decode-tier TPOT
        # p99 across the journaled KV handoff (the parity/zero-retrace/
        # zero-drop invariants hard-gate inside bench_mfu itself).
        "disagg_ttft_p99_ms": compute.get("serve_disagg", {})
        .get("disagg_ttft_p99_ms"),
        "disagg_tpot_p99_ms": compute.get("serve_disagg", {})
        .get("disagg_tpot_p99_ms"),
        # Speculative-decoding numbers (serve_spec section), hoisted for
        # the trend guards: spec-engine throughput at equal HBM and the
        # mean acceptance length (ceiling k under self-draft; the
        # parity/zero-retrace/budget invariants hard-gate inside
        # bench_mfu itself).
        "spec_tokens_per_s": compute.get("serve_spec", {})
        .get("spec_tokens_per_s"),
        "spec_accept_len_mean": compute.get("serve_spec", {})
        .get("spec_accept_len_mean"),
        # Fleet-router numbers (serve_fleet section), hoisted for the
        # trend guards: fleet goodput across the pool and the global
        # prefix-hit ratio under the affinity policy (the zero-drop/
        # parity/beats-spread invariants hard-gate inside bench_mfu
        # itself).
        "fleet_goodput_tokens_per_s": compute.get("serve_fleet", {})
        .get("fleet_goodput_tokens_per_s"),
        "fleet_prefix_hit_ratio": compute.get("serve_fleet", {})
        .get("fleet_prefix_hit_ratio"),
        # Multi-LoRA numbers (serve_lora section), hoisted for the trend
        # guards: N-adapter goodput at equal HBM and the adapter
        # admission hit ratio (the bit-identity / zero-retrace /
        # >=0.9x-of-one-adapter invariants hard-gate inside bench_mfu
        # itself).
        "lora_goodput_tokens_per_s": compute.get("serve_lora", {})
        .get("lora_goodput_tokens_per_s"),
        "adapter_hit_ratio": compute.get("serve_lora", {})
        .get("adapter_hit_ratio"),
        # Interference bench numbers (serve_interference section),
        # hoisted for the trend guard: the governor-OFF inflation is the
        # scenario's signal strength (the governed/overhead bounds hard-
        # gate inside bench_mfu itself).
        "interference_p99_inflation_pct": compute.get(
            "serve_interference", {}
        ).get("interference_p99_inflation_pct"),
        "interference_governed_pct": compute.get(
            "serve_interference", {}
        ).get("governed_p99_inflation_pct"),
        # Gang-admission storm numbers, hoisted like the WAL fields; the
        # zero-partial/zero-double invariants already hard-failed above.
        "gang_throughput_gangs_s": gang.get("throughput_gangs_s"),
        "gang_partial_grants": gang.get("partial_grants"),
        "gang_double_assignments": gang.get("double_assignments"),
        # Defrag churn numbers, hoisted for the trend guards: what the
        # churn trace still strands after the loop drains, and the
        # packing density it achieves. The strict before->after
        # improvement already hard-gated above.
        "defrag_stranded_after_pct": defrag.get("stranded_after_pct"),
        "defrag_binpack_after_pct": defrag.get("binpack_after_pct"),
        # Sharded-extender scale numbers, hoisted for the trend guards:
        # the 8-shard router's admission throughput and p99 on the
        # mid-size matrix config (the full 1k-node story is
        # --scale-bench). The audit/drain invariants hard-failed above.
        "scale_admissions_per_s": scale.get("admissions_per_s"),
        "scale_admission_p99_ms": scale.get("admission_p99_ms"),
        "scale_speedup": scale.get("speedup_max_nodes"),
        "concurrent": concurrent,
        "gang": gang,
        "defrag": defrag,
        "extender": extender,
        "scale": scale,
        "compute": compute,
    }
    print(json.dumps(record))

    # Each guard has its own opt-out: bypassing an accepted latency
    # regression must not also waive the utilization bar (and vice versa).
    msgs = []
    if not args.no_trend_guard:
        msgs.append(trend_guard(p50, repo))
        msgs.append(p99_guard(p99, repo))
        msgs.append(wal_fsync_guard(record["wal_fsyncs_per_admission"], repo))
        msgs.append(wal_fsync_p99_guard(record["wal_fsync_p99_ms"], repo))
        msgs.append(serve_goodput_guard(record["serve_goodput_tokens_per_s"], repo))
        msgs.append(serve_ttft_guard(record["serve_ttft_p99_ms"], repo))
        msgs.append(serve_paged_goodput_guard(
            record["serve_paged_goodput_tokens_per_s"], repo
        ))
        msgs.append(prefix_hit_guard(record["serve_prefix_hit_ratio"], repo))
        msgs.append(interference_guard(
            record["interference_p99_inflation_pct"], repo
        ))
        msgs.append(disagg_ttft_guard(record["disagg_ttft_p99_ms"], repo))
        msgs.append(disagg_tpot_guard(record["disagg_tpot_p99_ms"], repo))
        msgs.append(spec_tokens_guard(record["spec_tokens_per_s"], repo))
        msgs.append(spec_accept_guard(record["spec_accept_len_mean"], repo))
        msgs.append(fleet_goodput_guard(
            record["fleet_goodput_tokens_per_s"], repo
        ))
        msgs.append(fleet_prefix_guard(record["fleet_prefix_hit_ratio"], repo))
        msgs.append(lora_goodput_guard(
            record["lora_goodput_tokens_per_s"], repo
        ))
        msgs.append(adapter_hit_guard(record["adapter_hit_ratio"], repo))
        msgs.append(gang_storm_guard(record["gang_throughput_gangs_s"], repo))
        msgs.append(defrag_stranded_guard(record["defrag_stranded_after_pct"], repo))
        msgs.append(defrag_binpack_guard(record["defrag_binpack_after_pct"], repo))
        msgs.append(scale_throughput_guard(record["scale_admissions_per_s"], repo))
        msgs.append(scale_p99_guard(record["scale_admission_p99_ms"], repo))
    if not args.no_util_guard:
        msgs.append(utilization_guard(record["binpack_utilization_pct"], repo))
    failed = [m for m in msgs if m is not None]
    if failed:
        for m in failed:
            print(m, file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
