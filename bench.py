"""North-star benchmark: pod Allocate() p50 latency through the full stack,
plus the compute-path numbers (flash-attention speedup, train-step MFU) when
a real TPU chip is attached.

Control-plane half: drives the complete admission path on one simulated
4-chip x 32 GiB host (BASELINE.md config 1/3 shape): in-process fake kubelet
grants fake-device IDs over **real gRPC on a unix socket** to the real
plugin server, whose ClusterAllocator lists pending pods from an in-process
apiserver over **real HTTP**, matches the pod, first-fit binpacks the chip,
and persists annotations with a strategic-merge PATCH — the reference's hot
path (``allocate.go:27-134``) end to end, nothing mocked below the wire.
Three independent trials; the reported p50 is the median of per-trial
medians and the spread across trials is printed so a regression can be told
from machine noise.

Compute half: delegates to ``bench_mfu.py`` in a subprocess (so this script
stays importable without jax) and folds its JSON into the ``compute`` key —
flash-vs-plain kernel wall-times compiled on the chip and the flagship
decoder's tokens/s + model-FLOPs MFU. Skipped cleanly off-TPU.

Prints ONE JSON line:
    {"metric": "allocate_p50_latency", "value": <ms>, "unit": "ms",
     "vs_baseline": <x>, ...}

The reference publishes no benchmark numbers at all (README.md:1-16;
BASELINE.json "published": {}). The only latency anchor in its code is the
allocate-path kubelet-poll retry tick of 100 ms (``podmanager.go:26,143-147``)
— the granularity its own Allocate() tolerates — so ``vs_baseline`` is
reported as 100 ms / p50 (higher is better, >1 means finer than the
reference's own retry tick).

Trend guard: exits nonzero (after printing the JSON line) when the measured
p50 regresses >20% against the newest committed ``BENCH_r*.json``, so a
latency regression can never land silently again (the round-1 -> round-3
drift went unnoticed for two rounds). ``--no-trend-guard`` disables it.
"""

from __future__ import annotations

import json
import re
import statistics
import subprocess
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent / "tests"))

NODE = "bench-node"
CHIPS = 4
HBM_GIB = 32
ROUNDS = 10
TRIALS = 3
# Pod sizes per fill round: [16,8,4,2,2] fills one 32-unit chip exactly;
# four repetitions pack the host 128/128 (first-fit lands them chip by chip).
POD_SIZES = [16, 8, 4, 2, 2] * CHIPS
TREND_GUARD_PCT = 20.0


def run_allocate_trial() -> tuple[list[float], float, float]:
    """One full fill/drain cycle; returns (latencies_ms, wall_s, peak_util%)."""
    from gpushare_device_plugin_tpu import const
    from gpushare_device_plugin_tpu.allocator.cluster import ClusterAllocator
    from gpushare_device_plugin_tpu.cluster.apiserver import ApiServerClient
    from gpushare_device_plugin_tpu.cluster.informer import PodInformer
    from gpushare_device_plugin_tpu.device import DeviceInventory
    from gpushare_device_plugin_tpu.discovery import MockBackend
    from gpushare_device_plugin_tpu.plugin import PluginConfig, TpuSharePlugin

    from fake_apiserver import FakeApiServer
    from fake_kubelet import FakeKubelet
    from k8s_fixtures import make_pod

    tmp = tempfile.mkdtemp(prefix="tpushare-bench-")
    api = FakeApiServer()
    api.add_node(NODE)
    api.start()
    kubelet = FakeKubelet(tmp)
    kubelet.start()

    client = ApiServerClient(api.url)
    inv = DeviceInventory(MockBackend(num_chips=CHIPS, hbm_bytes=HBM_GIB << 30).chips())
    # The daemon's default pod source: watch-backed informer cache (one
    # PATCH is then the only HTTP round-trip on the Allocate hot path).
    informer = PodInformer(client, NODE).start()
    allocator = ClusterAllocator(inv, client, informer, NODE)
    plugin = TpuSharePlugin(
        inv, allocate_fn=allocator.allocate, config=PluginConfig(plugin_dir=tmp)
    )
    plugin.serve()
    reg = kubelet.wait_for_registration()
    assert reg.resource_name == const.RESOURCE_MEM

    latencies: list[float] = []
    total_units = sum(inv.units_by_index().values())
    peak_used = 0
    pod_seq = 0
    fill_wall = 0.0
    for rnd in range(ROUNDS):
        t_fill0 = time.perf_counter()
        running: list[str] = []
        used = 0
        for size in POD_SIZES:
            name = f"bench-{pod_seq}"
            pod_seq += 1
            api.add_pod(make_pod(name, size, node=NODE))
            t0 = time.perf_counter()
            resp = kubelet.allocate(reg.endpoint, [[f"g{i}" for i in range(size)]])
            # Round 0 is warmup (first-call connection setup, code paths
            # still cold) — run it fully but keep it out of the stats.
            if rnd > 0:
                latencies.append((time.perf_counter() - t0) * 1e3)
            assert resp.container_responses[0].envs[const.ENV_TPU_VISIBLE_CHIPS]
            # kubelet starts the container: phase Running, so the next
            # allocation's usage accounting sees this pod. Wait (untimed)
            # for the watch to deliver the transition — usage accounting is
            # Running-only (reference parity, podmanager.go:102-115), and we
            # are benching allocate latency, not watch propagation. The poll
            # is an O(1) keyed read so it does not contend with the
            # delivery thread the way a full-cache scan would.
            api.set_pod_phase("default", name, "Running")
            deadline = time.perf_counter() + 2.0
            while time.perf_counter() < deadline:
                cached = informer.get_pod("default", name)
                if cached is not None and cached.get("status", {}).get("phase") == "Running":
                    break
                time.sleep(0.0005)
            running.append(name)
            used += size
        if rnd > 0:
            fill_wall += time.perf_counter() - t_fill0
        peak_used = max(peak_used, used)
        # Fill round complete: workload pods finish, host drains. Wait
        # (untimed) for the DELETED events to clear the informer before the
        # next fill round — otherwise the delete storm's watch processing
        # lands inside the next round's timed windows and the bench measures
        # delete propagation, not allocate latency.
        for name in running:
            api.delete_pod("default", name)
        deadline = time.perf_counter() + 2.0
        while time.perf_counter() < deadline:
            if all(informer.get_pod("default", n) is None for n in running):
                break
            time.sleep(0.0005)

    plugin.stop()
    kubelet.stop()
    informer.stop()
    api.stop()
    return latencies, fill_wall, 100.0 * peak_used / total_units


def _iter_json_objects(text: str):
    """Top-level JSON objects from a possibly-concatenated stream (the
    driver appends one record per bench invocation to the same file)."""
    dec = json.JSONDecoder()
    i = 0
    while True:
        i = text.find("{", i)
        if i < 0:
            return
        try:
            obj, end = dec.raw_decode(text, i)
        except json.JSONDecodeError:
            i += 1
            continue
        yield obj
        i = end


def previous_metric(repo: Path, field: str) -> tuple[float, str] | None:
    """(value, filename) of ``field`` from the newest committed
    ``BENCH_r*.json`` that carries it, if any."""
    newest: tuple[int, float, str] | None = None
    for f in repo.glob("BENCH_r*.json"):
        m = re.match(r"BENCH_r(\d+)\.json", f.name)
        if not m:
            continue
        try:
            vals = [
                float(parsed[field])
                for obj in _iter_json_objects(f.read_text())
                if isinstance(parsed := (obj.get("parsed") if isinstance(obj, dict) else None), dict)
                and parsed.get("metric") == "allocate_p50_latency"
                and isinstance(parsed.get(field), (int, float))
            ]
            if not vals:
                continue
        except OSError:
            continue
        n = int(m.group(1))
        if newest is None or n > newest[0]:
            newest = (n, vals[-1], f.name)
    return (newest[1], newest[2]) if newest else None


def previous_p50(repo: Path) -> tuple[float, str] | None:
    """(p50_ms, filename) from the newest committed BENCH_r*.json, if any."""
    return previous_metric(repo, "value")


def trend_guard(p50: float, repo: Path) -> str | None:
    """Failure message when ``p50`` regressed >TREND_GUARD_PCT vs the newest
    committed ``BENCH_r*.json``; None when within budget (or no history)."""
    prev = previous_p50(repo)
    if prev is None:
        return None
    prev_p50, fname = prev
    if p50 > prev_p50 * (1 + TREND_GUARD_PCT / 100.0):
        return (
            f"TREND GUARD: p50 {p50:.3f}ms regressed >{TREND_GUARD_PCT:.0f}% "
            f"vs {fname} ({prev_p50:.3f}ms)"
        )
    return None


def utilization_guard(util_pct: float, repo: Path) -> str | None:
    """Failure message when peak binpack utilization dropped below the
    newest committed record's (no tolerance: the fill schedule packs the
    host exactly, so any drop means pods the allocator used to place now
    fail); None when >= previous or no history."""
    prev = previous_metric(repo, "binpack_utilization_pct")
    if prev is None:
        return None
    prev_util, fname = prev
    if util_pct < prev_util:
        return (
            f"UTILIZATION GUARD: peak binpack utilization {util_pct:.1f}% "
            f"dropped below {fname} ({prev_util:.1f}%)"
        )
    return None


def run_compute_bench(repo: Path) -> dict:
    """bench_mfu.py in a subprocess; {} on any failure (never fatal here).

    bench_mfu re-prints its cumulative report after every section, so even
    a timeout (dead TPU tunnel mid-compile) salvages the sections that
    finished — the last parseable dict line wins.
    """
    stdout, stderr, note = "", "", None
    try:
        proc = subprocess.run(
            [sys.executable, str(repo / "bench_mfu.py")],
            capture_output=True, text=True, timeout=1800,
        )
        stdout, stderr = proc.stdout, proc.stderr
        note = None if proc.returncode == 0 else f"rc={proc.returncode}"
    except subprocess.TimeoutExpired as e:
        # kill-at-timeout can truncate multi-byte sequences: never raise
        def _txt(v):
            return v.decode(errors="replace") if isinstance(v, bytes) else (v or "")

        stdout, stderr = _txt(e.stdout), _txt(e.stderr)
        note = "timeout"
    except OSError as e:
        print(f"compute bench failed to run: {e}", file=sys.stderr)
        return {"error": str(e)}
    sys.stderr.write(stderr)
    for line in reversed(stdout.strip().splitlines()):
        try:
            obj = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(obj, dict):
            if note:
                obj["partial"] = note
            return obj
    return {"error": f"no JSON output ({note or 'empty'})"}


def main() -> int:
    args = sys.argv[1:]
    repo = Path(__file__).resolve().parent

    trial_p50s: list[float] = []
    trial_p99s: list[float] = []
    throughputs: list[float] = []
    utils: list[float] = []
    for i in range(TRIALS):
        latencies, wall, util = run_allocate_trial()
        trial_p50s.append(statistics.median(latencies))
        trial_p99s.append(statistics.quantiles(latencies, n=100)[98])
        throughputs.append(len(latencies) / wall)
        utils.append(util)
        print(
            f"trial {i + 1}/{TRIALS}: pods={len(latencies)} "
            f"p50={trial_p50s[-1]:.3f}ms p99={trial_p99s[-1]:.3f}ms "
            f"throughput={throughputs[-1]:.1f} pods/s",
            file=sys.stderr,
        )

    p50 = statistics.median(trial_p50s)
    p99 = statistics.median(trial_p99s)
    print(
        f"allocate: p50={p50:.3f}ms (spread {min(trial_p50s):.3f}-{max(trial_p50s):.3f}) "
        f"p99={p99:.3f}ms (spread {min(trial_p99s):.3f}-{max(trial_p99s):.3f}) "
        f"throughput={statistics.median(throughputs):.1f} pods/s "
        f"peak_binpack_utilization={max(utils):.1f}%",
        file=sys.stderr,
    )

    compute = {} if "--no-mfu" in args else run_compute_bench(repo)
    if compute.get("train"):
        t = compute["train"]
        print(
            f"compute: mfu={t.get('mfu_pct')}% tokens/s={t.get('tokens_per_s')} "
            f"flash_speedups={[f['speedup'] for f in compute.get('flash', [])]}",
            file=sys.stderr,
        )

    record = {
        "metric": "allocate_p50_latency",
        "value": round(p50, 3),
        "unit": "ms",
        "vs_baseline": round(100.0 / p50, 1),
        "p50_spread_ms": [round(min(trial_p50s), 3), round(max(trial_p50s), 3)],
        "p99_ms": round(p99, 3),
        "throughput_pods_s": round(statistics.median(throughputs), 1),
        # North star #2 (BASELINE.md, reference analog display.go:231-241):
        # peak TPU-HBM binpack utilization across trials — the fill rounds
        # pack the host completely, so anything under 100 is an allocator
        # regression.
        "binpack_utilization_pct": round(max(utils), 1),
        "trials": TRIALS,
        "compute": compute,
    }
    print(json.dumps(record))

    # Each guard has its own opt-out: bypassing an accepted latency
    # regression must not also waive the utilization bar (and vice versa).
    msgs = []
    if "--no-trend-guard" not in args:
        msgs.append(trend_guard(p50, repo))
    if "--no-util-guard" not in args:
        msgs.append(utilization_guard(record["binpack_utilization_pct"], repo))
    failed = [m for m in msgs if m is not None]
    if failed:
        for m in failed:
            print(m, file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
