"""North-star benchmark: pod Allocate() p50 latency through the full stack.

Drives the complete admission path on one simulated 4-chip x 32 GiB host
(BASELINE.md config 1/3 shape): in-process fake kubelet grants fake-device
IDs over **real gRPC on a unix socket** to the real plugin server, whose
ClusterAllocator lists pending pods from an in-process apiserver over
**real HTTP**, matches the pod, first-fit binpacks the chip, and persists
annotations with a strategic-merge PATCH — the reference's hot path
(``allocate.go:27-134``) end to end, nothing mocked below the wire.

Prints ONE JSON line:
    {"metric": "allocate_p50_latency", "value": <ms>, "unit": "ms",
     "vs_baseline": <x>}

The reference publishes no benchmark numbers at all (README.md:1-16;
BASELINE.json "published": {}). The only latency anchor in its code is the
allocate-path kubelet-poll retry tick of 100 ms (``podmanager.go:26,143-147``)
— the granularity its own Allocate() tolerates — so ``vs_baseline`` is
reported as 100 ms / p50 (higher is better, >1 means finer than the
reference's own retry tick). Secondary numbers (p99, throughput, final HBM
binpack utilization) go to stderr.
"""

from __future__ import annotations

import json
import statistics
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent / "tests"))

from gpushare_device_plugin_tpu import const
from gpushare_device_plugin_tpu.allocator.cluster import ClusterAllocator
from gpushare_device_plugin_tpu.cluster.apiserver import ApiServerClient
from gpushare_device_plugin_tpu.cluster.informer import PodInformer
from gpushare_device_plugin_tpu.device import DeviceInventory
from gpushare_device_plugin_tpu.discovery import MockBackend
from gpushare_device_plugin_tpu.plugin import PluginConfig, TpuSharePlugin

from fake_apiserver import FakeApiServer
from fake_kubelet import FakeKubelet
from k8s_fixtures import make_pod

NODE = "bench-node"
CHIPS = 4
HBM_GIB = 32
ROUNDS = 20
# Pod sizes per fill round: [16,8,4,2,2] fills one 32-unit chip exactly;
# four repetitions pack the host 128/128 (first-fit lands them chip by chip).
POD_SIZES = [16, 8, 4, 2, 2] * CHIPS


def main() -> None:
    tmp = tempfile.mkdtemp(prefix="tpushare-bench-")
    api = FakeApiServer()
    api.add_node(NODE)
    api.start()
    kubelet = FakeKubelet(tmp)
    kubelet.start()

    client = ApiServerClient(api.url)
    inv = DeviceInventory(MockBackend(num_chips=CHIPS, hbm_bytes=HBM_GIB << 30).chips())
    # The daemon's default pod source: watch-backed informer cache (one
    # PATCH is then the only HTTP round-trip on the Allocate hot path).
    informer = PodInformer(client, NODE).start()
    allocator = ClusterAllocator(inv, client, informer, NODE)
    plugin = TpuSharePlugin(
        inv, allocate_fn=allocator.allocate, config=PluginConfig(plugin_dir=tmp)
    )
    plugin.serve()
    reg = kubelet.wait_for_registration()
    assert reg.resource_name == const.RESOURCE_MEM

    latencies: list[float] = []
    units_per_chip = inv.units_by_index()
    total_units = sum(units_per_chip.values())
    peak_used = 0
    pod_seq = 0
    t_all0 = time.perf_counter()
    for _ in range(ROUNDS):
        running: list[str] = []
        used = 0
        for size in POD_SIZES:
            name = f"bench-{pod_seq}"
            pod_seq += 1
            api.add_pod(make_pod(name, size, node=NODE))
            t0 = time.perf_counter()
            resp = kubelet.allocate(reg.endpoint, [[f"g{i}" for i in range(size)]])
            latencies.append((time.perf_counter() - t0) * 1e3)
            assert resp.container_responses[0].envs[const.ENV_TPU_VISIBLE_CHIPS]
            # kubelet starts the container: phase Running, so the next
            # allocation's usage accounting sees this pod. Wait (untimed)
            # for the watch to deliver the transition — usage accounting is
            # Running-only (reference parity, podmanager.go:102-115), and we
            # are benching allocate latency, not watch propagation.
            api.set_pod_phase("default", name, "Running")
            deadline = time.perf_counter() + 2.0
            while time.perf_counter() < deadline:
                seen = {
                    p["metadata"]["name"]
                    for p in informer.running_share_pods()
                    if p.get("status", {}).get("phase") == "Running"
                }
                if name in seen:
                    break
                time.sleep(0.001)
            running.append(name)
            used += size
        peak_used = max(peak_used, used)
        # Fill round complete: workload pods finish, host drains.
        for name in running:
            api.delete_pod("default", name)
    wall = time.perf_counter() - t_all0

    plugin.stop()
    kubelet.stop()
    informer.stop()
    api.stop()

    p50 = statistics.median(latencies)
    p99 = statistics.quantiles(latencies, n=100)[98]
    util = 100.0 * peak_used / total_units
    print(
        f"pods={len(latencies)} p50={p50:.3f}ms p99={p99:.3f}ms "
        f"throughput={len(latencies) / wall:.1f} pods/s "
        f"peak_binpack_utilization={util:.1f}%",
        file=sys.stderr,
    )
    print(
        json.dumps(
            {
                "metric": "allocate_p50_latency",
                "value": round(p50, 3),
                "unit": "ms",
                "vs_baseline": round(100.0 / p50, 1),
            }
        )
    )


if __name__ == "__main__":
    main()
