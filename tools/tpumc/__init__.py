"""tpumc: exhaustive-interleaving model checker for the journaled protocols.

The control plane's hardest bugs — the gang double-booking, the
annotation-before-bind visibility race, the drain-handshake lost-snapshot
cases — were all *ordering* bugs that chaos found one schedule at a
time: ``make chaos-move``/``chaos-shard`` kill at every journal step but
execute only the one thread interleaving the OS happens to pick. tpumc
turns "chaos got lucky" into "all interleavings up to k preemptions are
proven clean, and violations replay deterministically":

- :mod:`.sched` — a deterministic cooperative scheduler. It hijacks the
  ``utils/lockrank.py`` factory seam (every lock in the package is
  already constructed through ``make_lock``/``make_rlock``/
  ``make_condition``/``make_event``) and the ``utils/faults.py`` fire
  hook, so under ``TPUSHARE_MC=1`` every acquire/release/wait, every
  fault-injection crash site, and every ``checkpoint.begin/commit/
  abort`` becomes a yield point, and exactly one model thread runs
  between yield points.
- :mod:`.explore` — CHESS/DPOR-style stateless DFS over schedules:
  partial-order reduction by sleep sets over a conservative independence
  relation (sound under the repo's locking discipline), and a
  preemption bound (k=2 default; k=∞ exhausts the smoke-sized models).
- :mod:`.models` — small-model harnesses for the three journaled
  protocols: gang-2PC prepare/decide/resolve (``extender/shards.py``),
  the defrag move protocol (``allocator/defrag.py``), and the engine
  drain handshake (``serving/drainproto.py``), each with the repo's
  standing invariants checked at every terminal state.
- :mod:`.memwal` — an ``AllocationCheckpoint``-compatible in-memory WAL
  so thousands of schedules re-run without touching a disk (the journal
  fault points still fire, so WAL steps stay yield points).

A violation dumps a replayable schedule id; ``python -m tools.tpumc
replay <id>`` re-executes the exact interleaving under the tracer and
flight recorder, so counterexamples are first-class artifacts instead of
flaky CI logs. ``docs/analysis.md`` documents the yield-point taxonomy,
the independence relation, the preemption-bound semantics, and the
replay workflow; ``make mc`` / ``make mc-smoke`` are the CI entries.
"""

from .explore import ExploreResult, Explorer, SCHEDULE_ID_PREFIX, Violation
from .sched import (
    InvariantViolation,
    MCScheduler,
    mc_session,
    mc_step,
)

__all__ = [
    "ExploreResult",
    "Explorer",
    "InvariantViolation",
    "MCScheduler",
    "SCHEDULE_ID_PREFIX",
    "Violation",
    "mc_session",
    "mc_step",
]
