"""Stateless DFS over schedules: CHESS-style preemption bounding plus
sleep-set partial-order reduction.

Exploration is *stateless*: every schedule re-runs the model's harness
from scratch under a forced prefix of choices, then continues with the
deterministic default policy (stay on the current thread while it is
enabled — the non-preemptive spine — else lowest task id). Determinism
is asserted, not assumed: a forced prefix must reproduce the exact
enabled sets and pending operations of the run that created it, or the
explorer aborts loudly (a model reading wall-clock control flow would
corrupt the search silently otherwise).

**Preemption bound** (``k``): switching away from a thread that is still
enabled is a preemption; schedules may use at most ``k``. Bounding is
CHESS's result — most concurrency bugs need very few preemptions, and
the schedule count stays polynomial. ``k=None`` means unbounded
(exhaustive), which the smoke-sized models use.

**Sleep sets** (``por=True``): after exploring child ``t1`` of a state,
its siblings need not re-explore schedules that begin with a transition
independent of everything that distinguishes them — ``t1`` "sleeps"
until a dependent operation executes. The independence relation is
deliberately conservative: two operations commute only when BOTH are
synchronization operations on DIFFERENT named objects; fault/protocol
fire points and model steps conflict with everything. That is sound for
this codebase because the locking discipline (tpulint + the runtime
witness) keeps cross-thread state behind the instrumented locks — see
docs/analysis.md for the argument, and the explorer self-tests for the
empirical check (POR on vs off finds identical violation sets). Sleep
sets compose safely with ``k=None``; with a finite bound the two
prunings can interact (a trace's only ≤k representative may be slept),
so bounded runs default POR **off** and exhaustive runs default it on.

A schedule id encodes the model, the bound, and the base-36 task id
chosen at every decision point — ``tpumc:<model>:<k>:<digits>`` — and
:func:`Explorer.replay` re-executes it choice for choice.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable

from .sched import (
    DeadlockDetected,
    InvariantViolation,
    MCScheduler,
    Op,
    Task,
    mc_session,
)

SCHEDULE_ID_PREFIX = "tpumc:"

_B36 = "0123456789abcdefghijklmnopqrstuvwxyz"

# Operation kinds that are pure synchronization on a named object; two of
# these on DIFFERENT objects commute. Everything else (fire points =
# journal/protocol steps, model steps, harness exceptions) conservatively
# conflicts with everything.
_SYNC_KINDS = frozenset({
    "acquire", "reacquire", "release",
    "evt_wait", "evt_wait_timed", "evt_set", "evt_clear",
    "cond_wait", "cond_wait_timed", "cond_notify",
})


def independent(a: Op, b: Op) -> bool:
    """Whether two transitions commute (the POR relation)."""
    if a[0] == "start" or b[0] == "start":
        return True  # starting a thread has no effect
    if a[0] in _SYNC_KINDS and b[0] in _SYNC_KINDS:
        return a[1] != b[1]
    return False


def encode_schedule_id(model: str, k: int | None, choices: list[int]) -> str:
    kk = "inf" if k is None else str(k)
    return SCHEDULE_ID_PREFIX + f"{model}:{kk}:" + "".join(
        _B36[c] for c in choices
    )


def decode_schedule_id(schedule_id: str) -> tuple[str, int | None, list[int]]:
    if not schedule_id.startswith(SCHEDULE_ID_PREFIX):
        raise ValueError(f"not a tpumc schedule id: {schedule_id!r}")
    body = schedule_id[len(SCHEDULE_ID_PREFIX):]
    model, _, rest = body.partition(":")
    kk, _, digits = rest.partition(":")
    if not model or not kk:
        raise ValueError(f"malformed schedule id: {schedule_id!r}")
    k = None if kk == "inf" else int(kk)
    return model, k, [_B36.index(c) for c in digits]


@dataclasses.dataclass(frozen=True)
class Violation:
    """One schedule that broke an invariant, deadlocked, or raised."""

    schedule_id: str
    kind: str  # "invariant" | "deadlock" | "exception"
    message: str
    trace: str

    def brief(self) -> str:
        return f"[{self.kind}] {self.schedule_id}: {self.message}"


@dataclasses.dataclass
class ExploreResult:
    model: str
    k: int | None
    por: bool
    schedules: int = 0
    pruned: int = 0
    choice_points: int = 0
    max_depth: int = 0
    wall_s: float = 0.0
    truncated: bool = False
    violations: list[Violation] = dataclasses.field(default_factory=list)

    def summary(self) -> str:
        kk = "inf" if self.k is None else str(self.k)
        line = (
            f"{self.model}: {self.schedules} schedule(s) explored "
            f"(k={kk}, por={'on' if self.por else 'off'}, "
            f"{self.pruned} sleep-pruned, max depth {self.max_depth}, "
            f"{self.wall_s:.2f}s) — "
            f"{len(self.violations)} violation(s)"
        )
        if self.truncated:
            line += " [TRUNCATED at max-schedules: NOT exhaustive]"
        return line


class _ChoicePoint:
    """One decision on the current DFS path."""

    __slots__ = (
        "enabled", "ops", "current_tid", "preemptions_before",
        "sleep_entry", "explored", "chosen",
    )

    def __init__(
        self,
        enabled: list[int],
        ops: dict[int, Op],
        current_tid: int | None,
        preemptions_before: int,
        sleep_entry: dict[int, Op],
        chosen: int,
    ) -> None:
        self.enabled = enabled
        self.ops = ops
        self.current_tid = current_tid
        self.preemptions_before = preemptions_before
        self.sleep_entry = sleep_entry
        self.explored = [chosen]
        self.chosen = chosen


class ScheduleDivergence(RuntimeError):
    """A forced prefix did not reproduce the recorded enabled set: the
    model is not schedule-deterministic (wall-clock control flow,
    ambient randomness) and the search would be silently wrong."""


class _RunController:
    """Drives one run: forced prefix, then the default policy; records
    new choice points and maintains the live sleep set."""

    def __init__(
        self, stack: list[_ChoicePoint], por: bool, replay_only: bool
    ) -> None:
        self.stack = stack
        self.por = por
        self.replay_only = replay_only  # don't record new points
        self.depth = 0
        self.sleep: dict[int, Op] = {}
        self.preemptions = 0
        self.pruned = False
        self.new_records: list[_ChoicePoint] = []
        self.path: list[int] = []

    # sched.on_op: every executed transition filters the sleep set
    def on_op(self, task: Task, op: Op) -> None:
        if not self.sleep:
            return
        for tid in list(self.sleep):
            if not independent(self.sleep[tid], op):
                del self.sleep[tid]

    def choose(self, sched: MCScheduler, enabled: list[Task]) -> Task:
        by_tid = {t.tid: t for t in enabled}
        enabled_tids = sorted(by_tid)
        ops = {t.tid: t.pending for t in enabled}
        current = sched.current.tid if sched.current is not None else None
        i = self.depth
        self.depth += 1
        if i < len(self.stack):
            cp = self.stack[i]
            if cp.enabled != enabled_tids or (
                not self.replay_only and cp.ops != ops
            ):
                raise ScheduleDivergence(
                    f"choice point {i}: recorded enabled={cp.enabled} "
                    f"ops={cp.ops} but this run sees "
                    f"enabled={enabled_tids} ops={ops}"
                )
            chosen_tid = cp.chosen
            prior = [t for t in cp.explored if t != chosen_tid]
        else:
            if self.pruned or self.replay_only:
                # beyond the recorded path of an abandoned (sleep-
                # blocked) run, or replaying: default policy, unrecorded
                chosen_tid = self._default(enabled_tids, current, ops)
                prior = []
                cp = None
            else:
                awake = [t for t in enabled_tids if t not in self.sleep]
                if not awake:
                    # sleep-blocked: every continuation from here is
                    # covered by an already-explored trace — finish the
                    # run silently, record nothing more
                    self.pruned = True
                    chosen_tid = self._default(enabled_tids, current, ops)
                    prior = []
                    cp = None
                else:
                    chosen_tid = self._default(awake, current, ops)
                    cp = _ChoicePoint(
                        enabled_tids, ops, current, self.preemptions,
                        dict(self.sleep), chosen_tid,
                    )
                    self.new_records.append(cp)
                    prior = []
        if current is not None and chosen_tid != current and current in by_tid:
            self.preemptions += 1
        if cp is not None and self.por:
            chosen_op = ops[chosen_tid]
            merged = dict(cp.sleep_entry)
            for tid in prior:
                merged[tid] = cp.ops[tid]
            self.sleep = {
                tid: op for tid, op in merged.items()
                if tid != chosen_tid and independent(op, chosen_op)
            }
        elif not self.por:
            self.sleep = {}
        self.path.append(chosen_tid)
        return by_tid[chosen_tid]

    @staticmethod
    def _default(
        candidates: list[int], current: int | None, ops: dict[int, Op]
    ) -> int:
        if current is not None and current in candidates:
            return current
        return min(candidates)


class _ReplayController:
    """Forces an exact choice sequence from a schedule id."""

    def __init__(self, choices: list[int]) -> None:
        self.choices = choices
        self.depth = 0

    def on_op(self, task: Task, op: Op) -> None:
        pass

    def choose(self, sched: MCScheduler, enabled: list[Task]) -> Task:
        by_tid = {t.tid: t for t in enabled}
        i = self.depth
        self.depth += 1
        if i >= len(self.choices):
            raise ScheduleDivergence(
                f"schedule id ends at choice {len(self.choices)} but the "
                f"run reached choice point {i} — model changed since the "
                "id was minted"
            )
        tid = self.choices[i]
        if tid not in by_tid:
            raise ScheduleDivergence(
                f"choice point {i}: id names task {tid} but enabled set "
                f"is {sorted(by_tid)} — model changed since the id was "
                "minted"
            )
        return by_tid[tid]


@dataclasses.dataclass
class RunOutcome:
    schedule_id: str
    violation: Violation | None
    trace: str
    pruned: bool
    depth: int
    preemptions: int


class Explorer:
    """Bounded exhaustive exploration of one model.

    ``model`` must expose ``name`` (str) and ``build() -> harness``
    where the harness exposes ``tasks`` (list of ``(name, callable)``)
    and ``check()`` (raises :class:`InvariantViolation` at a bad
    terminal state). ``build`` is called once per schedule — everything
    the threads share must be constructed inside it, under the session,
    so its locks are cooperative.
    """

    def __init__(
        self,
        model: Any,
        k: int | None = 2,
        por: bool | None = None,
        branch_on_release: bool = False,
        max_schedules: int | None = None,
        stop_on_violation: bool = False,
        progress: Callable[[int], None] | None = None,
    ) -> None:
        self.model = model
        self.k = k
        # POR defaults on only for unbounded search: sleep sets compose
        # with k=inf; under a finite bound the prunings can interact
        # (module docstring), so bounded runs enumerate plainly.
        self.por = (k is None) if por is None else por
        self.branch_on_release = branch_on_release
        self.max_schedules = max_schedules
        self.stop_on_violation = stop_on_violation
        self.progress = progress

    # --- one schedule -----------------------------------------------------

    def _execute(
        self, controller: Any, collect_trace: bool
    ) -> tuple[Violation | None, str, int]:
        sched = MCScheduler(
            controller.choose,
            on_op=controller.on_op,
            branch_on_release=self.branch_on_release,
        )
        violation_body: tuple[str, str] | None = None
        with mc_session(sched):
            harness = self.model.build()
            for name, fn in harness.tasks:
                sched.spawn(name, fn)
            try:
                sched.run()
            except DeadlockDetected as e:
                violation_body = ("deadlock", str(e))
            except ScheduleDivergence:
                raise
            except InvariantViolation as e:
                violation_body = ("invariant", str(e))
            except Exception as e:  # noqa: BLE001 — any harness escape
                # is a finding: protocol code raised where the real
                # system would have no handler
                violation_body = ("exception", f"{type(e).__name__}: {e}")
            else:
                try:
                    harness.check()
                except InvariantViolation as e:
                    violation_body = ("invariant", str(e))
        trace = sched.trace_text() if (collect_trace or violation_body) else ""
        violation: Violation | None = None
        if violation_body is not None:
            violation = Violation(
                schedule_id="",  # stamped by the caller (id needs the path)
                kind=violation_body[0],
                message=violation_body[1],
                trace=trace,
            )
        return violation, trace, sched.preemptions

    def run_one(
        self, stack: list[_ChoicePoint], collect_trace: bool = False
    ) -> RunOutcome:
        ctrl = _RunController(stack, por=self.por, replay_only=False)
        violation, trace, preemptions = self._execute(ctrl, collect_trace)
        stack.extend(ctrl.new_records)
        schedule_id = encode_schedule_id(self.model.name, self.k, ctrl.path)
        if violation is not None:
            violation = dataclasses.replace(violation, schedule_id=schedule_id)
        return RunOutcome(
            schedule_id=schedule_id,
            violation=violation,
            trace=trace,
            pruned=ctrl.pruned,
            depth=len(ctrl.path),
            preemptions=preemptions,
        )

    # --- the search -------------------------------------------------------

    def _candidates(self, cp: _ChoicePoint) -> list[int]:
        """Unexplored, non-sleeping, bound-feasible alternatives at a
        choice point, non-preemptive spine first."""
        out = []
        ordered = sorted(
            cp.enabled,
            key=lambda t: (0 if t == cp.current_tid else 1, t),
        )
        for tid in ordered:
            if tid in cp.explored or tid in cp.sleep_entry:
                continue
            costs_preemption = (
                cp.current_tid is not None
                and tid != cp.current_tid
                and cp.current_tid in cp.enabled
            )
            if (
                costs_preemption
                and self.k is not None
                and cp.preemptions_before >= self.k
            ):
                continue
            out.append(tid)
        return out

    def _backtrack(self, stack: list[_ChoicePoint]) -> bool:
        while stack:
            cp = stack[-1]
            cands = self._candidates(cp)
            if cands:
                cp.explored.append(cands[0])
                cp.chosen = cands[0]
                return True
            stack.pop()
        return False

    def explore(self) -> ExploreResult:
        result = ExploreResult(model=self.model.name, k=self.k, por=self.por)
        t0 = time.perf_counter()
        stack: list[_ChoicePoint] = []
        first = True
        while first or self._backtrack(stack):
            first = False
            if (
                self.max_schedules is not None
                and result.schedules >= self.max_schedules
            ):
                # never a silent cap: the summary says NOT exhaustive
                result.truncated = True
                break
            outcome = self.run_one(stack)
            result.schedules += 1
            result.max_depth = max(result.max_depth, outcome.depth)
            if outcome.pruned:
                result.pruned += 1
            if outcome.violation is not None:
                result.violations.append(outcome.violation)
                if self.stop_on_violation:
                    break
            if self.progress is not None and result.schedules % 200 == 0:
                self.progress(result.schedules)
        result.choice_points = sum(len(cp.explored) for cp in stack)
        result.wall_s = time.perf_counter() - t0
        return result

    # --- replay -----------------------------------------------------------

    def replay(self, schedule_id: str) -> RunOutcome:
        """Re-execute one schedule choice for choice; the returned
        outcome carries the full transition trace."""
        _model, _k, choices = decode_schedule_id(schedule_id)
        ctrl = _ReplayController(choices)
        violation, trace, preemptions = self._execute(ctrl, collect_trace=True)
        if violation is not None:
            violation = dataclasses.replace(violation, schedule_id=schedule_id)
        return RunOutcome(
            schedule_id=schedule_id,
            violation=violation,
            trace=trace,
            pruned=False,
            depth=len(choices),
            preemptions=preemptions,
        )
