"""Small-model harnesses for the journaled protocols.

Each model builds a FRESH harness per schedule (under the mc_session, so
every lock in the object graph is cooperative) and checks the repo's
standing invariants at the terminal state:

- **gang2pc** — two cross-shard gang groups race the REAL two-phase
  protocol (``ShardRouter.admit_gang_group`` with planning stubbed to a
  fixed plan — scoring is not the protocol under test) over two
  2-chip nodes whose capacity admits only one group per chip. After a
  terminal :func:`resolve_gang2pc` pass: no per-chip overcommit, no
  partial gang, no orphaned cross-shard reservation, no pending gang2pc
  journal entry.
- **move** — one :class:`SliceMover` executes the journaled
  plan→drain→copy→switch→resume protocol while a concurrent admission
  books capacity through the same :class:`AssumeCache`; the terminal
  reconciler pass resolves whatever is pending. Invariants: no per-chip
  overcommit, the moved pod lives on exactly one chip, the ledger fully
  drained, no pending move entry after resolve.
- **drain-handshake** — the REAL :class:`DrainHandshake` between a
  simulated serving loop (retire-or-capture per iteration boundary,
  exactly ``PagedSlotEngine.run``'s shape) and a mover
  (request→wait→restore). Invariant: every submitted request is
  delivered exactly once — at the source before capture or at the
  destination after restore; never lost, never duplicated.
  ``drain-broken`` seeds the pre-PR-13 bug (arming without resetting
  the prior cycle's answer) and exists so the checker provably FINDS
  the lost-capture schedule — the explorer self-tests pin it.
- **handoff** — two :class:`HandoffMover` instances race the journaled
  export→transfer→import→commit KV-handoff protocol
  (``serving/handoffproto.py``) into one decode-tier import ledger over
  a page pool too small for both stagings, with a reconciler pass
  interleaved; ``handoff-crash`` seeds pre-crashed journal entries (a
  partial ``transfer``, a sealed ``import``) the reconciler must roll
  back/forward. Invariants: every handoff serves its request exactly
  once (KV import or re-prefill — never lost, never duplicated), the
  page pool drains to fully free, no pending handoff entry after
  resolve.
- **scale** — one :class:`ScaleExecutor` drains a fleet replica through
  the journaled cordon→drain→migrate→release protocol
  (``serving/router.py``) while a rival executor races the same scale
  id (claim gating) and a reconciler pass interleaves;
  ``scale-crash`` seeds pre-crashed entries a dead incarnation left in
  ``drain`` (rolls back: journaled rows re-queued on survivors) and
  ``migrate`` (rolls forward: the drained snapshot re-delivered).
  Invariants: every in-flight request on the drained replica is served
  exactly once (migrated or re-queued — never lost, never duplicated),
  the replica ends closed to routes, no pending scale entry after
  resolve, no leaked claim.
- **racy-counter** / **indep-workers** — toy models for the explorer's
  own tests: a classic read-modify-write race (found at k>=1), and a
  mostly-independent workload where sleep-set POR must prune schedules
  without losing the seeded violation.

Models must be *schedule-deterministic*: control flow may not depend on
wall-clock or ambient randomness (TTLs here are hundreds of seconds —
never reached inside a run; timestamps ride record payloads only).
"""

from __future__ import annotations

import copy
from typing import Any, Callable

from gpushare_device_plugin_tpu import const
from gpushare_device_plugin_tpu.allocator.assume import AssumeCache
from gpushare_device_plugin_tpu.allocator.defrag import MovePlan, SliceMover, resolve_move
from gpushare_device_plugin_tpu.cluster import pods as P
from gpushare_device_plugin_tpu.cluster.apiserver import ApiError
from gpushare_device_plugin_tpu.extender import simcluster as S
from gpushare_device_plugin_tpu.extender.shards import (
    GANG2PC_NS,
    LeaderLease,
    ShardExtender,
    ShardRouter,
    resolve_gang2pc,
)
from gpushare_device_plugin_tpu.serving.drainproto import DrainHandshake
from gpushare_device_plugin_tpu.serving.handoffproto import (
    HandoffImportLedger,
    HandoffMover,
    HandoffPeerClient,
    HandoffPlan,
    HandoffSink,
    handoff_key,
    resolve_handoff,
)
from gpushare_device_plugin_tpu.serving.pages import PageAllocator
from gpushare_device_plugin_tpu.serving.router import (
    ScaleExecutor,
    resolve_scale,
    scale_key,
)
from gpushare_device_plugin_tpu.utils.circuit import CircuitBreaker
from gpushare_device_plugin_tpu.utils.faults import FAULTS
from gpushare_device_plugin_tpu.utils.metrics import MetricsRegistry

from .memwal import MemJournal
from .sched import InvariantViolation, mc_step


class Harness:
    """One schedule's world: tasks to run and the terminal check."""

    def __init__(
        self,
        tasks: list[tuple[str, Callable[[], Any]]],
        check: Callable[[], None],
    ) -> None:
        self.tasks = tasks
        self._check = check

    def check(self) -> None:
        self._check()


# ---------------------------------------------------------------------------
# in-process apiserver stub
# ---------------------------------------------------------------------------


class ModelApi:
    """Duck-typed ``ApiServerClient`` subset over plain dicts. Every verb
    fires the ``apiserver.request`` fault point first, so each apiserver
    round-trip is a scheduler yield point (and the mutation that follows
    rides a conservatively-dependent transition — see explore.py)."""

    def __init__(self) -> None:
        self.pods: dict[tuple[str, str], dict] = {}
        self.nodes: dict[str, dict] = {}

    # setup-side (no fires; runs before the schedule starts)
    def add_pod(self, pod: dict) -> None:
        self.pods[(P.namespace(pod), P.name(pod))] = pod

    def add_node(self, node: dict) -> None:
        self.nodes[node["metadata"]["name"]] = node

    # --- client verbs -----------------------------------------------------

    def get_pod(self, ns: str, name: str) -> dict:
        FAULTS.fire("apiserver.request")
        pod = self.pods.get((ns, name))
        if pod is None:
            raise ApiError(404, f"pod {ns}/{name} not found")
        return copy.deepcopy(pod)

    def list_pods(self) -> list[dict]:
        FAULTS.fire("apiserver.request")
        return [copy.deepcopy(p) for p in self.pods.values()]

    def get_node(self, name: str) -> dict:
        FAULTS.fire("apiserver.request")
        node = self.nodes.get(name)
        if node is None:
            raise ApiError(404, f"node {name} not found")
        return copy.deepcopy(node)

    def list_nodes(self) -> list[dict]:
        FAULTS.fire("apiserver.request")
        return [copy.deepcopy(n) for n in self.nodes.values()]

    def patch_pod(self, ns: str, name: str, patch: dict) -> dict:
        FAULTS.fire("apiserver.request")
        pod = self.pods.get((ns, name))
        if pod is None:
            raise ApiError(404, f"pod {ns}/{name} not found")
        ann = patch.get("metadata", {}).get("annotations") or {}
        pod.setdefault("metadata", {}).setdefault("annotations", {}).update(
            {k: str(v) for k, v in ann.items()}
        )
        return copy.deepcopy(pod)

    def patch_node(self, name: str, patch: dict) -> dict:
        FAULTS.fire("apiserver.request")
        node = self.nodes.get(name)
        if node is None:
            raise ApiError(404, f"node {name} not found")
        ann = patch.get("metadata", {}).get("annotations") or {}
        node.setdefault("metadata", {}).setdefault("annotations", {}).update(
            {k: str(v) for k, v in ann.items()}
        )
        return copy.deepcopy(node)

    def bind_pod(self, ns: str, name: str, node: str) -> None:
        FAULTS.fire("apiserver.request")
        pod = self.pods.get((ns, name))
        if pod is None:
            raise ApiError(404, f"pod {ns}/{name} not found")
        pod.setdefault("spec", {})["nodeName"] = node


def _pod(
    name: str,
    units: int,
    *,
    ns: str = "default",
    node: str = "",
    phase: str = "Pending",
    annotations: dict | None = None,
    labels: dict | None = None,
) -> dict:
    return {
        "metadata": {
            "name": name,
            "namespace": ns,
            "uid": f"uid-{ns}-{name}",
            "creationTimestamp": "2026-01-01T00:00:00Z",
            "annotations": dict(annotations or {}),
            "labels": dict(labels or {}),
        },
        "spec": {
            "nodeName": node,
            "containers": [{
                "name": "c0",
                "resources": {"limits": {const.RESOURCE_MEM: str(units)}},
            }],
        },
        "status": {"phase": phase},
    }


# ---------------------------------------------------------------------------
# toy models (explorer self-tests)
# ---------------------------------------------------------------------------


class RacyCounterModel:
    """The classic lost update: read, yield, write. The invariant
    (counter == workers) fails on any schedule that interleaves two
    read-modify-write windows — reachable from the non-preemptive spine
    only with >=1 preemption, which pins the bound's semantics."""

    def __init__(self, workers: int = 2, steps: int = 1) -> None:
        self.name = "racy-counter"
        self.workers = workers
        self.steps = steps

    def build(self) -> Harness:
        state = {"v": 0}

        def worker() -> None:
            for _ in range(self.steps):
                mc_step("read")
                tmp = state["v"]
                mc_step("write")
                state["v"] = tmp + 1

        def check() -> None:
            want = self.workers * self.steps
            if state["v"] != want:
                raise InvariantViolation(
                    f"lost update: counter {state['v']} != {want}"
                )

        return Harness(
            [(f"w{i}", worker) for i in range(self.workers)], check
        )


class IndepWorkersModel:
    """Two workers on independent locks plus the racy pair: sleep-set
    POR must prune the independent chatter WITHOUT losing the racy
    violation (the POR-vs-full equivalence test runs this)."""

    def __init__(self) -> None:
        self.name = "indep-workers"

    def build(self) -> Harness:
        from .sched import active_scheduler

        sched = active_scheduler()
        assert sched is not None, "indep-workers only runs under tpumc"
        factory = sched.factory()
        lock_a = factory.lock("model.a")
        lock_b = factory.lock("model.b")
        cells = {"a": 0, "b": 0, "v": 0}

        def indep(lock: Any, cell: str) -> Callable[[], None]:
            def body() -> None:
                with lock:
                    cells[cell] += 1
            return body

        def racy() -> None:
            mc_step("read")
            tmp = cells["v"]
            mc_step("write")
            cells["v"] = tmp + 1

        def check() -> None:
            if cells["a"] != 1 or cells["b"] != 1:
                raise InvariantViolation(f"independent counters: {cells}")
            if cells["v"] != 2:
                raise InvariantViolation(
                    f"lost update: v={cells['v']} != 2"
                )

        return Harness(
            [
                ("ia", indep(lock_a, "a")),
                ("ib", indep(lock_b, "b")),
                ("r1", racy),
                ("r2", racy),
            ],
            check,
        )


# ---------------------------------------------------------------------------
# drain handshake
# ---------------------------------------------------------------------------


class _BrokenDrainHandshake(DrainHandshake):
    """The seeded defect: arm WITHOUT resetting the prior cycle's
    answer. A mover arming between runs then consumes the stale
    everything-retired answer immediately, while the flag left up makes
    the NEXT run quiesce into a capture nobody collects — lost
    requests. The checker must find this at k>=1."""

    def request(self) -> None:  # noqa: D102 — deliberately buggy
        with self._lock:
            self._request_evt.set()


class DrainModel:
    """The engine half of the move protocol: a serving loop racing a
    mover through the real :class:`DrainHandshake`."""

    def __init__(
        self,
        batches: tuple[tuple[str, ...], ...] = (
            ("r1", "r2"), ("r3", "r4"), ("r5",),
        ),
        broken: bool = False,
    ) -> None:
        self.name = "drain-broken" if broken else "drain-handshake"
        self.batches = batches
        self.broken = broken

    def build(self) -> Harness:
        h: DrainHandshake = (
            _BrokenDrainHandshake() if self.broken else DrainHandshake()
        )
        submitted: list[str] = []
        delivered: list[str] = []
        restored: list[str] = []
        restored_ids: set[str] = set()

        def source() -> None:
            # back-to-back runs of one engine: each run serves a batch
            # to completion unless a drain captures the remainder —
            # after a capture the pod is moving, so no further run
            # starts (PagedSlotEngine.run's shape, requests modeled as
            # opaque ids)
            for batch in self.batches:
                submitted.extend(batch)
                i = 0
                while i < len(batch):
                    mc_step("boundary")
                    if h.armed():
                        h.publish({
                            "snapshot_id": "move#1",
                            "requests": list(batch[i:]),
                        })
                        return
                    delivered.append(batch[i])
                    i += 1
                mc_step("run-end")
                h.finish_run()

        def mover() -> None:
            h.request()
            try:
                snap = h.wait(timeout=5.0)
            except TimeoutError:
                return  # move failed cleanly; the source kept serving
            if snap is not None:
                sid = snap.get("snapshot_id")
                if sid is not None and sid in restored_ids:
                    return  # duplicate delivery: deduped, never re-served
                if sid is not None:
                    restored_ids.add(sid)
                restored.extend(snap["requests"])

        def check() -> None:
            got = sorted(delivered + restored)
            want = sorted(submitted)
            if got != want:
                lost = [r for r in want if r not in got]
                dup = [r for r in got if got.count(r) > 1]
                raise InvariantViolation(
                    "tokens-delivered-exactly-once broken: "
                    f"delivered={delivered} restored={restored} "
                    f"submitted={submitted} lost={sorted(set(lost))} "
                    f"duplicated={sorted(set(dup))}"
                )

        return Harness([("serve", source), ("mover", mover)], check)


# ---------------------------------------------------------------------------
# gang-2PC
# ---------------------------------------------------------------------------


class _FixedPlanRouter(ShardRouter):
    """The real 2PC driver with planning stubbed to a fixed placement:
    scoring is not the protocol under test, and a fixed plan keeps the
    schedule space on the prepare/decide/commit/resolve machinery."""

    def __init__(self, *args: Any, plans: dict[str, list[dict]], **kw: Any):
        super().__init__(*args, **kw)
        self._plans = plans

    def _plan_group(self, pods: Any) -> tuple[list[dict], str]:
        group = P.gang_group(pods[0])
        return [dict(m) for m in self._plans[group]], ""


class Gang2pcModel:
    """Two gang groups race admission over chips only one can hold."""

    def __init__(self, per_chip: int = 48, chip_units: int = 64) -> None:
        self.name = "gang2pc"
        self.per_chip = per_chip
        self.chip_units = chip_units

    def build(self) -> Harness:
        api = ModelApi()
        nodes = {
            "n0": S.synth_node("n0", "2", 2, self.chip_units),
            "n1": S.synth_node("n1", "2", 2, self.chip_units),
        }
        for node in nodes.values():
            api.add_node(node)
        groups = {"ga": ("a1", "a2"), "gb": ("b1", "b2")}
        plans: dict[str, list[dict]] = {}
        for group, members in groups.items():
            plan = []
            for member, (sid, node) in zip(
                members, (("shard-0", "n0"), ("shard-1", "n1"))
            ):
                api.add_pod(_pod(
                    member, self.per_chip,
                    annotations={
                        const.ANN_GANG_SHAPE: "1",
                        const.ANN_GANG_GROUP: group,
                    },
                    labels={
                        const.LABEL_RESOURCE_KEY: const.LABEL_RESOURCE_VALUE,
                    },
                ))
                plan.append({
                    "ns": "default", "name": member, "shard": sid,
                    "node": node, "chips": (0,), "units": self.per_chip,
                    "shape": "1", "request": self.per_chip,
                })
            plans[group] = plan
        shards = [
            ShardExtender(sid, api, informer=None, checkpoint=MemJournal())
            for sid in ("shard-0", "shard-1")
        ]
        shards[0].set_nodes([nodes["n0"]])
        shards[1].set_nodes([nodes["n1"]])
        lease = LeaderLease()
        router = _FixedPlanRouter(shards, lease=lease, plans=plans)
        pods_of = {
            g: [api.pods[("default", m)] for m in members]
            for g, members in groups.items()
        }
        outcomes: dict[str, dict] = {}

        def drive(group: str) -> Callable[[], None]:
            def body() -> None:
                outcomes[group] = router.admit_gang_group(pods_of[group])
            return body

        def check() -> None:
            resolve_gang2pc(shards, api, lease)
            # 1. no pending gang2pc journal entry after resolve
            for shard in shards:
                left = shard.twopc_pending()
                if left:
                    raise InvariantViolation(
                        f"{shard.shard_id} still holds gang2pc journal "
                        f"entries after resolve: {left}"
                    )
            # 2. no partial gang visible in the apiserver
            for group, members in groups.items():
                bound = [
                    bool(P.gang_chips_from_annotation(api.pods[("default", m)]))
                    for m in members
                ]
                if any(bound) and not all(bound):
                    raise InvariantViolation(
                        f"partial gang {group}: member states {bound} "
                        f"(outcomes: {outcomes})"
                    )
            # 3. no orphaned reservation: anything still in a ledger must
            # protect a COMMITTED member pending watch visibility
            annotated: dict[tuple[str, str], dict[int, int]] = {}
            for (ns, name), pod in api.pods.items():
                usage = P.gang_usage_by_chip(pod)
                if usage:
                    annotated[(ns, name)] = usage
            reserved: dict[str, dict[int, int]] = {}
            for shard in shards:
                for key, members_r in shard._ledger.gang_snapshot().items():
                    if key[0] != GANG2PC_NS:
                        raise InvariantViolation(
                            f"foreign ledger key {key} in {shard.shard_id}"
                        )
                    _group, _, podref = key[1].partition("/")
                    ns, _, name = podref.partition("/")
                    pod = api.pods.get((ns, name))
                    if pod is None or not P.gang_chips_from_annotation(pod):
                        raise InvariantViolation(
                            f"orphaned gang reservation {key} on "
                            f"{shard.shard_id}: pod not committed"
                        )
                    # committed & reserved: count ONCE (the reservation
                    # protects exactly the annotated usage)
                    node = P.node_name(pod)
                    row = reserved.setdefault(node, {})
                    for chip, units in members_r:
                        row[chip] = max(row.get(chip, 0), units)
            # 4. no per-chip overcommit: annotations are the persisted
            # truth; a committed member's reservation duplicates its own
            # annotation and must not double-count
            for node_name in nodes:
                cap = self.chip_units
                usage: dict[int, int] = {}
                for (ns, name), per_chip in annotated.items():
                    pod = api.pods[(ns, name)]
                    if P.node_name(pod) != node_name:
                        continue
                    for chip, units in per_chip.items():
                        usage[chip] = usage.get(chip, 0) + units
                for chip, units in usage.items():
                    if units > cap:
                        raise InvariantViolation(
                            f"chip {node_name}/{chip} overcommitted: "
                            f"{units} > {cap} (outcomes: {outcomes})"
                        )

        return Harness(
            [("admit-ga", drive("ga")), ("admit-gb", drive("gb"))], check
        )


class Gang2pcResolveModel:
    """A LIVE reconciler pass racing a live coordinator, with a second
    group competing for one chip — the race that found a real defect.

    Groups: A = (a1@n0/chip0, a2@n1/chip0), B = (b1@n0/chip0,
    b2@n1/chip1): B conflicts with A only on n0/chip0. Threads: the two
    coordinators plus a concurrent ``resolve_gang2pc`` pass.

    ``gated=False`` reproduces the pre-fix ``shards.main`` wiring — the
    resolve loop ran WITHOUT the coordinator lease, so it presumed-
    aborted a live coordinator's undecided prepare; group B then booked
    the freed chip and group A's durable decision rolled forward on top
    of it (n0/chip0 at 96 > 64). ``gated=True`` is the fixed wiring
    (one lease shared by router and resolver; the live-prepare grace in
    ``resolve_gang2pc``) and must be clean — both pinned by
    tests/test_tpumc.py."""

    def __init__(self, gated: bool = True, per_chip: int = 48,
                 chip_units: int = 64) -> None:
        self.name = (
            "gang2pc-resolve" if gated else "gang2pc-resolve-ungated"
        )
        self.gated = gated
        self.per_chip = per_chip
        self.chip_units = chip_units

    def build(self) -> Harness:
        api = ModelApi()
        nodes = {
            "n0": S.synth_node("n0", "2", 2, self.chip_units),
            "n1": S.synth_node("n1", "2", 2, self.chip_units),
        }
        for node in nodes.values():
            api.add_node(node)
        members = {
            "ga": (("a1", "shard-0", "n0", (0,)),
                   ("a2", "shard-1", "n1", (0,))),
            "gb": (("b1", "shard-0", "n0", (0,)),
                   ("b2", "shard-1", "n1", (1,))),
        }
        plans: dict[str, list[dict]] = {}
        for group, ms in members.items():
            plan = []
            for (member, sid, node, chips) in ms:
                api.add_pod(_pod(
                    member, self.per_chip,
                    annotations={
                        const.ANN_GANG_SHAPE: "1",
                        const.ANN_GANG_GROUP: group,
                    },
                    labels={
                        const.LABEL_RESOURCE_KEY: const.LABEL_RESOURCE_VALUE,
                    },
                ))
                plan.append({
                    "ns": "default", "name": member, "shard": sid,
                    "node": node, "chips": chips, "units": self.per_chip,
                    "shape": "1", "request": self.per_chip,
                })
            plans[group] = plan
        shards = [
            ShardExtender(sid, api, informer=None, checkpoint=MemJournal())
            for sid in ("shard-0", "shard-1")
        ]
        shards[0].set_nodes([nodes["n0"]])
        shards[1].set_nodes([nodes["n1"]])
        lease = LeaderLease()
        router = _FixedPlanRouter(shards, lease=lease, plans=plans)
        pods_of = {
            g: [api.pods[("default", m[0])] for m in ms]
            for g, ms in members.items()
        }

        def drive(group: str) -> Callable[[], None]:
            def body() -> None:
                router.admit_gang_group(pods_of[group])
            return body

        def live_resolve() -> None:
            # gated = the fixed shards.main wiring (shared lease);
            # ungated = the pre-fix wiring (lease-less resolve loop)
            resolve_gang2pc(shards, api, lease if self.gated else None)

        def check() -> None:
            resolve_gang2pc(shards, api, lease)
            for shard in shards:
                left = shard.twopc_pending()
                if left:
                    raise InvariantViolation(
                        f"{shard.shard_id} pending after resolve: {left}"
                    )
            for group, ms in members.items():
                bound = [
                    bool(P.gang_chips_from_annotation(
                        api.pods[("default", m[0])]
                    ))
                    for m in ms
                ]
                if any(bound) and not all(bound):
                    raise InvariantViolation(
                        f"partial gang {group}: {bound}"
                    )
            for node_name in nodes:
                usage: dict[int, int] = {}
                for pod in api.pods.values():
                    if P.node_name(pod) != node_name:
                        continue
                    for chip, units in P.gang_usage_by_chip(pod).items():
                        usage[chip] = usage.get(chip, 0) + units
                for chip, units in usage.items():
                    if units > self.chip_units:
                        raise InvariantViolation(
                            f"chip {node_name}/{chip} overcommitted: "
                            f"{units} > {self.chip_units}"
                        )

        return Harness(
            [
                ("admit-ga", drive("ga")),
                ("admit-gb", drive("gb")),
                ("resolve", live_resolve),
            ],
            check,
        )


# ---------------------------------------------------------------------------
# move protocol
# ---------------------------------------------------------------------------


class _ModelPodSource:
    """The pod-source surface the mover consults: chip usage derived
    straight from the apiserver stub's annotations."""

    def __init__(self, api: ModelApi) -> None:
        self._api = api

    def chip_state(self) -> tuple[dict[int, int], set[int]]:
        mem_used: dict[int, int] = {}
        for pod in self._api.pods.values():
            if not P.is_assigned(pod):
                continue
            if P.phase(pod) in ("Succeeded", "Failed"):
                continue
            idx = P.chip_idx_from_annotation(pod)
            units = P.mem_units_of_pod(pod)
            if idx >= 0 and units > 0:
                mem_used[idx] = mem_used.get(idx, 0) + units
        return mem_used, set()

    def note_pod_update(self, pod: dict) -> None:
        self._api.pods[(P.namespace(pod), P.name(pod))] = copy.deepcopy(pod)


class MoveModel:
    """The journaled move protocol racing a concurrent admission for the
    destination chip's last capacity."""

    def __init__(
        self,
        capacity: int = 64,
        moved_units: int = 40,
        admit_units: int = 40,
        with_reconciler: bool = False,
    ) -> None:
        self.name = "move-reconciler" if with_reconciler else "move"
        self.capacity = capacity
        self.moved_units = moved_units
        self.admit_units = admit_units
        self.with_reconciler = with_reconciler

    def build(self) -> Harness:
        from gpushare_device_plugin_tpu.allocator.defrag import move_key

        cap = {0: self.capacity, 1: self.capacity}
        api = ModelApi()
        api.add_pod(_pod(
            "p0", self.moved_units, node="n0", phase="Running",
            annotations={
                const.ENV_MEM_IDX: "0",
                const.ENV_MEM_POD: str(self.moved_units),
                const.ENV_ASSIGNED_FLAG: "true",
                const.ENV_ASSUME_TIME: "1",
            },
            labels={const.LABEL_RESOURCE_KEY: const.LABEL_RESOURCE_VALUE},
        ))
        api.add_pod(_pod("q", self.admit_units, node="n0"))
        assume = AssumeCache()
        ckpt = MemJournal()
        source = _ModelPodSource(api)
        mover = SliceMover(
            api, source, assume, ckpt, "n0", lambda: dict(cap),
        )
        plan = MovePlan(
            pod=("default", "p0"), src=0, dst=1, units=self.moved_units,
        )

        def run_move() -> None:
            mover.execute(plan)

        def admit() -> None:
            key = ("default", "q")
            if not assume.claim(key):
                return
            chip = None
            with assume.transaction():
                mem_used, core_held = assume.overlaid_state(source.chip_state)
                for c in sorted(cap):
                    if c in core_held:
                        continue
                    if cap[c] - mem_used.get(c, 0) >= self.admit_units:
                        chip = c
                        break
                if chip is None:
                    assume.release(key)
                    return
                assume.reserve_mem(key, chip, self.admit_units)
            api.patch_pod("default", "q", {"metadata": {"annotations": {
                const.ENV_MEM_IDX: str(chip),
                const.ENV_MEM_POD: str(self.admit_units),
                const.ENV_ASSIGNED_FLAG: "true",
                const.ENV_ASSUME_TIME: "2",
            }}})
            assume.release(key)

        def reconcile_pass() -> None:
            for key, data in ckpt.pending().items():
                if data.get("kind") != "move":
                    continue
                if assume.is_claimed(key):
                    continue  # a live mover owns it (the real
                    # reconciler's claim gate)
                resolve_move(ckpt, assume, api, key, data)

        def check() -> None:
            reconcile_pass()
            if ckpt.pending():
                raise InvariantViolation(
                    f"pending move entries after resolve: {ckpt.pending()}"
                )
            claims, mem, core = assume.snapshot()
            gang = assume.gang_snapshot()
            if claims or mem or core or gang:
                raise InvariantViolation(
                    "ledger not drained at terminal state: "
                    f"claims={claims} mem={mem} core={core} gang={gang}"
                )
            if assume.is_claimed(move_key(plan.pod)):
                raise InvariantViolation("move claim leaked")
            usage: dict[int, int] = {}
            p0 = api.pods[("default", "p0")]
            idx = P.chip_idx_from_annotation(p0)
            if idx not in (0, 1):
                raise InvariantViolation(f"p0 on no valid chip: {idx}")
            for pod in api.pods.values():
                if not P.is_assigned(pod):
                    continue
                pidx = P.chip_idx_from_annotation(pod)
                if pidx >= 0:
                    usage[pidx] = usage.get(pidx, 0) + P.mem_units_of_pod(pod)
            for chip, units in usage.items():
                if units > cap[chip]:
                    raise InvariantViolation(
                        f"chip {chip} overcommitted: {units} > {cap[chip]} "
                        f"(usage {usage})"
                    )

        tasks = [("mover", run_move), ("admit", admit)]
        if self.with_reconciler:
            tasks.append(("reconciler", reconcile_pass))
        return Harness(tasks, check)


# ---------------------------------------------------------------------------
# KV handoff protocol
# ---------------------------------------------------------------------------


class HandoffModel:
    """The journaled prefill→decode KV-handoff protocol: movers racing
    one decode-tier import ledger (and, in the crash variant, a
    reconciler finishing what a dead incarnation journaled). All real
    code — :class:`HandoffMover`, :class:`HandoffSink`,
    :class:`HandoffImportLedger`, :class:`resolve_handoff` — over the
    in-memory journal; only the decode ENGINE is simulated (import =
    record + release pages, exactly the retire-side effect)."""

    def __init__(self, crashed: bool = False) -> None:
        self.name = "handoff-crash" if crashed else "handoff"
        self.crashed = crashed

    @staticmethod
    def _plan(hid: str, n_pages: int) -> HandoffPlan:
        return HandoffPlan(
            handoff_id=hid,
            request={"rid": hid, "prompt": [1, 2], "tokens": [3],
                     "max_new": 4, "tier": "critical"},
            meta={"page_size": 2},
            pages=tuple(
                f"kv-{hid}-{i}".encode() for i in range(n_pages)
            ),
        )

    def build(self) -> Harness:
        assume = AssumeCache()
        ckpt = MemJournal()
        # pool sized so two 2-page stagings cannot coexist: whichever
        # mover stages second degrades to re-prefill (unless the first
        # already adopted and released — both outcomes are legal; the
        # invariant is exactly-once either way)
        pool = PageAllocator(5 if self.crashed else 3)
        ledger = HandoffImportLedger()
        served: dict[str, list[str]] = {}

        def import_cb(pages, blobs, meta, record) -> None:
            # the simulated decode engine: adopting a handoff serves its
            # request and (the retire-side effect) recycles the pages
            served.setdefault(str(record["handoff_id"]), []).append("kv")
            pool.release(pages)

        def reprefill_cb(record) -> None:
            served.setdefault(
                str(record["handoff_id"]), []
            ).append("reprefill")

        sink = HandoffSink(
            ledger, pool.alloc, pool.release, import_cb, reprefill_cb
        )
        # deterministic plumbing: no wall-clock reads may steer control
        # flow (frozen clock = deadlines/breaker timeouts never fire)
        peer = HandoffPeerClient(
            sink, sleep=lambda s: None, clock=lambda: 0.0,
            breaker=CircuitBreaker("handoff-peer", clock=lambda: 0.0),
        )
        mover = HandoffMover(
            ckpt, assume, peer, fallback_fn=sink.deliver, node="mc",
        )
        expected = set()

        def run_one(hid: str, n_pages: int):
            expected.add(hid)
            return lambda: mover.execute(self._plan(hid, n_pages))

        def reconcile_pass() -> None:
            for key, data in ckpt.pending().items():
                if data.get("kind") != "handoff":
                    continue
                if assume.is_claimed(key):
                    continue  # a live mover owns it
                resolve_handoff(
                    ckpt, assume, key, data,
                    deliver_fn=sink.deliver, abort_fn=sink.abort,
                )

        if self.crashed:
            # pre-crash state a dead incarnation left behind: hc1 died
            # in "transfer" with a partial staging (rolls back to
            # re-prefill), hc2 died in "import" with a sealed staging
            # (rolls forward to a KV adopt). Journaled WITHOUT claims —
            # exactly what restart recovery sees.
            from gpushare_device_plugin_tpu.serving.handoffproto import page_crc

            for hid, phase, puts in (("hc1", "transfer", 1), ("hc2", "import", 2)):
                plan = self._plan(hid, 2)
                expected.add(hid)
                ckpt.begin(handoff_key(hid), {
                    "kind": "handoff", "handoff_id": hid,
                    "request": plan.request, "meta": plan.meta,
                    "n_pages": 2, "node": "dead", "phase": phase,
                })
                ledger.stage(hid, 2, plan.meta, pool.alloc)
                for i in range(puts):
                    ledger.put_page(
                        hid, i, plan.pages[i], page_crc(plan.pages[i])
                    )
            tasks = [
                ("mover", run_one("h3", 1)),
                ("reconciler", reconcile_pass),
            ]
        else:
            tasks = [
                ("mover-a", run_one("ha", 2)),
                ("mover-b", run_one("hb", 2)),
                ("reconciler", reconcile_pass),
            ]

        def check() -> None:
            reconcile_pass()
            if ckpt.pending():
                raise InvariantViolation(
                    f"pending handoff entries after resolve: {ckpt.pending()}"
                )
            for hid in expected:
                modes = served.get(hid, [])
                if len(modes) != 1:
                    raise InvariantViolation(
                        f"handoff {hid} served {len(modes)} times "
                        f"({modes}): exactly-once violated (all: {served})"
                    )
            if pool.free_pages != pool.total or ledger.pages_in_flight:
                raise InvariantViolation(
                    f"leaked pages at terminal state: free "
                    f"{pool.free_pages}/{pool.total}, "
                    f"{ledger.pages_in_flight} still staged"
                )
            claims, mem, core = assume.snapshot()
            if claims or mem or core or assume.gang_snapshot():
                raise InvariantViolation(
                    f"ledger not drained: claims={claims} mem={mem}"
                )

        return Harness(tasks, check)


# ---------------------------------------------------------------------------
# fleet scale-down protocol
# ---------------------------------------------------------------------------


class ScaleModel:
    """The journaled fleet scale-down protocol (cordon → drain →
    migrate → release, ``serving/router.py``): one
    :class:`ScaleExecutor` drains a replica onto a survivor while a
    rival executor races the same scale id and a reconciler pass
    interleaves. All real protocol code — :class:`ScaleExecutor`,
    :func:`resolve_scale` — over the in-memory journal; only the fleet
    binding is simulated (drain = pop rows into a snapshot, migrate =
    idempotent adopt by snapshot_id, requeue = rid-deduped re-prefill),
    exactly the side-effect shape ``serving/fleet.py`` provides.

    The crash variant seeds pre-crashed journal entries a dead
    incarnation left behind: one in ``drain`` on a replica that no
    longer exists (rolls back — the journaled rows re-queue on
    survivors) and one in ``migrate`` (rolls forward — the drained
    snapshot re-delivers, idempotently)."""

    def __init__(self, crashed: bool = False) -> None:
        self.name = "scale-crash" if crashed else "scale"
        self.crashed = crashed

    def build(self) -> Harness:
        assume = AssumeCache()
        ckpt = MemJournal()
        registry = MetricsRegistry()
        # the simulated fleet: per-replica frozen in-flight rows, and
        # which replicas are open to new routes
        inflight: dict[str, list[dict]] = {
            "e0": [{"rid": "r0"}, {"rid": "r1"}],
            "e1": [],
        }
        routable: dict[str, bool] = {"e0": True, "e1": True}
        served: dict[str, list[str]] = {}
        adopted: set[str] = set()
        expected = {"r0", "r1"}

        def adopt(snapshot: dict) -> int:
            # the survivor's restore: idempotent by snapshot_id, exactly
            # PagedSlotEngine.restore_snapshot's dedup contract
            sid = str(snapshot.get("snapshot_id", ""))
            rows = snapshot.get("rows") or []
            if not rows or sid in adopted:
                return 0
            adopted.add(sid)
            for row in rows:
                served.setdefault(str(row["rid"]), []).append("migrated")
            return len(rows)

        def cordon(engine: str) -> None:
            routable[engine] = False

        def rows_of(engine: str) -> list[dict]:
            return [dict(r) for r in inflight.get(engine, [])]

        def drain(engine: str) -> dict:
            rows = inflight.get(engine, [])
            inflight[engine] = []
            return {
                "snapshot_id": f"snap-{engine}",
                "rows": [dict(r) for r in rows],
            }

        def release(engine: str) -> None:
            inflight.pop(engine, None)
            routable.pop(engine, None)

        executor = ScaleExecutor(
            ckpt, assume,
            cordon_fn=cordon, rows_fn=rows_of, drain_fn=drain,
            migrate_fn=lambda snap, record: adopt(snap),
            release_fn=release, node="mc", registry=registry,
        )

        def deliver(scale_id: str, record: dict) -> None:
            adopt(record.get("snapshot") or {})
            release(str(record.get("engine", "")))

        def requeue(scale_id: str, record: dict) -> None:
            engine = str(record.get("engine", ""))
            if engine in routable:
                routable[engine] = True  # replica lives: just un-cordon
                return
            for row in record.get("rows") or []:
                rid = str(row["rid"])
                if rid not in served:  # rid-deduped, as in the fleet
                    served.setdefault(rid, []).append("requeued")

        def reconcile_pass() -> None:
            for key, data in ckpt.pending().items():
                if data.get("kind") != "scale":
                    continue
                if assume.is_claimed(key):
                    continue  # a live executor owns it
                resolve_scale(
                    ckpt, assume, key, data,
                    deliver_fn=deliver, requeue_fn=requeue,
                )

        def run_exec() -> None:
            executor.execute("s1", "e0")

        if self.crashed:
            # pre-crash state without claims — exactly what restart
            # recovery sees: sc1 died in "drain" on a replica that is
            # gone (rolls back: rows re-queue), sc2 died in "migrate"
            # (rolls forward: snapshot re-delivers)
            expected.update({"rc1", "rc2"})
            ckpt.begin(scale_key("sc1"), {
                "kind": "scale", "scale_id": "sc1", "engine": "gone",
                "node": "dead", "phase": "drain",
                "rows": [{"rid": "rc1"}],
            })
            ckpt.begin(scale_key("sc2"), {
                "kind": "scale", "scale_id": "sc2", "engine": "e9",
                "node": "dead", "phase": "migrate",
                "rows": [{"rid": "rc2"}],
                "snapshot": {"snapshot_id": "snap-e9",
                             "rows": [{"rid": "rc2"}]},
            })
            tasks = [
                ("executor", run_exec),
                ("reconciler", reconcile_pass),
            ]
        else:
            tasks = [
                ("executor", run_exec),
                ("rival", run_exec),
                ("reconciler", reconcile_pass),
            ]

        def check() -> None:
            reconcile_pass()
            if ckpt.pending():
                raise InvariantViolation(
                    f"pending scale entries after resolve: {ckpt.pending()}"
                )
            for rid in expected:
                modes = served.get(rid, [])
                if len(modes) != 1:
                    raise InvariantViolation(
                        f"request {rid} served {len(modes)} times "
                        f"({modes}): exactly-once violated (all: {served})"
                    )
            if routable.get("e0"):
                raise InvariantViolation(
                    "drained replica still open to routes at terminal "
                    f"state: {routable}"
                )
            claims, mem, core = assume.snapshot()
            if claims or mem or core:
                raise InvariantViolation(
                    f"ledger not drained: claims={claims} mem={mem}"
                )

        return Harness(tasks, check)


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------


# name -> zero-arg maker; the ONE registry `run --model`, `list`, and
# the suites resolve against (a model added here shows up everywhere).
MODELS: dict[str, Callable[[], Any]] = {
    "racy-counter": RacyCounterModel,
    "indep-workers": IndepWorkersModel,
    "drain-handshake": DrainModel,
    "drain-broken": lambda: DrainModel(broken=True),
    "gang2pc": Gang2pcModel,
    "gang2pc-resolve": Gang2pcResolveModel,
    "gang2pc-resolve-ungated": lambda: Gang2pcResolveModel(gated=False),
    "move": MoveModel,
    "move-reconciler": lambda: MoveModel(with_reconciler=True),
    "handoff": HandoffModel,
    "handoff-crash": lambda: HandoffModel(crashed=True),
    "scale": ScaleModel,
    "scale-crash": lambda: ScaleModel(crashed=True),
}


def get_model(name: str) -> Any:
    """A fresh model instance by registry name."""
    try:
        return MODELS[name]()
    except KeyError:
        raise SystemExit(
            f"tpumc: unknown model {name!r} (known: {', '.join(sorted(MODELS))})"
        ) from None


# (model name, k, por) per suite; k=None means exhaustive. The smoke
# suite is the tier-1 gate (tests/test_mc_smoke.py): the drain model is
# exhausted outright; the WAL-heavy protocol models are exhausted within
# the preemption bound (every schedule with <=k preemptions).
SMOKE_SUITE: tuple[tuple[str, int | None], ...] = (
    ("drain-handshake", None),
    ("gang2pc", 2),
    ("gang2pc-resolve", 1),
    ("move", 2),
    ("move-reconciler", 1),
    ("handoff", 1),
    ("handoff-crash", 2),
    ("scale", 1),
    ("scale-crash", 2),
)

FULL_SUITE: tuple[tuple[str, int | None], ...] = (
    ("drain-handshake", None),
    ("gang2pc", 2),
    ("gang2pc-resolve", 2),
    ("move", 3),
    ("move-reconciler", 2),
    ("handoff", 2),
    ("handoff-crash", 2),
    ("scale", 2),
    ("scale-crash", 2),
)
