"""Deterministic cooperative scheduler: the model checker's runtime.

One schedule = one run of a model harness in which exactly ONE logical
thread executes at a time, and control changes hands only at *yield
points*. Yield points are where the production code already talks to its
concurrency substrate:

- lock ``acquire`` (and, optionally, ``release``) through the
  ``utils/lockrank.py`` factory seam;
- event ``wait``/``set`` (``make_event`` — the drain handshake);
- condition ``wait``/``notify``;
- every ``FAULTS.fire(point)`` site (``utils/faults.py``) — which makes
  each ``checkpoint.begin/commit/abort`` durability boundary and each
  ``defrag.*``/``gang2pc.*`` protocol phase a scheduling decision, i.e.
  exactly the boundaries the chaos suites kill at;
- explicit model-level steps (:func:`mc_step`) for harness-local
  actions (a simulated serving loop's iteration boundary).

The segment of code between two yield points runs atomically with
respect to other model threads. That is a *granularity choice*, and it
is sound for this repo because the locking discipline (enforced by
tpulint's lock rules and the runtime witness) keeps every cross-thread
mutable structure behind a ranked lock — so any cross-thread conflict is
bracketed by instrumented acquires. Lock ``release`` is a recorded but
non-branching yield by default: the schedules it would add are
reorderings of segments that only touch state still guarded by other
instrumented operations; ``branch_on_release=True`` turns them into full
decision points (the explorer's self-tests use it to validate the
default on the small models).

Blocking is modeled, not real: a thread whose pending operation is not
*enabled* (acquire of a held lock, wait on an unset event) simply is not
scheduled until the state changes. "No live thread enabled" is therefore
a detected deadlock, reported like any other violation. Timed waits get
quiesce semantics: the timeout branch is enabled only once every other
thread has finished — real timeouts are seconds long, so a timeout while
the system is still making progress is noise, and this keeps every model
terminating.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Any, Callable, Iterator

# Task states.
NEW = "new"
RUNNING = "running"
PARKED = "parked"  # at a yield point, pending op recorded
DONE = "done"

# A hard cap on executed ops per run: a model that loops without
# yielding progress is a harness bug, not a schedule to explore.
MAX_OPS_PER_RUN = 200_000

Op = tuple[str, str]  # (kind, object name / point name)


class InvariantViolation(AssertionError):
    """A model invariant failed at a terminal state."""


class DeadlockDetected(RuntimeError):
    """No live task is enabled: a real cyclic wait under this schedule."""


class _MCAbort(BaseException):
    """Unwinds a parked model thread when exploration abandons the run
    (deadlock found, explorer shutdown). BaseException so no harness
    ``except Exception`` swallows the teardown."""


class Task:
    """One logical model thread."""

    __slots__ = (
        "tid", "name", "fn", "thread", "gate", "state", "pending",
        "wait_obj", "exc", "timed_out",
    )

    def __init__(self, tid: int, name: str, fn: Callable[[], Any]) -> None:
        self.tid = tid
        self.name = name
        self.fn = fn
        self.thread: threading.Thread | None = None
        self.gate = threading.Event()  # scheduler -> task handoff
        self.state = NEW
        self.pending: Op | None = None
        self.wait_obj: Any = None
        self.exc: BaseException | None = None
        self.timed_out = False  # result slot for timed waits

    def __repr__(self) -> str:  # pragma: no cover - debug only
        return f"<Task {self.tid}:{self.name} {self.state} {self.pending}>"


class MCScheduler:
    """Runs registered tasks one at a time, consulting ``controller``
    at every point where more than one task is enabled.

    ``controller`` is a callable ``(sched, enabled: list[Task]) ->
    Task`` invoked only at real decision points; ``on_op`` (optional) is
    called with ``(task, op)`` for every executed operation — the
    explorer's sleep-set filter rides it.
    """

    def __init__(
        self,
        controller: Callable[["MCScheduler", list[Task]], Task],
        on_op: Callable[[Task, Op], None] | None = None,
        branch_on_release: bool = False,
    ) -> None:
        self.controller = controller
        self.on_op = on_op
        self.branch_on_release = branch_on_release
        self.tasks: list[Task] = []
        self.trace: list[tuple[int, str, str]] = []  # (tid, kind, name)
        self.current: Task | None = None
        self.preemptions = 0
        self._sched_evt = threading.Event()
        self._tls = threading.local()
        self._aborting = False
        self._ops = 0

    # --- wiring -----------------------------------------------------------

    def spawn(self, name: str, fn: Callable[[], Any]) -> Task:
        if len(self.tasks) >= 36:
            raise ValueError("schedule ids encode tids base-36; 36 tasks max")
        task = Task(len(self.tasks), name, fn)
        self.tasks.append(task)
        return task

    def factory(self) -> "_Factory":
        """The lockrank ``set_mc_factory`` object bound to this run."""
        return _Factory(self)

    def current_task(self) -> Task | None:
        """The managed task executing on THIS os thread (None for the
        scheduler/driver thread and any unmanaged helper)."""
        return getattr(self._tls, "task", None)

    # --- task-side protocol ----------------------------------------------

    def _thread_main(self, task: Task) -> None:
        self._tls.task = task
        try:
            self._park(task, ("start", task.name), None)
            task.fn()
        except _MCAbort:
            pass
        except BaseException as e:  # noqa: BLE001 — recorded, re-raised
            # by the driver as a violation
            task.exc = e
        finally:
            task.state = DONE
            task.pending = None
            self._sched_evt.set()

    def _park(self, task: Task, op: Op, wait_obj: Any) -> None:
        """Hand control to the scheduler; return once scheduled again."""
        task.pending = op
        task.wait_obj = wait_obj
        task.state = PARKED
        self._sched_evt.set()
        task.gate.wait()
        task.gate.clear()
        if self._aborting:
            raise _MCAbort()
        task.state = RUNNING
        task.pending = None
        task.wait_obj = None
        self._record(task, op)

    def perform(self, op: Op, wait_obj: Any = None) -> None:
        """A branching yield point: park with ``op`` pending; the
        scheduler resumes this task only when ``op`` is enabled. Called
        from instrumented primitives and :func:`mc_step`. No-op when the
        calling thread is not a managed task (harness setup, terminal
        invariant checks)."""
        task = self.current_task()
        if task is None:
            return
        self._park(task, op, wait_obj)

    def note(self, op: Op) -> None:
        """A recorded, NON-branching operation (lock release, event
        clear, reentrant re-acquire): applied inline, traced, and fed to
        the sleep-set filter, but the thread keeps running."""
        task = self.current_task()
        if task is None:
            return
        self._record(task, op)

    def _record(self, task: Task, op: Op) -> None:
        self._ops += 1
        if self._ops > MAX_OPS_PER_RUN:
            raise RuntimeError(
                f"model exceeded {MAX_OPS_PER_RUN} operations — a harness "
                "loop without scheduler progress"
            )
        self.trace.append((task.tid, op[0], op[1]))
        if self.on_op is not None:
            self.on_op(task, op)

    # --- enabledness ------------------------------------------------------

    def _others_done(self, task: Task) -> bool:
        return all(t is task or t.state == DONE for t in self.tasks)

    def _enabled(self, task: Task) -> bool:
        op = task.pending
        if op is None:
            return False
        kind = op[0]
        if kind == "acquire":
            lock: MCLock = task.wait_obj
            return lock.owner is None or (lock.reentrant and lock.owner is task)
        if kind == "evt_wait":
            evt: MCEvent = task.wait_obj
            return evt.flag
        if kind == "evt_wait_timed":
            evt = task.wait_obj
            return evt.flag or self._others_done(task)
        if kind == "cond_wait":
            cond: MCCondition = task.wait_obj
            return task in cond.notified
        if kind == "cond_wait_timed":
            cond = task.wait_obj
            return task in cond.notified or self._others_done(task)
        return True  # start / fire / step / evt_set / cond_notify / ...

    # --- the drive loop ---------------------------------------------------

    def run(self) -> None:
        """Execute every spawned task to completion under the
        controller's schedule. Raises :class:`DeadlockDetected` when no
        live task is enabled, and re-raises the first task exception
        (models treat unexpected exceptions as violations)."""
        for task in self.tasks:
            task.thread = threading.Thread(
                target=self._thread_main, args=(task,),
                name=f"tpumc-{task.tid}-{task.name}", daemon=True,
            )
            task.thread.start()
        try:
            while True:
                self._sched_evt.wait()
                self._sched_evt.clear()
                live = [t for t in self.tasks if t.state == PARKED]
                starting = [t for t in self.tasks if t.state == NEW]
                if starting:
                    # a freshly spawned thread has not reached its start
                    # yield yet; let it park before deciding (brief GIL
                    # handoff, then re-check)
                    time.sleep(0)
                    self._sched_evt.set()
                    continue
                if not live:
                    if all(t.state == DONE for t in self.tasks):
                        break
                    # a resumed task is still running; wait for its next
                    # yield (its park/finish sets the event)
                    continue
                enabled = [t for t in live if self._enabled(t)]
                if not enabled:
                    if any(t.state != DONE and t.state != PARKED
                           for t in self.tasks):
                        continue  # someone still running
                    raise DeadlockDetected(
                        "no enabled task; pending: " + ", ".join(
                            f"{t.name}:{t.pending}" for t in live
                        )
                    )
                if len(enabled) == 1:
                    chosen = enabled[0]
                else:
                    chosen = self.controller(self, enabled)
                if (
                    self.current is not None
                    and chosen is not self.current
                    and self.current in enabled
                ):
                    self.preemptions += 1
                self._resume(chosen)
        finally:
            self._teardown()
        for task in self.tasks:
            if task.exc is not None:
                raise task.exc

    def _resume(self, task: Task) -> None:
        self.current = task
        task.gate.set()

    def _teardown(self) -> None:
        """Unwind any still-parked threads (deadlock, abandoned run)."""
        self._aborting = True
        for task in self.tasks:
            if task.state != DONE:
                task.gate.set()
        for task in self.tasks:
            if task.thread is not None:
                task.thread.join(timeout=5.0)

    # --- introspection ----------------------------------------------------

    def trace_text(self) -> str:
        """The executed transition trace, one op per line — the replay
        byte-identity artifact."""
        return "\n".join(
            f"{tid} {kind} {name}" for tid, kind, name in self.trace
        )


# ---------------------------------------------------------------------------
# cooperative primitives (handed out by the lockrank factory seam)
# ---------------------------------------------------------------------------


class MCLock:
    """Cooperative mutex. Managed tasks park at ``acquire`` until the
    scheduler picks them with the lock free; unmanaged threads (harness
    setup, terminal checks — which never run concurrently with model
    tasks) pass through on simple counters."""

    def __init__(self, sched: MCScheduler, name: str, reentrant: bool) -> None:
        self.sched = sched
        self.name = name
        self.reentrant = reentrant
        self.owner: Task | None = None
        self.count = 0

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        task = self.sched.current_task()
        if task is None:
            if self.owner is not None:
                raise RuntimeError(
                    f"unmanaged thread acquiring MC lock {self.name} held "
                    f"by task {self.owner.name}"
                )
            self.count += 1
            return True
        if self.owner is task:
            if not self.reentrant:
                raise DeadlockDetected(
                    f"self-deadlock: task {task.name} re-acquired "
                    f"non-reentrant lock {self.name}"
                )
            self.count += 1
            self.sched.note(("reacquire", self.name))
            return True
        self.sched.perform(("acquire", self.name), wait_obj=self)
        # scheduled => enabled => free
        self.owner = task
        self.count = 1
        return True

    def release(self) -> None:
        task = self.sched.current_task()
        if task is None:
            self.count -= 1
            return
        if self.owner is not task:
            raise RuntimeError(
                f"task {task.name} releasing lock {self.name} it does "
                "not hold"
            )
        self.count -= 1
        if self.count == 0:
            self.owner = None
        if self.sched.branch_on_release:
            self.sched.perform(("release", self.name))
        else:
            self.sched.note(("release", self.name))

    def __enter__(self) -> "MCLock":
        self.acquire()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.release()

    def locked(self) -> bool:
        return self.owner is not None or self.count > 0

    def _is_owned(self) -> bool:
        task = self.sched.current_task()
        if task is None:
            return self.count > 0
        return self.owner is task


class MCEvent:
    """Cooperative event flag: ``wait`` parks until ``set``; a timed
    wait's timeout branch is enabled only once every other task is done
    (quiesce semantics — see the module docstring)."""

    def __init__(self, sched: MCScheduler, name: str) -> None:
        self.sched = sched
        self.name = name
        self.flag = False

    def is_set(self) -> bool:
        return self.flag

    def set(self) -> None:
        self.sched.perform(("evt_set", self.name))
        self.flag = True

    def clear(self) -> None:
        self.flag = False
        self.sched.note(("evt_clear", self.name))

    def wait(self, timeout: float | None = None) -> bool:
        task = self.sched.current_task()
        if task is None:
            if not self.flag:
                raise RuntimeError(
                    f"unmanaged thread waiting on MC event {self.name}"
                )
            return True
        if timeout is None:
            self.sched.perform(("evt_wait", self.name), wait_obj=self)
            return True
        self.sched.perform(("evt_wait_timed", self.name), wait_obj=self)
        # enabled either because the flag is up or the system quiesced:
        # the flag distinguishes success from timeout, exactly like
        # threading.Event.wait's return value
        return self.flag


class MCCondition:
    """Cooperative condition variable over a reentrant MC lock (the
    shape ``make_condition`` hands out). FIFO wakeups for determinism."""

    def __init__(self, sched: MCScheduler, name: str) -> None:
        self.sched = sched
        self.name = name
        self._lock = MCLock(sched, name, reentrant=True)
        self.waiters: list[Task] = []
        self.notified: set[Task] = set()

    # lock protocol delegation
    def acquire(self, *a: Any, **kw: Any) -> bool:
        return self._lock.acquire(*a, **kw)

    def release(self) -> None:
        self._lock.release()

    def __enter__(self) -> "MCCondition":
        self.acquire()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.release()

    def wait(self, timeout: float | None = None) -> bool:
        task = self.sched.current_task()
        if task is None:
            raise RuntimeError(
                f"unmanaged thread waiting on MC condition {self.name}"
            )
        if self._lock.owner is not task:
            raise RuntimeError("cond.wait() without the lock held")
        depth, self._lock.count = self._lock.count, 0
        self._lock.owner = None
        self.waiters.append(task)
        kind = "cond_wait" if timeout is None else "cond_wait_timed"
        self.sched.perform((kind, self.name), wait_obj=self)
        woke = task in self.notified
        self.notified.discard(task)
        if task in self.waiters:
            self.waiters.remove(task)
        # re-acquire at the saved depth
        self.sched.perform(("acquire", self.name), wait_obj=self._lock)
        self._lock.owner = task
        self._lock.count = depth
        return woke or timeout is None

    def notify(self, n: int = 1) -> None:
        self.sched.perform(("cond_notify", self.name))
        for task in self.waiters[:n]:
            self.notified.add(task)

    def notify_all(self) -> None:
        self.notify(len(self.waiters))


class _Factory:
    """The object handed to ``lockrank.set_mc_factory``."""

    def __init__(self, sched: MCScheduler) -> None:
        self._sched = sched

    def lock(self, name: str) -> MCLock:
        return MCLock(self._sched, name, reentrant=False)

    def rlock(self, name: str) -> MCLock:
        return MCLock(self._sched, name, reentrant=True)

    def condition(self, name: str) -> MCCondition:
        return MCCondition(self._sched, name)

    def event(self, name: str) -> MCEvent:
        return MCEvent(self._sched, name)


# ---------------------------------------------------------------------------
# ambient session
# ---------------------------------------------------------------------------

_ACTIVE: MCScheduler | None = None


def active_scheduler() -> MCScheduler | None:
    return _ACTIVE


def mc_step(label: str) -> None:
    """A model-level yield point (a harness loop's iteration boundary).
    No-op outside an :func:`mc_session` or on unmanaged threads."""
    sched = _ACTIVE
    if sched is not None:
        sched.perform(("step", label))


@contextlib.contextmanager  # noqa: E302
def mc_session(sched: MCScheduler) -> Iterator[MCScheduler]:
    """Install ``sched`` as the process's model-checking context:
    the lockrank factory seam hands out cooperative primitives, every
    ``FAULTS.fire`` yields, and ``TPUSHARE_MC=1`` is set for code that
    wants to know. Restores everything on exit — including on the
    explorer's abandon paths."""
    import os

    from gpushare_device_plugin_tpu.utils import lockrank
    from gpushare_device_plugin_tpu.utils.faults import FAULTS

    global _ACTIVE
    if _ACTIVE is not None:
        raise RuntimeError("nested mc_session")
    _ACTIVE = sched
    lockrank.set_mc_factory(sched.factory())

    def fire_hook(point: str) -> None:
        sched.perform(("fire", point))

    FAULTS.set_yield_hook(fire_hook)
    os.environ["TPUSHARE_MC"] = "1"
    try:
        yield sched
    finally:
        os.environ.pop("TPUSHARE_MC", None)
        FAULTS.set_yield_hook(None)
        lockrank.set_mc_factory(None)
        _ACTIVE = None


