"""``python -m tools.tpumc``: run the model checker / replay a schedule.

Subcommands:

- ``run [--model NAME | --suite smoke|full] [--k N|inf] [--por on|off]
  [--max-schedules N]`` — explore and report: schedule counts, prunes,
  violations (each with its replayable schedule id). Exit 1 on any
  violation.
- ``replay <schedule-id> [--dump PATH]`` — re-execute one exact
  interleaving under the tracer + flight recorder: prints the full
  transition trace, re-raises the violation verdict, and dumps a flight
  record (logs + trace spans) next to it, so a counterexample is a
  first-class artifact instead of a flaky CI log.
- ``list`` — the model registry.
"""

from __future__ import annotations

import argparse
import sys

from .explore import Explorer, decode_schedule_id
from .models import FULL_SUITE, SMOKE_SUITE, get_model


def _parse_k(raw: str) -> int | None:
    return None if raw in ("inf", "none", "") else int(raw)


def _run(args: argparse.Namespace) -> int:
    if args.model:
        suite = [(args.model, _parse_k(args.k))]
    else:
        suite = list(SMOKE_SUITE if args.suite == "smoke" else FULL_SUITE)
        if args.k:
            suite = [(name, _parse_k(args.k)) for name, _k in suite]
    por = None if args.por == "auto" else (args.por == "on")
    total = 0
    failed = False
    for name, k in suite:
        model = get_model(name)
        explorer = Explorer(
            model, k=k, por=por, max_schedules=args.max_schedules,
            stop_on_violation=args.stop_on_violation,
        )
        result = explorer.explore()
        total += result.schedules
        print(result.summary())
        for v in result.violations:
            failed = True
            print(f"  VIOLATION {v.brief()}")
            print(f"  replay with: python -m tools.tpumc replay {v.schedule_id}")
        if result.truncated:
            failed = True  # a truncated exploration proves nothing
    print(f"tpumc: {total} schedule(s) explored across {len(suite)} model(s)")
    return 1 if failed else 0


def _replay(args: argparse.Namespace) -> int:
    from gpushare_device_plugin_tpu.utils.flightrec import FlightRecorder
    from gpushare_device_plugin_tpu.utils.tracing import TRACER

    model_name, k, _choices = decode_schedule_id(args.schedule_id)
    model = get_model(model_name)
    # counterexamples replay under full observability: every span
    # sampled, the flight recorder capturing logs from the replayed
    # protocol code, one dump per replay
    recorder = FlightRecorder()
    recorder.install(directory=args.dump_dir)
    TRACER.configure(sample_ratio=1.0)
    explorer = Explorer(model, k=k)
    try:
        with TRACER.span("tpumc.replay", attributes={
            "schedule_id": args.schedule_id, "model": model_name,
        }):
            outcome = explorer.replay(args.schedule_id)
    finally:
        dump_path = recorder.dump(f"tpumc replay {args.schedule_id}")
        recorder.uninstall()
    print(f"# replay {args.schedule_id}")
    print(f"# model={model_name} k={'inf' if k is None else k} "
          f"preemptions={outcome.preemptions}")
    print(outcome.trace)
    print(f"# flight record: {dump_path}")
    if outcome.violation is not None:
        print(f"VIOLATION [{outcome.violation.kind}] "
              f"{outcome.violation.message}")
        return 1
    print("clean: no violation on this schedule")
    return 0


def _list_models(_args: argparse.Namespace) -> int:
    from .models import MODELS

    for name in sorted(MODELS):
        print(name)
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="tpumc", description=__doc__)
    sub = parser.add_subparsers(dest="cmd", required=True)

    run_p = sub.add_parser("run", help="explore a model or a suite")
    run_p.add_argument("--model", default="", help="one model by name")
    run_p.add_argument("--suite", default="smoke", choices=["smoke", "full"])
    run_p.add_argument("--k", default="", help="preemption bound (int or 'inf')")
    run_p.add_argument("--por", default="auto", choices=["auto", "on", "off"])
    run_p.add_argument("--max-schedules", type=int, default=None)
    run_p.add_argument("--stop-on-violation", action="store_true")
    run_p.set_defaults(fn=_run)

    replay_p = sub.add_parser("replay", help="re-execute one schedule id")
    replay_p.add_argument("schedule_id")
    replay_p.add_argument(
        "--dump-dir", default="/tmp/tpumc",
        help="directory for the replay's flight-record dump",
    )
    replay_p.set_defaults(fn=_replay)

    list_p = sub.add_parser("list", help="list models")
    list_p.set_defaults(fn=_list_models)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
