"""In-memory WAL: ``AllocationCheckpoint``'s journal surface without the
disk.

Thousands of schedules re-run the protocol harnesses from scratch;
fsyncing a real file per journal record would make exploration I/O-bound
and non-deterministic in wall time. This journal keeps the exact
*semantic* surface the protocols program against —

- ``begin`` stamps a monotonic ``_seq`` into the entry and returns it;
  a same-key re-begin replaces the entry (the real loader keeps the
  newest record per key);
- ``commit``/``abort`` with ``seq`` resolve only the exact incarnation
  the caller saw (the seq-guard that keeps a slow resolver from popping
  a fresh same-key begin);
- ``pending()`` is the begun-but-unresolved map, ``last_seq`` the
  newest stamp;

— and fires the same ``checkpoint.begin|commit|abort`` fault points in
the same order (after the state change, where the durability boundary
sits), so every WAL step remains a scheduler yield point exactly like
the on-disk journal. State is mutated under the real ``checkpoint.
journal`` rank through the lockrank factory, so journal mutations are
bracketed by instrumented acquires — which is what makes the explorer's
conservative independence relation sound for them too.
"""

from __future__ import annotations

from typing import Any

from gpushare_device_plugin_tpu.utils.faults import FAULTS
from gpushare_device_plugin_tpu.utils.lockrank import make_rlock

PodKey = tuple[str, str]


class MemJournal:
    """Drop-in for ``AllocationCheckpoint`` wherever the protocols only
    need begin/commit/abort/pending/last_seq (the 2PC participant, the
    move protocol, serve-from-checkpoint warmup)."""

    def __init__(self) -> None:
        self._lock = make_rlock("checkpoint.journal")
        self._entries: dict[PodKey, dict] = {}
        self._seq = 0
        self._fenced = False
        self.path = "<memwal>"

    # --- introspection ----------------------------------------------------

    @property
    def last_seq(self) -> int:
        with self._lock:
            return self._seq

    @property
    def fenced(self) -> bool:
        with self._lock:
            return self._fenced

    def pending(self) -> dict[PodKey, dict]:
        with self._lock:
            return {k: dict(v) for k, v in self._entries.items()}

    # --- journal ops ------------------------------------------------------

    def begin(self, key: PodKey, data: dict) -> int | None:
        from gpushare_device_plugin_tpu.allocator.checkpoint import (
            StaleDaemonError,
        )

        with self._lock:
            if self._fenced:
                raise StaleDaemonError("superseded (model fence)")
            # seq stamp + entry install in ONE lock block: the real
            # journal's loader keeps the newest record per key, so a
            # same-key begin race must never let an older seq overwrite
            # a newer entry (the fire stays outside — the durability
            # boundary sits after the state change)
            self._seq += 1
            seq = self._seq
            data = dict(data)
            data["_seq"] = seq
            self._entries[key] = data
        FAULTS.fire("checkpoint.begin")
        return seq

    def commit(self, key: PodKey, seq: int | None = None) -> bool:
        resolved = self._resolve(key, seq)
        FAULTS.fire("checkpoint.commit")
        return resolved

    def abort(self, key: PodKey, seq: int | None = None) -> bool:
        resolved = self._resolve(key, seq)
        FAULTS.fire("checkpoint.abort")
        return resolved

    def _resolve(self, key: PodKey, seq: int | None) -> bool:
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                return False
            if seq is not None and entry.get("_seq") != seq:
                return False  # a newer begin owns this key now
            self._entries.pop(key, None)
            return True

    # --- lifecycle noise the real journal has -----------------------------

    def flush(self, timeout_s: float | None = None) -> bool:
        return True

    def compact(self) -> None:
        pass

    def close(self) -> None:
        pass

    def fence(self) -> None:
        """Model hook: make the next begin raise StaleDaemonError."""
        with self._lock:
            self._fenced = True

    def __repr__(self) -> str:  # pragma: no cover - debug only
        return f"<MemJournal seq={self._seq} pending={len(self._entries)}>"


def any_pending(journals: "list[MemJournal]") -> dict[Any, dict]:
    """Union of pending entries across journals (invariant checks)."""
    out: dict[Any, dict] = {}
    for j in journals:
        for key, data in j.pending().items():
            out[(j, key)] = data
    return out
