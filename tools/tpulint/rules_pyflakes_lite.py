"""The pyflakes subset `make lint` gates on: unused imports and unused
local variables.

The image does not ship pyflakes (and nothing may be installed), so the
Makefile's old ``pyflakes ... || true`` was doubly toothless: the tool
was missing AND failures were swallowed. ``make lint`` now runs
``python -m tools.tpulint --pyflakes``, which prefers the real pyflakes
when importable and otherwise runs these two rules — either way the
exit code gates the build.

Both rules are tuned for zero false positives over recall:

- **unused-import** skips ``__init__.py`` (re-export idiom), ``from
  __future__``, star imports, and anything whose bound name appears in
  a Load/attribute context or in ``__all__``.
- **unused-local** flags only simple single-target assignments whose
  name is never loaded anywhere in the function (nested scopes
  included), skipping ``_``-prefixed names, tuple unpacking, for/with
  targets, augmented assignments, and functions that use
  ``locals``/``eval``/``exec``/``vars``.
"""

from __future__ import annotations

import ast

from .engine import Finding, Module


def _bound_names(node: ast.stmt) -> list[tuple[str, int]]:
    out = []
    if isinstance(node, ast.Import):
        for alias in node.names:
            name = alias.asname or alias.name.split(".")[0]
            out.append((name, node.lineno))
    elif isinstance(node, ast.ImportFrom):
        if node.module == "__future__":
            return []
        for alias in node.names:
            if alias.name == "*":
                return []
            out.append((alias.asname or alias.name, node.lineno))
    return out


def _used_names(tree: ast.AST) -> set[str]:
    used: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name) and isinstance(
            node.ctx, (ast.Load, ast.Del)
        ):
            used.add(node.id)
        elif isinstance(node, ast.Constant) and isinstance(node.value, str):
            pass  # __all__ strings handled below
    return used


def _all_exports(tree: ast.Module) -> set[str]:
    exports: set[str] = set()
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for target in node.targets:
                if isinstance(target, ast.Name) and target.id == "__all__":
                    if isinstance(node.value, (ast.List, ast.Tuple)):
                        for elt in node.value.elts:
                            if isinstance(elt, ast.Constant) and isinstance(
                                elt.value, str
                            ):
                                exports.add(elt.value)
    return exports


def check_unused_imports(modules: list[Module]) -> list[Finding]:
    findings: list[Finding] = []
    for mod in modules:
        if mod.path.endswith("__init__.py"):
            continue  # re-export idiom
        used = _used_names(mod.tree) | _all_exports(mod.tree)
        for node in ast.walk(mod.tree):
            for name, line in _bound_names(node) if isinstance(
                node, (ast.Import, ast.ImportFrom)
            ) else []:
                if name not in used:
                    findings.append(
                        Finding(
                            mod.path, line, "unused-import",
                            f"{name!r} imported but unused",
                        )
                    )
    return findings


_DYNAMIC = {"locals", "vars", "eval", "exec", "globals"}


def _own_scope_stmts(fn: ast.AST):
    """Statement-level nodes of ``fn``'s own scope: walk, but do not
    descend into nested function/class definitions."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)
        ):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def check_unused_locals(modules: list[Module]) -> list[Finding]:
    findings: list[Finding] = []
    for mod in modules:
        for fn in ast.walk(mod.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            calls = {
                n.func.id
                for n in ast.walk(fn)
                if isinstance(n, ast.Call) and isinstance(n.func, ast.Name)
            }
            if calls & _DYNAMIC:
                continue
            # Loads count across nested scopes (closures capture), but
            # stores are THIS function's own statements only — an
            # assignment inside a nested def/class is that scope's
            # binding (a nested class's `protocol_version = ...` is a
            # class attribute the framework reads, not a dead local).
            loads: set[str] = set()
            stores: dict[str, int] = {}
            aug: set[str] = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.Name) and isinstance(
                    node.ctx, (ast.Load, ast.Del)
                ):
                    loads.add(node.id)
            for node in _own_scope_stmts(fn):
                if isinstance(node, ast.AugAssign) and isinstance(
                    node.target, ast.Name
                ):
                    aug.add(node.target.id)
                elif isinstance(node, ast.Assign) and len(node.targets) == 1:
                    t = node.targets[0]
                    if isinstance(t, ast.Name):
                        stores[t.id] = node.lineno
                elif isinstance(node, ast.AnnAssign) and isinstance(
                    node.target, ast.Name
                ):
                    stores[node.target.id] = node.lineno
            for name, line in sorted(stores.items(), key=lambda kv: kv[1]):
                if name.startswith("_") or name in loads or name in aug:
                    continue
                findings.append(
                    Finding(
                        mod.path, line, "unused-local",
                        f"local variable {name!r} assigned but never used",
                    )
                )
    return findings
