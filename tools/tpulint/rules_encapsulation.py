"""Ledger-encapsulation rule: the concurrent ledgers' internals are
touched only inside their own modules.

PR 6's gang work hit exactly this class of bug: a helper outside the
index module updated one aggregate of the usage ledger and missed its
sibling, and only a 16-way admission storm caught the double-booking.
The ledgers' whole correctness story is that every mutation goes
through their locked methods — so any ``obj._mem``-style reach into
another object's protected state from outside the defining module is a
defect, whether it reads (unlocked snapshot: torn reads) or writes
(bypasses the lock and the invariant maintenance).

``self._attr`` within any class is fine (that is the object's own
state); the defining module is fine (the implementation); everything
else is flagged. Tests exercise the rule against fixtures; the
production tree must be clean with zero waivers.
"""

from __future__ import annotations

import ast

from .engine import Finding, Module

# class -> (defining module suffix, protected attributes)
PROTECTED: dict[str, tuple[str, frozenset[str]]] = {
    "AssumeCache": (
        "allocator/assume.py",
        frozenset({"_claimed", "_mem", "_core", "_gang", "_stamps"}),
    ),
    "ClusterUsageIndex": (
        "extender/index.py",
        frozenset({"_nodes", "_gen", "_epoch"}),
    ),
    "NodeChipUsage": (
        "cluster/usage.py",
        frozenset({"_mem_used", "_core_refs"}),
    ),
    # the multi-LoRA residency ledger: pin counts and the LRU clock are
    # the same class of state as the allocator refcounts above — a read
    # outside the lock is a torn hit-ratio, a write is a double-release
    "AdapterCache": (
        "serving/adapters.py",
        frozenset({"_entries", "_clock"}),
    ),
}

_ATTR_TO_CLASS: dict[str, str] = {
    attr: cls for cls, (_mod, attrs) in PROTECTED.items() for attr in attrs
}

# Sharded-extender discipline (ISSUE 14, the PR 6 double-booking class
# one layer up): shard code books cross-shard gang reservations in an
# AssumeCache, and it may ONLY do so through the 2PC reserve API below.
# The single-chip reservation families (reserve_mem/reserve_core), the
# reconciler-only surface (release_if_unclaimed, snapshot), the
# transaction scope, and the list-mode serial lock are all off limits —
# a shard reaching for them bypasses the all-or-nothing gang entry that
# makes a partial cross-shard booking structurally impossible.
TWOPC_MODULE_SUFFIX = "shards.py"
TWOPC_ALLOWED = frozenset({
    "claim", "renew", "is_claimed", "release", "reserve_gang",
    "gang_snapshot", "expire_stale",
})
TWOPC_FORBIDDEN = frozenset({
    "reserve_mem", "reserve_core", "snapshot", "release_if_unclaimed",
    "transaction", "overlaid_state", "serial_lock",
})
_LEDGER_RECEIVER_HINTS = ("ledger", "assume")


def _ledger_receiver(node: ast.expr) -> bool:
    """Curated receiver-name hints, rules_locks style: `self._ledger`,
    `shard._ledger`, `assume`, ..."""
    if isinstance(node, ast.Name):
        name = node.id
    elif isinstance(node, ast.Attribute):
        name = node.attr
    else:
        return False
    name = name.lstrip("_").lower()
    return any(h in name for h in _LEDGER_RECEIVER_HINTS)


def check_encapsulation(modules: list[Module]) -> list[Finding]:
    findings: list[Finding] = []
    for mod in modules:
        if not mod.in_package:
            continue
        exempt = {
            cls for cls, (suffix, _a) in PROTECTED.items()
            if mod.path.endswith(suffix)
        }
        shard_module = mod.path.endswith(TWOPC_MODULE_SUFFIX)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Attribute):
                continue
            if (
                shard_module
                and node.attr in TWOPC_FORBIDDEN
                and _ledger_receiver(node.value)
            ):
                findings.append(
                    Finding(
                        mod.path, node.lineno, "ledger-encapsulation",
                        f"shard code calls AssumeCache.{node.attr} — the "
                        "sharded extender may touch the ledger only "
                        "through the 2PC reserve API "
                        f"({'/'.join(sorted(TWOPC_ALLOWED))}); anything "
                        "else can book a partial cross-shard gang",
                    )
                )
                continue
            cls = _ATTR_TO_CLASS.get(node.attr)
            if cls is None or cls in exempt:
                continue
            if isinstance(node.value, ast.Name) and node.value.id == "self":
                continue  # a class's own attribute of the same name
            findings.append(
                Finding(
                    mod.path, node.lineno, "ledger-encapsulation",
                    f"access to {cls}.{node.attr} outside "
                    f"{PROTECTED[cls][0]} — ledger internals must be "
                    "reached through the locked methods "
                    "(snapshot/overlaid_state/node_state/...), never "
                    "directly",
                )
            )
    return findings
