"""Ledger-encapsulation rule: the concurrent ledgers' internals are
touched only inside their own modules.

PR 6's gang work hit exactly this class of bug: a helper outside the
index module updated one aggregate of the usage ledger and missed its
sibling, and only a 16-way admission storm caught the double-booking.
The ledgers' whole correctness story is that every mutation goes
through their locked methods — so any ``obj._mem``-style reach into
another object's protected state from outside the defining module is a
defect, whether it reads (unlocked snapshot: torn reads) or writes
(bypasses the lock and the invariant maintenance).

``self._attr`` within any class is fine (that is the object's own
state); the defining module is fine (the implementation); everything
else is flagged. Tests exercise the rule against fixtures; the
production tree must be clean with zero waivers.
"""

from __future__ import annotations

import ast

from .engine import Finding, Module

# class -> (defining module suffix, protected attributes)
PROTECTED: dict[str, tuple[str, frozenset[str]]] = {
    "AssumeCache": (
        "allocator/assume.py",
        frozenset({"_claimed", "_mem", "_core", "_gang", "_stamps"}),
    ),
    "ClusterUsageIndex": (
        "extender/index.py",
        frozenset({"_nodes", "_gen", "_epoch"}),
    ),
    "NodeChipUsage": (
        "cluster/usage.py",
        frozenset({"_mem_used", "_core_refs"}),
    ),
}

_ATTR_TO_CLASS: dict[str, str] = {
    attr: cls for cls, (_mod, attrs) in PROTECTED.items() for attr in attrs
}


def check_encapsulation(modules: list[Module]) -> list[Finding]:
    findings: list[Finding] = []
    for mod in modules:
        if not mod.in_package:
            continue
        exempt = {
            cls for cls, (suffix, _a) in PROTECTED.items()
            if mod.path.endswith(suffix)
        }
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Attribute):
                continue
            cls = _ATTR_TO_CLASS.get(node.attr)
            if cls is None or cls in exempt:
                continue
            if isinstance(node.value, ast.Name) and node.value.id == "self":
                continue  # a class's own attribute of the same name
            findings.append(
                Finding(
                    mod.path, node.lineno, "ledger-encapsulation",
                    f"access to {cls}.{node.attr} outside "
                    f"{PROTECTED[cls][0]} — ledger internals must be "
                    "reached through the locked methods "
                    "(snapshot/overlaid_state/node_state/...), never "
                    "directly",
                )
            )
    return findings
