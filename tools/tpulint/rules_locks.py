"""Lock-order, IO-under-lock, and unranked-lock rules.

The ground truth is the declared ranking in
``gpushare_device_plugin_tpu.utils.lockrank.RANKS`` plus the factory
calls (``make_lock("name")``) that bind every lock attribute in the
package to a rank. From the ASTs this module builds:

1. a **lock table**: (class, attribute) -> rank, from factory-call
   assignments (including the match-stripe list comprehension);
2. per-function **summaries** via a fixpoint over the package call
   graph: the set of ranks a call may acquire transitively, whether it
   may block on I/O, and — for lock-returning helpers like
   ``AssumeCache.transaction()`` / ``_serial_guard()`` — the rank their
   returned context manager acquires;
3. the **acquisition graph**: for every ``with``-held rank, an edge to
   every rank acquired inside the block (directly nested ``with``s and
   through resolved calls).

Checks:
- ``lock-order``: every edge must go strictly up-rank (same-lock
  re-entry is legal for rlocks/conditions), and the edge graph must be
  acyclic.
- ``lock-io``: no blocking call (apiserver verbs, checkpoint journal
  waits, fsync, sleep, Ticket.wait, informer refresh) may run while a
  lock whose rank declares ``io_ok=False`` is held.
- ``lock-unranked``: no ``threading.Lock/RLock/Condition`` constructed
  directly in the package — everything goes through the ranked factory
  so both this analyzer and the runtime witness see it.

Resolution is deliberately curated rather than clever: cross-object
calls resolve only through the receiver-name hints below, and only when
the named method actually exists on the hinted class. Unresolvable
calls are skipped (under-approximation) — the rule set must hold with
zero waivers on the real tree, so precision beats recall at the margin;
the runtime witness covers what static resolution cannot see.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Any, Iterable

from gpushare_device_plugin_tpu.utils.lockrank import RANKS

from .engine import Finding, Module

FACTORY_FUNCS = {"make_lock", "make_rlock", "make_condition"}
LOCKRANK_PATH = "gpushare_device_plugin_tpu/utils/lockrank.py"

# Receiver-name -> candidate classes, for cross-object call resolution.
# A method call binds only when the method exists on the hinted class.
RECEIVER_HINTS: list[tuple[re.Pattern[str], tuple[str, ...]]] = [
    (re.compile(r"^_?(assume|ledger)$"), ("AssumeCache",)),
    (re.compile(r"^_?(ckpt|checkpoint)$"), ("AllocationCheckpoint",)),
    (re.compile(r"^_?(pods|pod_source|informer)$"), ("PodInformer",)),
    (re.compile(r"^_?usage$"), ("NodeChipUsage",)),
    (re.compile(r"^_?index$"), ("ClusterUsageIndex",)),
    (
        re.compile(r"^(ix|_pending|_labeled)$"),
        (
            "ClusterUsageIndex", "NodeChipUsage", "PendingPodIndex",
            "LabeledPodIndex", "_BucketedPodIndex",
        ),
    ),
    (re.compile(r"^_?(writer|batcher)$"), ("GroupBatcher",)),
    (re.compile(r"^_?(registry|REGISTRY)$", re.IGNORECASE), ("MetricsRegistry",)),
    (re.compile(r"^FAULTS$"), ("FaultRegistry",)),
    (re.compile(r"^_?(api|c|client)$"), ("ApiServerClient",)),
    (re.compile(r"^ticket$"), ("Ticket",)),
]

# Blocking-call seeds for the IO rule. Cross-object calls resolved to
# these (class, method) pairs — or to any ApiServerClient verb — block
# on I/O; so do the direct calls below.
IO_SEED_METHODS = {
    ("AllocationCheckpoint", "begin"),
    ("AllocationCheckpoint", "commit"),
    ("AllocationCheckpoint", "abort"),
    ("AllocationCheckpoint", "flush"),
    ("AllocationCheckpoint", "compact"),
    ("AllocationCheckpoint", "acquire_fence"),
    ("AllocationCheckpoint", "verify_fence"),
    ("GroupBatcher", "flush"),
    ("GroupBatcher", "stop"),
    ("Ticket", "wait"),
    ("PodInformer", "refresh"),
}
IO_ALL_METHODS_CLASSES = {"ApiServerClient"}
# Direct blocking calls: module.attr form.
IO_SEED_CALLS = {("os", "fsync"), ("time", "sleep"), ("_time", "sleep")}


@dataclasses.dataclass
class FuncInfo:
    module: Module
    cls: str | None  # enclosing class name (None = module-level)
    name: str
    node: ast.FunctionDef
    acquires: set[str] = dataclasses.field(default_factory=set)
    io: bool = False
    ctx_rank: str | None = None  # rank acquired by the returned ctx manager
    calls: list[tuple[str, str]] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class ClassInfo:
    module: Module
    name: str
    bases: list[str]
    methods: dict[str, FuncInfo] = dataclasses.field(default_factory=dict)
    lock_attrs: dict[str, str] = dataclasses.field(default_factory=dict)


class _Model:
    """The package-wide lock/call model shared by the three checks."""

    def __init__(self, modules: list[Module]):
        self.modules = [m for m in modules if m.in_package]
        self.classes: dict[str, ClassInfo] = {}
        self.module_funcs: dict[str, dict[str, FuncInfo]] = {}
        self.global_funcs: dict[str, list[FuncInfo]] = {}
        self.funcs: list[FuncInfo] = []
        self._collect()
        self._fixpoint()

    # --- collection -------------------------------------------------------

    def _collect(self) -> None:
        for mod in self.modules:
            if mod.path == LOCKRANK_PATH:
                continue
            per_module = self.module_funcs.setdefault(mod.path, {})
            for node in mod.tree.body:
                if isinstance(node, ast.ClassDef):
                    ci = ClassInfo(
                        mod, node.name,
                        [b.id for b in node.bases if isinstance(b, ast.Name)],
                    )
                    # last definition wins on (unlikely) name collisions
                    self.classes[node.name] = ci
                    for sub in node.body:
                        if isinstance(sub, ast.FunctionDef):
                            fi = FuncInfo(mod, node.name, sub.name, sub)
                            ci.methods[sub.name] = fi
                            self.funcs.append(fi)
                            self._scan_lock_decls(ci, sub)
                elif isinstance(node, ast.FunctionDef):
                    fi = FuncInfo(mod, None, node.name, node)
                    per_module[node.name] = fi
                    self.global_funcs.setdefault(node.name, []).append(fi)
                    self.funcs.append(fi)
        for fi in self.funcs:
            self._summarize(fi)

    def _scan_lock_decls(self, ci: ClassInfo, fn: ast.FunctionDef) -> None:
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            target = node.targets[0]
            if not (
                isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self"
            ):
                continue
            rank = _factory_rank(node.value)
            if rank is not None:
                ci.lock_attrs[target.attr] = rank

    # --- per-function summaries -------------------------------------------

    def _summarize(self, fi: FuncInfo) -> None:
        cls = self.classes.get(fi.cls) if fi.cls else None
        for node in ast.walk(fi.node):
            if isinstance(node, ast.With):
                for item in node.items:
                    rank = self.with_item_rank(item.context_expr, fi)
                    if rank is not None:
                        fi.acquires.add(rank)
            elif isinstance(node, ast.Call):
                callee = self._resolve_call(node, fi)
                if callee is not None:
                    fi.calls.append(callee)
                if self._direct_io(node):
                    fi.io = True
            elif isinstance(node, ast.Return) and node.value is not None:
                rank = self._ctx_from_expr(node.value, fi)
                if rank is not None:
                    fi.ctx_rank = rank
        # @contextlib.contextmanager helpers: `with <lock>: yield` means
        # the returned context manager holds that lock for its body.
        if _is_contextmanager(fi.node):
            for node in ast.walk(fi.node):
                if isinstance(node, ast.With) and any(
                    isinstance(n, ast.Yield) for n in ast.walk(node)
                ):
                    for item in node.items:
                        rank = self.with_item_rank(item.context_expr, fi)
                        if rank is not None:
                            fi.ctx_rank = rank
        _ = cls

    def _ctx_from_expr(self, expr: ast.expr, fi: FuncInfo) -> str | None:
        if isinstance(expr, ast.Call) and _callee_name(expr) == "timed_acquire":
            if expr.args:
                return self.lock_expr_rank(expr.args[0], fi)
        return None

    def _direct_io(self, call: ast.Call) -> bool:
        fn = call.func
        if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name):
            if (fn.value.id, fn.attr) in IO_SEED_CALLS:
                return True
        return False

    def _resolve_call(
        self, call: ast.Call, fi: FuncInfo
    ) -> tuple[str, str] | None:
        """-> ("class" or "module:<path>", func name) key, or None."""
        fn = call.func
        if isinstance(fn, ast.Name):
            # same module first, then unique package-wide; class
            # constructors resolve to their __init__
            name = fn.id
            if name in self.module_funcs.get(fi.module.path, {}):
                return ("module:" + fi.module.path, name)
            if name in self.classes and "__init__" in self.classes[name].methods:
                return (name, "__init__")
            defs = self.global_funcs.get(name, [])
            if len(defs) == 1:
                return ("module:" + defs[0].module.path, name)
            return None
        if not isinstance(fn, ast.Attribute):
            return None
        recv = fn.value
        method = fn.attr
        if isinstance(recv, ast.Name) and recv.id == "self" and fi.cls:
            owner = self._find_method(fi.cls, method)
            if owner is not None:
                return (owner, method)
            return None
        hint = _receiver_hint_name(recv)
        if hint is None:
            return None
        for pattern, class_names in RECEIVER_HINTS:
            if pattern.match(hint):
                for cname in class_names:
                    owner = self._find_method(cname, method)
                    if owner is not None:
                        return (owner, method)
        return None

    def _find_method(self, cls_name: str, method: str) -> str | None:
        """Walk the (package-local) MRO by name; -> defining class."""
        seen = set()
        queue = [cls_name]
        while queue:
            cname = queue.pop(0)
            if cname in seen:
                continue
            seen.add(cname)
            ci = self.classes.get(cname)
            if ci is None:
                continue
            if method in ci.methods:
                return cname
            queue.extend(ci.bases)
        return None

    def func_for(self, key: tuple[str, str]) -> FuncInfo | None:
        owner, name = key
        if owner.startswith("module:"):
            return self.module_funcs.get(owner[len("module:"):], {}).get(name)
        ci = self.classes.get(owner)
        return ci.methods.get(name) if ci else None

    def call_is_io_seed(self, key: tuple[str, str]) -> bool:
        owner, name = key
        if owner in IO_ALL_METHODS_CLASSES:
            return True
        return (owner, name) in IO_SEED_METHODS

    # --- fixpoint ---------------------------------------------------------

    def _fixpoint(self) -> None:
        changed = True
        while changed:
            changed = False
            for fi in self.funcs:
                for key in fi.calls:
                    callee = self.func_for(key)
                    if self.call_is_io_seed(key) and not fi.io:
                        fi.io = True
                        changed = True
                    if callee is None:
                        continue
                    new = (callee.acquires - fi.acquires)
                    if new:
                        fi.acquires |= new
                        changed = True
                    if callee.ctx_rank and callee.ctx_rank not in fi.acquires:
                        # calling a ctx factory does not itself acquire;
                        # but `with f():` callers are handled in the edge
                        # walk — for summary purposes count it (callers
                        # that merely *call* without `with` don't hold it,
                        # a conservative over-approximation kept because
                        # every such helper in-tree is used via `with`)
                        fi.acquires.add(callee.ctx_rank)
                        changed = True
                    if callee.io and not fi.io:
                        fi.io = True
                        changed = True

    # --- expression -> rank resolution ------------------------------------

    def with_item_rank(self, expr: ast.expr, fi: FuncInfo) -> str | None:
        if isinstance(expr, ast.Call):
            name = _callee_name(expr)
            if name == "timed_acquire" and expr.args:
                return self.lock_expr_rank(expr.args[0], fi)
            if name == "nullcontext":
                return None
            key = self._resolve_call(expr, fi)
            if key is not None:
                callee = self.func_for(key)
                if callee is not None:
                    return callee.ctx_rank
            return None
        return self.lock_expr_rank(expr, fi)

    def lock_expr_rank(self, expr: ast.expr, fi: FuncInfo) -> str | None:
        if isinstance(expr, ast.Subscript):
            return self.lock_expr_rank(expr.value, fi)
        if isinstance(expr, ast.Attribute):
            attr = expr.attr
            if isinstance(expr.value, ast.Name) and expr.value.id == "self":
                if fi.cls:
                    rank = self._attr_rank(fi.cls, attr)
                    if rank is not None:
                        return rank
                return None
            hint = _receiver_hint_name(expr.value)
            if hint is not None:
                for pattern, class_names in RECEIVER_HINTS:
                    if pattern.match(hint):
                        for cname in class_names:
                            rank = self._attr_rank(cname, attr)
                            if rank is not None:
                                return rank
            return None
        if isinstance(expr, ast.Name):
            # simple local alias: stripe = self._match_locks[...]
            assigned = _local_assignment(fi.node, expr.id)
            if assigned is not None and not isinstance(assigned, ast.Name):
                return self.lock_expr_rank(assigned, fi)
            return None
        return None

    def _attr_rank(self, cls_name: str, attr: str) -> str | None:
        seen = set()
        queue = [cls_name]
        while queue:
            cname = queue.pop(0)
            if cname in seen:
                continue
            seen.add(cname)
            ci = self.classes.get(cname)
            if ci is None:
                continue
            if attr in ci.lock_attrs:
                return ci.lock_attrs[attr]
            queue.extend(ci.bases)
        return None


def _factory_rank(value: ast.expr) -> str | None:
    """make_lock("x") / [make_lock("x") for ...] -> "x"."""
    if isinstance(value, ast.ListComp):
        return _factory_rank(value.elt)
    if isinstance(value, ast.Call):
        name = _callee_name(value)
        if name in FACTORY_FUNCS and value.args:
            arg = value.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                return arg.value
    return None


def _callee_name(call: ast.Call) -> str | None:
    if isinstance(call.func, ast.Name):
        return call.func.id
    if isinstance(call.func, ast.Attribute):
        return call.func.attr
    return None


def _receiver_hint_name(expr: ast.expr) -> str | None:
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    return None


def _local_assignment(fn: ast.FunctionDef, name: str) -> ast.expr | None:
    found: ast.expr | None = None
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            if isinstance(t, ast.Name) and t.id == name:
                found = node.value
    return found


def _is_contextmanager(fn: ast.FunctionDef) -> bool:
    for dec in fn.decorator_list:
        name = dec.attr if isinstance(dec, ast.Attribute) else (
            dec.id if isinstance(dec, ast.Name) else None
        )
        if name == "contextmanager":
            return True
    return False


# --- edge extraction --------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Edge:
    outer: str
    inner: str
    path: str
    line: int
    via: str


def _walk_edges(model: _Model) -> tuple[list[Edge], list[Finding]]:
    """Edges of the acquisition graph + IO findings, from every
    with-block in the package."""
    edges: list[Edge] = []
    io_findings: list[Finding] = []

    def body_ranks_and_io(
        stmts: Iterable[ast.stmt], fi: FuncInfo
    ) -> tuple[set[tuple[str, int, str]], list[tuple[int, str]]]:
        """(ranks acquired in stmts with (rank, line, via)), blocking
        calls in stmts as (line, description). Nested withs recurse via
        the main walker, so only this level's items + calls count here.
        """
        ranks: set[tuple[str, int, str]] = set()
        blocking: list[tuple[int, str]] = []
        for stmt in stmts:
            for node in ast.walk(stmt):
                if isinstance(node, ast.With):
                    for item in node.items:
                        rank = model.with_item_rank(item.context_expr, fi)
                        if rank is not None:
                            ranks.add((rank, node.lineno, "with"))
                        if _callee_of_item(item) == "timed_acquire":
                            # timed_acquire records its wait histogram
                            # while holding the acquired lock
                            ranks.add(
                                ("metrics.registry", node.lineno,
                                 "timed_acquire")
                            )
                elif isinstance(node, ast.Call):
                    key = model._resolve_call(node, fi)
                    if key is not None:
                        callee = model.func_for(key)
                        desc = f"{key[0]}.{key[1]}"
                        if model.call_is_io_seed(key):
                            blocking.append((node.lineno, desc + " (blocking)"))
                        if callee is not None:
                            for r in callee.acquires:
                                ranks.add((r, node.lineno, desc))
                            if callee.io and not model.call_is_io_seed(key):
                                blocking.append(
                                    (node.lineno, desc + " (does I/O)")
                                )
                    if model._direct_io(node):
                        blocking.append(
                            (node.lineno, ast.unparse(node.func) + " (blocking)")
                        )
        return ranks, blocking

    for fi in model.funcs:
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.With):
                continue
            held: list[str] = []
            for item in node.items:
                rank = model.with_item_rank(item.context_expr, fi)
                if rank is not None:
                    for outer in held:
                        edges.append(
                            Edge(outer, rank, fi.module.path, node.lineno,
                                 "with-items")
                        )
                    held.append(rank)
            if not held:
                continue
            ranks, blocking = body_ranks_and_io(node.body, fi)
            for outer in held:
                for rank, line, via in ranks:
                    edges.append(Edge(outer, rank, fi.module.path, line, via))
                if not RANKS[outer].io_ok:
                    for line, desc in blocking:
                        io_findings.append(
                            Finding(
                                fi.module.path, line, "lock-io",
                                f"blocking call {desc} while holding "
                                f"{outer!r} (declared in-memory-only; "
                                f"rank {RANKS[outer].rank})",
                            )
                        )
    return edges, io_findings


def _callee_of_item(item: ast.withitem) -> str | None:
    if isinstance(item.context_expr, ast.Call):
        return _callee_name(item.context_expr)
    return None


# --- public checks ----------------------------------------------------------

# check_lock_order and check_lock_io share the model + edge walk (the
# dominant cost of a lint run: full-package AST walk + call-graph
# fixpoint). One entry, identity-checked, so the same `modules` list —
# which run_rules passes to every rule — builds the model exactly once.
_shared: list[Any] = []


def _model_and_edges(
    modules: list[Module],
) -> tuple[_Model, list["Edge"], list[Finding]]:
    if _shared and _shared[0] is modules:
        return _shared[1], _shared[2], _shared[3]
    model = _Model(modules)
    edges, io_findings = _walk_edges(model)
    _shared[:] = [modules, model, edges, io_findings]
    return model, edges, io_findings


def check_lock_order(modules: list[Module]) -> list[Finding]:
    _model, edges, _io = _model_and_edges(modules)
    findings: list[Finding] = []
    graph: dict[str, set[str]] = {}
    for e in edges:
        if e.outer == e.inner:
            if RANKS[e.outer].kind in ("rlock", "condition"):
                continue  # legal re-entry
            findings.append(
                Finding(
                    e.path, e.line, "lock-order",
                    f"non-reentrant lock {e.outer!r} re-acquired while "
                    f"held (via {e.via})",
                )
            )
            continue
        graph.setdefault(e.outer, set()).add(e.inner)
        if RANKS[e.outer].rank >= RANKS[e.inner].rank:
            findings.append(
                Finding(
                    e.path, e.line, "lock-order",
                    f"acquires {e.inner!r} (rank {RANKS[e.inner].rank}) "
                    f"while holding {e.outer!r} (rank "
                    f"{RANKS[e.outer].rank}) via {e.via} — against the "
                    "declared ranking in utils/lockrank.py",
                )
            )
    # cycle check on the observed graph (subsumed by the rank check when
    # that is clean, but reported independently per the rule contract)
    cycle = _find_cycle(graph)
    if cycle:
        findings.append(
            Finding(
                "gpushare_device_plugin_tpu", 0, "lock-order",
                "acquisition-graph cycle: " + " -> ".join(cycle),
            )
        )
    return findings


def _find_cycle(graph: dict[str, set[str]]) -> list[str] | None:
    WHITE, GRAY, BLACK = 0, 1, 2
    color = {n: WHITE for n in set(graph) | {v for vs in graph.values() for v in vs}}
    stack: list[str] = []

    def dfs(n: str) -> list[str] | None:
        color[n] = GRAY
        stack.append(n)
        for m in graph.get(n, ()):
            if color[m] == GRAY:
                return stack[stack.index(m):] + [m]
            if color[m] == WHITE:
                found = dfs(m)
                if found:
                    return found
        color[n] = BLACK
        stack.pop()
        return None

    for n in list(color):
        if color[n] == WHITE:
            found = dfs(n)
            if found:
                return found
    return None


def check_lock_io(modules: list[Module]) -> list[Finding]:
    _model, _edges, io_findings = _model_and_edges(modules)
    return io_findings


def check_unranked_locks(modules: list[Module]) -> list[Finding]:
    findings: list[Finding] = []
    for mod in modules:
        if not mod.in_package or mod.path == LOCKRANK_PATH:
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if (
                isinstance(fn, ast.Attribute)
                and isinstance(fn.value, ast.Name)
                and fn.value.id == "threading"
                and fn.attr in ("Lock", "RLock", "Condition")
            ):
                findings.append(
                    Finding(
                        mod.path, node.lineno, "lock-unranked",
                        f"threading.{fn.attr}() created directly; use "
                        "utils.lockrank.make_lock/make_rlock/"
                        "make_condition with a declared rank so the "
                        "static analyzer and runtime witness both see it",
                    )
                )
    return findings
