"""WAL-protocol rule: every journal ``begin`` is dominated by a
``commit``/``abort`` on all handled control-flow paths.

The invariant (docs/analysis.md, "WAL begin/commit protocol"): a
``checkpoint.begin(key, ...)`` journals an in-flight decision durably
*before* the apiserver PATCH leaves the node. After that:

- on every path the function completes normally on, a ``commit`` or
  ``abort`` for the entry must have run (try/except/finally aware);
- a ``return`` that skips resolution is a defect (the entry would stay
  pending with the admission concluded);
- an exception that *propagates out of the function* is legal: the
  entry stays pending on purpose — restart replay re-installs it as a
  reservation and the drift reconciler retro-resolves it against the
  apiserver. But an ``except`` handler that *swallows* the exception
  and completes normally must itself resolve (or re-raise);
- and no persist write (``patch_pod``/``bind_pod``/
  ``persist_pod_assignment``/``_persist``) may run before the first
  ``begin`` in a function that journals — the decision must be durable
  before the PATCH is on the wire ("no code proceeds past begin before
  durability" is enforced by ``begin()`` itself blocking on its fsync
  ticket; this check pins the call *order*).

Recognized begin/resolve forms: calls through a checkpoint-hinted
receiver (``self._ckpt.begin(...)``, ``ckpt.abort(...)``) and the
thin module delegation helpers — ``_journal_begin``/``_journal_resolve``
on the admission path, ``_journal_phase``/``_journal_resolve`` on the
defragmentation move path (record kind ``"move"``),
``_journal_handoff``/``_journal_resolve`` on the prefill/decode
KV-handoff path (record kind ``"handoff"``, serving/handoffproto.py),
and ``_journal_scale``/``_journal_resolve`` on the fleet scale-down
drain path (record kind ``"scale"``, serving/router.py).
The phase-style helpers journal a fresh begin for their protocol key at
every phase, so every call site carries the same domination obligation
a plain ``begin`` does.
"""

from __future__ import annotations

import ast

from .engine import Finding, Module

CKPT_RECEIVERS = ("_ckpt", "ckpt", "checkpoint", "_checkpoint")
BEGIN_HELPERS = (
    "_journal_begin", "_journal_phase", "_journal_handoff", "_journal_scale",
)
RESOLVE_HELPERS = ("_journal_resolve",)
# Cross-shard two-phase "gang2pc" records (extender/shards.py) have a
# DIFFERENT obligation than ordinary begins: a prepare legitimately
# leaves the journal entry pending across the process boundary (the
# coordinator's decision or the reconciler resolves it later), so the
# same-function domination rule does not apply. What IS checkable: the
# helper returns the begin's seq, and the seq is the ONLY handle a later
# commit/abort can seq-guard with — a call whose result is discarded
# creates an entry nobody can ever safely resolve.
TWOPC_HELPERS = ("_journal_2pc",)
RESOLVE_METHODS = ("commit", "abort")
PERSIST_CALLS = (
    "patch_pod", "bind_pod", "persist_pod_assignment", "_persist",
)


def _is_ckpt_call(node: ast.Call, methods: tuple[str, ...]) -> bool:
    fn = node.func
    if isinstance(fn, ast.Attribute) and fn.attr in methods:
        recv = fn.value
        name = None
        if isinstance(recv, ast.Name):
            name = recv.id
        elif isinstance(recv, ast.Attribute):
            name = recv.attr
        return name in CKPT_RECEIVERS
    return False


def _is_twopc_call(call: ast.Call) -> bool:
    fn = call.func
    if isinstance(fn, ast.Name):
        return fn.id in TWOPC_HELPERS
    if isinstance(fn, ast.Attribute):
        return fn.attr in TWOPC_HELPERS
    return False


def _is_begin(node: ast.stmt) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            if _is_ckpt_call(n, ("begin",)):
                return True
            if isinstance(n.func, ast.Name) and n.func.id in BEGIN_HELPERS:
                return True
    return False


def _is_resolve(node: ast.stmt) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            if _is_ckpt_call(n, RESOLVE_METHODS):
                return True
            if isinstance(n.func, ast.Name) and n.func.id in RESOLVE_HELPERS:
                return True
    return False


# Path outcomes for the CFG-lite evaluator. The machinery is shared: the
# ``span-leak`` rule (rules_spans) evaluates the same outcome lattice
# with a different resolve predicate, so both predicates thread through.
R = "resolved"      # a resolve ran; subsequent flow is fine
T = "terminated"    # raised: entry stays pending for replay (legal)
F = "fallthrough"   # completed the block without resolving yet
RET = "returned"    # returned without resolving: a defect


def stmt_outcomes(stmt: ast.stmt, is_resolve=None) -> set[str]:
    """Outcome set of one statement under ``is_resolve`` (defaults to
    the WAL commit/abort detector)."""
    if is_resolve is None:
        is_resolve = _is_resolve
    if is_resolve(stmt):
        return {R}
    if isinstance(stmt, ast.Raise):
        return {T}
    if isinstance(stmt, ast.Return):
        return {RET}
    if isinstance(stmt, ast.Try):
        body = eval_outcomes(stmt.body, is_resolve)
        if F in body and stmt.orelse:
            body = (body - {F}) | eval_outcomes(stmt.orelse, is_resolve)
        out = set(body)
        for handler in stmt.handlers:
            hout = eval_outcomes(handler.body, is_resolve)
            # a handler can be entered from any point in the body —
            # including before a resolve — so its own outcomes stand alone
            out |= hout
        if stmt.finalbody:
            fin = eval_outcomes(stmt.finalbody, is_resolve)
            if fin == {R}:
                # the finally resolves unconditionally: every exit path
                # (normal, return, raise) passes through it
                return {R}
            out |= fin - {F}
        return out
    if isinstance(stmt, ast.If):
        return eval_outcomes(stmt.body, is_resolve) | (
            eval_outcomes(stmt.orelse, is_resolve) if stmt.orelse else {F}
        )
    if isinstance(stmt, (ast.For, ast.While)):
        body = eval_outcomes(stmt.body, is_resolve)
        # the loop may run zero times (fallthrough), and break/continue
        # fold into fallthrough/retry conservatively
        out = {F} | (body - {F})
        if stmt.orelse:
            out |= eval_outcomes(stmt.orelse, is_resolve)
        return out
    if isinstance(stmt, ast.With):
        return eval_outcomes(stmt.body, is_resolve)
    if isinstance(stmt, (ast.Break, ast.Continue)):
        return {F}
    return {F}


def eval_outcomes(stmts: list[ast.stmt], is_resolve=None) -> set[str]:
    """Outcomes of executing a statement list from its start."""
    outcomes = {F}
    for stmt in stmts:
        if F not in outcomes:
            break
        outcomes.discard(F)
        outcomes |= stmt_outcomes(stmt, is_resolve)
    return outcomes


def _eval(stmts: list[ast.stmt]) -> set[str]:
    return eval_outcomes(stmts, _is_resolve)


def _path_to(stmts: list[ast.stmt], target: ast.stmt) -> list[tuple[list[ast.stmt], int]] | None:
    """Chain of (block, index) leading to ``target`` within ``stmts``."""
    for i, stmt in enumerate(stmts):
        if stmt is target:
            return [(stmts, i)]
        for block in _child_blocks(stmt):
            sub = _path_to(block, target)
            if sub is not None:
                return [(stmts, i)] + sub
    return None


def _child_blocks(stmt: ast.stmt) -> list[list[ast.stmt]]:
    blocks = []
    for field in ("body", "orelse", "finalbody"):
        val = getattr(stmt, field, None)
        if val:
            blocks.append(val)
    for handler in getattr(stmt, "handlers", []) or []:
        blocks.append(handler.body)
    return blocks


def _check_begin_site(
    fn: ast.FunctionDef, begin_stmt: ast.stmt
) -> str | None:
    """None when the begin is properly dominated; else a message."""
    path = _path_to(fn.body, begin_stmt)
    if path is None:
        return None  # begin nested in a lambda/def we don't model
    # Evaluate the continuation: the rest of each enclosing block,
    # innermost first; fallthrough propagates outward.
    outcomes = {F}
    for block, idx in reversed(path):
        if F not in outcomes:
            break
        outcomes.discard(F)
        outcomes |= _eval(block[idx + 1:])
        # when this block is a try body, an exception after the begin
        # can divert into its handlers; find the enclosing Try (if any)
        # one level up and require its handlers to resolve or re-raise
    if RET in outcomes:
        return (
            "journal begin may be followed by a return without "
            "commit()/abort() — the entry would stay pending with the "
            "admission concluded"
        )
    if F in outcomes:
        return (
            "journal begin is not dominated by commit()/abort() on every "
            "normal completion path of this function"
        )
    return None


def _broad_handler(handler: ast.excepthandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = [t] if not isinstance(t, ast.Tuple) else list(t.elts)
    return any(
        isinstance(n, ast.Name) and n.id in ("Exception", "BaseException")
        for n in names
    )


def _try_emits_unresolved(t: ast.Try) -> bool:
    """True when an exception raised in ``t``'s body can leave ``t``
    without a resolve having run: either a type no handler catches
    propagates (no broad catch), or a handler re-raises before
    resolving."""
    if t.finalbody and _eval(t.finalbody) == {R}:
        return False  # the finally resolves on every exit
    if not any(_broad_handler(h) for h in t.handlers):
        return True
    for h in t.handlers:
        if T in _eval(h.body):  # raise with no prior resolve in the handler
            return True
    return False


def _post_begin_emits_unresolved(block: list[ast.stmt], idx: int) -> bool:
    """Can a statement after the begin (at the begin's block level) raise
    an exception that escapes this level *unresolved*? Plain calls are
    assumed non-raising here — the journal API degrades instead of
    raising by design (see AllocationCheckpoint) — so the signal is
    explicit raises and try-blocks that let exceptions out unresolved."""
    for stmt in block[idx + 1:]:
        if _is_resolve(stmt):
            return False  # resolution reached; later raises are post-resolve
        if isinstance(stmt, ast.Raise):
            return True
        if isinstance(stmt, ast.Try):
            if _try_emits_unresolved(stmt):
                return True
        elif _contains_persist_call(stmt):
            # persist calls raise by contract (ApiError and friends) —
            # a bare one after begin reaches the enclosing handlers
            return True
    return False


def _contains_persist_call(stmt: ast.stmt) -> bool:
    for n in ast.walk(stmt):
        if isinstance(n, ast.Call):
            name = (
                n.func.attr if isinstance(n.func, ast.Attribute)
                else n.func.id if isinstance(n.func, ast.Name) else None
            )
            if name in PERSIST_CALLS:
                return True
    return False


def _handlers_resolve(fn: ast.FunctionDef, begin_stmt: ast.stmt) -> str | None:
    """For a begin inside a try body: a handler that *swallows* (completes
    normally or returns) an exception that can be raised unresolved after
    the begin must itself resolve. Handlers that only see
    already-resolved exceptions (re-raised by an inner resolving handler)
    are fine, as are handlers that re-raise — propagation keeps the
    entry pending for the restart replay + reconciler by design."""
    for node in ast.walk(fn):
        if not (isinstance(node, ast.Try) and _contains(node.body, begin_stmt)):
            continue
        body_path = _path_to(node.body, begin_stmt)
        assert body_path is not None
        # a begin nested deeper (an if/with inside the try body) is
        # positioned at its enclosing top-level statement
        block, idx = body_path[0]
        if not _post_begin_emits_unresolved(block, idx):
            continue
        for handler in node.handlers:
            hout = _eval(handler.body)
            if F in hout or RET in hout:
                return (
                    f"except handler at line {handler.lineno} can swallow "
                    "a failure after journal begin without "
                    "commit()/abort()"
                )
    return None


def _contains(stmts: list[ast.stmt], target: ast.stmt) -> bool:
    return _path_to(stmts, target) is not None


def check_wal_protocol(modules: list[Module]) -> list[Finding]:
    findings: list[Finding] = []
    for mod in modules:
        if not mod.in_package:
            continue
        if mod.path.endswith("allocator/checkpoint.py"):
            continue  # the journal's own implementation
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.FunctionDef):
                continue
            if node.name in BEGIN_HELPERS + RESOLVE_HELPERS + TWOPC_HELPERS:
                continue  # the thin delegation helpers themselves
            # gang2pc begins: flag DISCARDED results (an Expr statement
            # whose value is a bare _journal_2pc call) — the returned
            # seq is the resolution handle and must be kept
            for stmt in ast.walk(node):
                if (
                    isinstance(stmt, ast.Expr)
                    and isinstance(stmt.value, ast.Call)
                    and _is_twopc_call(stmt.value)
                ):
                    findings.append(Finding(
                        mod.path, stmt.lineno, "wal-protocol",
                        "gang2pc journal begin's (key, seq) result is "
                        "discarded — without the seq no commit/abort can "
                        "ever seq-guard-resolve this entry; assign or "
                        "return it",
                    ))
            begin_stmts = [s for s in ast.walk(node)
                           if isinstance(s, ast.stmt) and _is_begin(s)
                           and not any(_is_begin(c) for c in _sub_stmts(s))]
            if not begin_stmts:
                continue
            # order: no persist call on a line before the first begin
            first_begin_line = min(s.lineno for s in begin_stmts)
            for call in ast.walk(node):
                if isinstance(call, ast.Call):
                    name = (
                        call.func.attr if isinstance(call.func, ast.Attribute)
                        else call.func.id if isinstance(call.func, ast.Name)
                        else None
                    )
                    if name in PERSIST_CALLS and call.lineno < first_begin_line:
                        findings.append(
                            Finding(
                                mod.path, call.lineno, "wal-protocol",
                                f"persist call {name}() runs before the "
                                "journal begin — the decision must be "
                                "durable before the PATCH is on the wire",
                            )
                        )
            for stmt in begin_stmts:
                msg = _check_begin_site(node, stmt) or _handlers_resolve(
                    node, stmt
                )
                if msg:
                    findings.append(
                        Finding(mod.path, stmt.lineno, "wal-protocol", msg)
                    )
    return findings


def _sub_stmts(stmt: ast.stmt) -> list[ast.stmt]:
    out = []
    for block in _child_blocks(stmt):
        for s in block:
            out.append(s)
            out.extend(_sub_stmts(s))
    return out
