"""``decision-outcome`` rule: decision-provenance emission is dominated
on every handled outcome path.

The decision log (``utils/decisions.py``) exists so every admission verb
leaves a queryable "why" — a verb that emits a record on its success
path but silently returns (or falls through) on a rejection branch
produces a provenance hole that "works" in every test that only checks
behavior: the pod was refused and nothing says why. This rule makes the
hole a lint finding, mirroring the WAL rule's discipline:

- a function that calls ``DECISIONS.emit(...)`` anywhere must reach an
  emit on **every normal completion path** (fallthrough) and **every
  return**;
- an exception that *propagates out of the function* is legal, exactly
  as in ``wal-protocol``: propagation is a crash path the HTTP layer /
  gRPC error machinery records on its own, and the canonical shape
  ``except AllocationFailure: emit(outcome="error"); raise`` emits
  before re-raising anyway;
- functions with no emit call are out of scope — the rule pins the
  discipline of emitting functions, it does not decide which functions
  should emit (that is a design-review question, not a static one).

Receiver hints: ``DECISIONS`` / ``decisions`` / ``_decisions``, the
same curated-name approach as the lock, WAL, and span rules. The
decision log's own module is exempt (its ``emit`` is the primitive).

Shares the CFG-outcome machinery (R/T/F/RET lattice over
try/except/finally/loops) with ``rules_wal`` via an emit-specific
resolve predicate.
"""

from __future__ import annotations

import ast

from .engine import Finding, Module
from .rules_wal import F, RET, eval_outcomes

DECISION_RECEIVERS = ("DECISIONS", "decisions", "_decisions")
EXEMPT = ("gpushare_device_plugin_tpu/utils/decisions.py",)


def _is_emit_call(node: ast.Call) -> bool:
    fn = node.func
    if not (isinstance(fn, ast.Attribute) and fn.attr == "emit"):
        return False
    recv = fn.value
    name = None
    if isinstance(recv, ast.Name):
        name = recv.id
    elif isinstance(recv, ast.Attribute):
        name = recv.attr
    return name in DECISION_RECEIVERS


def _is_emit(stmt: ast.stmt) -> bool:
    # Compound statements never match directly — the outcome evaluator
    # recurses into their blocks instead, so an emit on ONE branch of an
    # if/try does not absolve the other branches (stricter than the WAL
    # predicate, deliberately: nothing replays a missing "why").
    if isinstance(stmt, (ast.If, ast.Try, ast.For, ast.While, ast.With)):
        return False
    for n in ast.walk(stmt):
        if isinstance(n, ast.Call) and _is_emit_call(n):
            return True
    return False


def check_decision_outcomes(modules: list[Module]) -> list[Finding]:
    findings: list[Finding] = []
    for mod in modules:
        if not mod.in_package or mod.path in EXEMPT:
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.FunctionDef):
                continue
            if not any(
                isinstance(n, ast.Call) and _is_emit_call(n)
                for n in ast.walk(node)
            ):
                continue
            outcomes = eval_outcomes(node.body, _is_emit)
            if F in outcomes:
                findings.append(
                    Finding(
                        mod.path, node.lineno, "decision-outcome",
                        f"{node.name}() emits decision records but can "
                        "complete normally without emitting — a verb "
                        "outcome with no 'why' record",
                    )
                )
            if RET in outcomes:
                findings.append(
                    Finding(
                        mod.path, node.lineno, "decision-outcome",
                        f"{node.name}() emits decision records but can "
                        "return without emitting — a verb outcome with "
                        "no 'why' record",
                    )
                )
    return findings
