"""Annotation-coverage rule: the public control-plane surface of the
strict packages (allocator/, cluster/, extender/, utils/) is fully
annotated.

This is the deterministic in-repo proxy for the mypy strict gate
configured in pyproject.toml: the image does not ship mypy (and nothing
may be installed), so ``make typecheck`` runs mypy when available and
falls back to this rule — which at minimum pins that every public
function and method (``__init__`` included) declares its parameter and
return types, the part of strict mode that regresses most easily.

Scope: module-level ``def``s and direct methods of module-level classes
whose names don't start with ``_`` (dunders other than ``__init__`` are
skipped, as are ``*args``/``**kwargs`` and ``self``/``cls``).
"""

from __future__ import annotations

import ast

from .engine import Finding, Module

STRICT_PREFIXES = tuple(
    f"gpushare_device_plugin_tpu/{p}/"
    for p in ("allocator", "cluster", "extender", "utils")
)


import builtins

_BUILTINS = frozenset(dir(builtins))


def _module_bindings(tree: ast.Module) -> frozenset[str]:
    """Names bound at module level (imports, defs, classes, assigns) —
    what an evaluated annotation could resolve against."""
    bound: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                bound.add(alias.asname or alias.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom):
            for alias in node.names:
                if alias.name != "*":
                    bound.add(alias.asname or alias.name)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            bound.add(node.name)
        elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
            bound.add(node.id)
    return frozenset(bound)


def _unresolvable(ann: ast.expr, bound: frozenset[str]) -> list[str]:
    """Names in an annotation expression that nothing binds — with
    ``from __future__ import annotations`` these pass at runtime and the
    image has no mypy/pyflakes to notice, so the gate lives here.
    String annotations (forward refs) are skipped."""
    bad = []
    for node in ast.walk(ann):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            if node.id not in bound and node.id not in _BUILTINS:
                bad.append(node.id)
    return bad


def _check_fn(
    mod: Module,
    fn: ast.FunctionDef,
    qual: str,
    bound: frozenset[str],
    findings: list[Finding],
) -> None:
    missing = [
        a.arg
        for a in fn.args.args + fn.args.kwonlyargs + fn.args.posonlyargs
        if a.annotation is None and a.arg not in ("self", "cls")
    ]
    needs_return = fn.returns is None
    undefined: list[str] = []
    annotations = [
        a.annotation
        for a in fn.args.args + fn.args.kwonlyargs + fn.args.posonlyargs
        if a.annotation is not None
    ]
    if fn.returns is not None:
        annotations.append(fn.returns)
    for ann in annotations:
        undefined.extend(_unresolvable(ann, bound))
    if missing or needs_return or undefined:
        parts = []
        if missing:
            parts.append("unannotated parameter(s): " + ", ".join(missing))
        if needs_return:
            parts.append("missing return annotation")
        if undefined:
            parts.append(
                "annotation uses undefined name(s): "
                + ", ".join(sorted(set(undefined)))
            )
        findings.append(
            Finding(
                mod.path, fn.lineno, "annotations",
                f"{qual}: " + "; ".join(parts),
            )
        )


def check_annotations(modules: list[Module]) -> list[Finding]:
    findings: list[Finding] = []
    for mod in modules:
        if not mod.path.startswith(STRICT_PREFIXES):
            continue
        bound = _module_bindings(mod.tree)
        for node in mod.tree.body:
            if isinstance(node, ast.FunctionDef):
                if not node.name.startswith("_"):
                    _check_fn(mod, node, node.name, bound, findings)
            elif isinstance(node, ast.ClassDef) and not node.name.startswith(
                "_"
            ):
                for sub in node.body:
                    if not isinstance(sub, ast.FunctionDef):
                        continue
                    public = not sub.name.startswith("_")
                    if public or sub.name == "__init__":
                        _check_fn(
                            mod, sub, f"{node.name}.{sub.name}", bound,
                            findings,
                        )
    return findings
