"""Project-specific static analysis (see engine module docstring)."""

from .engine import Finding, Module, load_modules, main, run_rules

__all__ = ["Finding", "Module", "load_modules", "main", "run_rules"]
