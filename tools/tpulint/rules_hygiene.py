"""Threaded-daemon hygiene rules.

- **broad-except-pass**: an ``except:`` / ``except Exception:`` /
  ``except BaseException:`` whose body is only ``pass`` inside the
  package. In a supervised daemon loop this silently eats the failure
  the supervisor exists to observe (narrow catches like ``except
  OSError: pass`` around best-effort cleanup are fine and not flagged).
- **unbounded-queue**: ``queue.Queue()`` with no maxsize in the
  package. An unbounded queue in front of a slow consumer is the
  outage-amplifier PR 1 removed from the event emitter; keep it out.
- **test-blind-sleep**: ``time.sleep(<constant ≥ 0.5s>)`` in tests/.
  Long blind sleeps make the suite slow *and* flaky — poll with a
  deadline instead (short poll-loop sleeps stay legal).
"""

from __future__ import annotations

import ast

from .engine import Finding, Module

BROAD = {"Exception", "BaseException"}
SLEEP_LIMIT_S = 0.5


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    if isinstance(t, ast.Name):
        return t.id in BROAD
    if isinstance(t, ast.Tuple):
        return any(isinstance(e, ast.Name) and e.id in BROAD for e in t.elts)
    return False


def _queue_unbounded(node: ast.Call) -> bool:
    """No maxsize at all, or an explicit maxsize <= 0 (queue.Queue treats
    both as unbounded)."""
    size: ast.expr | None = None
    if node.args:
        size = node.args[0]
    for kw in node.keywords:
        if kw.arg == "maxsize":
            size = kw.value
    if size is None:
        return True
    if isinstance(size, ast.Constant) and isinstance(size.value, (int, float)):
        return size.value <= 0
    return False  # dynamic maxsize: assume the caller bounded it


def check_hygiene(modules: list[Module]) -> list[Finding]:
    findings: list[Finding] = []
    for mod in modules:
        if mod.in_package:
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.ExceptHandler):
                    only_pass = len(node.body) == 1 and isinstance(
                        node.body[0], ast.Pass
                    )
                    if only_pass and _is_broad(node):
                        findings.append(
                            Finding(
                                mod.path, node.lineno, "hygiene",
                                "broad except swallowed with bare `pass` — "
                                "log it (or narrow the exception type); a "
                                "supervised loop that eats failures "
                                "silently defeats its supervisor",
                            )
                        )
                elif isinstance(node, ast.Call):
                    fn = node.func
                    is_queue = (
                        isinstance(fn, ast.Attribute)
                        and fn.attr == "Queue"
                        and isinstance(fn.value, ast.Name)
                        and fn.value.id == "queue"
                    ) or (isinstance(fn, ast.Name) and fn.id == "Queue")
                    if is_queue and _queue_unbounded(node):
                        findings.append(
                            Finding(
                                mod.path, node.lineno, "hygiene",
                                "unbounded queue.Queue() — give it a "
                                "maxsize; an unbounded queue in front of "
                                "a slow consumer amplifies outages",
                            )
                        )
        if mod.is_test:
            for node in ast.walk(mod.tree):
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "sleep"
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id in ("time", "_time")
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, (int, float))
                    and node.args[0].value >= SLEEP_LIMIT_S
                ):
                    findings.append(
                        Finding(
                            mod.path, node.lineno, "hygiene",
                            f"blind {node.args[0].value}s sleep in a test — "
                            "poll with a deadline instead (slow AND flaky)",
                        )
                    )
    return findings
