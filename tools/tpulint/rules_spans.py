"""``span-leak`` rule: every manually started tracing span is dominated
by ``end()`` on all paths.

The tracing API (``utils/tracing.py``) has two shapes: the context
manager ``with TRACER.span(...)`` — structurally leak-free, exit always
ends — and the explicit ``sp = TRACER.start_span(...)`` escape hatch for
spans whose lifetime crosses statement structure. A started span that is
never ended silently never reaches the trace store: the admission it
described vanishes from ``/traces``, the flight recorder, and the
exemplars — an observability hole that "works" in every test that only
checks behavior. This rule makes the hole a lint finding:

- ``X.start_span(...)`` whose result is discarded is a leak outright;
- an assigned span must reach a ``<var>.end(...)`` on **every** path out
  of the function — fallthrough, ``return``, and explicit ``raise``
  included (unlike the WAL rule, where propagation is legal because
  restart replay resolves the entry, nothing resolves a leaked span);
  wrap the region in ``try/finally`` or use the context manager.

``utils/tracing.py`` itself is exempt: the ``AdmissionTraces`` registry
holds per-pod root spans open across webhook verbs by design (bounded +
TTL'd there). Receiver hints: ``TRACER``/``tracer``/``_tracer``, same
curated-name approach as the lock and WAL rules.

Shares the CFG-outcome machinery with ``rules_wal`` (R/T/F/RET lattice
over try/except/finally/loops) via a span-specific resolve predicate.
"""

from __future__ import annotations

import ast

from .engine import Finding, Module
from .rules_wal import F, R, RET, T, _path_to, eval_outcomes

TRACER_RECEIVERS = ("TRACER", "tracer", "_tracer")
EXEMPT = ("gpushare_device_plugin_tpu/utils/tracing.py",)


def _is_start_span_call(node: ast.Call) -> bool:
    fn = node.func
    if not (isinstance(fn, ast.Attribute) and fn.attr == "start_span"):
        return False
    recv = fn.value
    name = None
    if isinstance(recv, ast.Name):
        name = recv.id
    elif isinstance(recv, ast.Attribute):
        name = recv.attr
    return name in TRACER_RECEIVERS


def _ends_var(var: str):
    """Resolve predicate: does this statement call ``<var>.end(...)``?"""

    def is_resolve(stmt: ast.stmt) -> bool:
        for n in ast.walk(stmt):
            if isinstance(n, ast.Call):
                fn = n.func
                if (
                    isinstance(fn, ast.Attribute)
                    and fn.attr == "end"
                    and isinstance(fn.value, ast.Name)
                    and fn.value.id == var
                ):
                    return True
        return False

    return is_resolve


def _start_assignments(
    fn: ast.FunctionDef,
) -> list[tuple[ast.stmt, str | None]]:
    """(statement, assigned-name-or-None) for every start_span call at
    statement level; None means the span object was discarded."""
    out: list[tuple[ast.stmt, str | None]] = []
    for node in ast.walk(fn):
        if not isinstance(node, (ast.Assign, ast.Expr)):
            continue
        value = node.value
        if not (isinstance(value, ast.Call) and _is_start_span_call(value)):
            continue
        var = None
        if isinstance(node, ast.Assign):
            if len(node.targets) == 1 and isinstance(node.targets[0], ast.Name):
                var = node.targets[0].id
        out.append((node, var))
    return out


def _leak_message(fn: ast.FunctionDef, stmt: ast.stmt, var: str) -> str | None:
    path = _path_to(fn.body, stmt)
    if path is None:
        return None  # inside a lambda/nested def we don't model
    is_resolve = _ends_var(var)
    outcomes = {F}
    for level in range(len(path) - 1, -1, -1):
        block, idx = path[level]
        if F in outcomes:
            outcomes.discard(F)
            outcomes |= eval_outcomes(block[idx + 1:], is_resolve)
        # Leaving a try's body/handler/orelse passes through its finally
        # on EVERY path — raise and return included — so an enclosing
        # finally that resolves unconditionally absolves all outcomes:
        # the canonical "start inside try / end() in a finally" shape.
        # (Never break early on a resolved-looking outcome set: an outer
        # resolving finally can still matter for T/RET paths.)
        owner = path[level - 1][0][path[level - 1][1]] if level else None
        if (
            isinstance(owner, ast.Try)
            and owner.finalbody
            and block is not owner.finalbody
            and eval_outcomes(owner.finalbody, is_resolve) == {R}
        ):
            return None
    leaks = []
    if F in outcomes:
        leaks.append("a normal completion path")
    if RET in outcomes:
        leaks.append("a return path")
    if T in outcomes:
        leaks.append("a raise path")
    if not leaks:
        return None
    return (
        f"span {var!r} from start_span() is not end()ed on "
        + " and ".join(leaks)
        + " — the span never reaches the trace store; use "
        "`with TRACER.span(...)` or end() in a finally"
    )


def check_span_leak(modules: list[Module]) -> list[Finding]:
    findings: list[Finding] = []
    for mod in modules:
        if mod.path in EXEMPT:
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.FunctionDef):
                continue
            for stmt, var in _start_assignments(node):
                if var is None:
                    findings.append(
                        Finding(
                            mod.path, stmt.lineno, "span-leak",
                            "start_span() result discarded — the span can "
                            "never be end()ed; use `with TRACER.span(...)`",
                        )
                    )
                    continue
                msg = _leak_message(node, stmt, var)
                if msg:
                    findings.append(
                        Finding(mod.path, stmt.lineno, "span-leak", msg)
                    )
    return findings
