"""string-consts rule: the apiserver-facing vocabulary lives in const.py.

"The apiserver is the database" makes annotation keys and injected env
names the schema of this system: ``tpushare.aliyun.com/*`` annotation
keys and the ``ALIYUN_COM_*``/``TPU_*`` env-var family are read back by
the informer indexes, the reconciler, the inspect CLI, and the pod-side
runtime. A key inlined at one of those sites can drift from the writer's
spelling and the failure is silent — the annotation simply never
matches. ``const.py`` is the declaration point; this rule flags any
inline literal of those shapes elsewhere in the package.

Exemptions, each with a reason the rule encodes rather than waives:

- ``const.py`` itself (the declarations);
- docstrings (prose, not keys);
- declared twins in :data:`DECLARED_TWINS` — ``utils/tracing.py`` must
  stay import-light (everything imports it to trace), so it carries a
  duplicate of ``const.ANN_TRACE_ID`` that ``test_tracing`` pins equal;
  the twin is *declared* here so a third copy is still a finding;
- tests and fixtures (they construct adversarial/garbled keys on
  purpose) — out of scope via the package filter.
"""

from __future__ import annotations

import ast
import re

from .engine import Finding, Module, docstring_constants

RULE = "string-consts"

CONST_PATH = "gpushare_device_plugin_tpu/const.py"

ANNOTATION_RE = re.compile(r"^tpushare\.aliyun\.com/[A-Za-z0-9._/-]+$")
ENV_RE = re.compile(r"^(ALIYUN_COM|TPU)_[A-Z0-9_]+$")

# (module path, literal) pairs that are deliberate, test-pinned twins.
DECLARED_TWINS = frozenset({
    # tracing must stay import-light (no package imports); test_tracing
    # pins this equal to const.ANN_TRACE_ID
    ("gpushare_device_plugin_tpu/utils/tracing.py",
     "tpushare.aliyun.com/trace-id"),
})


def check_string_consts(modules: list[Module]) -> list[Finding]:
    findings: list[Finding] = []
    for mod in modules:
        if not mod.in_package or mod.path == CONST_PATH:
            continue
        docstrings = docstring_constants(mod.tree)
        for node in ast.walk(mod.tree):
            if not (
                isinstance(node, ast.Constant)
                and isinstance(node.value, str)
            ):
                continue
            value = node.value
            if id(node) in docstrings:
                continue
            if not (ANNOTATION_RE.match(value) or ENV_RE.match(value)):
                continue
            if (mod.path, value) in DECLARED_TWINS:
                continue
            kind = (
                "annotation key" if value.startswith("tpushare.")
                else "env-var name"
            )
            findings.append(Finding(
                mod.path, node.lineno, RULE,
                f"inline {kind} literal {value!r} — declare it in "
                "const.py and reference the const (inlined schema "
                "strings drift silently; the reader just stops "
                "matching)",
            ))
    return findings
