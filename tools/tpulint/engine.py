"""tpulint: project-specific static analysis for the tpushare control plane.

The reference repo gates commits on ``go test -race``; this is the other
half of our Python substitute (the runtime half is the lock-order
witness in ``gpushare_device_plugin_tpu/utils/lockrank.py``). The rules
here are *project-specific theorems*, not generic style checks:

- ``lock-order`` / ``lock-io`` / ``lock-unranked`` (rules_locks):
  the lock-acquisition graph extracted from ``with`` statements and the
  cross-module call graph must be consistent with the declared ranking
  in ``utils/lockrank.py`` — no cycles, no down-rank edges, no blocking
  I/O under locks declared in-memory-only, no lock created outside the
  ranked factory.
- ``wal-protocol`` (rules_wal): every ``checkpoint.begin()`` is
  dominated by a ``commit()``/``abort()`` on all handled control-flow
  paths (try/except/finally aware; unhandled propagation is legal — the
  restart replay + reconciler resolve those), and no persist write runs
  before its begin.
- ``span-leak`` (rules_spans): every ``tracer.start_span()`` is
  dominated by ``end()`` on ALL paths out of the function (raise paths
  included — nothing replays a leaked span); discarded start_span
  results are findings outright. ``with TRACER.span(...)`` is the
  structurally-safe form. Same CFG-outcome machinery as wal-protocol.
- ``decision-outcome`` (rules_decisions): a function emitting
  decision-provenance records (``DECISIONS.emit``) reaches an emit on
  every normal completion and every return — a verb outcome with no
  "why" record is a provenance hole. Branch-precise; propagation is
  legal. Same CFG-outcome machinery.
- ``ledger-encapsulation`` (rules_encapsulation): the AssumeCache /
  ClusterUsageIndex / NodeChipUsage internals are mutated only inside
  their own modules — the exact class of bug PR 6's gang storms caught.
- ``metric-contract`` (rules_metrics): every ``tpushare_*`` metric
  family is declared once in ``utils/metric_catalog.py`` (name, type,
  label set); exporters and the CLI parsers reference catalog consts,
  call kinds match declared types, and call-site labels stay inside
  the declared label set.
- ``string-consts`` (rules_strconsts): ``tpushare.aliyun.com/*``
  annotation keys and ``ALIYUN_COM_*``/``TPU_*`` env names are declared
  in ``const.py`` only — inline schema strings drift silently.
- ``hygiene`` (rules_hygiene): threaded-daemon hygiene — no broad
  except-pass swallows, no unbounded queues, no long blind sleeps in
  tests.
- ``unused-import`` / ``unused-local`` (rules_pyflakes_lite): the
  pyflakes subset `make lint` gates on (the image does not ship
  pyflakes; when it is installed the Makefile target prefers it).
- ``annotations`` (rules_annotations): public control-plane surface in
  allocator/cluster/extender/utils is fully annotated — the
  deterministic in-repo proxy for the mypy strict gate (mypy itself is
  not in the image; ``make typecheck`` runs it when available).

Usage: ``python -m tools.tpulint [--rules a,b | --pyflakes | --typecheck]``.
Exit code 1 when any finding is reported. ``docs/analysis.md`` documents
each rule's rationale and the defects this tooling found.
"""

from __future__ import annotations

import argparse
import ast
import dataclasses
import os
import sys
from typing import Callable, Iterable

# Directories/files scanned relative to the repo root.
DEFAULT_ROOTS = (
    "gpushare_device_plugin_tpu",
    "tools",
    "tests",
    "bench.py",
    "bench_mfu.py",
    "__graft_entry__.py",
)
# Never scanned: fixtures exist to *fail* rules; pb2 is generated.
EXCLUDES = (
    "tests/lint_fixtures/",
    "gpushare_device_plugin_tpu/plugin/api/deviceplugin_pb2.py",
    "__pycache__",
)

PACKAGE_PREFIX = "gpushare_device_plugin_tpu/"


@dataclasses.dataclass(frozen=True)
class Finding:
    path: str
    line: int
    rule: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


@dataclasses.dataclass
class Module:
    """One parsed source file."""

    path: str  # repo-root-relative, posix separators
    source: str
    tree: ast.Module

    @property
    def in_package(self) -> bool:
        return self.path.startswith(PACKAGE_PREFIX)

    @property
    def is_test(self) -> bool:
        return self.path.startswith("tests/")


def _iter_files(root_dir: str, roots: Iterable[str]) -> Iterable[str]:
    for root in roots:
        full = os.path.join(root_dir, root)
        if os.path.isfile(full):
            yield root
            continue
        for dirpath, dirnames, filenames in os.walk(full):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for name in sorted(filenames):
                if not name.endswith(".py"):
                    continue
                rel = os.path.relpath(os.path.join(dirpath, name), root_dir)
                rel = rel.replace(os.sep, "/")
                if any(x in rel for x in EXCLUDES):
                    continue
                yield rel


def load_modules(
    root_dir: str, roots: Iterable[str] = DEFAULT_ROOTS
) -> list[Module]:
    modules = []
    for rel in _iter_files(root_dir, roots):
        full = os.path.join(root_dir, rel)
        with open(full, encoding="utf-8") as f:
            source = f.read()
        try:
            tree = ast.parse(source, filename=rel)
        except SyntaxError as e:
            # compileall in `make lint` reports these too, but a lint run
            # must not silently skip an unparseable file
            modules.append(
                Module(rel, source, ast.Module(body=[], type_ignores=[]))
            )
            tree = modules[-1].tree
            tree._tpulint_syntax_error = e  # type: ignore[attr-defined]
            continue
        modules.append(Module(rel, source, tree))
    return modules


def docstring_constants(tree: ast.Module) -> set[int]:
    """ids of Constant nodes in docstring position — shared by rules
    that scan string literals (docstrings are prose, never findings)."""
    out: set[int] = set()
    for node in ast.walk(tree):
        body = getattr(node, "body", None)
        if not isinstance(
            node, (ast.Module, ast.FunctionDef, ast.AsyncFunctionDef,
                   ast.ClassDef)
        ) or not body:
            continue
        first = body[0]
        if isinstance(first, ast.Expr) and isinstance(first.value, ast.Constant):
            out.add(id(first.value))
    return out


RuleFn = Callable[[list[Module]], list[Finding]]


def _registry() -> dict[str, RuleFn]:
    from . import (
        rules_annotations,
        rules_decisions,
        rules_encapsulation,
        rules_hygiene,
        rules_locks,
        rules_metrics,
        rules_pyflakes_lite,
        rules_spans,
        rules_strconsts,
        rules_wal,
    )

    return {
        "lock-order": rules_locks.check_lock_order,
        "lock-io": rules_locks.check_lock_io,
        "lock-unranked": rules_locks.check_unranked_locks,
        "wal-protocol": rules_wal.check_wal_protocol,
        "span-leak": rules_spans.check_span_leak,
        "decision-outcome": rules_decisions.check_decision_outcomes,
        "ledger-encapsulation": rules_encapsulation.check_encapsulation,
        "metric-contract": rules_metrics.check_metric_contract,
        "string-consts": rules_strconsts.check_string_consts,
        "hygiene": rules_hygiene.check_hygiene,
        "unused-import": rules_pyflakes_lite.check_unused_imports,
        "unused-local": rules_pyflakes_lite.check_unused_locals,
        "annotations": rules_annotations.check_annotations,
    }


PYFLAKES_RULES = ("unused-import", "unused-local")


def run_rules(
    modules: list[Module], rule_names: Iterable[str] | None = None
) -> list[Finding]:
    registry = _registry()
    names = list(rule_names) if rule_names is not None else list(registry)
    findings: list[Finding] = []
    for mod in modules:
        err = getattr(mod.tree, "_tpulint_syntax_error", None)
        if err is not None:
            findings.append(
                Finding(mod.path, err.lineno or 0, "syntax", str(err))
            )
    for name in names:
        if name not in registry:
            raise SystemExit(f"tpulint: unknown rule {name!r}")
        findings.extend(registry[name](modules))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def _run_real_pyflakes(root_dir: str) -> int | None:
    """Run installed pyflakes over the tree; None when not installed."""
    try:
        from pyflakes.api import checkRecursive
        from pyflakes.reporter import Reporter
    except ImportError:
        return None
    # File-by-file through the same walker the built-in rules use, so the
    # EXCLUDES list (lint fixtures, the generated pb2 module) holds for
    # both paths — checkRecursive over the raw directories would scan the
    # protobuf-generated file and fail on its runtime-injected names.
    targets = [
        os.path.join(root_dir, rel) for rel in _iter_files(root_dir, DEFAULT_ROOTS)
    ]
    return checkRecursive(targets, Reporter(sys.stdout, sys.stderr))


def _run_mypy(root_dir: str) -> int | None:
    """Run installed mypy over the strict packages; None if unavailable."""
    try:
        from mypy import api as mypy_api
    except ImportError:
        return None
    pkgs = [
        os.path.join(root_dir, "gpushare_device_plugin_tpu", p)
        for p in ("allocator", "cluster", "extender", "utils")
    ]
    stdout, stderr, status = mypy_api.run(pkgs)
    if stdout:
        sys.stdout.write(stdout)
    if stderr:
        sys.stderr.write(stderr)
    return status


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="tpulint", description=__doc__)
    parser.add_argument(
        "--rules", default="",
        help="comma-separated rule subset (default: every rule)",
    )
    parser.add_argument(
        "--root", default="",
        help="repo root to scan (default: the parent of tools/)",
    )
    parser.add_argument(
        "--pyflakes", action="store_true",
        help="pyflakes-compat mode for `make lint`: run the real pyflakes "
        "when installed, else tpulint's unused-import/unused-local rules",
    )
    parser.add_argument(
        "--typecheck", action="store_true",
        help="typecheck mode for `make typecheck`: run mypy (strict "
        "config in pyproject.toml) when installed, else the annotations "
        "rule as the deterministic in-repo fallback",
    )
    parser.add_argument("--list", action="store_true", help="list rules")
    args = parser.parse_args(argv)

    root_dir = args.root or os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    if args.list:
        for name in _registry():
            print(name)
        return 0

    if args.pyflakes:
        rc = _run_real_pyflakes(root_dir)
        if rc is not None:
            print(f"tpulint: pyflakes pass {'clean' if rc == 0 else 'FAILED'}")
            return 1 if rc else 0
        print(
            "tpulint: pyflakes not installed in this image; running the "
            "built-in unused-import/unused-local rules instead"
        )
        rule_names: Iterable[str] | None = PYFLAKES_RULES
    elif args.typecheck:
        rc = _run_mypy(root_dir)
        if rc is not None:
            print(f"tpulint: mypy pass {'clean' if rc == 0 else 'FAILED'}")
            return 1 if rc else 0
        print(
            "tpulint: mypy not installed in this image; running the "
            "annotations rule over the strict packages instead"
        )
        rule_names = ("annotations",)
    else:
        rule_names = (
            [r.strip() for r in args.rules.split(",") if r.strip()]
            if args.rules
            else None
        )

    modules = load_modules(root_dir)
    findings = run_rules(modules, rule_names)
    for f in findings:
        print(f.render())
    if findings:
        print(f"tpulint: {len(findings)} finding(s)")
        return 1
    print(f"tpulint: clean ({len(modules)} files)")
    return 0
