"""metric-contract rule: every ``tpushare_*`` family is declared once.

The catalog (``gpushare_device_plugin_tpu/utils/metric_catalog.py``)
declares each family's name, exposition type, and allowed label set.
This rule closes the exporter/parser drift loop statically:

1. a ``tpushare_*`` name literal anywhere in the package OUTSIDE the
   catalog module is a finding — exporters and the CLI parsers must
   reference catalog consts, so renames are one-line and lint-checked;
2. a metric call (``counter_inc``/``gauge_set``/``observe``, the
   programmatic readers, ``timed_acquire``) whose resolved family name
   is not in the catalog is a finding (an undeclared family is
   invisible to the contract);
3. the call kind must agree with the declared type (``counter_inc`` on
   a gauge family is the drift this rule exists for);
4. explicit label keywords at the call site must be a subset of the
   declared label set (``**labels`` pass-throughs are dynamic and
   trusted — the declared set documents them).

Name resolution follows assignments and ``from ... import`` chains, so
``REGISTRY.gauge_value(STRANDED_PCT_GAUGE)`` where the const was
imported from another module that imported it from the catalog still
resolves. Tests and bench drivers are out of scope (they are consumers
and synthetic emitters, not the exported contract); lint fixtures are
excluded by the engine.
"""

from __future__ import annotations

import ast
import re

from .engine import Finding, Module, docstring_constants

CATALOG_PATH = "gpushare_device_plugin_tpu/utils/metric_catalog.py"

RULE = "metric-contract"

# Call attr -> required exposition type (None = any declared family).
EMIT_KINDS = {
    "counter_inc": "counter",
    "gauge_set": "gauge",
    "observe": "histogram",
    "counter_value": "counter",
    "gauge_value": "gauge",
    "gauge_series": "gauge",
    "histogram_stats": "histogram",
    "histogram_quantile": "histogram",
    "exemplar": None,
}

# Keywords on metric calls that are NOT labels.
NON_LABEL_KW = frozenset({"help_text", "value", "buckets", "registry"})

NAME_RE = re.compile(r"^tpushare_[a-z0-9_]+$")
TYPE_NAMES = {"COUNTER": "counter", "GAUGE": "gauge", "HISTOGRAM": "histogram"}


def _literal_assigns(tree: ast.Module) -> dict[str, str]:
    """Module-level ``NAME = "tpushare_..."`` bindings."""
    out: dict[str, str] = {}
    for node in tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and isinstance(node.value, ast.Constant)
            and isinstance(node.value.value, str)
            and node.value.value.startswith("tpushare")
        ):
            out[node.targets[0].id] = node.value.value
    return out


def _parse_catalog(
    mod: Module,
) -> tuple[dict[str, str], dict[str, tuple[str, frozenset[str]]]]:
    """(const name -> family literal, family -> (type, labels))."""
    consts = _literal_assigns(mod.tree)
    specs: dict[str, tuple[str, frozenset[str]]] = {}
    for node in ast.walk(mod.tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "_m"
            and node.args
        ):
            continue
        a0 = node.args[0]
        if isinstance(a0, ast.Constant):
            name = str(a0.value)
        elif isinstance(a0, ast.Name):
            name = consts.get(a0.id, "")
        else:
            continue
        mtype = ""
        if len(node.args) > 1 and isinstance(node.args[1], ast.Name):
            mtype = TYPE_NAMES.get(node.args[1].id, "")
        labels = frozenset(
            str(a.value) for a in node.args[2:]
            if isinstance(a, ast.Constant)
        )
        if name and mtype:
            specs[name] = (mtype, labels)
    return consts, specs


def _resolve_bindings(
    modules: list[Module], catalog_consts: dict[str, str]
) -> dict[str, dict[str, str]]:
    """Per-module name -> family-literal maps, following import chains
    (three passes cover catalog -> exporter -> consumer re-exports)."""
    bindings: dict[str, dict[str, str]] = {CATALOG_PATH: dict(catalog_consts)}
    for mod in modules:
        local = bindings.setdefault(mod.path, {})
        local.update(_literal_assigns(mod.tree))
    for _pass in range(3):
        global_names: dict[str, str] = {}
        for per_mod in bindings.values():
            for name, lit in per_mod.items():
                global_names.setdefault(name, lit)
        for mod in modules:
            local = bindings[mod.path]
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.ImportFrom):
                    continue
                for alias in node.names:
                    lit = global_names.get(alias.name)
                    if lit is not None:
                        local.setdefault(alias.asname or alias.name, lit)
    return bindings


def _metric_name(
    arg: ast.expr, local: dict[str, str]
) -> str | None:
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value if arg.value.startswith("tpushare") else None
    if isinstance(arg, ast.Name):
        lit = local.get(arg.id)
        return lit if lit and lit.startswith("tpushare") else None
    return None


def check_metric_contract(modules: list[Module]) -> list[Finding]:
    findings: list[Finding] = []
    catalog = next((m for m in modules if m.path == CATALOG_PATH), None)
    if catalog is None:
        return [Finding(
            CATALOG_PATH, 0, RULE,
            "metric catalog module missing — the metric contract has no "
            "declaration point",
        )]
    consts, specs = _parse_catalog(catalog)
    bindings = _resolve_bindings(modules, consts)
    for mod in modules:
        if not mod.in_package:
            continue
        local = bindings.get(mod.path, {})
        docstrings = docstring_constants(mod.tree)
        for node in ast.walk(mod.tree):
            # 1) inline family literals outside the catalog
            if (
                isinstance(node, ast.Constant)
                and isinstance(node.value, str)
                and NAME_RE.match(node.value)
                and id(node) not in docstrings
                and mod.path != CATALOG_PATH
            ):
                findings.append(Finding(
                    mod.path, node.lineno, RULE,
                    f"inline metric name literal {node.value!r} — import "
                    "the const from utils/metric_catalog.py (the single "
                    "declaration point for every tpushare_* family)",
                ))
            if not isinstance(node, ast.Call):
                continue
            # 2-4) metric calls against the contract
            name: str | None = None
            required: str | None = None
            label_kws: list[str] = []
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in EMIT_KINDS
                and node.args
            ):
                name = _metric_name(node.args[0], local)
                required = EMIT_KINDS[node.func.attr]
                label_kws = [
                    kw.arg for kw in node.keywords
                    if kw.arg is not None and kw.arg not in NON_LABEL_KW
                ]
            elif (
                (
                    isinstance(node.func, ast.Name)
                    and node.func.id == "timed_acquire"
                )
                or (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "timed_acquire"
                )
            ) and len(node.args) >= 2:
                name = _metric_name(node.args[1], local)
                required = "histogram"
                label_kws = [
                    kw.arg for kw in node.keywords
                    if kw.arg is not None and kw.arg not in NON_LABEL_KW
                ]
            if name is None:
                continue
            spec = specs.get(name)
            if spec is None:
                findings.append(Finding(
                    mod.path, node.lineno, RULE,
                    f"metric family {name!r} is not declared in "
                    "utils/metric_catalog.py (name, type, label set)",
                ))
                continue
            mtype, allowed = spec
            if required is not None and mtype != required:
                findings.append(Finding(
                    mod.path, node.lineno, RULE,
                    f"{name!r} is declared a {mtype} but this call emits/"
                    f"reads it as a {required}",
                ))
            extra = [kw for kw in label_kws if kw not in allowed]
            if extra:
                findings.append(Finding(
                    mod.path, node.lineno, RULE,
                    f"label(s) {sorted(extra)} on {name!r} are outside its "
                    f"declared label set {sorted(allowed)} — scrapes and "
                    "the CLI parsers key on the declared labels",
                ))
    return findings
