"""Discovery data model shared by all backends.

The reference collapses discovery to: per-GPU UUID, ``/dev/nvidia<i>`` path,
total memory, and an XID-event health feed (``nvidia.go:53-91,102-154``).
The TPU model carries the same essentials plus slice topology, which TPU
workloads need for ``TPU_PROCESS_BOUNDS`` injection (multi-host slices:
each host's DaemonSet advertises only local chips, SURVEY.md section 2).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Callable, Iterator, Protocol, Sequence


class ChipHealth(enum.Enum):
    HEALTHY = "Healthy"
    UNHEALTHY = "Unhealthy"


@dataclasses.dataclass(frozen=True)
class TpuChip:
    """One physical TPU chip on this host."""

    id: str  # stable unique ID (UUID-like), e.g. "tpu-v4-host0-chip2"
    index: int  # local chip index, the value injected as TPU_VISIBLE_CHIPS
    device_path: str  # /dev/accel<N> (or "" when virtual)
    hbm_bytes: int  # total HBM on this chip
    health: ChipHealth = ChipHealth.HEALTHY


@dataclasses.dataclass(frozen=True)
class TpuTopology:
    """Host-local view of the slice topology.

    ``process_bounds`` / ``chips_per_process_bounds`` are the strings a JAX
    workload needs to form its mesh (e.g. v4-32: 4 hosts -> "2,2,1" bounds);
    empty strings mean single-host default.
    """

    generation: str = "v4"  # "v4", "v5e", "v5p", ...
    chips_per_host: int = 4
    host_index: int = 0
    num_hosts: int = 1
    process_bounds: str = ""
    chips_per_process_bounds: str = ""


@dataclasses.dataclass(frozen=True)
class HealthEvent:
    """A health transition for one chip (or all chips when ``chip_id=None``).

    Analog of an NVML XID critical event (``nvidia.go:121-152``): events
    without a device attribution mark every chip unhealthy.

    ``severity`` classifies the fault the way the reference classifies
    XIDs (``nvidia.go:133-137`` skips application-level XIDs 31/43/45):

    - ``"hard"`` — infrastructure fault; flips schedulability (the
      allocator excludes the chip, ListAndWatch marks it Unhealthy).
    - ``"transient"`` — infrastructure blip that self-healed inside the
      grace window (driver reset); informational only, never flips health.
    - ``"app"`` — application-level fault (e.g. correctable-error counter
      ticked); surfaced as a log line and a Kubernetes event but NEVER
      changes chip health — a user bug must not de-advertise hardware.
    """

    chip_id: str | None
    health: ChipHealth
    reason: str = ""
    severity: str = "hard"


class DiscoveryBackend(Protocol):
    """Chip enumeration + health feed. Implementations: mock, jax, tpuvm."""

    def probe(self) -> bool:
        """Cheap check whether this backend can run on this host."""
        ...

    def chips(self) -> Sequence[TpuChip]:
        """Enumerate local chips. Stable order by ``index``."""
        ...

    def topology(self) -> TpuTopology:
        ...

    def watch_health(self, stop: Callable[[], bool]) -> Iterator[HealthEvent]:
        """Yield health transitions until ``stop()`` returns True.

        Implementations poll; callers run this in a thread (reference runs
        ``watchXIDs`` with a 5 s event wait, ``nvidia.go:121-128``).
        """
        ...
