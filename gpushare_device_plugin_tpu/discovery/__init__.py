"""TPU chip / HBM enumeration backends.

Replaces the reference's L0 NVML layer (``pkg/gpu/nvidia/nvidia.go:47-91`` +
the vendored cgo shim): on TPU-VM hosts there is no NVML; chips are found via
``/dev/accel*`` device files, TPU-VM metadata env, or libtpu through the
native ``tpuinfo`` C++ shim. A config-driven mock backend enables the full
Register -> ListAndWatch -> Allocate cycle on CPU-only clusters (the test
capability the reference lacks, SURVEY.md section 4).
"""

from .base import ChipHealth, DiscoveryBackend, TpuChip, TpuTopology
from .mock import MockBackend

__all__ = [
    "ChipHealth",
    "DiscoveryBackend",
    "TpuChip",
    "TpuTopology",
    "MockBackend",
    "from_name",
]


def from_name(name: str, **kwargs) -> DiscoveryBackend:
    """Build a backend by flag value (``--discovery=mock|jax|tpuvm|auto``)."""
    if name == "mock":
        return MockBackend(**kwargs)
    if name == "jax":
        from .jaxdev import JaxBackend

        return JaxBackend(**kwargs)
    if name == "tpuvm":
        from .tpuvm import TpuVmBackend

        return TpuVmBackend(**kwargs)
    if name == "auto":
        # Best real backend that probes OK; else an empty mock, which makes
        # the daemon park (reference behavior on driverless nodes,
        # gpumanager.go:36-47) instead of crash-looping. Backend-specific
        # kwargs are not forwarded in auto mode; any probe failure falls
        # through rather than crashing.
        for load in (_load_tpuvm, _load_jax):
            try:
                be = load()
                if be.probe():
                    return be
            except Exception:
                continue
        return MockBackend(num_chips=0)
    raise ValueError(f"unknown discovery backend {name!r}")


def _load_tpuvm() -> DiscoveryBackend:
    from .tpuvm import TpuVmBackend

    return TpuVmBackend()


def _load_jax() -> DiscoveryBackend:
    from .jaxdev import JaxBackend

    return JaxBackend()
