"""Discovery backend backed by a live JAX runtime.

Useful on TPU-VM hosts where importing jax is acceptable (e.g. the bench
harness or a sidecar): chips come from ``jax.local_devices()`` and HBM from
``memory_stats()['bytes_limit']``. The production daemon prefers the tpuvm
backend (no jax import, no TPU runtime lock — a JAX client holds the chips
while alive, which a DaemonSet must never do for the node's workloads).
"""

from __future__ import annotations

import time
from typing import Callable, Iterator, Sequence

from .base import ChipHealth, HealthEvent, TpuChip, TpuTopology

_DEFAULT_HBM = 16 << 30  # conservative fallback when memory_stats is absent


class JaxBackend:
    def __init__(self, hbm_bytes: int | None = None):
        self._hbm_override = hbm_bytes
        self._devices = None

    def _jax(self):
        import jax  # deferred: only this backend needs it

        return jax

    def probe(self) -> bool:
        try:
            jax = self._jax()
            return any(d.platform == "tpu" for d in jax.local_devices())
        except Exception:
            return False

    def _local_devices(self):
        if self._devices is None:
            self._devices = list(self._jax().local_devices())
        return self._devices

    def chips(self) -> Sequence[TpuChip]:
        out = []
        for i, dev in enumerate(self._local_devices()):
            hbm = self._hbm_override
            if hbm is None:
                try:
                    stats = dev.memory_stats() or {}
                    hbm = int(stats.get("bytes_limit", _DEFAULT_HBM))
                except Exception:
                    hbm = _DEFAULT_HBM
            out.append(
                TpuChip(
                    id=f"jax-{dev.platform}-{dev.id}",
                    index=i,
                    device_path=f"/dev/accel{i}",
                    hbm_bytes=hbm,
                )
            )
        return out

    def topology(self) -> TpuTopology:
        jax = self._jax()
        devs = self._local_devices()
        kind = devs[0].device_kind if devs else "unknown"
        return TpuTopology(
            generation=str(kind),
            chips_per_host=len(devs),
            host_index=jax.process_index(),
            num_hosts=jax.process_count(),
        )

    def watch_health(self, stop: Callable[[], bool]) -> Iterator[HealthEvent]:
        """Liveness poll: a trivial device_put doubles as a runtime heartbeat."""
        jax = self._jax()
        last_ok = True
        while not stop():
            try:
                jax.device_put(0, self._local_devices()[0]).block_until_ready()
                ok = True
            except Exception:
                ok = False
            if ok != last_ok:
                yield HealthEvent(
                    chip_id=None,
                    health=ChipHealth.HEALTHY if ok else ChipHealth.UNHEALTHY,
                    reason="jax-runtime-heartbeat",
                )
                last_ok = ok
            time.sleep(5.0)
