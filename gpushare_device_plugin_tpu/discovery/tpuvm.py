"""TPU-VM discovery backend: /dev/accel* + metadata env + optional native shim.

The production analog of the reference's NVML layer (``nvidia.go:47-91``)
without any ML-runtime import: chip device files are enumerated from
``/dev`` (``accel0..N`` on TPU-VM; ``vfio/*`` on newer images), HBM per chip
comes from the accelerator-type metadata (``TPU_ACCELERATOR_TYPE`` /
``ACCELERATOR_TYPE`` env on TPU-VMs, e.g. ``v4-8``), and — when the native
``libtpuinfo`` C++ shim is built (``native/``) — from libtpu itself via
ctypes. The shim is optional by design, mirroring the reference's lazy
``dlopen`` of libnvidia-ml (``nvml_dl.c:21-27``) so one DaemonSet image runs
on non-TPU nodes and simply parks.
"""

from __future__ import annotations

import glob
import os
import re
import time
from typing import Callable, Iterator, Sequence

from .. import const
from .base import ChipHealth, HealthEvent, TpuChip, TpuTopology

# Per-chip HBM by TPU generation (public Cloud TPU specs).
HBM_BY_GENERATION = {
    "v2": 8 << 30,
    "v3": 16 << 30,
    "v4": 32 << 30,
    "v5e": 16 << 30,
    "v5litepod": 16 << 30,
    "v5p": 95 << 30,
    "v6e": 32 << 30,
}
# Chips per host by generation (full-host TPU-VMs).
CHIPS_PER_HOST = {"v2": 4, "v3": 4, "v4": 4, "v5e": 8, "v5litepod": 8, "v5p": 4, "v6e": 8}

# The TPU_-prefixed spellings live in const.py (string-consts rule);
# the unprefixed legacy fallbacks are tpuvm-local.
ENV_ACCEL_TYPE = (const.ENV_TPU_ACCELERATOR_TYPE, "ACCELERATOR_TYPE")
ENV_WORKER_ID = (const.ENV_TPU_WORKER_ID, "WORKER_ID")
ENV_HBM_OVERRIDE = "TPUSHARE_HBM_GIB"
ENV_SYSFS_ROOT = "TPUINFO_SYSFS_ROOT"

# Health classification knobs (see watch_health):
# A device file must stay missing for MORE than this many consecutive polls
# before the chip goes Unhealthy — a shorter blip (driver reset, host
# maintenance tick) never surfaces, so the allocator never excludes the chip.
DEVICE_GONE_GRACE_POLLS = 1
# After a hard error-counter hit, this many quiet polls heal the chip.
COUNTER_QUIET_POLLS = 6
# sysfs error counters (best-effort: present on some driver versions under
# /sys/class/accel/accel<N>/device/). Uncorrectable errors are
# infrastructure faults -> hard; correctable errors are the app-level
# analog of XID 31/43/45 (``nvidia.go:133-137``) -> never de-advertise.
HARD_COUNTER_FILES = ("uncorrectable_errors",)
APP_COUNTER_FILES = ("correctable_errors",)


def parse_accelerator_type(accel: str) -> tuple[str, int]:
    """``"v4-32" -> ("v4", 32)`` (generation, total cores in slice)."""
    m = re.fullmatch(r"(v\d+[a-z]*(?:pod)?)-(\d+)", accel.strip())
    if not m:
        return "", 0
    return m.group(1), int(m.group(2))


class TpuVmBackend:
    def __init__(
        self,
        dev_glob: str = "/dev/accel*",
        vfio_glob: str = "/dev/vfio/[0-9]*",
        env: dict | None = None,
        native_lib: str | None = None,
        sysfs_root: str | None = None,
        poll_s: float = 5.0,
        grace_polls: int = DEVICE_GONE_GRACE_POLLS,
    ):
        self._dev_glob = dev_glob
        self._vfio_glob = vfio_glob
        # An explicit env dict makes metadata lookups hermetic: the native
        # shim reads the *process* env, so its metadata-derived values
        # (HBM) are only trusted when no override dict was given.
        self._env_overridden = env is not None
        self._env = env if env is not None else dict(os.environ)
        self._native = None
        self._native_lib = native_lib
        self._native_tried = False
        self._sysfs_root = sysfs_root or self._env.get(ENV_SYSFS_ROOT) or "/sys"
        self._poll_s = poll_s
        self._grace_polls = grace_polls

    # --- native shim (optional) -------------------------------------------

    def _load_native(self):
        if self._native_tried:
            return self._native
        self._native_tried = True
        try:
            from ..native import tpuinfo

            self._native = tpuinfo.load(self._native_lib)
        except Exception:
            self._native = None
        return self._native

    # --- enumeration -------------------------------------------------------

    def _device_paths(self) -> list[str]:
        return self._device_paths_numbered()[0]

    def _device_paths_numbered(self) -> tuple[list[str], bool]:
        """(sorted device paths, numbers-are-chip-indices).

        ``/dev/accelN``'s N *is* the chip number (stable across a vanished
        sibling); ``/dev/vfio/N`` is an IOMMU group number with no chip
        meaning, so vfio paths get positional indices.
        """
        paths = sorted(
            glob.glob(self._dev_glob),
            key=lambda p: int(re.sub(r"\D", "", p) or 0),
        )
        if paths:
            return paths, True
        return sorted(
            glob.glob(self._vfio_glob),
            key=lambda p: int(re.sub(r"\D", "", p) or 0),
        ), False

    def _accel_type(self) -> str:
        for key in ENV_ACCEL_TYPE:
            if self._env.get(key):
                return self._env[key]
        return ""

    def _hbm_bytes(self) -> int:
        override = self._env.get(ENV_HBM_OVERRIDE)
        if override:
            try:
                return int(override) << 30
            except ValueError:
                pass  # garbled operator env: fall through to real sources
        if not self._env_overridden:
            native = self._load_native()
            if native is not None:
                hbm = native.hbm_bytes_per_chip()
                if hbm > 0:
                    return hbm
        gen, _ = parse_accelerator_type(self._accel_type())
        return HBM_BY_GENERATION.get(gen, 16 << 30)

    def probe(self) -> bool:
        return bool(self._device_paths())

    def chips(self) -> Sequence[TpuChip]:
        """Chip list keyed by the *device number*, not the glob position.

        ``/dev/accel2`` is chip 2 even when ``/dev/accel1`` has vanished
        (driver reset mid-rescan): positional numbering would renumber the
        surviving chips, silently remapping every pod's
        ``TPU_VISIBLE_CHIPS`` — the same stability contract as the native
        shim's devnum keying (``native/tpuinfo.cpp:150-153``) and the
        reference's index-from-path parse (``nvidia.go:66``). When the shim
        is loaded it is the authoritative enumerator (it reads the same
        /dev but adds libtpu-derived HBM); the pure-Python glob is the
        fallback so driverless images still park cleanly.
        """
        hbm = self._hbm_bytes()
        gen, _ = parse_accelerator_type(self._accel_type())
        host = self._worker_id()
        if not self._env_overridden:
            native = self._load_native()
            if native is not None:
                try:
                    native.rescan()
                    nchips = native.chips()
                except OSError:
                    nchips = []
                if nchips:
                    return [
                        TpuChip(
                            id=c.id or f"tpu-{gen or 'unknown'}-host{host}-chip{c.index}",
                            index=c.index,
                            device_path=c.device_path,
                            hbm_bytes=c.hbm_bytes if c.hbm_bytes > 0 else hbm,
                        )
                        for c in nchips
                    ]
        out = []
        paths, numbered = self._device_paths_numbered()
        for pos, path in enumerate(paths):
            m = re.search(r"(\d+)$", path) if numbered else None
            idx = int(m.group(1)) if m else pos
            out.append(
                TpuChip(
                    id=f"tpu-{gen or 'unknown'}-host{host}-chip{idx}",
                    index=idx,
                    device_path=path,
                    hbm_bytes=hbm,
                )
            )
        return out

    def _worker_id(self) -> int:
        for key in ENV_WORKER_ID:
            v = self._env.get(key)
            if v is not None:
                try:
                    return int(v)
                except ValueError:
                    pass
        return 0

    def topology(self) -> TpuTopology:
        gen, cores = parse_accelerator_type(self._accel_type())
        local = len(self._device_paths())
        chips_per_host = CHIPS_PER_HOST.get(gen, local or 4)
        # v2/v3/v4/v5p accelerator-types count TensorCores (2 per chip);
        # v5e/v5litepod/v6e count chips. So v4-32 = 16 chips = 4 hosts.
        cores_per_chip = 2 if gen in ("v2", "v3", "v4", "v5p") else 1
        total_chips = cores // cores_per_chip
        num_hosts = max(1, total_chips // chips_per_host) if total_chips else 1
        return TpuTopology(
            generation=gen or "unknown",
            chips_per_host=local or chips_per_host,
            host_index=self._worker_id(),
            num_hosts=num_hosts,
        )

    # --- health ------------------------------------------------------------

    def _read_counters(self, device_path: str) -> dict[str, int]:
        """Best-effort sysfs error counters for one chip ({} when absent)."""
        name = os.path.basename(device_path)
        base = os.path.join(self._sysfs_root, "class", "accel", name, "device")
        out: dict[str, int] = {}
        for fname in HARD_COUNTER_FILES + APP_COUNTER_FILES:
            try:
                with open(os.path.join(base, fname)) as f:
                    out[fname] = int(f.read().strip())
            except (OSError, ValueError):
                continue
        return out

    def watch_health(self, stop: Callable[[], bool]) -> Iterator[HealthEvent]:
        """Per-chip classified health poll (default 5 s, ``nvidia.go:128``).

        Three signals, classified per chip (the reference's XID watcher
        granularity, ``nvidia.go:102-154``, vs round-3's whole-host flag):

        - **device file presence** with a grace window: a file missing for
          <= ``grace_polls`` consecutive polls is a transient blip (driver
          reset) and surfaces nothing — the allocator never excludes the
          chip; longer outages go Unhealthy with reason
          ``device-file-gone``, and recover the moment the file returns
          (the recovery path the reference never implemented, FIXME
          ``server.go:184``).
        - **sysfs error counters** (when the driver exposes them): an
          uncorrectable-error delta is a hard fault (immediate Unhealthy,
          healed after ``COUNTER_QUIET_POLLS`` quiet polls); a
          correctable-error delta is the app-level analog of XID 31/43/45
          (``nvidia.go:133-137``) — an ``"app"``-severity event that never
          flips schedulability.
        - **libtpu runtime liveness** via the native shim, whole-host, hard
          (a dead runtime takes every chip with it).
        """
        state: dict[str, bool] = {}  # cid -> currently advertised healthy
        miss: dict[str, int] = {}  # cid -> consecutive missing polls
        quiet: dict[str, int] = {}  # cid -> polls since last hard counter hit
        counters: dict[str, dict[str, int]] = {}
        seen: dict[str, str] = {}  # chip id -> device path, sticky
        native_ok = True
        while not stop():
            # Same hermeticity gate as _hbm_bytes: the shim reads the
            # process env, so its health feed is only meaningful when this
            # backend does too.
            native = None if self._env_overridden else self._load_native()
            if native is not None:
                ok = native.runtime_healthy()
                if ok != native_ok:
                    yield HealthEvent(
                        chip_id=None,
                        health=ChipHealth.HEALTHY if ok else ChipHealth.UNHEALTHY,
                        reason="libtpu-runtime",
                    )
                    native_ok = ok
            # Re-enumerate each cycle so chips appearing after a late driver
            # init get watched; keep previously-seen chips in ``seen`` so a
            # vanished device file (no longer globbed) still reports
            # unhealthy and recovers when it returns.
            for chip in self.chips():
                seen.setdefault(chip.id, chip.device_path)
            for cid, path in seen.items():
                healthy = state.get(cid, True)
                if os.path.exists(path):
                    blip = miss.pop(cid, 0)
                    if not healthy and quiet.get(cid) is None:
                        # gone past grace, now back: recover immediately
                        state[cid] = True
                        yield HealthEvent(
                            chip_id=cid, health=ChipHealth.HEALTHY,
                            reason="device-file-restored",
                        )
                        continue
                    if blip and healthy:
                        # infrastructure blip inside the grace window:
                        # informational, schedulability untouched
                        yield HealthEvent(
                            chip_id=cid, health=ChipHealth.HEALTHY,
                            reason=f"device-file-blip({blip} polls)",
                            severity="transient",
                        )
                else:
                    miss[cid] = miss.get(cid, 0) + 1
                    if healthy and miss[cid] > self._grace_polls:
                        state[cid] = False
                        quiet.pop(cid, None)  # cause: device, not counters
                        yield HealthEvent(
                            chip_id=cid, health=ChipHealth.UNHEALTHY,
                            reason=f"device-file-gone({miss[cid]} polls)",
                        )
                    continue  # no counters to read while the file is gone

                cur = self._read_counters(path)
                last = counters.get(cid)
                if cur:
                    counters[cid] = cur
                if not cur or last is None:
                    # No counters (driver doesn't expose them, or they
                    # vanished across a reset) or first observation: no
                    # deltas to classify — but a counter-unhealthy chip
                    # still makes quiet progress, else vanished counter
                    # files would pin it Unhealthy forever.
                    hard_delta = app_delta = 0
                else:
                    hard_delta = sum(
                        cur.get(f, 0) - last.get(f, 0)
                        for f in HARD_COUNTER_FILES
                        if cur.get(f, 0) > last.get(f, 0)
                    )
                    app_delta = sum(
                        cur.get(f, 0) - last.get(f, 0)
                        for f in APP_COUNTER_FILES
                        if cur.get(f, 0) > last.get(f, 0)
                    )
                if app_delta:
                    yield HealthEvent(
                        chip_id=cid, health=ChipHealth.HEALTHY,
                        reason=f"correctable-errors+{app_delta}",
                        severity="app",
                    )
                if hard_delta:
                    quiet[cid] = 0
                    if state.get(cid, True):
                        state[cid] = False
                        yield HealthEvent(
                            chip_id=cid, health=ChipHealth.UNHEALTHY,
                            reason=f"uncorrectable-errors+{hard_delta}",
                        )
                elif quiet.get(cid) is not None:
                    quiet[cid] += 1
                    if quiet[cid] >= COUNTER_QUIET_POLLS:
                        quiet.pop(cid)
                        if not state.get(cid, True):
                            state[cid] = True
                            yield HealthEvent(
                                chip_id=cid, health=ChipHealth.HEALTHY,
                                reason=f"error-counter-quiet({COUNTER_QUIET_POLLS} polls)",
                            )
            # stop-aware wait (0.1 s stop latency)
            waited = 0.0
            while waited < self._poll_s:
                if stop():
                    return
                step = min(0.1, self._poll_s - waited)
                time.sleep(step)
                waited += step
