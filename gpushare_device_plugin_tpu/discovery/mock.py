"""Config-driven mock discovery backend.

Enables the full plugin cycle on a CPU-only/kind cluster (BASELINE config 1)
— the fake-chip backend the reference never had (its only test is a live
kubelet smoke test, SURVEY.md section 4). Chip count / HBM / topology come
from constructor args or the ``TPUSHARE_MOCK_*`` env family, and health can
be driven from a control file for e2e fault-injection tests.
"""

from __future__ import annotations

import json
import os
import time
from typing import Callable, Iterator, Sequence

from .base import ChipHealth, HealthEvent, TpuChip, TpuTopology

ENV_NUM_CHIPS = "TPUSHARE_MOCK_CHIPS"
ENV_HBM_GIB = "TPUSHARE_MOCK_HBM_GIB"
ENV_HEALTH_FILE = "TPUSHARE_MOCK_HEALTH_FILE"


def _int_env(key: str, default: int) -> int:
    try:
        return int(os.environ.get(key, default))
    except ValueError:
        return default


class MockBackend:
    def __init__(
        self,
        num_chips: int | None = None,
        hbm_bytes: int | None = None,
        generation: str = "v4",
        host_index: int = 0,
        num_hosts: int = 1,
        health_file: str | None = None,
        poll_interval_s: float = 0.05,
    ):
        if num_chips is None:
            num_chips = _int_env(ENV_NUM_CHIPS, 4)
        if hbm_bytes is None:
            hbm_bytes = _int_env(ENV_HBM_GIB, 32) << 30
        self._num_chips = num_chips
        self._hbm_bytes = hbm_bytes
        self._generation = generation
        self._host_index = host_index
        self._num_hosts = num_hosts
        self._health_file = health_file or os.environ.get(ENV_HEALTH_FILE)
        self._poll_interval_s = poll_interval_s

    def probe(self) -> bool:
        return True

    def chips(self) -> Sequence[TpuChip]:
        return [
            TpuChip(
                id=f"tpu-{self._generation}-host{self._host_index}-chip{i}",
                index=i,
                device_path=f"/dev/accel{i}",
                hbm_bytes=self._hbm_bytes,
            )
            for i in range(self._num_chips)
        ]

    def topology(self) -> TpuTopology:
        return TpuTopology(
            generation=self._generation,
            chips_per_host=self._num_chips,
            host_index=self._host_index,
            num_hosts=self._num_hosts,
        )

    def watch_health(self, stop: Callable[[], bool]) -> Iterator[HealthEvent]:
        """Poll the health control file for ``{"chip_id"|null: "Unhealthy"}``.

        The control file holds a JSON object mapping chip id (or "*") to
        "Healthy"/"Unhealthy"; transitions are emitted as events.
        """
        from ..utils.faults import FAULTS

        last: dict[str, str] = {}
        while not stop():
            # chaos hook: lets tests kill the stream mid-flight (the
            # supervised HealthWatcher must revive it)
            FAULTS.fire("discovery.watch_health")
            if self._health_file and os.path.exists(self._health_file):
                try:
                    with open(self._health_file) as f:
                        cur = json.load(f)
                    if not isinstance(cur, dict):
                        raise ValueError("health file must hold a JSON object")
                    events = []
                    # removed keys are implicit recoveries to Healthy
                    for chip_id in set(last) | set(cur):
                        state = cur.get(chip_id, ChipHealth.HEALTHY.value)
                        if last.get(chip_id, ChipHealth.HEALTHY.value) != state:
                            events.append(
                                HealthEvent(
                                    chip_id=None if chip_id == "*" else chip_id,
                                    health=ChipHealth(state),
                                    reason="mock-health-file",
                                )
                            )
                except (OSError, ValueError, AttributeError):
                    # unreadable/garbled control file: keep the watcher alive
                    events, cur = [], last
                yield from events
                last = dict(cur)
            time.sleep(self._poll_interval_s)
