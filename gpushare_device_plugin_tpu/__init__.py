"""gpushare-device-plugin-tpu: TPU-native accelerator sharing for Kubernetes.

A brand-new TPU-first implementation of the capabilities of the reference
``gpushare-device-plugin`` (a Kubernetes DaemonSet that lets multiple pods
share one accelerator by memory slice):

- ``discovery``  — TPU chip / HBM enumeration (mock, jax, tpuvm+libtpu backends)
- ``device``     — fake-device fan-out: one schedulable device per HBM unit
- ``plugin``     — Kubernetes device-plugin v1beta1 gRPC server + registration
- ``allocator``  — HBM binpack policy and the Allocate() flow (env injection)
- ``cluster``    — kube-apiserver / kubelet REST clients + pod state machine
- ``manager``    — daemon lifecycle: socket watch, signals, health, restart
- ``extender``   — scheduler-extender half: cluster-level binpack placement
- ``cli``        — daemon entrypoint, kubectl-inspect-tpushare, podgetter
- ``parallel``   — pod-side JAX runtime: Mesh from injected env, shardings,
  ring + Ulysses sequence parallelism
- ``workloads``  — demo JAX workloads (MNIST, ResNet, BERT, Llama-style
  decoder) with training loop, checkpointing, and KV-cache generation
- ``ops``        — Pallas TPU kernels used by the demo workloads
"""

__version__ = "0.1.0"
