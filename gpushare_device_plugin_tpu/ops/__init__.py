"""TPU Pallas kernels for workload hot ops.

The reference schedules containers and has no compute kernels at all
(SURVEY.md section 2: the only native code is the NVML cgo shim); the
workloads *this* plugin co-schedules spend their FLOPs in attention, so the
hot op gets a hand-written TPU kernel: a flash-attention forward/backward
pair that streams K/V through VMEM instead of materializing the [S, S]
score matrix in HBM.
"""

from .flash_attention import flash_attention, flash_attention_lse

__all__ = ["flash_attention", "flash_attention_lse"]
