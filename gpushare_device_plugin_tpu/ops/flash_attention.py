"""Flash attention as a TPU Pallas kernel (forward + backward).

Why a kernel at all: plain attention materializes the ``[S, S]`` score
matrix in HBM — at S=8k, bf16, 16 heads that is 2 GiB *per layer* of pure
bandwidth waste. The flash formulation streams K/V blocks through VMEM and
keeps an online-softmax accumulator, so HBM traffic is O(S·D) and the MXU
sees back-to-back ``[block_q, D] x [D, block_k]`` matmuls.

Design notes (per the TPU kernel playbook):
- Grid ``(batch*heads, q_blocks, kv_blocks)`` with the KV dimension
  innermost: TPU grids execute sequentially, so the accumulator lives in
  VMEM scratch across the inner dimension and the output block is written
  once, on the last contributing KV step.
- Causal masking skips fully-masked KV blocks with ``pl.when`` (no wasted
  MXU work past the diagonal) and masks the diagonal block with
  ``broadcasted_iota`` (TPU needs >=2D iota).
- Scores/accumulators are float32 (``preferred_element_type``) regardless
  of input dtype; bf16 inputs hit the MXU natively.
- Running max/denominator are stored lane-broadcast ``(block_q, 128)`` to
  respect the float32 (8, 128) tile.
- The backward pass recomputes scores flash-style (two kernels: dQ over the
  KV grid, dK/dV over the Q grid) from the saved logsumexp — nothing
  quadratic is ever resident.

The public entry point autodetects non-TPU backends and falls back to
Pallas interpreter mode, so the same code path is unit-testable on CPU
(tests/test_flash_attention.py) and compiled on TPU.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = float("-inf")
LANES = 128
# Grid dims: (rows, outer blocks) are independent, the innermost dim carries
# the running accumulator — telling Mosaic so unlocks cross-iteration
# scheduling on the parallel dims. (CompilerParams is the post-0.7 name of
# TPUCompilerParams; accept either so the jax>=0.6 floor keeps importing.)
_SEMANTICS = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
_SEMANTICS = _SEMANTICS(dimension_semantics=("parallel", "parallel", "arbitrary"))
# Per-row stats (lse, delta) travel HBM as [BH, S, STAT_LANES] float32:
# Mosaic requires the last block dim to be 128-divisible or equal to the
# array dim, and the sublane dim 8-divisible — so a flat [BH, S] layout is
# unlowerable and a [BH, S, 128] broadcast wastes 128x the bandwidth. Eight
# lanes (the f32 tile minimum) is the cheapest legal layout.
STAT_LANES = 8


def _causal_mask(s, qi, ki, block_q, block_k):
    """Mask the score block with global positions (2D iota, TPU-safe)."""
    q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(q_pos >= k_pos, s, NEG_INF)


def _pad_mask(s, ki, block_k, start):
    """Mask keys before this row's first real (non-pad) position."""
    k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(k_pos >= start, s, NEG_INF)


def _end_mask(s, ki, block_k, end):
    """Mask keys at/after this row's sequence length (right padding) —
    the mirror image of ``_pad_mask`` for the serving engine's
    RIGHT-padded fresh-slot prompt chunks (``kv_len``)."""
    k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(k_pos < end, s, NEG_INF)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _unpack_bounds(rest, has_start, has_end):
    """Peel the optional per-row start/kv_len operands off ``rest`` —
    shared by all three kernels so the operand order can never drift."""
    i = 0
    start_ref = end_ref = None
    if has_start:
        start_ref = rest[i]
        i += 1
    if has_end:
        end_ref = rest[i]
        i += 1
    return start_ref, end_ref, rest[i:]


def _fwd_kernel(
    q_ref, k_ref, v_ref, *rest,
    scale, causal, block_q, block_k, num_kv, has_start, has_end,
):
    start_ref, end_ref, rest = _unpack_bounds(rest, has_start, has_end)
    o_ref, lse_ref, m_scr, l_scr, acc_scr = rest
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # Last KV block that can contribute to this Q block under causality.
    last_ki = (
        jax.lax.div(qi * block_q + block_q - 1, block_k) if causal else num_kv - 1
    )
    live = ki <= last_ki
    if has_start:
        # Left padding: KV blocks entirely before this row's first real
        # position contribute nothing — skip their MXU work too.
        live = live & (ki * block_k + block_k - 1 >= start_ref[0, 0, 0])
    if has_end:
        # Right padding: KV blocks entirely at/after this row's length
        # are all pad — skip them like causal future blocks.
        live = live & (ki * block_k < end_ref[0, 0, 0])

    @pl.when(live)
    def _step():
        # Dots run on the inputs' native dtype: bf16 x bf16 -> f32 on the
        # MXU accumulates in f32 anyway, so upcasting first would only cost
        # ~4x MXU throughput for zero precision gain.
        q = q_ref[0]  # [bq, D]
        k = k_ref[0]  # [bk, D]
        v = v_ref[0]  # [bk, D]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [bq, bk]
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k)
        if has_start:
            s = _pad_mask(s, ki, block_k, start_ref[0, 0, 0])
        if has_end:
            s = _end_mask(s, ki, block_k, end_ref[0, 0, 0])
        m_prev = m_scr[:, :1]  # [bq, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        # Fully-masked rows keep m=-inf; shift by 0 there so exp() gives 0.
        m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        p = jnp.exp(s - m_safe)  # [bq, bk] f32
        alpha = jnp.exp(m_prev - m_safe)  # [bq, 1], 0 where m_prev=-inf
        l_new = alpha * l_scr[:, :1] + jnp.sum(p, axis=-1, keepdims=True)
        # p in [0, 1] cast to the V dtype (bf16 keeps ~3 significant
        # digits; the f32 accumulator absorbs the summation error).
        acc_scr[:] = alpha * acc_scr[:] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == num_kv - 1)
    def _finalize():
        m = m_scr[:, :1]
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        # logsumexp for the backward pass; -inf rows (fully masked) saturate.
        lse = jnp.where(l == 0.0, NEG_INF, m + jnp.log(l))
        lse_ref[0] = jnp.broadcast_to(lse, lse_ref[0].shape)


def _kv_row(b, heads, kv_heads):
    """Grid row over B*heads -> row of the grouped [B*kv_heads, S, D] K/V."""
    groups = heads // kv_heads
    return (b // heads) * kv_heads + (b % heads) // groups


def _causal_kv_map(causal, block_q, block_k, heads, kv_heads):
    """KV-side BlockSpec index map for the (BH, num_q, num_kv) grids.

    Causal: the fetched KV index is clamped at the Q block's last visible
    block — the same ``(i*block_q + block_q - 1) // block_k`` boundary the
    kernels' ``last_ki`` live condition uses, so no live step ever sees a
    clamped (wrong) block, and Mosaic elides the copies of the skipped
    future blocks (consecutive identical indices). The pad-mask skip can't
    be clamped: ``start`` is runtime data and index maps see only grid
    indices.
    """
    def kv_map(b, i, j):
        if causal:
            j = jnp.minimum(j, (i * block_q + block_q - 1) // block_k)
        return (_kv_row(b, heads, kv_heads), j, 0)

    return kv_map


def _fwd(
    q, k, v, start, end, *, scale, causal, block_q, block_k, heads, kv_heads,
    interpret,
):
    BH, S, D = q.shape
    num_q = S // block_q
    num_kv = S // block_k
    kernel = functools.partial(
        _fwd_kernel,
        scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, num_kv=num_kv,
        has_start=start is not None, has_end=end is not None,
    )
    # GQA-native: K/V stay [B*kv_heads, S, D] in HBM; each query head's
    # grid row streams its group's KV blocks directly (no repeated copy),
    # with causal fetch-elision clamping (see _causal_kv_map).
    kv_map = _causal_kv_map(causal, block_q, block_k, heads, kv_heads)
    in_specs = [
        pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_k, D), kv_map),
        pl.BlockSpec((1, block_k, D), kv_map),
    ]
    operands = [q, k, v]
    for bound in (start, end):
        if bound is not None:
            in_specs.append(
                pl.BlockSpec((1, 1, STAT_LANES), lambda b, i, j: (b, 0, 0))
            )
            operands.append(bound)
    o, lse = pl.pallas_call(
        kernel,
        grid=(BH, num_q, num_kv),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, STAT_LANES), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, D), q.dtype),
            jax.ShapeDtypeStruct((BH, S, STAT_LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, LANES), jnp.float32),  # running max
            pltpu.VMEM((block_q, LANES), jnp.float32),  # running denom
            pltpu.VMEM((block_q, D), jnp.float32),  # output accumulator
        ],
        compiler_params=_SEMANTICS,
        interpret=interpret,
    )(*operands)
    return o, lse  # o: [BH, S, Dh]; lse: [BH, S, STAT_LANES] (lane-broadcast)


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
    scale, causal, block_q, block_k, num_kv, has_start, has_end,
):
    start_ref, end_ref, rest = _unpack_bounds(rest, has_start, has_end)
    dq_ref, dq_scr = rest
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    last_ki = (
        jax.lax.div(qi * block_q + block_q - 1, block_k) if causal else num_kv - 1
    )
    live = ki <= last_ki
    if has_start:
        live = live & (ki * block_k + block_k - 1 >= start_ref[0, 0, 0])
    if has_end:
        live = live & (ki * block_k < end_ref[0, 0, 0])

    @pl.when(live)
    def _step():
        # Native-dtype dots (see _fwd_kernel): bf16 MXU rate, f32 accumulate.
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][:, :1]  # [bq, 1]
        delta = delta_ref[0][:, :1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k)
        if has_start:
            s = _pad_mask(s, ki, block_k, start_ref[0, 0, 0])
        if has_end:
            s = _end_mask(s, ki, block_k, end_ref[0, 0, 0])
        if has_start or has_end:
            # Rows fully inside the pad have lse=-inf; shift by 0 there so
            # exp(-inf - 0) gives the 0 the mask means (not -inf+inf=NaN).
            lse = jnp.where(jnp.isneginf(lse), 0.0, lse)
        p = jnp.exp(s - lse)  # [bq, bk]; exp(-inf)=0 handles the mask
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = (p * (dp - delta) * scale).astype(k.dtype)
        dq_scr[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(ki == num_kv - 1)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
    scale, causal, block_q, block_k, num_q, has_start, has_end,
):
    start_ref, end_ref, rest = _unpack_bounds(rest, has_start, has_end)
    dk_ref, dv_ref, dk_scr, dv_scr = rest
    ki = pl.program_id(1)
    # Innermost dim fuses (group member, q block): dK/dV of one KV head sum
    # contributions from every query head in its group, so the whole group
    # runs under one accumulator before the single writeback.
    qi = pl.program_id(2) % num_q
    gq = pl.program_id(2)

    @pl.when(gq == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    # First Q block that sees this KV block under causality.
    first_qi = jax.lax.div(ki * block_k, block_q) if causal else 0
    live = qi >= first_qi
    if has_start:
        # KV blocks wholly inside the pad produce zero dK/dV: skip their
        # MXU work (scratch init at gq==0 is unconditional, so safe).
        live = live & (ki * block_k + block_k - 1 >= start_ref[0, 0, 0])
    if has_end:
        live = live & (ki * block_k < end_ref[0, 0, 0])

    @pl.when(live)
    def _step():
        # Native-dtype dots (see _fwd_kernel): bf16 MXU rate, f32 accumulate.
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][:, :1]  # [bq, 1]
        delta = delta_ref[0][:, :1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k)
        if has_start:
            s = _pad_mask(s, ki, block_k, start_ref[0, 0, 0])
        if has_end:
            s = _end_mask(s, ki, block_k, end_ref[0, 0, 0])
        if has_start or has_end:
            lse = jnp.where(jnp.isneginf(lse), 0.0, lse)  # see _dq_kernel
        p = jnp.exp(s - lse)  # [bq, bk] f32
        # dv += p^T @ do
        dv_scr[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = (p * (dp - delta) * scale).astype(q.dtype)  # [bq, bk]
        # dk += ds^T @ q
        dk_scr[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(gq == pl.num_programs(2) - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd(
    q, k, v, o, lse, do, start, end, *, scale, causal, block_q, block_k,
    heads, kv_heads, interpret, dlse=None,
):
    BH, S, D = q.shape
    BKV = k.shape[0]
    groups = heads // kv_heads
    num_q = S // block_q
    num_kv = S // block_k
    # delta_i = rowsum(dO * O): tiny elementwise reduce, XLA fuses it.
    # With an lse cotangent (the (o, lse) pair entry), ds gains dlse * p:
    # ds = p*(dp - delta) + dlse*p = p*(dp - (delta - dlse)) — the whole
    # lse backward folds into this one subtraction.
    delta_row = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    if dlse is not None:
        delta_row = delta_row - dlse.astype(jnp.float32)
    delta = jnp.broadcast_to(delta_row[..., None], (BH, S, STAT_LANES))

    kv_map = _causal_kv_map(causal, block_q, block_k, heads, kv_heads)
    dq_in_specs = [
        pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_k, D), kv_map),
        pl.BlockSpec((1, block_k, D), kv_map),
        pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_q, STAT_LANES), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_q, STAT_LANES), lambda b, i, j: (b, i, 0)),
    ]
    dq_operands = [q, k, v, do, lse, delta]
    for bound in (start, end):
        if bound is not None:
            dq_in_specs.append(
                pl.BlockSpec((1, 1, STAT_LANES), lambda b, i, j: (b, 0, 0))
            )
            dq_operands.append(bound)
    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k, num_kv=num_kv,
            has_start=start is not None, has_end=end is not None,
        ),
        grid=(BH, num_q, num_kv),
        in_specs=dq_in_specs,
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, S, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        compiler_params=_SEMANTICS,
        interpret=interpret,
    )(*dq_operands)

    # dK/dV grid runs over KV heads; the innermost dim is (group member,
    # q block) so one KV head's accumulator sums its whole query group.
    # Q-side rows for grid cell b (a KV-head row) and inner index gq:
    #   q_row = (b // kv_heads) * heads + (b % kv_heads) * groups + gq // num_q
    # Causal: Q blocks before this KV block's first visible one are
    # clamped up to it, so their (skipped) fetches are elided like the
    # forward's future-KV blocks.
    def q_map(b, j, gq):
        row = (b // kv_heads) * heads + (b % kv_heads) * groups + gq // num_q
        qi = gq % num_q
        if causal:
            qi = jnp.maximum(qi, (j * block_k) // block_q)
        return (row, qi, 0)

    dkv_in_specs = [
        pl.BlockSpec((1, block_q, D), q_map),
        pl.BlockSpec((1, block_k, D), lambda b, j, gq: (b, j, 0)),
        pl.BlockSpec((1, block_k, D), lambda b, j, gq: (b, j, 0)),
        pl.BlockSpec((1, block_q, D), q_map),
        pl.BlockSpec((1, block_q, STAT_LANES), q_map),
        pl.BlockSpec((1, block_q, STAT_LANES), q_map),
    ]
    dkv_operands = [q, k, v, do, lse, delta]
    for bound in (start, end):
        if bound is not None:
            # start/kv_len are per batch row (constant over heads): any
            # q-side row of this KV row's batch reads the same value.
            dkv_in_specs.append(
                pl.BlockSpec(
                    (1, 1, STAT_LANES),
                    lambda b, j, gq: ((b // kv_heads) * heads, 0, 0),
                )
            )
            dkv_operands.append(bound)
    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k, num_q=num_q,
            has_start=start is not None, has_end=end is not None,
        ),
        grid=(BKV, num_kv, groups * num_q),
        in_specs=dkv_in_specs,
        out_specs=[
            pl.BlockSpec((1, block_k, D), lambda b, j, gq: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, j, gq: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BKV, S, D), k.dtype),
            jax.ShapeDtypeStruct((BKV, S, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        compiler_params=_SEMANTICS,
        interpret=interpret,
    )(*dkv_operands)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public entry
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10, 11))
def _flash(
    q, k, v, start, end, scale, causal, block_q, block_k, heads, kv_heads,
    interpret,
):
    o, _ = _fwd(
        q, k, v, start, end, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k, heads=heads, kv_heads=kv_heads, interpret=interpret,
    )
    return o


def _flash_fwd(
    q, k, v, start, end, scale, causal, block_q, block_k, heads, kv_heads,
    interpret,
):
    o, lse = _fwd(
        q, k, v, start, end, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k, heads=heads, kv_heads=kv_heads, interpret=interpret,
    )
    return o, (q, k, v, o, lse, start, end)


def _flash_bwd(scale, causal, block_q, block_k, heads, kv_heads, interpret, res, do):
    import numpy as np

    q, k, v, o, lse, start, end = res
    dq, dk, dv = _bwd(
        q, k, v, o, lse, do, start, end, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, heads=heads, kv_heads=kv_heads,
        interpret=interpret,
    )
    # start/kv_len are integer data (pad counts): cotangent type float0.
    dstart = (
        None if start is None else np.zeros(start.shape, jax.dtypes.float0)
    )
    dend = None if end is None else np.zeros(end.shape, jax.dtypes.float0)
    return dq, dk, dv, dstart, dend


_flash.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def _flash_pair(q, k, v, scale, causal, block_q, block_k, heads, kv_heads, interpret):
    o, lse = _fwd(
        q, k, v, None, None, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k, heads=heads, kv_heads=kv_heads, interpret=interpret,
    )
    return o, lse[..., 0]  # lse: [BH, S] (drop the lane broadcast)


def _flash_pair_fwd(
    q, k, v, scale, causal, block_q, block_k, heads, kv_heads, interpret
):
    o, lse = _fwd(
        q, k, v, None, None, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k, heads=heads, kv_heads=kv_heads, interpret=interpret,
    )
    return (o, lse[..., 0]), (q, k, v, o, lse)


def _flash_pair_bwd(
    scale, causal, block_q, block_k, heads, kv_heads, interpret, res, cts
):
    do, dlse = cts
    q, k, v, o, lse = res
    dq, dk, dv = _bwd(
        q, k, v, o, lse, do, None, None, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, heads=heads, kv_heads=kv_heads,
        interpret=interpret, dlse=dlse,
    )
    return dq, dk, dv


_flash_pair.defvjp(_flash_pair_fwd, _flash_pair_bwd)


def fits_kernel(S: int, D: int | None = None) -> bool:
    """True when the auto-fit in ``_entry_prologue`` lands on a legal
    block configuration for sequence length ``S`` — THE predicate every
    trace-time gate consults (``workloads.attention.use_flash``, the
    ring's flash-hop gate), exported from here so a block-policy change
    can never silently diverge from its gates. ``D`` is accepted for
    future head-dim-dependent policies; the current fit is D-independent
    (large D only halves the starting defaults, which the shrink loop
    covers anyway).
    """
    del D
    return S % 128 == 0 or (S <= 1024 and S % 8 == 0)


def _entry_prologue(q, k, block_q, block_k, scale, interpret):
    """Shared public-entry prologue (flash_attention AND
    flash_attention_lse — one copy so block tuning can never drift
    between them): interpret autodetect, GQA validation, default-block
    auto-fit, divisibility check, scale default, head-fold.

    Defaults (512, 1024) won the on-chip sweep at S in [1k, 8k] for
    Dh <= 128; larger head dims halve both (the f32 score/prob tiles
    plus double-buffered KV blocks scale with Dh and would crowd the
    ~16 MB VMEM budget). The auto path shrinks the default to a
    power-of-two divisor of S, floored at 128 (the MXU dimension — an
    8-row block would be a pathological kernel), then falls back to a
    single whole-sequence block when S is short enough for VMEM;
    anything else raises. Explicit block sizes are clamped to S but
    otherwise honored strictly: a non-dividing choice raises rather than
    silently running a different configuration than the caller tuned.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    B, S, H, D = q.shape
    Hkv = k.shape[2]
    if H % Hkv:
        raise ValueError(f"q heads {H} not a multiple of kv heads {Hkv}")

    def _fit(requested, default):
        if requested is not None:
            return min(requested, S)
        b = min(default, S)
        while b > 128 and S % b:
            b //= 2
        # Whole-sequence fallback: both blocks may land here, making the
        # f32 score tile S x S — 1024 keeps that worst case at 4 MB VMEM.
        if S % b and S <= 1024:
            b = S
        return b

    block_q = _fit(block_q, 512 if D <= 128 else 256)
    block_k = _fit(block_k, 1024 if D <= 128 else 512)
    if S % block_q or S % block_k:
        raise ValueError(
            f"sequence length {S} not divisible by blocks ({block_q}, {block_k})"
        )
    sc = scale if scale is not None else 1.0 / math.sqrt(D)

    def fold(x):  # [B, S, h, D] -> [B*h, S, D]
        h = x.shape[2]
        return x.transpose(0, 2, 1, 3).reshape(B * h, S, x.shape[-1])

    return block_q, block_k, sc, interpret, fold, (B, S, H, D, Hkv)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    scale: float | None = None,
    block_q: int | None = None,
    block_k: int | None = None,
    start: jax.Array | None = None,
    kv_len: jax.Array | None = None,
    interpret: bool | None = None,
) -> jax.Array:
    """Flash attention over ``[B, S, H, D]`` arrays (layout of
    :func:`..parallel.ring.full_attention`, the correctness oracle).

    **GQA-native**: ``k``/``v`` may carry fewer heads than ``q`` (``H`` a
    multiple of ``Hkv``; KV head ``i`` serves query heads
    ``[i*g, (i+1)*g)``). The grouped K/V stream through the kernel as-is —
    no repeated copies in HBM, 1/g the KV bandwidth — and dK/dV accumulate
    each query group inside the kernel before a single writeback.

    ``start`` ([B] int32 leading-pad counts) masks each batch row's keys at
    positions ``< start[b]`` — LEFT-padded variable-length batches (the
    serving prefill layout, ``workloads.generate``) stay on the kernel
    instead of falling back to materialized-score attention. Rows whose
    queries sit entirely in the pad region produce zeros, never NaN, and
    KV blocks wholly inside the pad are skipped like causal future blocks.

    ``kv_len`` ([B] int32 per-row sequence lengths) is the mirror image
    for RIGHT padding: keys at positions ``>= kv_len[b]`` are masked and
    KV blocks wholly past the length are skipped — the continuous-batching
    engine's fresh-slot prompt chunks (``workloads.generate.prefill_slot``)
    keep their per-slot length bound in-kernel. Composes with ``start``
    (a two-sided window) and with ``causal``; real (in-window) rows'
    outputs are unchanged by either bound.

    ``interpret=None`` autodetects: compiled Mosaic on TPU, Pallas
    interpreter elsewhere (CPU tests, the virtual-device mesh harness).
    Sequence length must be divisible by the (auto-shrunk) block sizes
    (see ``_entry_prologue`` for the block policy).
    """
    block_q, block_k, sc, interpret, fold, (B, S, H, D, Hkv) = _entry_prologue(
        q, k, block_q, block_k, scale, interpret
    )

    def _per_row_bound(bound, what):
        if bound.shape != (B,):
            raise ValueError(f"{what} must be [{B}] (one bound per row)")
        # One row per folded (batch, head) pair, lane-broadcast to the
        # minimum legal f32/int32 tile (see STAT_LANES).
        return jnp.broadcast_to(
            jnp.repeat(bound.astype(jnp.int32), H)[:, None, None],
            (B * H, 1, STAT_LANES),
        )

    start_bh = None if start is None else _per_row_bound(start, "start")
    end_bh = None if kv_len is None else _per_row_bound(kv_len, "kv_len")

    o = _flash(
        fold(q), fold(k), fold(v), start_bh, end_bh, sc, causal, block_q,
        block_k, H, Hkv, interpret,
    )
    return o.reshape(B, H, S, D).transpose(0, 2, 1, 3)


def flash_attention_lse(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    scale: float | None = None,
    block_q: int | None = None,
    block_k: int | None = None,
    interpret: bool | None = None,
) -> tuple[jax.Array, jax.Array]:
    """:func:`flash_attention` that also returns the per-row logsumexp.

    Returns ``(o [B, S, H, D], lse [B, S, H] f32)``. The lse is what an
    online-softmax consumer needs to MERGE partial attention results —
    the ring (``parallel/ring.py``) runs this kernel per hop and combines
    the per-hop (o, lse) pairs exactly, so sequence-parallel long context
    gets kernel-grade attention instead of materialized score blocks.
    Fully differentiable: the lse cotangent folds into the backward's
    delta term (see ``_bwd``). Same block auto-fit and GQA contract as
    :func:`flash_attention` (shared ``_entry_prologue``); no pad-mask
    variant (the ring masks by hop).
    """
    block_q, block_k, sc, interpret, fold, (B, S, H, D, Hkv) = _entry_prologue(
        q, k, block_q, block_k, scale, interpret
    )
    o, lse = _flash_pair(
        fold(q), fold(k), fold(v), sc, causal, block_q, block_k, H, Hkv,
        interpret,
    )
    o = o.reshape(B, H, S, D).transpose(0, 2, 1, 3)
    lse = lse.reshape(B, H, S).transpose(0, 2, 1)  # [B, S, H]
    return o, lse
