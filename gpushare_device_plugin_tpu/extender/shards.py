"""Horizontally sharded scheduler extender.

One extender process tops out around 32 nodes / 960 pods on the storm
bench: every admission funnels its O(cluster-nodes) scoring pass and its
bind WAL fsync through one core. This module shards the extender by
NODE ownership:

- :class:`HashRing` — consistent-hash partitioning of nodes across N
  shards (virtual nodes for balance, minimal remap on resize). Each
  node has exactly ONE owner shard, so single-node placements have a
  single writer and cannot race across shards by construction.
- :class:`ShardExtender` — one shard: a full :class:`ExtenderCore`
  scoring from snapshot reads of its OWN informer index, journaling
  binds into its OWN per-shard group-commit WAL, plus the cross-shard
  two-phase-commit participant half (prepare/commit/abort of "gang2pc"
  reservations through an :class:`AssumeCache` ledger).
- :class:`ShardRouter` — a thin stateless router that fans webhook
  verbs out to the owning shards and merges ranked
  :class:`ScoreVector` results (projecting to the 0-10 wire scale only
  at its own edge). Shards that fail a fan-out land in
  ``degraded_shards`` on the merged decision record — "not consulted"
  is distinguishable from "rejected". The admission hot path
  (:meth:`ShardRouter.admit`) consults only the ``fanout`` most
  promising shards by cached free-capacity summaries — the
  work-reduction that buys the scale win (kube-scheduler's
  percentage-of-nodes-to-score, sharded) — and falls back to a full
  fan-out before declaring a pod unschedulable.
- Cross-shard gang groups — pods sharing ``ANN_GANG_GROUP`` are one
  distributed job whose members land on different nodes (and therefore
  different shards) and must be admitted all-or-nothing. The router
  runs a leader-elected two-phase reserve: the coordinator shard (the
  ring owner of the group id, fenced by a :class:`LeaderLease` epoch)
  collects a placement plan, every member shard journals a "gang2pc"
  prepare record and books the chips in its ledger BEFORE any member
  binds, the coordinator journals ONE durable commit/abort decision,
  and only then do members bind. :func:`resolve_gang2pc` is the
  reconciler half: incomplete prepares roll back, durable commit
  decisions roll forward — by phase, exactly like the PR 10 move
  protocol — so a crash at ANY step leaves zero partial gangs and zero
  orphaned cross-shard reservations (``make chaos-shard`` kills at
  every step and checks).

The ledger discipline is pinned by tpulint's ledger-encapsulation rule:
this module touches :class:`AssumeCache` ONLY through the 2PC reserve
API (claim/renew/reserve_gang/release/is_claimed/gang_snapshot/
expire_stale) — never per-shard internals, and never the single-chip
reservation families (the PR 6 gang double-booking class, again).
"""

from __future__ import annotations

import bisect
import hashlib
import json
import time
from typing import Any, Iterable, Sequence

from .. import const
from ..allocator.assume import AssumeCache, PodKey
from ..cluster import pods as P
from ..cluster.apiserver import ApiError, ApiServerClient
from ..utils.decisions import DECISIONS, ScoreVector, rank_scores
from ..utils.faults import FAULTS
from ..utils.lockrank import make_lock
from ..utils.log import get_logger
from ..utils.metrics import REGISTRY
from . import logic
from .server import ExtenderCore
from ..utils.metric_catalog import GANG2PC_TOTAL as TWOPC_METRIC

# A committed 2PC reservation normally drains when the watch shows the
# annotated pod on its node. Two paths never get that signal: the pod
# deleted before any scoring read observed it, and list-source cores
# with no informer at all. After this grace the reservation releases
# anyway — by then either the pod source counts the real pod (release
# is correct) or the pod is gone (release is overdue); holding longer
# only strands capacity.
COMMIT_VISIBILITY_GRACE_S = 60.0

# How long an undecided prepare belonging to a LIVE coordinator (its
# lease epoch still held) is protected from the reconciler's presumed-
# abort rollback. A live protocol finishes in milliseconds; a prepare
# this old under a still-held lease means the coordinator wedged, and
# the override then both rolls back AND FENCES: the resolver takes a
# higher lease epoch and seeds it onto the member + coordinator shards,
# so a late-waking driver hits StaleCoordinator at its (epoch-gated)
# decision point instead of committing on top of re-booked chips.
# Without the gate itself, the live resolve loop could roll back a
# prepare the coordinator was about to decide on — releasing chips its
# durable decision later rolls FORWARD onto, after a competing group
# booked them: the gang double-booking one layer up, found by
# tools/tpumc (model "gang2pc-resolve", pinned in tests/test_tpumc.py).
LIVE_PREPARE_GRACE_S = 60.0

log = get_logger("shards")

# Synthetic namespace for cross-shard two-phase reservations in the
# ledger (the defrag mover's "tpushare-defrag" pattern): keys under it
# can never collide with a real pod's admission claim.
GANG2PC_NS = "tpushare-gang2pc"

WAL_KIND_2PC = "gang2pc"

TWOPC_HELP = (
    "Cross-shard two-phase gang operations by phase and outcome "
    "(prepare/decide/commit/abort/rollforward/rollback)"
)


class ShardUnavailable(ConnectionError):
    """A shard could not be consulted (partitioned, crashed): the router
    records it in ``degraded_shards`` instead of failing the verb."""


class StaleCoordinator(RuntimeError):
    """A 2PC message carried a fenced coordinator epoch: a newer leader
    has taken over this group and the old one must stop driving it."""


# --- consistent-hash ring ---------------------------------------------------


def _h64(data: str) -> int:
    return int.from_bytes(
        hashlib.blake2b(data.encode(), digest_size=8).digest(), "big"
    )


class HashRing:
    """Consistent-hash ownership of node names across shard ids.

    ``vnodes`` virtual points per shard keep the partition balanced
    (128 points put the max/mean node spread around ~15% at 1k nodes);
    resizing from N to N+1 shards remaps ~1/(N+1) of the nodes instead
    of reshuffling the world. Pure function of (shard_ids, vnodes) —
    every router and every shard derive the SAME ownership with no
    coordination."""

    def __init__(self, shard_ids: Sequence[str], vnodes: int = 128) -> None:
        if not shard_ids:
            raise ValueError("hash ring needs at least one shard")
        self._shard_ids = tuple(shard_ids)
        self._vnodes = vnodes
        points: list[tuple[int, str]] = []
        for sid in self._shard_ids:
            for v in range(vnodes):
                points.append((_h64(f"{sid}#{v}"), sid))
        points.sort()
        self._points = points
        self._keys = [p[0] for p in points]

    @property
    def shard_ids(self) -> tuple[str, ...]:
        return self._shard_ids

    def owner(self, name: str) -> str:
        """The shard owning ``name`` (a node name, or any key needing a
        deterministic home — gang-group leader election hashes the
        group id through the same ring)."""
        h = _h64(name)
        i = bisect.bisect_right(self._keys, h)
        if i >= len(self._points):
            i = 0
        return self._points[i][1]

    def partition(self, names: Iterable[str]) -> dict[str, list[str]]:
        """Group ``names`` by owner shard (owners with no names absent)."""
        out: dict[str, list[str]] = {}
        for name in names:
            out.setdefault(self.owner(name), []).append(name)
        return out

    def doc(self, node_names: Iterable[str] = ()) -> dict[str, Any]:
        """Ring summary for the shard-map CLI."""
        counts = {sid: 0 for sid in self._shard_ids}
        for name in node_names:
            counts[self.owner(name)] += 1
        return {
            "shards": len(self._shard_ids),
            "vnodes": self._vnodes,
            "nodes_per_shard": counts,
        }


# --- leader lease -----------------------------------------------------------


class LeaderLease:
    """Per-gang-group coordinator epochs — the 2PC fencing tokens.

    ``acquire`` hands the caller a strictly higher epoch for the group
    and records it as current; participants reject 2PC messages whose
    epoch is below the highest they have seen, so a coordinator that
    lost its lease mid-protocol (chaos: "leader fenced mid-commit")
    cannot keep driving — the new leader re-drives from the journaled
    state via :func:`resolve_gang2pc`."""

    def __init__(self) -> None:
        self._lock = make_lock("extender.lease")
        self._epochs: dict[str, int] = {}
        self._holders: dict[str, str] = {}

    def acquire(self, group: str, shard_id: str) -> int:
        with self._lock:
            epoch = self._epochs.get(group, 0) + 1
            self._epochs[group] = epoch
            self._holders[group] = shard_id
            return epoch

    def current(self, group: str) -> tuple[str, int]:
        """(holder shard id, epoch); ("", 0) when never acquired."""
        with self._lock:
            return self._holders.get(group, ""), self._epochs.get(group, 0)

    def forget(self, group: str) -> None:
        """Drop a finished group's lease state (bounded tables)."""
        with self._lock:
            self._epochs.pop(group, None)
            self._holders.pop(group, None)


# --- one shard --------------------------------------------------------------


class ShardExtender:
    """One horizontal shard of the extender.

    Owns a full :class:`ExtenderCore` (its own informer usage index,
    NodeView cache, in-flight overlay, and per-shard group-commit bind
    WAL) restricted by the router to the ring's nodes, plus the 2PC
    participant half: journaled "gang2pc" reservations in an
    :class:`AssumeCache` ledger, folded into every scoring read through
    the core's usage-overlay hook so a prepared-but-undecided gang
    member is invisible to NO placement decision.
    """

    def __init__(
        self,
        shard_id: str,
        api: ApiServerClient,
        informer: Any = None,
        checkpoint: Any = None,
        policy: "str | logic.PlacementPolicy" = "best-fit",
        ledger: AssumeCache | None = None,
    ) -> None:
        self.shard_id = shard_id
        self._api = api
        self._ckpt = checkpoint
        # the configured placement policy, public so the router's
        # gang-group planner scores members with the SAME policy the
        # shard's own verbs use
        self.policy = policy
        self._ledger = ledger if ledger is not None else AssumeCache()
        self._twopc_lock = make_lock("extender.twopc")
        # 2PC side-state, reconstructible from the WAL: ledger key ->
        # {"node", "chips", "units", "epoch", "group", "shape"}. The
        # ledger's reserve_gang entry carries (chip, units) but not the
        # node — this map pins each reservation to the node it protects.
        self._twopc: dict[PodKey, dict[str, Any]] = {}
        self._epochs: dict[str, int] = {}  # group -> highest seen epoch
        # Test hook: a partitioned shard refuses every consultation, the
        # way a network-split real shard would.
        self.partitioned = False
        self.core = ExtenderCore(
            api,
            policy=policy,
            informer=informer,
            checkpoint=checkpoint,
            shard=shard_id,
            usage_overlay_fn=self._twopc_overlay,
        )
        self._owned: dict[str, dict] = {}
        self._summary_cache: tuple[float, dict[str, Any]] | None = None
        self._summary_ttl_s = 0.25
        if checkpoint is not None:
            self._replay_2pc()

    # --- wiring -----------------------------------------------------------

    def set_nodes(self, nodes: Iterable[dict]) -> None:
        """The node objects this shard owns (router-assigned from the
        ring partition; refreshed when the catalog changes)."""
        self._owned = {
            n.get("metadata", {}).get("name", ""): n for n in nodes
        }
        self._summary_cache = None

    def owned_nodes(self) -> list[dict]:
        return list(self._owned.values())

    def owned_node(self, name: str) -> dict | None:
        return self._owned.get(name)

    def _check_reachable(self) -> None:
        if self.partitioned:
            raise ShardUnavailable(f"shard {self.shard_id} partitioned")

    # --- scoring/verbs (router-facing) ------------------------------------

    def batch_scored(self, args: dict) -> dict:
        self._check_reachable()
        return self.core.batch_scored(args)

    def filter(self, args: dict) -> dict:
        self._check_reachable()
        return self.core.filter(args)

    def prioritize(self, args: dict) -> list[dict]:
        self._check_reachable()
        return self.core.prioritize(args)

    def bind(self, args: dict) -> dict:
        self._check_reachable()
        return self.core.bind(args)

    def summary(self) -> dict[str, Any]:
        """Cheap routing summary over the shard's owned nodes — total
        free units and the largest single-chip free block — cached for
        ``_summary_ttl_s`` so the router's per-admission shard ranking
        costs O(1) amortized instead of O(nodes/shard)."""
        self._check_reachable()
        now = time.monotonic()
        cached = self._summary_cache
        if cached is not None and now - cached[0] < self._summary_ttl_s:
            return cached[1]
        free_total = 0
        max_free = 0
        for view in self.core.node_views(
            list(self._owned.values()), const.RESOURCE_MEM
        ):
            for units in view.free().values():
                free_total += units
                if units > max_free:
                    max_free = units
        doc = {
            "shard": self.shard_id,
            "nodes": len(self._owned),
            "free_units": free_total,
            "max_free_chip": max_free,
        }
        self._summary_cache = (now, doc)
        return doc

    # --- 2PC participant ---------------------------------------------------

    @staticmethod
    def twopc_key(group: str, ns: str, name: str) -> PodKey:
        return (GANG2PC_NS, f"{group}/{ns}/{name}")

    def _journal_2pc(self, key: PodKey, data: dict) -> int | None:
        """Journal one gang2pc record (durable before any side effect).
        Returns the begin seq for seq-guarded resolution — callers must
        keep it (resolve it, return it, or store it in the 2PC
        side-state); a discarded seq can never be resolved by anyone
        and is flagged by tpulint's wal-protocol rule."""
        if self._ckpt is None:
            return None
        data = dict(data)
        data["kind"] = WAL_KIND_2PC
        data["ts"] = time.time()
        return self._ckpt.begin(key, data)

    def _resolve_2pc(self, op: str, key: PodKey, seq: int | None) -> None:
        if self._ckpt is None:
            return
        if op == "commit":
            self._ckpt.commit(key, seq=seq)
        else:
            self._ckpt.abort(key, seq=seq)

    def _note_epoch(self, group: str, epoch: int) -> None:
        """Record the highest coordinator epoch seen for ``group``;
        raises :class:`StaleCoordinator` for a lower one."""
        with self._twopc_lock:
            seen = self._epochs.get(group, 0)
            if epoch < seen:
                raise StaleCoordinator(
                    f"shard {self.shard_id}: epoch {epoch} < seen {seen} "
                    f"for group {group}"
                )
            self._epochs[group] = epoch

    def prepare_gang(
        self,
        group: str,
        ns: str,
        name: str,
        node: str,
        chips: Sequence[int],
        per_chip: int,
        shape: str,
        epoch: int,
        coordinator: str,
    ) -> tuple[bool, str]:
        """Phase 1: journal the member's reservation durably, book the
        chips in the ledger as ONE atomic gang entry, then re-validate
        the node inside the booked overlay (the defrag mover's
        reserve-then-check pattern: a plan the world outran aborts
        cleanly instead of over-booking). -> (prepared, reason)."""
        self._check_reachable()
        self._note_epoch(group, epoch)
        key = self.twopc_key(group, ns, name)
        # Claim BEFORE journaling: a same-member re-prepare (a retrying
        # router racing a crashed attempt's pending entry) must fail
        # here without writing — journaling first would overwrite the
        # live attempt's pending record and the claim-failure abort
        # would then pop it, orphaning its reservation journal-less.
        if not self._ledger.claim(key):
            return False, f"{key[1]} already mid-2PC on {self.shard_id}"
        seq = self._journal_2pc(key, {
            "phase": "prepare",
            "group": group,
            "pod_ns": ns,
            "pod_name": name,
            "node": node,
            "chips": [int(c) for c in chips],
            "units": int(per_chip),
            "shape": shape,
            "epoch": epoch,
            "coordinator": coordinator,
        })
        FAULTS.fire("gang2pc.prepare")
        self._ledger.reserve_gang(key, [(int(c), per_chip) for c in chips])
        with self._twopc_lock:
            self._twopc[key] = {
                "node": node, "chips": tuple(int(c) for c in chips),
                "units": int(per_chip), "epoch": epoch, "group": group,
                "shape": shape, "seq": seq, "phase": "prepare",
                "pod_ns": ns, "pod_name": name,
            }
        FAULTS.fire("gang2pc.reserve")
        # Re-validate INSIDE the booked overlay: our own reservation is
        # now counted, so per-chip usage must sit within capacity and no
        # member may be exclusively held. A concurrent admission that
        # landed between the router's plan and this prepare fails the
        # check and the member aborts cleanly.
        node_obj = self._owned.get(node)
        if node_obj is None:
            try:
                node_obj = self._api.get_node(node)
            except ApiError as e:
                self._rollback_member(key, seq)
                return False, f"node {node} unreadable: {e}"
        view = self.core.node_view(node_obj, const.RESOURCE_MEM)
        for c in chips:
            if c in view.core_held or view.used.get(c, 0) > view.capacity.get(c, -1):
                self._rollback_member(key, seq)
                return False, (
                    f"chip {c} on {node} no longer admits {per_chip} "
                    f"units (outrun by a concurrent admission)"
                )
        REGISTRY.counter_inc(
            TWOPC_METRIC, TWOPC_HELP, phase="prepare", outcome="ok",
        )
        return True, ""

    def _rollback_member(
        self, key: PodKey, seq: int | None, drop_epoch: bool = True
    ) -> None:
        """``drop_epoch=False`` is the wedged-coordinator fencing path:
        the resolver seeds a higher fencing epoch BEFORE this rollback,
        and the normal finished-group pruning here would drop that fence
        in the exact window the late-waking driver needs it."""
        self._ledger.release(key)
        with self._twopc_lock:
            entry = self._twopc.pop(key, None)
        if seq is None and entry is not None:
            seq = entry.get("seq")
        if seq is not None:
            # seq-guarded only: an unguarded abort could pop a NEWER
            # same-key begin (a fresh 2PC attempt racing this idempotent
            # re-delivery) — with no seq in hand, leave any pending
            # entry for the reconciler, which resolves with the seq it
            # read from the journal itself
            self._resolve_2pc("abort", key, seq)
        if drop_epoch:
            self._drop_finished_epoch(
                entry.get("group", "") if entry else ""
            )
        REGISTRY.counter_inc(
            TWOPC_METRIC, TWOPC_HELP, phase="abort", outcome="ok",
        )

    def _drop_finished_epoch(self, group: str) -> None:
        """Prune a finished group's fencing epoch once no 2PC side-state
        references it — the epoch only fences an in-flight protocol, and
        an unbounded epoch table would grow with every gang group the
        shard ever saw (the storm mints a fresh group id per burst)."""
        if not group:
            return
        with self._twopc_lock:
            if any(e.get("group") == group for e in self._twopc.values()):
                return
            self._epochs.pop(group, None)

    def commit_gang(
        self, group: str, ns: str, name: str, epoch: int,
        total_request: int = 0,
    ) -> tuple[bool, str]:
        """Phase 2 (commit): persist the member's gang annotations + v1
        Binding from the prepared reservation. The coordinator calls
        this only after its commit decision is durable; the reservation
        stays in the ledger until the watch shows the annotated pod
        (the overlay's visibility release), so there is no window where
        the member is counted nowhere."""
        self._check_reachable()
        self._note_epoch(group, epoch)
        key = self.twopc_key(group, ns, name)
        with self._twopc_lock:
            entry = self._twopc.get(key)
        if entry is None:
            # already committed (idempotent re-delivery), or never
            # prepared here — the apiserver is the arbiter
            try:
                pod = self._api.get_pod(ns, name)
            except ApiError as e:
                return False, f"no prepared entry and pod unreadable: {e}"
            if P.gang_chips_from_annotation(pod):
                return True, ""
            return False, "no prepared entry for member"
        try:
            pod = self._api.get_pod(ns, name)
        except ApiError as e:
            return False, f"pod unreadable at commit: {e}"
        annotations = self._member_annotations(pod, entry, total_request)
        try:
            self._api.patch_pod(
                ns, name, {"metadata": {"annotations": annotations}}
            )
            self._api.bind_pod(ns, name, entry["node"])
        except ApiError as e:
            return False, f"member persist failed: {e}"
        FAULTS.fire("gang2pc.patch")
        self._resolve_2pc("commit", key, entry.get("seq"))
        with self._twopc_lock:
            entry["phase"] = "committed"
            entry["committed_ts"] = time.monotonic()
        FAULTS.fire("gang2pc.commit")
        REGISTRY.counter_inc(
            TWOPC_METRIC, TWOPC_HELP, phase="commit", outcome="ok",
        )
        return True, ""

    def note_committed(self, group: str, ns: str, name: str) -> None:
        """Flip a member's 2PC side-state to committed WITHOUT releasing
        its ledger reservation: the reservation must keep protecting the
        chips until the informer shows the annotated pod (the overlay's
        visibility release) — releasing at resolve time would open the
        same counted-nowhere window the allocator ledger's
        persist->release ordering exists to close."""
        key = self.twopc_key(group, ns, name)
        with self._twopc_lock:
            entry = self._twopc.get(key)
            if entry is not None:
                entry["phase"] = "committed"
                entry["committed_ts"] = time.monotonic()

    def abort_gang(self, group: str, ns: str, name: str, epoch: int) -> bool:
        """Phase 2 (abort): release the member's reservation and resolve
        its journal entry. Idempotent. Unlike commit, abort checks the
        epoch against the ENTRY's own epoch, not the group's highest
        seen: a coordinator fenced mid-prepare must still be able to
        presumed-abort what IT booked (no decision exists, so aborting
        is always safe), while an old coordinator can never abort a
        NEWER attempt's prepare."""
        self._check_reachable()
        key = self.twopc_key(group, ns, name)
        with self._twopc_lock:
            entry = self._twopc.get(key)
        if entry is not None and epoch < int(entry.get("epoch") or 0):
            raise StaleCoordinator(
                f"shard {self.shard_id}: abort epoch {epoch} below the "
                f"prepared entry's {entry.get('epoch')} for {key[1]}"
            )
        self._rollback_member(key, entry.get("seq") if entry else None)
        return True

    def _member_annotations(
        self, pod: dict, entry: dict[str, Any], total_request: int
    ) -> dict[str, str]:
        """The member's one-PATCH gang grant, mirroring
        ``logic.choose_gang_scored``'s annotation shape so the device
        plugin's branch A re-validates it identically."""
        family = logic.RESOURCE_FAMILIES[const.RESOURCE_MEM]
        chips = entry["chips"]
        per_chip = entry["units"]
        request = total_request or P.mem_units_of_pod(pod)
        containers = pod.get("spec", {}).get("containers", [])
        alloc_map: dict[str, dict[str, int]] = {}
        for i, c in enumerate(containers):
            units = P.mem_units_of_container(c, const.RESOURCE_MEM)
            if units <= 0:
                continue
            per = units // len(chips)
            alloc_map[c.get("name", f"c{i}")] = {
                str(idx): per for idx in chips
            }
        # the owned-node map can be empty at recovery time (shards.main
        # runs resolve_gang2pc before the first catalog refresh): fall
        # back to the apiserver so ENV_MEM_DEV carries the real chip
        # capacity — the serving engine sizes its pool from it
        node_obj = self._owned.get(entry["node"])
        if node_obj is None:
            try:
                node_obj = self._api.get_node(entry["node"])
            except ApiError:
                node_obj = {}
        cap = logic.node_capacity(node_obj, const.RESOURCE_MEM) if node_obj else {}
        return {
            const.ENV_GANG_CHIPS: ",".join(str(i) for i in chips),
            const.ENV_GANG_SHAPE: entry.get("shape", str(len(chips))),
            const.ENV_GANG_PER_CHIP: str(per_chip),
            const.ANN_GANG_GROUP: entry.get("group", ""),
            family["pod"]: str(request),
            family["dev"]: str(cap.get(chips[0], 0)),
            family["assigned"]: "false",
            family["assume"]: str(time.time_ns()),
            const.ANN_EXTENDER_ALLOCATION: json.dumps(alloc_map),
        }

    # --- overlay + replay --------------------------------------------------

    def _twopc_overlay(self, node: str, resource: str) -> dict[int, int]:
        """The core's usage-overlay hook: in-flight gang2pc reservations
        for ``node``, with lazy visibility release — once the informer
        shows the committed member's annotated pod on the node, the pod
        source counts it and the reservation is redundant (same
        persist->release window rule as the allocator ledger)."""
        if resource != const.RESOURCE_MEM:
            return {}
        with self._twopc_lock:
            entries = [
                (key, dict(e)) for key, e in self._twopc.items()
                if e.get("node") == node
            ]
        if not entries:
            return {}
        informer = getattr(self.core, "_informer", None)
        now = time.monotonic()
        extra: dict[int, int] = {}
        release: list[PodKey] = []
        for key, entry in entries:
            if entry.get("phase") == "committed":
                if informer is not None:
                    cached = informer.get_pod(
                        entry.get("pod_ns", ""), entry.get("pod_name", "")
                    )
                    # Release only when the index provably counts the pod
                    # ON THIS NODE: the annotation MODIFIED can precede
                    # the bind MODIFIED (nodeName still empty), filing
                    # the pod under node "" — releasing then would leave
                    # the member counted NOWHERE for a window, the
                    # cross-shard double-booking this storm-tested
                    # overlay exists to prevent.
                    if (
                        cached is not None
                        and P.gang_chips_from_annotation(cached)
                        and P.node_name(cached) == node
                    ):
                        release.append(key)
                        continue
                # no visibility signal will ever come for a pod deleted
                # before the watch showed it (or on list-source cores):
                # after the grace, release anyway — the pod source now
                # counts the real pod or the pod is gone
                if (
                    now - float(entry.get("committed_ts") or now)
                    > COMMIT_VISIBILITY_GRACE_S
                ):
                    release.append(key)
                    continue
            for c in entry["chips"]:
                extra[c] = extra.get(c, 0) + entry["units"]
        for key in release:
            self._ledger.release(key)
            with self._twopc_lock:
                released = self._twopc.pop(key, None)
            self._drop_finished_epoch(
                released.get("group", "") if released else ""
            )
        return extra

    def _replay_2pc(self) -> None:
        """Reinstall 2PC reservations from the per-shard WAL at restart:
        a prepared-but-undecided member keeps protecting its chips until
        :func:`resolve_gang2pc` rolls it forward or back — the same
        pending-entry contract as ``replay_checkpoint``."""
        restored = 0
        for key, data in self._ckpt.pending().items():
            if data.get("kind") != WAL_KIND_2PC:
                continue
            if data.get("phase") != "prepare":
                continue
            chips = [int(c) for c in (data.get("chips") or ())]
            units = int(data.get("units") or 0)
            if not chips or units <= 0:
                continue
            self._ledger.claim(key)
            self._ledger.reserve_gang(key, [(c, units) for c in chips])
            with self._twopc_lock:
                self._twopc[key] = {
                    "node": str(data.get("node", "")),
                    "chips": tuple(chips),
                    "units": units,
                    "epoch": int(data.get("epoch") or 0),
                    "group": str(data.get("group", "")),
                    "shape": str(data.get("shape", "")),
                    "seq": data.get("_seq"),
                    "phase": "prepare",
                    "pod_ns": str(data.get("pod_ns", "")),
                    "pod_name": str(data.get("pod_name", "")),
                }
            restored += 1
        if restored:
            log.info(
                "shard %s: %d gang2pc reservation(s) replayed from WAL",
                self.shard_id, restored,
            )

    # --- introspection -----------------------------------------------------

    def twopc_pending(self) -> list[dict[str, Any]]:
        """Pending gang2pc journal entries (prepares AND coordinator
        decisions) from this shard's WAL, for the reconciler and the
        shard-map CLI."""
        if self._ckpt is None:
            return []
        out = []
        for key, data in self._ckpt.pending().items():
            if data.get("kind") != WAL_KIND_2PC:
                continue
            doc = dict(data)
            doc["key"] = list(key)
            out.append(doc)
        return out

    def doc(self) -> dict[str, Any]:
        """One shard's row in the shard map."""
        gangs = self.twopc_pending()
        return {
            "shard": self.shard_id,
            "nodes": len(self._owned),
            "partitioned": self.partitioned,
            "wal_seq": (
                self._ckpt.last_seq if self._ckpt is not None else 0
            ),
            "wal_pending": (
                len(self._ckpt.pending()) if self._ckpt is not None else 0
            ),
            "gangs_inflight": sum(
                1 for g in gangs if g.get("phase") == "prepare"
            ),
        }


# --- router -----------------------------------------------------------------


class ShardRouter:
    """Stateless verb router over the shard set.

    Holds no placement state of its own — ownership is the pure hash
    ring, scoring state lives in the shards, durability in their WALs —
    so any number of router replicas can front the same shards. The
    only router-local state is the cached shard summaries that steer
    the pruned admission fan-out, and those are reconstructible
    cache."""

    def __init__(
        self,
        shards: Sequence[ShardExtender],
        ring: HashRing | None = None,
        fanout: int = 2,
        lease: LeaderLease | None = None,
    ) -> None:
        if not shards:
            raise ValueError("router needs at least one shard")
        self._shards = {s.shard_id: s for s in shards}
        self._ring = ring or HashRing([s.shard_id for s in shards])
        self._fanout = max(1, fanout)
        self._lease = lease or LeaderLease()
        self._lock = make_lock("extender.router")
        self._nodes: dict[str, dict] = {}

    @property
    def ring(self) -> HashRing:
        return self._ring

    @property
    def lease(self) -> LeaderLease:
        """The gang-group coordinator lease. A live reconciler pass MUST
        resolve with this same lease (``resolve_gang2pc(..., lease=
        router.lease)``) so it can tell a live coordinator's undecided
        prepare from a dead one's — rolling back the former re-creates
        the cross-shard double-booking (see :data:`LIVE_PREPARE_GRACE_S`
        and the tpumc counterexample it cites)."""
        return self._lease

    def set_nodes(self, nodes: Iterable[dict]) -> None:
        """Install the node catalog: partitions by ring owner and hands
        each shard its owned node objects."""
        nodes = list(nodes)
        with self._lock:
            self._nodes = {
                n.get("metadata", {}).get("name", ""): n for n in nodes
            }
        owned = self._ring.partition(
            n.get("metadata", {}).get("name", "") for n in nodes
        )
        by_name = {n.get("metadata", {}).get("name", ""): n for n in nodes}
        for sid, shard in self._shards.items():
            shard.set_nodes([by_name[name] for name in owned.get(sid, [])])

    def shard(self, shard_id: str) -> ShardExtender:
        return self._shards[shard_id]

    # --- fan-out verbs -----------------------------------------------------

    def _partitioned_nodes(
        self, nodes: list[dict]
    ) -> dict[str, list[dict]]:
        owned = self._ring.partition(
            n.get("metadata", {}).get("name", "") for n in nodes
        )
        by_name = {n.get("metadata", {}).get("name", ""): n for n in nodes}
        return {
            sid: [by_name[name] for name in names]
            for sid, names in owned.items()
        }

    def batch(self, args: dict, _verb: str = "batch") -> dict:
        """Fan the batch verb out to every owning shard and merge the
        ranked ScoreVector results (wire shape via the SAME
        ``batch_wire`` projection the single core uses — the two
        deployments cannot drift). Unreachable shards degrade: their
        nodes appear in neither ``nodenames`` nor ``failedNodes`` —
        they were never consulted — and the merged decision record (and
        the wire response) names them in ``degraded_shards``. ``_verb``
        labels the decision record when another verb (prioritize)
        delegates here."""
        from .server import batch_wire

        pod = args.get("pod") or {}
        nodes = args.get("nodes", {}).get("items") or []
        meta = pod.get("metadata", {}) if pod else {}
        pod_key = f"{meta.get('namespace', 'default')}/{meta.get('name', '')}"
        if logic.pod_resource(pod) is None:
            # not a share pod: everything passes with score 0, exactly
            # like the single extender (a scoreless merge would filter
            # every node out — the scheduler would see it unschedulable)
            names = [n.get("metadata", {}).get("name", "") for n in nodes]
            DECISIONS.emit(
                pod_key, _verb, candidates=len(nodes),
                reason="pod requests no share resource (all nodes pass)",
                shard="router",
            )
            wire = batch_wire({
                "fits": names, "failed": {}, "scores": {},
                "resource": None, "nodes": nodes,
            })
            wire["degraded_shards"] = []
            return wire
        merged_fits: list[str] = []
        merged_failed: dict[str, str] = {}
        merged_scores: dict[str, ScoreVector] = {}
        degraded: list[str] = []
        resource = ""
        for sid, sub_nodes in sorted(self._partitioned_nodes(nodes).items()):
            shard = self._shards[sid]
            try:
                rich = shard.batch_scored(
                    {"pod": pod, "nodes": {"items": sub_nodes}}
                )
            except (ShardUnavailable, ApiError, OSError) as e:
                log.warning("shard %s degraded on %s: %s", sid, _verb, e)
                degraded.append(sid)
                continue
            merged_fits.extend(rich["fits"])
            merged_failed.update(rich["failed"])
            merged_scores.update(rich["scores"])
            resource = rich["resource"] or resource
        DECISIONS.emit(
            pod_key, _verb,
            candidates=len(nodes),
            rejected=merged_failed,
            scores=merged_scores,
            shard="router",
            degraded_shards=degraded,
        )
        fit_set = set(merged_fits)
        wire = batch_wire({
            # fits ranked best-first by the merged RAW scores — the
            # cross-shard half of the deterministic ordering
            "fits": [n for n in rank_scores(merged_scores)
                     if n in fit_set],
            "failed": merged_failed,
            "scores": merged_scores,
            "resource": resource or const.RESOURCE_MEM,
            "nodes": nodes,
        })
        wire["degraded_shards"] = degraded
        return wire

    def filter(self, args: dict) -> dict:
        """Filter fan-out: each owning shard runs its own (score-less)
        filter verb — a two-verb scheduler must not pay the batch
        verb's full scoring pass twice per cycle. Degraded shards'
        nodes are not consulted and reported as such."""
        pod = args.get("pod") or {}
        nodes = args.get("nodes", {}).get("items") or []
        merged_fits: list[str] = []
        merged_failed: dict[str, str] = {}
        degraded: list[str] = []
        for sid, sub_nodes in sorted(self._partitioned_nodes(nodes).items()):
            shard = self._shards[sid]
            try:
                res = shard.filter(
                    {"pod": pod, "nodes": {"items": sub_nodes}}
                )
            except (ShardUnavailable, ApiError, OSError) as e:
                log.warning("shard %s degraded on filter: %s", sid, e)
                degraded.append(sid)
                continue
            merged_fits.extend(res.get("nodenames") or [])
            merged_failed.update(res.get("failedNodes") or {})
        fit_set = set(merged_fits)
        return {
            "nodes": {"items": [
                n for n in nodes
                if n.get("metadata", {}).get("name") in fit_set
            ]},
            "nodenames": merged_fits,
            "failedNodes": merged_failed,
            "degraded_shards": degraded,
            "error": "",
        }

    def prioritize(self, args: dict) -> list[dict]:
        """Prioritize fan-out (the batch machinery, recorded under its
        own verb so ``/decisions?verb=prioritize`` matches the wire)."""
        return self.batch(args, _verb="prioritize")["hostPriorityList"]

    def bind(self, args: dict) -> dict:
        """Route the bind to the node's owner shard — the single writer
        for everything on that node."""
        node = args.get("node", "")
        sid = self._ring.owner(node)
        try:
            return self._shards[sid].bind(args)
        except (ShardUnavailable, OSError) as e:
            return {"error": f"owner shard {sid} unavailable: {e}"}

    # --- pruned admission (the scale hot path) ----------------------------

    def _ranked_shards(self, request_units: int) -> list[ShardExtender]:
        """Shards most likely to admit ``request_units``, best first:
        cached summaries, largest feasible single-chip block first, then
        total free. Degraded shards rank last (still consulted in the
        full-fanout fallback — a partitioned shard heals)."""
        scored: list[tuple[int, int, int, str]] = []
        for sid, shard in self._shards.items():
            try:
                s = shard.summary()
            except (ShardUnavailable, ApiError, OSError):
                scored.append((1, 0, 0, sid))
                continue
            feasible = 0 if s["max_free_chip"] >= request_units else 1
            scored.append(
                (feasible, -s["max_free_chip"], -s["free_units"], sid)
            )
        scored.sort()
        return [self._shards[sid] for _f, _m, _t, sid in scored]

    def admit(self, pod: dict) -> dict[str, Any]:
        """One end-to-end admission: consult the ``fanout`` most
        promising shards' own nodes (batch_scored), pick the best raw
        score across them, bind on the owner. Falls back to a full
        fan-out when the pruned consultation finds nothing — a pod is
        only unschedulable when EVERY reachable shard says so. ->
        ``{"node", "shard", "error", "consulted", "degraded_shards"}``."""
        meta = pod.get("metadata", {})
        ns = meta.get("namespace", "default")
        name = meta.get("name", "")
        if logic.pod_resource(pod) is None:
            return {
                "node": "", "shard": "",
                "error": "pod requests no share resource",
                "consulted": 0, "degraded_shards": [],
            }
        request = P.mem_units_of_pod(pod)
        ranked = self._ranked_shards(request)
        degraded: list[str] = []
        consulted = 0
        for attempt_set in (ranked[: self._fanout], ranked[self._fanout:]):
            best: tuple[float, str, str] | None = None  # (-raw, node, shard)
            for shard in attempt_set:
                sub_nodes = shard.owned_nodes()
                if not sub_nodes:
                    continue
                try:
                    rich = shard.batch_scored(
                        {"pod": pod, "nodes": {"items": sub_nodes}}
                    )
                except (ShardUnavailable, ApiError, OSError) as e:
                    log.warning(
                        "shard %s degraded on admit: %s", shard.shard_id, e
                    )
                    degraded.append(shard.shard_id)
                    continue
                consulted += 1
                for node_name in rich["fits"]:
                    sv = rich["scores"].get(node_name)
                    if sv is None:
                        continue
                    cand = (-sv.raw, node_name, shard.shard_id)
                    if best is None or cand < best:
                        best = cand
            if best is None:
                continue
            _raw, node_name, sid = best
            result = self._shards[sid].bind({
                "podNamespace": ns, "podName": name, "node": node_name,
                "podObject": pod,
                "nodeObject": self._shards[sid].owned_node(node_name),
            })
            if result.get("error"):
                # the chosen chip was outrun mid-flight; surface the
                # error — the driver retries like a real scheduler would
                return {
                    "node": "", "shard": sid, "error": result["error"],
                    "consulted": consulted, "degraded_shards": degraded,
                }
            return {
                "node": node_name, "shard": sid, "error": "",
                "consulted": consulted, "degraded_shards": degraded,
            }
        return {
            "node": "", "shard": "", "error": "no shard admits the pod",
            "consulted": consulted, "degraded_shards": degraded,
        }

    # --- cross-shard gang groups (two-phase reserve) -----------------------

    def admit_gang_group(self, pods: Sequence[dict]) -> dict[str, Any]:
        """All-or-nothing admission of a gang GROUP (pods sharing
        ``ANN_GANG_GROUP``) whose members land on different nodes and
        shards.

        Plan: place members greedily across shards (each member's
        candidate from its shard's current snapshot, overlaid with the
        group's earlier tentative members). Reserve: leader-elected
        coordinator drives prepare on every member shard — journaled,
        ledger-booked, re-validated. Decide: ONE durable commit/abort
        record on the coordinator's WAL. Commit: members persist their
        gang annotations + Bindings. A failure before the decision
        aborts every prepared member (presumed abort); a crash anywhere
        is resolved by :func:`resolve_gang2pc` with zero partial
        gangs."""
        # a refused group deserves a "why" as much as an admitted one —
        # every exit below emits a decision record (error or per-member)
        if not pods:
            DECISIONS.emit(
                "", "gang-group", outcome="error",
                reason="empty gang group",
            )
            return {"error": "empty gang group", "members": []}
        group = P.gang_group(pods[0])
        meta = pods[0].get("metadata", {})
        first_key = f"{meta.get('namespace', 'default')}/{meta.get('name', '')}"
        if not group or any(P.gang_group(p) != group for p in pods):
            DECISIONS.emit(
                first_key, "gang-group", outcome="error",
                reason="pods do not share one gang-group id",
            )
            return {
                "error": "pods do not share one gang-group id",
                "members": [],
            }
        plan, plan_err = self._plan_group(pods)
        if plan_err:
            DECISIONS.emit(
                first_key, "gang-group", outcome="error",
                reason=plan_err,
            )
            return {"error": plan_err, "members": [], "group": group}
        coordinator_id = self._ring.owner(f"gang-group:{group}")
        epoch = self._lease.acquire(group, coordinator_id)
        coordinator = self._shards[coordinator_id]
        prepared: list[dict[str, Any]] = []
        for member in plan:
            shard = self._shards[member["shard"]]
            try:
                ok, reason = shard.prepare_gang(
                    group, member["ns"], member["name"], member["node"],
                    member["chips"], member["units"], member["shape"],
                    epoch, coordinator_id,
                )
            except (ShardUnavailable, ApiError, OSError) as e:
                ok, reason = False, f"shard {member['shard']} unreachable: {e}"
            except StaleCoordinator as e:
                # a newer coordinator took the group mid-prepare: this
                # incarnation must stop driving, but its prepared prefix
                # still presumed-aborts below (abort accepts an epoch at
                # or above each entry's OWN epoch, so the fenced driver
                # can clean up what IT booked)
                ok, reason = False, f"fenced during prepare: {e}"
            if not ok:
                # presumed abort: no decision record exists, so aborting
                # the prepared prefix (and the failed member's own
                # journal entry, already resolved inside prepare) leaves
                # nothing for the reconciler
                for done in prepared:
                    try:
                        self._shards[done["shard"]].abort_gang(
                            group, done["ns"], done["name"], epoch
                        )
                    except (ShardUnavailable, ApiError, OSError) as e:
                        # the reconciler rolls this undecided prepare
                        # back on its next pass
                        log.warning(
                            "presumed-abort of %s on %s failed: %s",
                            done["name"], done["shard"], e,
                        )
                self._lease.forget(group)
                DECISIONS.emit(
                    f"{member['ns']}/{member['name']}", "gang-group",
                    outcome="error", node=member["node"],
                    reason=f"prepare failed: {reason}",
                    shard=member["shard"],
                )
                return {
                    "error": f"prepare failed for {member['name']}: {reason}",
                    "members": [], "group": group,
                }
            prepared.append(member)
        decision_key = (GANG2PC_NS, f"{group}/decision")
        try:
            # The commit point is epoch-gated: a resolver that overrode
            # this (wedged) coordinator past LIVE_PREPARE_GRACE_S has
            # already rolled its prepares back and seeded a higher
            # fencing epoch — journaling a decision now would roll the
            # group forward onto chips a competing booking may own.
            coordinator._note_epoch(group, epoch)
        except StaleCoordinator as e:
            for done in prepared:
                try:
                    self._shards[done["shard"]].abort_gang(
                        group, done["ns"], done["name"], epoch
                    )
                except (ShardUnavailable, ApiError, OSError,
                        StaleCoordinator):
                    # the fencing resolver already rolled this member
                    # back (or will, next pass)
                    pass
            self._lease.forget(group)
            DECISIONS.emit(
                first_key, "gang-group", outcome="error",
                reason=f"fenced at the decision point: {e}",
            )
            return {
                "error": f"fenced at the decision point: {e}",
                "members": [], "group": group,
            }
        decision_seq = coordinator._journal_2pc(decision_key, {
            "phase": "decision",
            "outcome": "commit",
            "group": group,
            "epoch": epoch,
            "members": [
                {
                    "ns": m["ns"], "name": m["name"], "node": m["node"],
                    "shard": m["shard"], "chips": list(m["chips"]),
                    "units": m["units"], "shape": m["shape"],
                    "request": m["request"],
                }
                for m in plan
            ],
        })
        FAULTS.fire("gang2pc.decide")
        REGISTRY.counter_inc(
            TWOPC_METRIC, TWOPC_HELP, phase="decide", outcome="commit",
        )
        # Decision provenance, per member, once the group's commit record
        # is durable: `inspect why` renders the all-or-nothing GROUP
        # admission — and for a disaggregated two-tier slice, which tier
        # each member serves and the group's tier composition.
        tiers: dict[str, int] = {}
        for m in plan:
            if m.get("tier"):
                tiers[m["tier"]] = tiers.get(m["tier"], 0) + 1
        for m in plan:
            DECISIONS.emit(
                f"{m['ns']}/{m['name']}", "gang-group",
                node=m["node"],
                placement={
                    "group": group,
                    "members": len(plan),
                    "chips": list(m["chips"]),
                    "shape": m["shape"],
                    "per_chip": m["units"],
                    **({"tier": m["tier"]} if m.get("tier") else {}),
                    **({"tiers": tiers} if tiers else {}),
                },
                seq=decision_seq,
                shard=m["shard"],
            )
        errors: list[str] = []
        try:
            for member in plan:
                shard = self._shards[member["shard"]]
                try:
                    ok, reason = shard.commit_gang(
                        group, member["ns"], member["name"], epoch,
                        total_request=member["request"],
                    )
                except (ShardUnavailable, ApiError, OSError,
                        StaleCoordinator) as e:
                    # the decision is durable — a member whose shard
                    # dropped out (or fenced this driver) mid-commit is
                    # the reconciler's to roll forward, never a raised
                    # error: later members still get their commit
                    # attempted now
                    ok, reason = False, str(e)
                if not ok:
                    errors.append(f"{member['name']}: {reason}")
            if errors:
                # the decision is durable: the members that did not
                # commit are the reconciler's to roll forward — the
                # entry stays pending so resolve_gang2pc finds it
                self._lease.forget(group)
                coordinator._drop_finished_epoch(group)
                return {
                    "error": "",
                    "group": group,
                    "members": [m["name"] for m in plan],
                    "pending_rollforward": errors,
                }
            coordinator._resolve_2pc("commit", decision_key, decision_seq)
            FAULTS.fire("gang2pc.done")
            self._lease.forget(group)
            # the decision-point epoch check noted the group on the
            # coordinator shard; a memberless coordinator has no
            # side-state whose release would prune it, so drop it here
            # (no-op while any member side-state still references the
            # group)
            coordinator._drop_finished_epoch(group)
            return {
                "error": "", "group": group,
                "members": [m["name"] for m in plan],
                "pending_rollforward": [],
            }
        finally:
            # group-level outcome record, keyed under the gang pseudo-
            # namespace (member pods keep the reference record shape
            # above): one "why" for the group as a whole, on every exit
            DECISIONS.emit(
                f"gang/{group}", "gang-group",
                reason="; ".join(errors),
                placement={
                    "group": group, "members": len(plan),
                    **({"tiers": tiers} if tiers else {}),
                },
                seq=decision_seq,
            )

    def _plan_group(
        self, pods: Sequence[dict]
    ) -> tuple[list[dict[str, Any]], str]:
        """Greedy cross-shard placement plan for a gang group: each
        member takes the best-scoring feasible slice over ALL shards'
        owned nodes, with earlier members' tentative chips overlaid so
        the plan never self-collides. -> (plan, error)."""
        tentative: dict[str, dict[int, int]] = {}  # node -> chip -> units
        plan: list[dict[str, Any]] = []
        for pod in pods:
            meta = pod.get("metadata", {})
            shape = P.gang_shape_request(pod)
            request = P.mem_units_of_pod(pod)
            if not shape or request <= 0:
                return [], (
                    f"group member {meta.get('name')} has no gang shape "
                    "or no tpu-mem request"
                )
            # pruned like admit(): scan the most-promising shards first
            # and widen to the rest only when nothing fits there
            ranked = self._ranked_shards(request)
            best: tuple[float, str, str, tuple[int, ...], int] | None = None
            for shard_set in (ranked[: self._fanout],
                              ranked[self._fanout:]):
                for shard in shard_set:
                    sid = shard.shard_id
                    try:
                        shard._check_reachable()
                        nodes = shard.owned_nodes()
                    except (ShardUnavailable, OSError):
                        continue
                    if not nodes:
                        continue
                    for view in shard.core.node_views(
                        nodes, const.RESOURCE_MEM
                    ):
                        node_name = view.name
                        for idx, units in tentative.get(
                            node_name, {}
                        ).items():
                            view.used[idx] = view.used.get(idx, 0) + units
                        cand, per_chip, _reason, score = (
                            logic.gang_candidate(
                                view, shape, request, shard.policy
                            )
                        )
                        if cand is None:
                            continue
                        key = (-score.raw, node_name, sid,
                               tuple(cand.chips), per_chip)
                        if best is None or key < best:
                            best = key
                if best is not None:
                    break
            if best is None:
                return [], (
                    f"no feasible placement for group member "
                    f"{meta.get('name')} (shape {shape})"
                )
            _raw, node_name, sid, chips, per_chip = best
            booked = tentative.setdefault(node_name, {})
            for c in chips:
                booked[c] = booked.get(c, 0) + per_chip
            plan.append({
                "ns": meta.get("namespace", "default"),
                "name": meta.get("name", ""),
                "shard": sid,
                "node": node_name,
                "chips": chips,
                "units": per_chip,
                "shape": shape,
                "request": request,
                # disaggregated-serving tier (serving/handoff.py): a
                # two-tier slice is one group — prefill gang + decode
                # gang — and `inspect why` shows the composition
                "tier": P.serving_tier(pod),
            })
        return plan, ""

    # --- introspection -----------------------------------------------------

    def shards_doc(self) -> dict[str, Any]:
        """The ``/shards`` endpoint body: ring ownership, per-shard WAL
        seq + queue depth, and 2PC gangs in flight — what
        ``kubectl-inspect-tpushare shards`` renders."""
        with self._lock:
            node_names = list(self._nodes)
        gangs: list[dict[str, Any]] = []
        rows = []
        for sid in sorted(self._shards):
            shard = self._shards[sid]
            rows.append(shard.doc())
            for entry in shard.twopc_pending():
                gangs.append({
                    "group": entry.get("group", ""),
                    "phase": entry.get("phase", ""),
                    "shard": sid,
                    "node": entry.get("node", ""),
                    "pod": entry.get("pod_name", ""),
                })
        return {
            "ring": self._ring.doc(node_names),
            "fanout": self._fanout,
            "shards": rows,
            "gangs_2pc": gangs,
        }


# --- recovery ---------------------------------------------------------------


def resolve_gang2pc(
    shards: Sequence[ShardExtender],
    api: ApiServerClient,
    lease: LeaderLease | None = None,
) -> dict[str, int]:
    """Resolve every pending "gang2pc" journal entry across ``shards``
    — the reconciler pass a restarted deployment (or a new leader after
    fencing) runs before serving.

    Rules, by phase — the PR 10 move-protocol discipline:

    - a durable COMMIT decision rolls the group FORWARD: members whose
      pods lack their gang annotations are re-persisted from the
      journaled plan (idempotent — an already-annotated member is left
      alone), then every member entry and the decision resolve;
    - a prepare with NO decision rolls BACK: presumed abort — the
      coordinator never reached its commit point, so the reservation
      releases and the entry aborts;
    - a member whose pod vanished mid-protocol resolves as rolled back
      (nothing to persist to), counted separately.

    Returns counts for tests/telemetry.
    """
    by_id = {s.shard_id: s for s in shards}
    decisions: dict[str, tuple[ShardExtender, dict]] = {}
    prepares: list[tuple[ShardExtender, dict]] = []
    for shard in shards:
        for entry in shard.twopc_pending():
            if entry.get("phase") == "decision":
                decisions[str(entry.get("group", ""))] = (shard, entry)
            elif entry.get("phase") == "prepare":
                prepares.append((shard, entry))
    counts = {
        "rolled_forward": 0, "rolled_back": 0,
        "member_gone": 0, "decisions_resolved": 0,
        "skipped_live": 0,
    }
    # roll forward every decided group
    for group, (coord, decision) in decisions.items():
        epoch = int(decision.get("epoch") or 0)
        new_epoch = (
            lease.acquire(group, coord.shard_id) if lease is not None
            else max(epoch, 1)
        )
        for member in decision.get("members") or []:
            shard = by_id.get(str(member.get("shard", "")))
            ns = str(member.get("ns", "default"))
            name = str(member.get("name", ""))
            if shard is None:
                continue
            key = ShardExtender.twopc_key(group, ns, name)
            try:
                pod = api.get_pod(ns, name)
            except ApiError:
                pod = None
            if pod is None:
                # the member pod vanished mid-protocol: nothing to roll
                # forward to — release whatever the shard still holds
                pending = {
                    tuple(e.get("key") or ()): e
                    for e in shard.twopc_pending()
                }
                entry = pending.get(key)
                shard._rollback_member(
                    key, entry.get("_seq") if entry else None
                )
                counts["member_gone"] += 1
                continue
            if not P.gang_chips_from_annotation(pod):
                ok, reason = shard.commit_gang(
                    group, ns, name, new_epoch,
                    total_request=int(member.get("request") or 0),
                )
                if not ok:
                    # re-prepare-less roll forward: persist directly from
                    # the journaled plan (the shard lost its side-state
                    # in the crash and has no prepared entry)
                    ok = _rollforward_member(shard, group, member, pod)
                if not ok:
                    log.warning(
                        "gang2pc rollforward failed for %s/%s: %s",
                        ns, name, reason,
                    )
                    continue
            else:
                # already persisted: drain the member's journal entry and
                # mark its side-state committed — the ledger reservation
                # drains via the overlay's visibility release, never here
                pending = {
                    tuple(e.get("key") or ()): e
                    for e in shard.twopc_pending()
                }
                entry = pending.get(key)
                if entry is not None:
                    shard._resolve_2pc("commit", key, entry.get("_seq"))
                shard.note_committed(group, ns, name)
            counts["rolled_forward"] += 1
            REGISTRY.counter_inc(
                TWOPC_METRIC, TWOPC_HELP,
                phase="rollforward", outcome="ok",
            )
        coord._resolve_2pc(
            "commit",
            (GANG2PC_NS, f"{group}/decision"),
            decision.get("_seq"),
        )
        if lease is not None:
            lease.forget(group)
        counts["decisions_resolved"] += 1
    # roll back every undecided prepare — UNLESS its coordinator is
    # provably live: the group's lease epoch is still held AND the
    # prepare is younger than LIVE_PREPARE_GRACE_S. A live coordinator
    # is between its prepares and its decision; releasing its member's
    # reservation here lets a competing group book the chips, and the
    # coordinator's (imminent, durable) commit decision then rolls the
    # member forward ON TOP of them — the double-booking tools/tpumc
    # found when the live resolve loop ran lease-less (the pre-fix
    # shards.main wiring; tests/test_tpumc.py replays the schedule).
    # Callers with no lease (startup recovery — no coordinator can be
    # live in a fresh process) roll back immediately, which the
    # kill-at-every-step chaos suite depends on; a wedged live
    # coordinator is overridden once its prepare ages past the grace.
    now = time.time()
    for shard, entry in prepares:
        group = str(entry.get("group", ""))
        if group in decisions:
            continue  # handled (or deliberately left) above
        fence_epoch = 0
        if lease is not None:
            _holder, held_epoch = lease.current(group)
            age = now - float(entry.get("ts") or 0.0)
            if held_epoch > 0:
                if age < LIVE_PREPARE_GRACE_S:
                    counts["skipped_live"] += 1
                    continue
                # Overriding a WEDGED coordinator (lease still held,
                # prepare aged past the grace): take a higher epoch and
                # seed it on the member AND coordinator shards BEFORE
                # anything releases — presumed abort alone is not
                # enough, because the wedged driver may wake later and
                # journal its commit decision on top of whatever
                # re-booked the freed chips; seeding first closes its
                # epoch-gated decision point before the chips free up.
                # The fence (the lease entry and the seeded epochs) is
                # deliberately NEVER pruned on this path: a paused
                # thread can wake arbitrarily late, and pruning would
                # re-open the gate for its stale decision. One retained
                # entry per wedge event — a logged anomaly, not a
                # per-group cost.
                fence_epoch = lease.acquire(group, "gang2pc-resolver")
                shard._note_epoch(group, fence_epoch)
                coord = by_id.get(str(entry.get("coordinator", "")))
                if coord is not None and coord is not shard:
                    coord._note_epoch(group, fence_epoch)
                log.warning(
                    "gang2pc: coordinator for group %s wedged past "
                    "%.0fs with an undecided prepare; fenced at epoch "
                    "%d and rolling the prepare back", group,
                    LIVE_PREPARE_GRACE_S, fence_epoch,
                )
        key = tuple(entry.get("key") or ())
        if len(key) != 2:
            continue
        shard._rollback_member(
            (key[0], key[1]), entry.get("_seq"),
            drop_epoch=not fence_epoch,
        )
        counts["rolled_back"] += 1
        REGISTRY.counter_inc(
            TWOPC_METRIC, TWOPC_HELP, phase="rollback", outcome="ok",
        )
    return counts


def _rollforward_member(
    shard: ShardExtender, group: str, member: dict, pod: dict
) -> bool:
    """Persist one member straight from the journaled decision plan (the
    crash wiped the shard's prepared side-state). Idempotent with the
    normal commit path — same annotation shape, same PATCH."""
    entry = {
        "node": str(member.get("node", "")),
        "chips": tuple(int(c) for c in (member.get("chips") or ())),
        "units": int(member.get("units") or 0),
        "group": group,
        "shape": str(member.get("shape", "")),
    }
    if not entry["chips"] or entry["units"] <= 0:
        return False
    ns = str(member.get("ns", "default"))
    name = str(member.get("name", ""))
    annotations = shard._member_annotations(
        pod, entry, int(member.get("request") or 0)
    )
    try:
        shard._api.patch_pod(
            ns, name, {"metadata": {"annotations": annotations}}
        )
        shard._api.bind_pod(ns, name, entry["node"])
    except ApiError as e:
        log.warning("rollforward PATCH failed for %s/%s: %s", ns, name, e)
        return False
    key = ShardExtender.twopc_key(group, ns, name)
    pending = {
        tuple(e.get("key") or ()): e for e in shard.twopc_pending()
    }
    journal_entry = pending.get(key)
    if journal_entry is not None:
        shard._resolve_2pc("commit", key, journal_entry.get("_seq"))
    shard.note_committed(group, ns, name)
    return True


def main(argv: "list[str] | None" = None) -> int:
    """``tpushare-sharded-extender``: one process hosting N shard cores
    behind the router, speaking the same webhook protocol as the single
    extender (the router's filter/prioritize/batch/bind signatures match
    ``ExtenderCore``'s, so ``ExtenderHTTPServer`` serves it unchanged).
    One informer feeds every shard's own usage index; each shard gets
    its own group-commit bind WAL under ``--checkpoint-dir``. The node
    catalog refreshes from the apiserver every ``--nodes-refresh``
    seconds."""
    import argparse
    import os as _os
    import threading

    from ..allocator.checkpoint import AllocationCheckpoint
    from ..cluster.informer import PodInformer
    from ..utils import log as logutil
    from ..utils.metrics import MetricsServer, publish_build_info
    from .server import ExtenderHTTPServer

    p = argparse.ArgumentParser(prog="tpushare-sharded-extender")
    p.add_argument("--shards", type=int, default=4)
    p.add_argument("--fanout", type=int, default=2,
                   help="shards consulted per pruned admission before "
                   "the full fan-out fallback")
    p.add_argument("--port", type=int, default=32766)
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--policy", default="best-fit",
                   choices=["first-fit", "best-fit", "spread"])
    p.add_argument("--placement-policy", default="",
                   help="pluggable placement policy (greedy-binpack | "
                   "multi-objective | learned | registered); overrides "
                   "--policy")
    p.add_argument("--checkpoint-dir", default="",
                   help="directory for the per-shard bind WALs "
                   "(shard-N.wal); empty disables journaling")
    p.add_argument("--metrics-port", type=int, default=0,
                   help="serve /metrics + /shards (the shard map the "
                   "inspect CLI reads) on this port (0 = off)")
    p.add_argument("--nodes-refresh", type=float, default=10.0)
    p.add_argument("--gang2pc-resolve-interval", type=float, default=30.0,
                   help="seconds between reconciler passes over pending "
                   "gang2pc journal entries (0 disables; one pass "
                   "always runs at start)")
    p.add_argument("--timeout", type=float, default=10.0)
    p.add_argument("-v", "--verbosity", type=int, default=0)
    args = p.parse_args(argv)
    logutil.setup(args.verbosity)
    try:
        api = ApiServerClient.from_env(timeout_s=args.timeout)
    except Exception as e:  # noqa: BLE001 — startup config, fatal
        log.fatal(f"apiserver config failed: {e}")
    informer = PodInformer(api).start()
    policy: "str | logic.PlacementPolicy" = args.policy
    if args.placement_policy:
        from .policy import get_policy

        policy = get_policy(args.placement_policy)
    shards = []
    for i in range(max(1, args.shards)):
        checkpoint = None
        if args.checkpoint_dir:
            _os.makedirs(args.checkpoint_dir, exist_ok=True)
            checkpoint = AllocationCheckpoint(
                _os.path.join(args.checkpoint_dir, f"shard-{i}.wal")
            )
        shards.append(ShardExtender(
            f"shard-{i}", api, informer=informer,
            checkpoint=checkpoint, policy=policy,
        ))
    # ONE lease shared by the router and every resolve pass: the live
    # resolve loop must see which groups a live coordinator is still
    # driving (resolve_gang2pc's live-prepare gate) — a lease-less
    # resolve racing admit_gang_group was the tpumc-found double-booking
    lease = LeaderLease()
    router = ShardRouter(shards, fanout=args.fanout, lease=lease)
    # inherited 2PC state first: a fresh process has no live
    # coordinators, so every undecided prepare legitimately rolls back
    resolve_gang2pc(shards, api, lease)

    def refresh_nodes() -> None:
        while True:
            try:
                router.set_nodes(api.list_nodes())
            except ApiError as e:
                log.warning("node catalog refresh failed: %s", e)
            time.sleep(args.nodes_refresh)

    def resolve_loop() -> None:
        # the live-process healing pass: a coordinator that died between
        # a member's prepare and its own decision leaves pending entries
        # only the reconciler resolves — once at start is not enough for
        # a long-lived deployment
        while True:
            time.sleep(args.gang2pc_resolve_interval)
            try:
                resolve_gang2pc(shards, api, lease)
            except ApiError as e:
                log.warning("gang2pc resolve pass failed: %s", e)

    threading.Thread(
        target=refresh_nodes, daemon=True, name="shard-nodes"
    ).start()
    if args.gang2pc_resolve_interval > 0:
        threading.Thread(
            target=resolve_loop, daemon=True, name="gang2pc-resolve"
        ).start()
    metrics_server = None
    if args.metrics_port:
        publish_build_info(component="sharded-extender")
        metrics_server = MetricsServer(
            port=args.metrics_port,
            ready_fn=lambda: bool(informer.synced),
            shards_doc_fn=router.shards_doc,
        ).start()
        log.info("metrics + /shards on :%d", metrics_server.port)
    server = ExtenderHTTPServer(router, host=args.host, port=args.port)
    server.start()
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        server.stop()
        informer.stop()
        if metrics_server is not None:
            metrics_server.stop()
    return 0


if __name__ == "__main__":  # pragma: no cover - thin process entry
    import sys

    sys.exit(main())
