"""Scheduler-extender placement logic (pure functions).

The reference device plugin relies on a *separate* gpushare-scheduler-extender
repo for cluster-level binpack placement (``README.md:14``; the plugin reads
its ``..._IDX`` annotation in branch A of Allocate, ``allocate.go:75-84``).
This module is our in-repo equivalent: node filtering, binpack scoring, and
the bind-time chip decision — generalized over resource names so one
extender serves TPU (``aliyun.com/tpu-mem``) and GPU (``aliyun.com/gpu-mem``)
nodes in a mixed fleet (BASELINE config 5).

A pod counts against a node's chips when it is active (phase not
Succeeded/Failed) and carries the IDX annotation — i.e. running workloads
AND extender-assumed pods whose kubelet admission is still in flight.
"""

from __future__ import annotations

import dataclasses
import json
import time
from typing import Callable

from .. import const
from ..allocator.binpack import AssignmentError, assign_chip
from ..cluster import pods as P
from ..cluster.noderes import chip_capacity_vector
from ..topology import ChipTopology, shape_size
from ..utils.decisions import ScoreVector
from .policy import PlacementPolicy, PolicyView
from . import policy as policy_mod

# Every scoring entry point accepts either a legacy chip-policy name
# ("best-fit"/"first-fit"/"spread" — resolved through the policy
# registry to the bit-identical binpack scorer) or an already-
# constructed PlacementPolicy ("greedy-binpack"/"multi-objective"/
# "learned"/anything registered). Resolution happens once per verb.

# resource name -> annotation/label vocabulary
RESOURCE_FAMILIES = {
    const.RESOURCE_MEM: {
        "count": const.RESOURCE_COUNT,
        "idx": const.ENV_MEM_IDX,
        "pod": const.ENV_MEM_POD,
        "dev": const.ENV_MEM_DEV,
        "assigned": const.ENV_ASSIGNED_FLAG,
        "assume": const.ENV_ASSUME_TIME,
    },
    const.RESOURCE_GPU_MEM: {
        "count": const.RESOURCE_GPU_COUNT,
        "idx": const.ENV_GPU_MEM_IDX,
        "pod": const.ENV_GPU_MEM_POD,
        "dev": const.ENV_GPU_MEM_DEV,
        "assigned": const.ENV_GPU_MEM_ASSIGNED,
        "assume": const.ENV_GPU_MEM_ASSUME_TIME,
    },
}


def pod_resource(pod: dict) -> str | None:
    """Which share resource this pod requests (tpu-mem preferred)."""
    for resource in RESOURCE_FAMILIES:
        if P.mem_units_of_pod(pod, resource=resource) > 0:
            return resource
    return None


@dataclasses.dataclass
class NodeView:
    name: str
    resource: str
    capacity: dict[int, int]  # chip index -> units
    used: dict[int, int]
    # chips exclusively held by assigned tpu-core pods: zero free units for
    # fractional placement (keeps the extender's decisions consistent with
    # the device plugin's cross-resource ledger — otherwise it would assume
    # mem pods onto held chips and Allocate would reject them forever)
    core_held: set[int] = dataclasses.field(default_factory=set)
    # the node's chip grid, for gang (multi-chip) placement; None on
    # resource families without an interconnect (gpu-mem)
    topology: ChipTopology | None = None

    def free(self) -> dict[int, int]:
        return {
            i: (
                0
                if i in self.core_held
                else self.capacity[i] - self.used.get(i, 0)
            )
            for i in self.capacity
        }


def node_topology(node: dict, capacity: dict[int, int]) -> ChipTopology | None:
    """The node's chip grid (``ChipTopology.from_node`` — the one label
    rule shared with the daemon and the inspect CLI); None when the node
    advertises no chips."""
    if not capacity:
        return None
    return ChipTopology.from_node(node, len(capacity))


def node_capacity(node: dict, resource: str) -> dict[int, int]:
    """Per-chip capacity from node status (shared helper with the inspect CLI)."""
    return chip_capacity_vector(node, resource, RESOURCE_FAMILIES[resource]["count"])


def group_pods_by_node(pods: list[dict]) -> dict[str, list[dict]]:
    """Group once per request so per-node accounting doesn't rescan the
    whole cluster pod list for every node."""
    by_node: dict[str, list[dict]] = {}
    for pod in pods:
        by_node.setdefault(P.node_name(pod), []).append(pod)
    return by_node


def node_usage(node_pods: list[dict], resource: str) -> dict[int, int]:
    """Units held per chip by active annotated pods (pods pre-filtered to
    one node via ``group_pods_by_node``)."""
    family = RESOURCE_FAMILIES[resource]
    used: dict[int, int] = {}
    for pod in node_pods:
        if P.phase(pod) in ("Succeeded", "Failed"):
            continue
        if resource == const.RESOURCE_MEM:
            gang = P.gang_usage_by_chip(pod)
            if gang:
                for idx, per in gang.items():
                    used[idx] = used.get(idx, 0) + per
                continue
        idx_raw = P.annotations(pod).get(family["idx"])
        if idx_raw is None:
            continue
        try:
            idx = int(idx_raw)
        except ValueError:
            continue
        if idx < 0:
            continue
        used[idx] = used.get(idx, 0) + P.mem_units_of_pod(pod, resource=resource)
    return used


def build_node_view(
    node: dict, pods_by_node: dict[str, list[dict]], resource: str
) -> NodeView:
    name = node.get("metadata", {}).get("name", "")
    node_pods = pods_by_node.get(name, [])
    capacity = node_capacity(node, resource)
    return NodeView(
        name=name,
        resource=resource,
        capacity=capacity,
        used=node_usage(node_pods, resource),
        core_held=(
            P.used_chips(node_pods) if resource == const.RESOURCE_MEM else set()
        ),
        topology=(
            node_topology(node, capacity)
            if resource == const.RESOURCE_MEM
            else None
        ),
    )


def node_fits(view: NodeView, request_units: int) -> bool:
    """A single chip must hold the whole request (no cross-chip spreading
    for fractional pods — same constraint the device plugin enforces)."""
    return any(f >= request_units for f in view.free().values())


def pod_gang_shape(pod: dict, resource: str) -> str:
    """The pod's gang-shape request, "" for single-chip pods. Gangs ride
    the TPU family only — GPU nodes have no ICI grid to place against."""
    if resource != const.RESOURCE_MEM:
        return ""
    return P.gang_shape_request(pod)


def _zero_score(pol: PlacementPolicy, request_units: int) -> ScoreVector:
    return ScoreVector(
        policy=pol.name, raw=0.0, free_units=0,
        request_units=request_units, binpack=0.0,
    )


def _gang_eval(
    view: NodeView,
    shape_raw: str,
    request_units: int,
    policy: "str | PlacementPolicy",
) -> tuple["object | None", int, str, ScoreVector]:
    """One node's gang answer: -> (best candidate or None, per-chip
    units, failure reason, :class:`ScoreVector`). The score reuses the
    single-chip policy semantics at per-chip granularity over the
    winning slice's members — so gang and single-chip node ranking stay
    comparable — and carries the slice's multi-objective components
    (ICI hops, stranded slivers, broken chips, tie-break) from
    ``best_slice_scored`` for decision provenance. A non-legacy
    :class:`PlacementPolicy` sees those components in its
    :class:`PolicyView` and may let them move the raw score (the
    multi-objective / learned policies do)."""
    pol = policy_mod.resolve(policy)
    try:
        size = shape_size(shape_raw)
    except ValueError as e:
        return (
            None, 0, f"invalid gang shape {shape_raw!r}: {e}",
            _zero_score(pol, request_units),
        )
    if size < 1 or request_units <= 0 or request_units % size:
        return (
            None, 0,
            f"{request_units} units of {view.resource} do not divide "
            f"evenly over gang shape {shape_raw!r} ({size} chips)",
            _zero_score(pol, request_units),
        )
    per_chip = request_units // size
    topo = view.topology or node_topology({}, view.capacity)
    if topo is None:
        return (
            None, 0, f"node does not advertise {view.resource}",
            _zero_score(pol, request_units),
        )
    free = view.free()
    scored = topo.best_slice_scored(
        shape_raw, free, per_chip,
        capacity=view.capacity, excluded=view.core_held,
    )
    if scored is None:
        return (
            None, per_chip,
            f"no {shape_raw} sub-slice with {per_chip} free units of "
            f"{view.resource} per chip (free: {free})",
            _zero_score(pol, per_chip),
        )
    cand, slice_score = scored
    member_free = [free[i] for i in cand.chips]
    feasible = [f for f in member_free if f >= per_chip]
    cap = max(view.capacity.values(), default=0)
    if not feasible or cap <= 0:
        score = _zero_score(pol, per_chip)
    else:
        decisive = (
            max(feasible) if pol.chip_policy == "spread" else min(feasible)
        )
        score = pol.score(PolicyView(
            free_units=decisive, capacity=cap, request_units=per_chip,
            free_vector=tuple(feasible),
            ici_hops=slice_score.hops, stranded=slice_score.stranded,
            broken=slice_score.broken, tie_break=slice_score.tie_break,
        ))
    return cand, per_chip, "", score


def gang_candidate(
    view: NodeView,
    shape_raw: str,
    request_units: int,
    policy: "str | PlacementPolicy" = "best-fit",
) -> tuple["object | None", int, str, ScoreVector]:
    """Public form of the per-node gang evaluation (``_gang_eval``) for
    planners — the shard router's cross-node gang-group placement picks
    each member's (slice, per-chip units) through this: -> (candidate
    slice or None, per-chip units, failure reason, score)."""
    return _gang_eval(view, shape_raw, request_units, policy)


def evaluate_filter(
    request_units: int, views: list[NodeView], gang_shape: str = ""
) -> tuple[list[str], dict[str, str]]:
    """Fit check over prebuilt views -> (fitting names, name -> reason)."""
    fits, failed = [], {}
    pol = policy_mod.resolve("best-fit")
    for view in views:
        if not view.capacity:
            failed[view.name] = f"node does not advertise {view.resource}"
        elif gang_shape:
            cand, _per, reason, _s = _gang_eval(
                view, gang_shape, request_units, pol
            )
            if cand is None:
                failed[view.name] = reason
            else:
                fits.append(view.name)
        elif not node_fits(view, request_units):
            failed[view.name] = (
                f"no single chip with {request_units} free units of "
                f"{view.resource} (free: {view.free()})"
            )
        else:
            fits.append(view.name)
    return fits, failed


def views_from_pods(
    pods: list[dict],
) -> Callable[[str, list[dict]], list["NodeView"]]:
    """views_fn over a full pod list (the LIST-backed path); the extender
    server passes its index-backed equivalent instead."""

    def views(resource: str, nodes: list[dict]) -> list[NodeView]:
        by_node = group_pods_by_node(pods)
        return [build_node_view(n, by_node, resource) for n in nodes]

    return views


def filter_with_views(
    pod: dict,
    nodes: list[dict],
    views_fn: Callable[[str, list[dict]], list["NodeView"]],
) -> tuple[list[str], dict[str, str]]:
    """-> (fitting node names, failed node -> reason).

    ``views_fn(resource, nodes) -> list[NodeView]`` supplies the accounting
    (full-scan or incremental-index) — verb semantics live here once."""
    resource = pod_resource(pod)
    if resource is None:
        # not a share pod: everything passes (we shouldn't be called, but
        # the scheduler may still route the pod through the extender)
        return [n.get("metadata", {}).get("name", "") for n in nodes], {}
    request = P.mem_units_of_pod(pod, resource=resource)
    return evaluate_filter(
        request, views_fn(resource, nodes),
        gang_shape=pod_gang_shape(pod, resource),
    )


def filter_nodes(
    pod: dict, nodes: list[dict], pods: list[dict]
) -> tuple[list[str], dict[str, str]]:
    return filter_with_views(pod, nodes, views_from_pods(pods))


def _score_free(
    free_values, cap: int, request_units: int,
    policy: "str | PlacementPolicy",
) -> ScoreVector:
    """The policy score over a free vector as a structured
    :class:`ScoreVector`: the raw fractional 0-10 score (full
    resolution — the deterministic tie-break the integer projection
    cannot provide at fleet scale), the decisive chip's free units, and
    the binpack slack term. Chip selection (tightest feasible for
    packing, roomiest for spread — ``PlacementPolicy.chip_policy``)
    lives here; the scoring formula is the policy's ``score`` over a
    :class:`PolicyView` (legacy names resolve to the ``chip_breakdown``
    scorer — ONE implementation shared with the allocator's provenance
    records, bit-identical to the pre-registry behavior, pinned by the
    existing verb tests)."""
    pol = policy_mod.resolve(policy)
    feasible = [f for f in free_values if f >= request_units]
    if not feasible or cap <= 0:
        return _zero_score(pol, request_units)
    decisive = max(feasible) if pol.chip_policy == "spread" else min(feasible)
    return pol.score(PolicyView(
        free_units=decisive, capacity=cap, request_units=request_units,
        free_vector=tuple(feasible),
    ))


def score_node_vector(
    view: NodeView, request_units: int,
    policy: "str | PlacementPolicy" = "best-fit",
) -> ScoreVector:
    """Node score as a structured :class:`ScoreVector`, consistent with
    the chip-level policy.

    Packing policies (first-fit/best-fit) prefer the node whose tightest
    feasible chip leaves the least slack (consolidates fragments, keeps
    big chips whole); ``spread`` inverts — prefer the node whose emptiest
    feasible chip has the MOST headroom, so pods fan out across nodes the
    same way they fan out across chips."""
    return _score_free(
        view.free().values(),
        max(view.capacity.values(), default=0),
        request_units,
        policy,
    )


def score_node(
    view: NodeView, request_units: int,
    policy: "str | PlacementPolicy" = "best-fit",
) -> int:
    """Node score 0-10 (the webhook wire projection of
    :func:`score_node_vector`)."""
    return score_node_vector(view, request_units, policy).projected


def chip_score_vector(
    view: NodeView, idx: int, request_units: int,
    policy: "str | PlacementPolicy" = "best-fit",
) -> ScoreVector:
    """The breakdown for one CHOSEN chip (bind-time provenance): the
    chip's pre-claim free units and its slack term, with the chip index
    as the tie-break. Unlike :func:`score_node_vector` this scores the
    concrete decision, not the node's best case."""
    pol = policy_mod.resolve(policy)
    free = view.free()
    return pol.score(PolicyView(
        free_units=free.get(idx, 0),
        capacity=max(view.capacity.values(), default=0),
        request_units=request_units,
        free_vector=tuple(f for f in free.values() if f >= request_units),
        chip=idx,
    ))


def evaluate_filter_and_scores(
    request_units: int,
    views: list[NodeView],
    policy: "str | PlacementPolicy" = "best-fit",
    gang_shape: str = "",
) -> tuple[list[str], dict[str, str], dict[str, ScoreVector]]:
    """One pass over prebuilt views -> (fits, failed reasons, score
    breakdowns for the fitting nodes). The batched filter+prioritize:
    each view's free vector is computed once and serves both the fit
    check and the score, where the two-verb protocol recomputes it per
    verb. Scores are full :class:`ScoreVector` breakdowns — the webhook
    response projects ``.projected``; the decision record keeps the
    whole vector."""
    fits: list[str] = []
    failed: dict[str, str] = {}
    scores: dict[str, ScoreVector] = {}
    pol = policy_mod.resolve(policy)
    for view in views:
        if not view.capacity:
            failed[view.name] = f"node does not advertise {view.resource}"
            continue
        if gang_shape:
            cand, _per, reason, score = _gang_eval(
                view, gang_shape, request_units, pol
            )
            if cand is None:
                failed[view.name] = reason
            else:
                fits.append(view.name)
                scores[view.name] = score
            continue
        free = view.free()
        if not any(f >= request_units for f in free.values()):
            failed[view.name] = (
                f"no single chip with {request_units} free units of "
                f"{view.resource} (free: {free})"
            )
            continue
        fits.append(view.name)
        scores[view.name] = _score_free(
            free.values(),
            max(view.capacity.values(), default=0),
            request_units,
            pol,
        )
    return fits, failed, scores


def evaluate_score_vectors(
    request_units: int,
    views: list[NodeView],
    policy: "str | PlacementPolicy" = "best-fit",
    gang_shape: str = "",
) -> dict[str, ScoreVector]:
    pol = policy_mod.resolve(policy)
    if gang_shape:
        return {
            v.name: _gang_eval(v, gang_shape, request_units, pol)[3]
            for v in views
        }
    return {
        v.name: score_node_vector(v, request_units, pol) for v in views
    }


def evaluate_scores(
    request_units: int,
    views: list[NodeView],
    policy: "str | PlacementPolicy" = "best-fit",
    gang_shape: str = "",
) -> dict[str, int]:
    """The 0-10 wire projection of :func:`evaluate_score_vectors`."""
    return {
        name: sv.projected
        for name, sv in evaluate_score_vectors(
            request_units, views, policy, gang_shape
        ).items()
    }


def prioritize_with_views(
    pod: dict,
    nodes: list[dict],
    views_fn: Callable[[str, list[dict]], list["NodeView"]],
    policy: "str | PlacementPolicy" = "best-fit",
) -> dict[str, ScoreVector]:
    """Per-node score breakdowns for the prioritize verb. The webhook
    projects each vector to its pinned 0-10 integer; the decision
    record keeps the full-resolution breakdown."""
    resource = pod_resource(pod)
    if resource is None:
        return {
            n.get("metadata", {}).get("name", ""): _zero_score(policy, 0)
            for n in nodes
        }
    request = P.mem_units_of_pod(pod, resource=resource)
    return evaluate_score_vectors(
        request, views_fn(resource, nodes), policy,
        gang_shape=pod_gang_shape(pod, resource),
    )


def prioritize_nodes(
    pod: dict, nodes: list[dict], pods: list[dict], policy: "str | PlacementPolicy" = "best-fit"
) -> dict[str, int]:
    return {
        name: sv.projected
        for name, sv in prioritize_with_views(
            pod, nodes, views_from_pods(pods), policy
        ).items()
    }


def choose_chip(
    pod: dict, node: dict, pods: list[dict], policy: "str | PlacementPolicy" = "best-fit"
) -> tuple[str, int, dict[str, str]]:
    """Bind-time decision: -> (resource, chip index, annotations to write).

    Raises ``AssignmentError`` when nothing fits anymore (the scheduler
    will retry the pod).
    """
    resource = pod_resource(pod)
    if resource is None:
        raise AssignmentError("pod requests no share resource")
    view = build_node_view(node, group_pods_by_node(pods), resource)
    return choose_chip_from_view(pod, view, policy=policy)


def choose_gang_from_view(
    pod: dict, view: NodeView, policy: "str | PlacementPolicy" = "best-fit"
) -> tuple[str, tuple[int, ...], int, dict[str, str]]:
    """Bind-time gang decision over a prebuilt view: -> (resource, member
    chips, per-chip units, annotations to write). The score-less form of
    :func:`choose_gang_scored`."""
    resource, chips, per_chip, annotations, _score = choose_gang_scored(
        pod, view, policy=policy
    )
    return resource, chips, per_chip, annotations


def choose_gang_scored(
    pod: dict, view: NodeView, policy: "str | PlacementPolicy" = "best-fit"
) -> tuple[str, tuple[int, ...], int, dict[str, str], ScoreVector]:
    """Bind-time gang decision over a prebuilt view: -> (resource, member
    chips, per-chip units, annotations to write, score breakdown). The
    annotations are the whole gang in ONE write — member chips,
    normalized shape, per-chip share, assigned=false — so the claim
    lands all-or-nothing and the device plugin's branch A can
    re-validate and honor it atomically. The :class:`ScoreVector` is the
    winning slice's breakdown, surfaced for the bind decision record.
    Raises ``AssignmentError`` when no feasible sub-slice remains."""
    resource = view.resource
    family = RESOURCE_FAMILIES[resource]
    shape_raw = pod_gang_shape(pod, resource)
    request = P.mem_units_of_pod(pod, resource=resource)
    cand, per_chip, reason, score = _gang_eval(
        view, shape_raw, request, policy
    )
    if cand is None:
        raise AssignmentError(reason)
    containers = pod.get("spec", {}).get("containers", [])
    alloc_map = {}
    for i, c in enumerate(containers):
        units = P.mem_units_of_container(c, resource)
        if units <= 0:
            continue
        per = units // len(cand.chips)
        alloc_map[c.get("name", f"c{i}")] = {
            str(idx): per for idx in cand.chips
        }
    annotations = {
        const.ENV_GANG_CHIPS: ",".join(str(i) for i in cand.chips),
        const.ENV_GANG_SHAPE: "x".join(str(d) for d in cand.shape),
        const.ENV_GANG_PER_CHIP: str(per_chip),
        family["pod"]: str(request),
        family["dev"]: str(view.capacity.get(cand.chips[0], 0)),
        family["assigned"]: "false",  # plugin flips to true at admission
        family["assume"]: str(time.time_ns()),
        const.ANN_EXTENDER_ALLOCATION: json.dumps(alloc_map),
    }
    return resource, cand.chips, per_chip, annotations, score


def choose_chip_from_view(
    pod: dict, view: NodeView, policy: "str | PlacementPolicy" = "best-fit"
) -> tuple[str, int, dict[str, str]]:
    """``choose_chip`` over a prebuilt view (the index-backed path); the
    score-less form of :func:`choose_chip_scored`."""
    resource, idx, annotations, _score = choose_chip_scored(
        pod, view, policy=policy
    )
    return resource, idx, annotations


def choose_chip_scored(
    pod: dict, view: NodeView, policy: "str | PlacementPolicy" = "best-fit"
) -> tuple[str, int, dict[str, str], ScoreVector]:
    """``choose_chip`` over a prebuilt view, plus the chosen chip's
    score breakdown (pre-claim free units, binpack slack) for the bind
    decision record."""
    resource = view.resource
    family = RESOURCE_FAMILIES[resource]
    pol = policy_mod.resolve(policy)
    request = P.mem_units_of_pod(pod, resource=resource)
    idx = assign_chip(
        request,
        view.capacity,
        view.used,
        unhealthy=sorted(view.core_held),
        policy=pol.chip_policy,
    )
    score = chip_score_vector(view, idx, request, pol)
    containers = pod.get("spec", {}).get("containers", [])
    alloc_map = {
        c.get("name", f"c{i}"): {str(idx): P.mem_units_of_container(c, resource)}
        for i, c in enumerate(containers)
        if P.mem_units_of_container(c, resource) > 0
    }
    annotations = {
        family["idx"]: str(idx),
        family["pod"]: str(request),
        family["dev"]: str(view.capacity.get(idx, 0)),
        family["assigned"]: "false",  # plugin flips to true at admission
        family["assume"]: str(time.time_ns()),
        const.ANN_EXTENDER_ALLOCATION: json.dumps(alloc_map),
    }
    return resource, idx, annotations, score
