"""Incremental per-node placement accounting for the scheduler extender.

Round 2's informer removed the LIST-per-webhook, but every verb still
walked all cached pods and rebuilt each node's view from scratch — O(pods)
pure-Python work per scheduling decision, ~13 ms at 2,000 pods. This index
subscribes to the cluster-wide ``PodInformer``'s cache mutations
(``PodInformer.add_index``) and maintains, per node:

- fractional units used per chip, per resource family (tpu-mem, gpu-mem) —
  counted for any active pod carrying the family's IDX annotation (assumed
  pods included), the same per-pod rule as ``logic.node_usage``;
- a refcount of exclusively-held chips (assigned tpu-core pods), the same
  per-pod rule as ``pods.used_chips``.

Webhook verbs then read O(nodes-under-consideration), not O(cluster pods).
The contribution of a pod is a pure function of its JSON, so
subtract-then-add on every mutation keeps the aggregates exactly equal to
a full recomputation over the cache.
"""

from __future__ import annotations


from .. import const
from ..cluster import pods as P
from .logic import RESOURCE_FAMILIES
from ..utils.lockrank import make_lock


def _contributions(pod: dict) -> tuple[list[tuple[str, int, int]], list[int]]:
    """-> ([(resource, chip idx, units)], [exclusively-held chip idx]).

    Mirrors ``logic.node_usage`` (fractional, gang pods spread per-chip)
    and ``P.used_chips`` (exclusive) for a single pod."""
    if not P.is_active(pod):
        return [], []
    ann = P.annotations(pod)
    frac: list[tuple[str, int, int]] = []
    gang = P.gang_usage_by_chip(pod)
    if gang:
        frac.extend(
            (const.RESOURCE_MEM, idx, per) for idx, per in sorted(gang.items())
        )
    for resource, family in RESOURCE_FAMILIES.items():
        if gang and resource == const.RESOURCE_MEM:
            continue  # the gang spread above IS this pod's tpu-mem usage
        raw = ann.get(family["idx"])
        if raw is None:
            continue
        try:
            idx = int(raw)
        except (TypeError, ValueError):
            continue
        if idx < 0:
            continue
        units = P.mem_units_of_pod(pod, resource=resource)
        if units > 0:
            frac.append((resource, idx, units))
    return frac, sorted(P.used_chips([pod]))


class ClusterUsageIndex:
    """Implements the PodInformer index protocol (rebuild/on_change)."""

    def __init__(self) -> None:
        self._lock = make_lock("extender.usageindex")
        # node -> {"frac": {resource: {chip: units}}, "core": {chip: refs}}
        self._nodes: dict[str, dict] = {}
        # change detection for the extender's NodeView cache: a per-node
        # counter bumped on every usage-affecting mutation, plus a global
        # epoch bumped on rebuild (which resets the per-node counters)
        self._gen: dict[str, int] = {}
        self._epoch = 0

    # --- informer index protocol -----------------------------------------

    def rebuild(self, pods: list[dict]) -> None:
        with self._lock:
            self._nodes.clear()
            self._gen.clear()
            self._epoch += 1
            for pod in pods:
                self._add(pod)

    def on_change(self, old: dict | None, new: dict | None) -> None:
        with self._lock:
            if old is not None:
                self._remove(old)
            if new is not None:
                self._add(new)

    # --- internals (lock held) -------------------------------------------

    def _agg(self, node: str) -> dict:
        agg = self._nodes.get(node)
        if agg is None:
            agg = self._nodes[node] = {"frac": {}, "core": {}, "classes": {}}
        return agg

    def _add(self, pod: dict) -> None:
        frac, cores = _contributions(pod)
        if not frac and not cores:
            return
        node = P.node_name(pod)
        self._gen[node] = self._gen.get(node, 0) + 1
        agg = self._agg(node)
        cls = P.workload_class(pod)
        for resource, idx, units in frac:
            used = agg["frac"].setdefault(resource, {})
            used[idx] = used.get(idx, 0) + units
            if resource == const.RESOURCE_MEM:
                per_chip = agg["classes"].setdefault(idx, {})
                per_chip[cls] = per_chip.get(cls, 0) + 1
        for idx in cores:
            agg["core"][idx] = agg["core"].get(idx, 0) + 1

    def _remove(self, pod: dict) -> None:
        frac, cores = _contributions(pod)
        if not frac and not cores:
            return
        node = P.node_name(pod)
        self._gen[node] = self._gen.get(node, 0) + 1
        agg = self._nodes.get(node)
        if agg is None:
            return
        cls = P.workload_class(pod)
        for resource, idx, units in frac:
            used = agg["frac"].get(resource, {})
            left = used.get(idx, 0) - units
            if left > 0:
                used[idx] = left
            else:
                used.pop(idx, None)
            if resource == const.RESOURCE_MEM:
                per_chip = agg["classes"].get(idx, {})
                refs = per_chip.get(cls, 0) - 1
                if refs > 0:
                    per_chip[cls] = refs
                else:
                    per_chip.pop(cls, None)
                    if not per_chip:
                        agg["classes"].pop(idx, None)
        for idx in cores:
            left = agg["core"].get(idx, 0) - 1
            if left > 0:
                agg["core"][idx] = left
            else:
                agg["core"].pop(idx, None)
        if not agg["core"] and not any(agg["frac"].values()):
            self._nodes.pop(node, None)

    # --- reads ------------------------------------------------------------

    def generation(self, node: str) -> tuple[int, int]:
        """Opaque change token for ``node``'s aggregates: equal tokens
        guarantee ``node_state(node, *)`` is unchanged. The extender's
        NodeView cache keys on it instead of re-reading per verb."""
        with self._lock:
            return (self._epoch, self._gen.get(node, 0))

    def node_state(self, node: str, resource: str) -> tuple[dict[int, int], set[int]]:
        """-> (units used per chip for ``resource``, exclusively-held
        chips) on ``node``; copies, safe to mutate (the extender overlays
        in-flight decisions on top)."""
        with self._lock:
            agg = self._nodes.get(node)
            if agg is None:
                return {}, set()
            return dict(agg["frac"].get(resource, {})), set(agg["core"])

    def chip_classes(self, node: str) -> dict[int, dict[str, int]]:
        """Per-chip workload-class residency counts for ``node``'s share
        pods (chip -> {class: pods}) — the class index the interference
        plane's future class-aware placement reads; maintained under the
        same generation tokens as the unit aggregates. Copies, safe to
        mutate."""
        with self._lock:
            agg = self._nodes.get(node)
            if agg is None:
                return {}
            return {
                idx: dict(per) for idx, per in agg["classes"].items()
            }
