"""HTTP scheduler-extender server.

Speaks the kube-scheduler extender webhook protocol (the v1 JSON API the
scheduler's ``extenders`` policy config points at):

- ``POST /scheduler/filter``      ExtenderArgs -> ExtenderFilterResult
- ``POST /scheduler/prioritize``  ExtenderArgs -> HostPriorityList
- ``POST /scheduler/bind``        ExtenderBindingArgs -> ExtenderBindingResult

Bind both persists the chip decision (IDX/assume-time/per-container
allocation annotations — exactly what Allocate's branch A and the inspect
CLI read) and creates the v1 Binding. Serialized by a single lock so two
same-size pods cannot race a chip (the in-flight one is visible to the next
decision via its annotations-in-apiserver plus a short local cache).
"""

from __future__ import annotations

import argparse
import copy
import dataclasses
import json
import sys
import threading
import time
from typing import Any
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..allocator.binpack import AssignmentError
from ..cluster import pods as P
from ..cluster.apiserver import ApiError, ApiServerClient
from ..utils.decisions import DECISIONS, rank_scores
from ..utils.log import get_logger
from ..utils import log as logutil
from ..utils.tracing import ADMISSIONS, TRACER, SpanContext
from . import logic
from .index import ClusterUsageIndex
from ..utils.lockrank import make_rlock
from ..utils.metric_catalog import (
    EXTENDER_VERB_SECONDS,
    EXTENDER_VERB_TOTAL,
    EXTENDER_VIEW_TOTAL,
)

log = get_logger("extender")


@dataclasses.dataclass
class _Inflight:
    """A bind decision the apiserver watch may not reflect yet.

    Single-chip: ``chips`` is empty, ``units`` lands on ``idx``. Gang:
    ``chips`` holds every member and ``units`` is the PER-CHIP share —
    the overlay books all members together, mirroring the all-or-nothing
    ledger entry on the plugin side."""

    node: str
    resource: str
    idx: int
    units: int
    annotations: dict[str, str]
    stamp: float
    chips: tuple[int, ...] = ()
    # The journal sequence of this decision's begin record (None when the
    # begin was degraded/unjournaled). The deferred expired-entry abort
    # resolves ONLY this incarnation: a fresh same-key begin (pod deleted
    # and recreated under the same name mid-verb) must not be popped by a
    # stale entry's cleanup.
    seq: int | None = None


class ExtenderCore:
    def __init__(
        self,
        api: ApiServerClient,
        policy: "str | logic.PlacementPolicy" = "best-fit",
        informer: Any = None,
        checkpoint: Any = None,
        shard: str = "",
        usage_overlay_fn: Any = None,
    ) -> None:
        """``informer``: an optional cluster-wide ``PodInformer`` (no node
        field-selector). With it, filter/prioritize/bind read incremental
        per-node aggregates (``ClusterUsageIndex``) off the watch cache —
        O(nodes) per webhook verb — instead of LISTing and walking every
        pod in the cluster per scheduling decision.

        ``checkpoint``: an optional ``AllocationCheckpoint`` journaling
        each bind decision before its PATCH (same WAL as the device
        plugin's allocator). On construction the unresolved entries seed
        the in-flight overlay — serve-from-checkpoint warmup — so a
        restarted extender keeps honoring decisions whose PATCH/Binding
        may have landed but are not yet visible on the watch, instead of
        double-booking those chips during its cold-start window. Entries
        age out of the overlay on the normal in-flight TTL, by which time
        the watch has either confirmed them or they never happened.

        ``shard``: this core's shard id in a horizontally sharded
        deployment ("" when unsharded) — stamped on every decision
        record so a placement is attributable to the shard that made it.
        ``usage_overlay_fn(node, resource) -> {chip: units}``: extra
        in-flight usage folded into every node view (the shard layer's
        cross-shard gang2pc reservations, which the core's own in-flight
        overlay cannot know about)."""
        self._api = api
        self._policy = policy
        self._informer = informer
        self._ckpt = checkpoint
        self._shard = shard
        self._usage_overlay_fn = usage_overlay_fn
        self._index: ClusterUsageIndex | None = None
        if informer is not None:
            self._index = ClusterUsageIndex()
            informer.add_index(self._index)
        # RLock: bind() holds it across its whole decision and calls
        # _node_views(), which also touches the in-flight cache
        self._lock = make_rlock("extender.core")
        self._inflight: dict[tuple[str, str], _Inflight] = {}
        self._inflight_ttl_s = 60.0
        # Overlay entries that aged out but whose journal abort has not
        # run yet: ((ns, name), begin seq) pairs. The abort blocks on the
        # WAL writer's fsync ticket, and _live_inflight() runs under the
        # decision lock on the bind path — so expiry only *queues* here
        # and each webhook verb drains after its locked section (tpulint's
        # lock-io rule pins this; journaling them inline under the lock
        # was a real defect this PR's tooling found — docs/analysis.md).
        self._expired_unjournaled: list[
            tuple[tuple[str, str], int | None]
        ] = []
        # Incremental NodeView cache, keyed (node, resource) with a
        # (node resourceVersion, usage-index generation) change token: a
        # filter round over N unchanged nodes re-parses zero capacity
        # vectors and re-copies zero usage maps — pod add/remove deltas
        # land in the index, which bumps the generation and invalidates
        # exactly the touched node.
        self._view_cache: dict[
            tuple[str, str],
            tuple[
                str, tuple, dict[int, int], dict[int, int], set[int],
                "logic.ChipTopology | None",
            ],
        ] = {}
        self._view_cache_max = 8192
        if checkpoint is not None:
            self._warmup_from_checkpoint()

    def _warmup_from_checkpoint(self) -> None:
        now = time.monotonic()
        wall = time.time()
        seeded = 0
        for key, data in self._ckpt.pending().items():
            # Cross-shard two-phase gang records ride the same per-shard
            # WAL but are NOT bind decisions: their resolution belongs to
            # the shard reconciler (roll forward on a durable commit
            # decision, roll back otherwise — extender/shards.py). The
            # warmup must neither replay them as phantom single-chip
            # capacity nor abort them as malformed.
            if data.get("kind") == "gang2pc":
                continue
            # Entries older than the in-flight TTL are stale survivors of
            # an earlier crash cycle: by now the watch has either shown
            # their bind or it never landed — resolve them at load instead
            # of replaying phantom capacity on every restart forever.
            ts = data.get("ts")
            if isinstance(ts, (int, float)) and wall - ts > self._inflight_ttl_s:
                self._ckpt.abort(key, seq=data.get("_seq"))
                continue
            try:
                entry = _Inflight(
                    node=str(data["node"]),
                    resource=str(data["resource"]),
                    idx=int(data["idx"]),
                    units=int(data["units"]),
                    annotations=dict(data.get("annotations") or {}),
                    stamp=now,
                    chips=tuple(int(i) for i in (data.get("chips") or ())),
                    seq=data.get("_seq"),
                )
            except (KeyError, TypeError, ValueError):
                log.warning("checkpoint warmup: malformed bind entry for %s", key)
                self._ckpt.abort(key, seq=data.get("_seq"))
                continue
            self._inflight[(key[0], key[1])] = entry
            seeded += 1
        if seeded:
            log.info(
                "serve-from-checkpoint warmup: %d in-flight bind "
                "decision(s) restored", seeded,
            )

    # --- helpers ----------------------------------------------------------

    def ready(self) -> bool:
        """Readiness for the metrics server's ``/readyz``: the informer
        has synced (decisions serve from the incremental index instead
        of cold LISTs) — serve-from-checkpoint warmup already completed
        in the constructor, so a constructed core has replayed its WAL.
        List-mode cores (no informer) are ready immediately."""
        if self._informer is None:
            return True
        return bool(self._informer.synced)

    def _use_index(self) -> bool:
        """The index serves reads only once the informer has synced: before
        the first LIST lands the cache reads as an empty cluster and every
        chip looks free — placements would over-commit. Until then fall
        back to direct LISTs (never weaker than the reference extender)."""
        return (
            self._index is not None
            and self._informer is not None
            and self._informer.synced
        )

    def _live_inflight(self) -> dict[tuple[str, str], _Inflight]:
        now = time.monotonic()
        with self._lock:
            expired = [
                (k, v.seq) for k, v in self._inflight.items()
                if now - v.stamp >= self._inflight_ttl_s
            ]
            for k, _seq in expired:
                self._inflight.pop(k)
            live = dict(self._inflight)
            # An overlay entry aging out means the watch has caught up (or
            # the bind never landed) — the journal entry has served its
            # purpose and must not be replayed at the next restart. The
            # abort itself blocks on WAL durability, so it only gets
            # QUEUED here; _drain_expired_aborts runs it outside the lock.
            if self._ckpt is not None and expired:
                self._expired_unjournaled.extend(expired)
        return live

    def _drain_expired_aborts(self) -> None:
        """Journal aborts for aged-out overlay entries, called at the end
        of every webhook verb with no lock held: abort() waits on the
        group-commit writer's fsync ticket, and a disk wait under the
        decision lock would serialize every concurrent bind behind it.
        Each abort carries the expired entry's begin seq, so a FRESH
        same-key begin journaled in the deferral window (same pod name
        recreated and re-bound) is never popped by the stale cleanup.
        Unjournaled keys (including already-committed ones) are a no-op
        inside abort()."""
        if self._ckpt is None:
            return
        with self._lock:
            expired, self._expired_unjournaled = self._expired_unjournaled, []
        for k, seq in expired:
            if seq is None:
                # this incarnation's begin was degraded (never journaled):
                # there is nothing of ITS to abort, and an unconditional
                # abort would pop a fresh same-key begin journaled since
                continue
            self._ckpt.abort(k, seq=seq)

    def _view_for(self, node: dict, resource: str) -> logic.NodeView:
        """One node's placement view off the incremental index, memoized.

        Cache hit requires BOTH halves unchanged: the node object's
        resourceVersion (capacity side) and the usage index's per-node
        generation (pod side). Nodes without a resourceVersion (some
        callers pass bare name-only dicts) are never cached — correctness
        over speed. The returned view carries fresh copies of the mutable
        maps because the in-flight overlay writes into ``used``."""
        from ..utils.metrics import REGISTRY

        name = node.get("metadata", {}).get("name", "")
        rv = node.get("metadata", {}).get("resourceVersion")
        gen = self._index.generation(name)
        key = (name, resource)
        outcome = "rebuild"
        with self._lock:
            entry = self._view_cache.get(key)
            if entry is not None and rv is not None and entry[0] == rv and entry[1] == gen:
                _rv, _gen, capacity, used, core_held, topo = entry
                outcome = "hit"
        if outcome == "rebuild":
            capacity = logic.node_capacity(node, resource)
            used, core_held = self._index.node_state(name, resource)
            # The topology grid is a pure function of node labels +
            # capacity (both covered by the resourceVersion key), and
            # rebuilding it was the single hottest line of a 1k-node
            # scoring pass — cache it with the rest of the view.
            topo = (
                logic.node_topology(node, capacity)
                if resource == logic.const.RESOURCE_MEM
                else None
            )
            if rv is not None:
                with self._lock:
                    if len(self._view_cache) >= self._view_cache_max:
                        self._view_cache.clear()  # crude, but bounds memory
                    self._view_cache[key] = (
                        rv, gen, capacity, used, core_held, topo
                    )
        REGISTRY.counter_inc(
            EXTENDER_VIEW_TOTAL,
            "NodeView constructions by outcome (hit = served from the "
            "incremental cache; rebuild = capacity re-parsed / usage re-read)",
            outcome=outcome,
        )
        return logic.NodeView(
            name=name,
            resource=resource,
            capacity=capacity,
            used=dict(used),
            core_held=(
                set(core_held) if resource == logic.const.RESOURCE_MEM else set()
            ),
            topology=topo,
        )

    def _node_views(
        self, resource: str, nodes: list[dict]
    ) -> list[logic.NodeView]:
        """Build per-node placement views for ``resource``.

        Index path: O(len(nodes)) reads of the incremental aggregates.
        List path: one LIST (or the synced cache) plus a full scan,
        identical semantics. This convenience fetches; it is for the
        UNLOCKED verbs (filter/prioritize/batch) — bind prefetches the
        raw pods before its decision lock and calls the in-memory halves
        directly, so no network read ever runs under the lock."""
        if self._use_index():
            return self._views_from_index(resource, nodes)
        return self._views_from_pods(
            resource, nodes, self._fetch_cluster_pods()
        )

    def _views_from_index(
        self, resource: str, nodes: list[dict]
    ) -> list[logic.NodeView]:
        """Index path: incremental per-node aggregates, then overlay
        in-flight bind decisions whose annotations have not yet arrived
        on the watch (once the pod's cached copy carries the IDX
        annotation the index already counts it — skip to avoid double
        counting). Pure memory."""
        views = []
        by_name: dict[str, logic.NodeView] = {}
        for node in nodes:
            view = self._view_for(node, resource)
            views.append(view)
            by_name[view.name] = view
        family = logic.RESOURCE_FAMILIES[resource]
        for (ns, pname), entry in self._live_inflight().items():
            if entry.resource != resource:
                continue
            view = by_name.get(entry.node)
            if view is None:
                continue
            cached = self._informer.get_pod(ns, pname)
            # Not cached yet (reservation made before the pod's watch
            # event, or before its PATCH even landed): the index cannot
            # be counting it, so the overlay must — skipping here would
            # let a concurrent bind double-book the chip. Only a pod
            # provably finished stops counting early (TTL otherwise).
            if cached is not None:
                if not P.is_active(cached):
                    continue
                ann = P.annotations(cached)
                marker = (
                    logic.const.ENV_GANG_CHIPS if entry.chips
                    else family["idx"]
                )
                if marker in ann and P.node_name(cached) == entry.node:
                    continue  # watch caught up; the index counts it on node
            # Otherwise the index either misses the pod or files it
            # under the wrong node (annotation MODIFIED can precede the
            # bind MODIFIED, leaving nodeName empty): count it here.
            # Gang entries book their PER-CHIP share on every member —
            # the overlay mirror of the all-or-nothing ledger entry.
            for member in entry.chips or (entry.idx,):
                view.used[member] = view.used.get(member, 0) + entry.units
        self._apply_usage_overlay(views, resource)
        return views

    def _views_from_pods(
        self, resource: str, nodes: list[dict], raw_pods: list[dict]
    ) -> list[logic.NodeView]:
        """List path from an already-fetched pod set: overlay + group +
        build, pure memory (safe under the decision lock)."""
        pods = self._overlay_pods(raw_pods)
        by_node = logic.group_pods_by_node(pods)
        views = [logic.build_node_view(n, by_node, resource) for n in nodes]
        self._apply_usage_overlay(views, resource)
        return views

    def _apply_usage_overlay(
        self, views: list[logic.NodeView], resource: str
    ) -> None:
        """Fold the shard layer's extra in-flight usage (cross-shard
        gang2pc reservations) into the views — pure memory, both the
        index and the list path run it so a prepared-but-undecided gang
        member is invisible to NO scoring read."""
        if self._usage_overlay_fn is None:
            return
        for view in views:
            extra = self._usage_overlay_fn(view.name, resource)
            if not extra:
                continue
            for idx, units in extra.items():
                view.used[idx] = view.used.get(idx, 0) + units

    def _fetch_cluster_pods(self) -> list[dict]:
        """The list-fallback's raw pod set: the synced cache, else one
        apiserver LIST. Network I/O — callers must not hold the decision
        lock (the lock-io rule pins this)."""
        if self._informer is not None and self._informer.synced:
            return self._informer.all_pods()
        return self._api.list_pods()

    def _overlay_pods(self, pods: list[dict]) -> list[dict]:
        out = []
        for pod in pods:
            if pod.get("status", {}).get("phase") in ("Succeeded", "Failed"):
                continue
            out.append(pod)
        # overlay in-flight decisions not yet visible in the list
        inflight = self._live_inflight()
        by_key = {(p.get("metadata", {}).get("namespace", "default"),
                   p.get("metadata", {}).get("name", "")): i
                  for i, p in enumerate(out)}
        for (ns, name), entry in inflight.items():
            i = by_key.get((ns, name))
            if i is not None:
                # copy before overlay: with an informer these dicts ARE the
                # cache entries and must not be mutated
                pod = copy.deepcopy(out[i])
                meta = pod.setdefault("metadata", {})
                merged = dict(meta.get("annotations") or {})
                merged.update(entry.annotations)
                meta["annotations"] = merged
                pod.setdefault("spec", {}).setdefault("nodeName", entry.node)
                out[i] = pod
        return out

    def node_views(
        self, nodes: list[dict], resource: str
    ) -> list[logic.NodeView]:
        """CURRENT placement views with every overlay applied (in-flight
        binds, shard gang2pc reservations) — the public read the shard
        layer re-validates 2PC prepares, plans gang members, and builds
        routing summaries against. ONE in-flight overlay pass covers the
        whole node list (per-node calls would pay O(in-flight) each).
        Network I/O (the list-fallback LIST) runs before the decision
        lock, mirroring ``bind``."""
        resource = resource or logic.const.RESOURCE_MEM
        raw_pods = None if self._use_index() else self._fetch_cluster_pods()
        with self._lock:
            if raw_pods is None:
                return self._views_from_index(resource, nodes)
            return self._views_from_pods(resource, nodes, raw_pods)

    def node_view(self, node: dict, resource: str) -> logic.NodeView:
        """One node's :meth:`node_views`."""
        return self.node_views([node], resource)[0]

    def _nodes_from_args(self, args: dict) -> list[dict]:
        if args.get("nodes") and args["nodes"].get("items"):
            return args["nodes"]["items"]
        names = args.get("nodenames") or args.get("nodeNames") or []
        nodes = []
        for name in names:
            try:
                nodes.append(self._api.get_node(name))
            except ApiError:
                continue
        return nodes

    # --- webhook verbs ----------------------------------------------------

    def _admission_ctx(self, pod: dict) -> SpanContext | None:
        """The pod's admission-trace root context (created on first
        touch): what stitches the scheduler's separate filter/prioritize/
        bind webhook calls into ONE trace per admission. None for
        anonymous pods and unsampled traces — every verb span is
        ``child_only``, so None means the verb records nothing."""
        meta = pod.get("metadata", {}) if pod else {}
        name = meta.get("name", "")
        if not name:
            return None
        return ADMISSIONS.root(meta.get("namespace", "default"), name)

    @staticmethod
    def _pod_key_of(pod: dict) -> str:
        meta = pod.get("metadata", {}) if pod else {}
        name = meta.get("name", "")
        if not name:
            return ""
        return f"{meta.get('namespace', 'default')}/{name}"

    def filter(self, args: dict) -> dict:
        pod = args.get("pod") or {}
        nodes = self._nodes_from_args(args)
        ctx = self._admission_ctx(pod)
        try:
            with TRACER.span(
                "extender.filter", parent=ctx, child_only=True,
                attributes={"nodes": len(nodes)},
            ) as sp:
                fits, failed = logic.filter_with_views(
                    pod, nodes, self._node_views
                )
                sp.set_attribute("fits", len(fits))
                sp.set_attribute("failed", len(failed))
        finally:
            self._drain_expired_aborts()
        log.v(4, "filter %s: fits=%s failed=%s",
              pod.get("metadata", {}).get("name"), fits, list(failed))
        # Decision provenance: every rejected node with its reason, built
        # from the dicts the verb already computed (no copies).
        DECISIONS.emit(
            self._pod_key_of(pod), "filter",
            candidates=len(nodes), rejected=failed,
            trace_id=ctx.trace_id if ctx is not None else "",
            shard=self._shard,
        )
        fit_set = set(fits)
        return {
            "nodes": {"items": [n for n in nodes
                                if n.get("metadata", {}).get("name") in fit_set]},
            "nodenames": fits,
            "failedNodes": failed,
            "error": "",
        }

    def prioritize(self, args: dict) -> list[dict]:
        pod = args.get("pod") or {}
        nodes = self._nodes_from_args(args)
        ctx = self._admission_ctx(pod)
        try:
            with TRACER.span(
                "extender.prioritize", parent=ctx, child_only=True,
                attributes={"nodes": len(nodes)},
            ) as sp:
                scores = logic.prioritize_with_views(
                    pod, nodes, self._node_views, policy=self._policy
                )
                sp.set_attribute("scored", len(scores))
        finally:
            self._drain_expired_aborts()
        DECISIONS.emit(
            self._pod_key_of(pod), "prioritize",
            candidates=len(nodes), scores=scores,
            trace_id=ctx.trace_id if ctx is not None else "",
            shard=self._shard,
        )
        # The wire format stays the pinned 0-10 integer projection; the
        # decision record above keeps the full-resolution breakdown.
        return [
            {"host": host, "score": sv.projected}
            for host, sv in scores.items()
        ]

    def batch_scored(self, args: dict) -> dict:
        """The batch verb's rich (in-process) form: one view build per
        node serves both the fit check and the score, and the answer
        keeps the full-resolution :class:`ScoreVector` per fitting node
        — ``{"fits", "failed", "scores", "resource"}``. The shard router
        merges THESE across shards (projecting only at its own wire
        edge); :meth:`batch` is the wire projection for direct webhook
        callers. Emits this core's decision record (shard-tagged when
        the core is a shard)."""
        pod = args.get("pod") or {}
        nodes = self._nodes_from_args(args)
        resource = logic.pod_resource(pod)
        if resource is None:
            names = [n.get("metadata", {}).get("name", "") for n in nodes]
            DECISIONS.emit(
                self._pod_key_of(pod), "batch",
                candidates=len(nodes),
                reason="pod requests no share resource (all nodes pass)",
                shard=self._shard,
            )
            return {
                "fits": names, "failed": {}, "scores": {},
                "resource": None, "nodes": nodes,
            }
        request = P.mem_units_of_pod(pod, resource=resource)
        ctx = self._admission_ctx(pod)
        try:
            with TRACER.span(
                "extender.batch", parent=ctx, child_only=True,
                attributes={"nodes": len(nodes)},
            ) as sp:
                views = self._node_views(resource, nodes)
                fits, failed, scores = logic.evaluate_filter_and_scores(
                    request, views, policy=self._policy,
                    gang_shape=logic.pod_gang_shape(pod, resource),
                )
                sp.set_attribute("fits", len(fits))
        finally:
            self._drain_expired_aborts()
        DECISIONS.emit(
            self._pod_key_of(pod), "batch",
            candidates=len(nodes), rejected=failed, scores=scores,
            trace_id=ctx.trace_id if ctx is not None else "",
            shard=self._shard,
        )
        return {
            "fits": fits, "failed": failed, "scores": scores,
            "resource": resource, "nodes": nodes,
        }

    def batch(self, args: dict) -> dict:
        """Batched filter + prioritize in one verb: one view build and one
        free-vector computation per node serve both answers (the two-verb
        protocol builds views twice per scheduling cycle). Same args as
        filter; the response adds ``hostPriorityList`` for the fitting
        nodes. Not part of the upstream extender protocol — callers are
        our own tooling (bench, tests) and schedulers taught the route."""
        return batch_wire(self.batch_scored(args))

    def bind(self, args: dict) -> dict:
        """Persist the chip decision and create the v1 Binding.

        Concurrency design: the lock guards only the in-memory decision —
        build the node view, choose the chip, and *reserve* it by inserting
        the in-flight entry — never network I/O or a durability wait. The
        GET pod/node (and, in ``--pod-source list`` fallback mode, the
        cluster LIST) run *before* the lock; the PATCH + binding POST run
        after it — so binds to different nodes proceed in parallel instead
        of serializing the whole cluster's admission behind one apiserver
        round-trip or one WAL fsync (tpulint's lock-io rule enforces this
        shape; both the fallback LIST and the expired-entry journal abort
        used to run under the lock — docs/analysis.md, defects table). The
        reservation is visible to every concurrent decision through the
        in-flight overlay (``_node_views``), which is exactly how mid-PATCH
        decisions were already kept from double-booking; a failed PATCH or
        Binding rolls the reservation back.
        """
        ns = args.get("podNamespace", "default")
        name = args.get("podName", "")
        node_name = args.get("node", "")
        ctx = ADMISSIONS.root(ns, name) if name else None
        try:
            with TRACER.span(
                "extender.bind", parent=ctx, child_only=True,
                attributes={"node": node_name},
            ) as bsp:
                result = self._bind(args, ns, name, node_name, bsp)
                if result.get("error"):
                    bsp.set_attribute("bind_error", result["error"])
                    bsp.end("error")
        except BaseException:
            if name:
                ADMISSIONS.finish(ns, name, "error")
            raise
        else:
            if name:
                ADMISSIONS.finish(
                    ns, name, "error" if result.get("error") else "ok"
                )
            return result
        finally:
            # failure paths included: keys queued by _live_inflight()
            # during this verb must not wait for some later verb (an
            # idle-then-restarted extender would replay their journal
            # entries as stale reservations)
            self._drain_expired_aborts()

    def _bind(
        self, args: dict, ns: str, name: str, node_name: str, bsp: Any
    ) -> dict:
        try:
            # Callers that already hold the objects (the shard router,
            # schedulers speaking the full ExtenderArgs shape) pass them
            # along; the GETs are the fallback for name-only callers.
            pod = args.get("podObject") or self._api.get_pod(ns, name)
            node = args.get("nodeObject") or self._api.get_node(node_name)
            resource = logic.pod_resource(pod)
            if resource is None:
                raise AssignmentError("pod requests no share resource")
            gang_shape = logic.pod_gang_shape(pod, resource)
            # list-fallback prefetch: the LIST is network I/O and must not
            # run under the decision lock. The in-flight overlay is still
            # applied under the lock, so concurrent binds see each other;
            # the LIST data itself is no staler than it already was.
            raw_pods = (
                None if self._use_index() else self._fetch_cluster_pods()
            )
            with TRACER.span("extender.decide", child_only=True) as dsp:
                with self._lock:
                    if raw_pods is None:
                        view = self._views_from_index(resource, [node])[0]
                    else:
                        view = self._views_from_pods(
                            resource, [node], raw_pods
                        )[0]
                    if gang_shape:
                        # gang bind: ONE decision covering every member
                        # chip, reserved whole in the in-flight overlay
                        # before any network write — all-or-nothing from
                        # the first moment
                        _, chips, per_chip, annotations, score = (
                            logic.choose_gang_scored(
                                pod, view, policy=self._policy
                            )
                        )
                        idx, units = chips[0], per_chip
                    else:
                        chips = ()
                        _, idx, annotations, score = logic.choose_chip_scored(
                            pod, view, policy=self._policy
                        )
                        units = P.mem_units_of_pod(pod, resource=resource)
                    self._inflight[(ns, name)] = _Inflight(
                        node=node_name,
                        resource=resource,
                        idx=idx,
                        units=units,
                        annotations=annotations,
                        stamp=time.monotonic(),
                        chips=tuple(chips),
                    )
                dsp.set_attribute("chip", list(chips) if chips else idx)
            # The bind span's context rides the PATCH as the trace-id
            # annotation: the device plugin's allocator adopts it after
            # the pod match, stitching the two processes into one trace.
            if bsp.recording:
                annotations[logic.const.ANN_TRACE_ID] = bsp.context().encode()
            # WAL begin before the PATCH/Binding: a crash inside the next
            # block leaves an unresolved entry the restarted extender's
            # warmup serves from (and a journal-less crash would forget).
            seq = None
            if self._ckpt is not None:
                with TRACER.span("wal.begin", child_only=True):
                    seq = self._ckpt.begin((ns, name), {
                        "node": node_name,
                        "resource": resource,
                        "idx": idx,
                        "units": units,
                        "chips": list(chips),
                        "annotations": annotations,
                        "ts": time.time(),  # warmup ages stale entries out by this
                    })
                # stamp the overlay entry with its begin incarnation so a
                # later TTL expiry aborts exactly this record
                with self._lock:
                    entry = self._inflight.get((ns, name))
                    if entry is not None:
                        entry.seq = seq
            try:
                with TRACER.span("pod.patch", child_only=True):
                    self._api.patch_pod(
                        ns, name, {"metadata": {"annotations": annotations}}
                    )
                with TRACER.span("pod.bindv1", child_only=True):
                    self._api.bind_pod(ns, name, node_name)
            except Exception:
                with self._lock:
                    self._inflight.pop((ns, name), None)
                # resolve OUR begin incarnation only: a slow failing PATCH
                # can overlap a fresh same-key begin (pod recreated under
                # the same name), which an unguarded abort would pop. A
                # degraded begin (seq None) journaled nothing to resolve.
                if self._ckpt is not None and seq is not None:
                    with TRACER.span("wal.abort", child_only=True):
                        self._ckpt.abort((ns, name), seq=seq)
                raise
            if self._ckpt is not None and seq is not None:
                with TRACER.span("wal.commit", child_only=True):
                    self._ckpt.commit((ns, name), seq=seq)
        except (ApiError, AssignmentError) as e:
            log.warning("bind %s/%s -> %s failed: %s", ns, name, node_name, e)
            from ..cluster.events import REASON_BIND_FAILED, emit_pod_event

            emit_pod_event(
                self._api,
                {"metadata": {"namespace": ns, "name": name}},
                REASON_BIND_FAILED,
                f"bind to {node_name} failed: {e}",
                component="tpushare-scheduler-extender",
                host=node_name,
            )
            # a rejected bind deserves a "why" as much as a granted one
            DECISIONS.emit(
                f"{ns}/{name}", "bind", outcome="error",
                node=node_name, reason=str(e),
                trace_id=bsp.trace_id if bsp.recording else "",
                shard=self._shard,
            )
            return {"error": str(e)}
        if chips:
            placement = {
                "chips": list(chips),
                "per_chip": units,
                "shape": annotations.get(logic.const.ENV_GANG_SHAPE, ""),
            }
            log.info(
                "bound gang %s/%s -> %s chips %s (%d units/chip)",
                ns, name, node_name, list(chips), units,
            )
        else:
            placement = {"chip": idx, "units": units}
            log.info("bound %s/%s -> %s chip %d", ns, name, node_name, idx)
        DECISIONS.emit(
            f"{ns}/{name}", "bind",
            node=node_name, scores={node_name: score}, placement=placement,
            trace_id=bsp.trace_id if bsp.recording else "",
            seq=seq, shard=self._shard,
        )
        return {"error": ""}


def batch_wire(rich: dict) -> dict:
    """THE rich->wire projection for the batch verb, shared by the
    single core and the shard router so the two deployments' response
    shapes can never drift. ``rich`` is a ``batch_scored`` result (or
    the router's cross-shard merge of several): 0-10 projected scores,
    hostPriorityList ordered best-first by the RAW fractional score
    (deterministic tie-break — the integer scale ties most nodes at
    fleet scale; the wire VALUES are the pinned projection, only the
    list order is added)."""
    nodes = rich["nodes"]
    if rich["resource"] is None:
        names = rich["fits"]
        return {
            "nodes": {"items": nodes},
            "nodenames": names,
            "failedNodes": {},
            "hostPriorityList": [{"host": n, "score": 0} for n in names],
            "error": "",
        }
    fits, failed, scores = rich["fits"], rich["failed"], rich["scores"]
    fit_set = set(fits)
    return {
        "nodes": {"items": [n for n in nodes
                            if n.get("metadata", {}).get("name") in fit_set]},
        "nodenames": fits,
        "failedNodes": failed,
        "hostPriorityList": [
            {"host": name, "score": scores[name].projected}
            for name in rank_scores(scores)
        ],
        "error": "",
    }


class ExtenderHTTPServer:
    def __init__(self, core: ExtenderCore, host: str = "0.0.0.0", port: int = 32766) -> None:
        self._core = core
        self._host = host
        self._port = port
        self._server: ThreadingHTTPServer | None = None

    @property
    def port(self) -> int:
        assert self._server is not None
        return self._server.server_address[1]

    def start(self) -> None:
        core = self._core

        class Handler(BaseHTTPRequestHandler):
            # Keep-alive matters twice over: the scheduler calls the webhook
            # per scheduling cycle, and each handler thread caches its own
            # persistent apiserver connection (ApiServerClient._connection
            # is thread-local) — HTTP/1.0's connection-per-request would
            # pay a fresh apiserver TCP/TLS handshake on every verb.
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt: str, *args: object) -> None:
                log.v(6, fmt, *args)

            def _send(self, code: int, body: object) -> None:
                data = json.dumps(body).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self) -> None:
                if self.path in ("/version", "/healthz"):
                    return self._send(200, {"version": "v1", "ok": True})
                return self._send(404, {"error": "not found"})

            def do_POST(self) -> None:
                from ..utils.metrics import REGISTRY

                n = int(self.headers.get("Content-Length", "0"))
                try:
                    body = json.loads(self.rfile.read(n) or b"{}")
                except json.JSONDecodeError:
                    return self._send(400, {"error": "bad json"})
                verbs = {
                    "/scheduler/filter": core.filter,
                    "/scheduler/prioritize": core.prioritize,
                    "/scheduler/batch": core.batch,
                    "/scheduler/bind": core.bind,
                }
                fn = verbs.get(self.path)
                if fn is None:
                    return self._send(404, {"error": f"unknown path {self.path}"})
                verb = self.path.rsplit("/", 1)[-1]
                t0 = time.perf_counter()
                try:
                    result = fn(body)
                except Exception as e:  # keep the webhook alive
                    log.error("extender verb %s failed: %s", self.path, e)
                    REGISTRY.counter_inc(
                        EXTENDER_VERB_TOTAL,
                        "Webhook verbs by outcome", verb=verb, outcome="error",
                    )
                    return self._send(200, {"error": str(e)})
                REGISTRY.observe(
                    EXTENDER_VERB_SECONDS,
                    time.perf_counter() - t0,
                    "Webhook verb latency", verb=verb,
                )
                REGISTRY.counter_inc(
                    EXTENDER_VERB_TOTAL,
                    "Webhook verbs by outcome", verb=verb, outcome="ok",
                )
                return self._send(200, result)

        self._server = ThreadingHTTPServer((self._host, self._port), Handler)
        t = threading.Thread(target=self._server.serve_forever, daemon=True)
        t.start()
        log.info("scheduler extender listening on %s:%d", self._host, self.port)

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="tpushare-scheduler-extender")
    p.add_argument("--port", type=int, default=32766)
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--policy", default="best-fit", choices=["first-fit", "best-fit", "spread"])
    p.add_argument("--placement-policy", default="",
                   help="pluggable placement policy from the registry "
                   "(greedy-binpack | multi-objective | learned | "
                   "anything register_policy()'d); overrides --policy. "
                   "Empty keeps the legacy chip-policy scorer")
    p.add_argument("--pod-source", default="informer", choices=["informer", "list"],
                   help="watch-backed cluster pod cache (default) or a full "
                   "LIST per webhook call")
    p.add_argument("--checkpoint-path", default="",
                   help="bind-decision WAL file; a restarted extender "
                   "warms its in-flight overlay from it instead of "
                   "double-booking chips whose bind is not yet on the "
                   "watch (empty disables)")
    p.add_argument("--wal-fsync", default="batch",
                   choices=["always", "batch"],
                   help="bind-WAL durability mode (same group-commit "
                   "writer as the device plugin's journal): 'batch' "
                   "amortizes one fsync across concurrent binds, 'always' "
                   "fsyncs per record")
    p.add_argument("--wal-batch-window-ms", type=float, default=2.0,
                   help="group-commit gather window in milliseconds")
    p.add_argument("--timeout", type=float, default=10.0)
    p.add_argument("--metrics-port", type=int, default=0,
                   help="serve Prometheus /metrics (+ /traces OTLP-JSON) "
                   "on this port (0 = off)")
    p.add_argument("--trace-sample", type=float, default=1.0,
                   help="admission-trace sample ratio in [0,1]: each "
                   "pod's filter->bind trace is kept with this "
                   "probability (0 disables tracing; unsampled "
                   "admissions pay O(ns))")
    p.add_argument("--decisions-ring", type=int, default=512,
                   help="in-memory decision-provenance ring size (per-"
                   "verb 'why' records served on /decisions; 0 disables "
                   "emission)")
    p.add_argument("--decisions-log", default="",
                   help="optional on-disk decision segment log (JSON "
                   "lines, fsync-free, size-rotated); empty disables")
    p.add_argument("-v", "--verbosity", type=int, default=0)
    args = p.parse_args(argv)
    logutil.setup(args.verbosity)
    TRACER.configure(sample_ratio=args.trace_sample)
    DECISIONS.configure(
        enabled=args.decisions_ring > 0,
        max_records=max(1, args.decisions_ring),
        segment_path=args.decisions_log,
    )
    # The metrics server (and its /healthz — the liveness probe) comes up
    # FIRST: informer sync, WAL load, and the core's serve-from-
    # checkpoint warmup can take long after a crash storm, and a
    # liveness probe that cannot reach /healthz during replay would
    # kubelet-kill the container into an eternal replay loop. /readyz is
    # late-bound: 503 until the core exists AND reports ready (informer
    # synced + warmup done in its constructor).
    core_ref: list[ExtenderCore] = []
    metrics_server = None
    if args.metrics_port:
        from ..utils.metrics import MetricsServer, publish_build_info

        publish_build_info(component="extender")
        metrics_server = MetricsServer(
            port=args.metrics_port,
            ready_fn=lambda: bool(core_ref) and core_ref[0].ready(),
        ).start()
        log.info("metrics on :%d/metrics", metrics_server.port)
    try:
        api = ApiServerClient.from_env(timeout_s=args.timeout)
    except Exception as e:
        log.fatal(f"apiserver config failed: {e}")
    informer = None
    if args.pod_source == "informer":
        from ..cluster.informer import PodInformer

        informer = PodInformer(api).start()
    checkpoint = None
    if args.checkpoint_path:
        from ..allocator.checkpoint import AllocationCheckpoint

        try:
            checkpoint = AllocationCheckpoint(
                args.checkpoint_path,
                fsync=args.wal_fsync,
                batch_window_s=args.wal_batch_window_ms / 1000.0,
            )
        except OSError as e:
            log.warning("bind checkpoint unavailable (%s); running without", e)
    policy: "str | logic.PlacementPolicy" = args.policy
    if args.placement_policy:
        from .policy import get_policy

        policy = get_policy(args.placement_policy)
    core = ExtenderCore(
        api, policy=policy, informer=informer, checkpoint=checkpoint
    )
    core_ref.append(core)
    server = ExtenderHTTPServer(core, host=args.host, port=args.port)
    server.start()
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        server.stop()
        if informer is not None:
            informer.stop()
        if metrics_server is not None:
            metrics_server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
