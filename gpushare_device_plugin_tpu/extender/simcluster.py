"""Simulated-cluster churn driver for scale benches and chaos suites.

The scale story needs a cluster three orders of magnitude past what the
dev box can host: this module synthesizes 1k-node catalogs with
heterogeneous chip topologies and drives Poisson pod churn (arrivals,
exponential lifetimes, periodic gang-group bursts) against any admission
function — the sharded router, a single extender core, or a future
scheduler — while recording per-admission latency and auditing the
resulting apiserver state for overcommit and partial gangs.

Virtual time: arrivals and deletions advance a simulated clock
(``rng.expovariate``), processed as fast as the host allows — the bench
measures the ADMISSION PATH's wall cost, not the trace's wall span.
Deterministic per seed.
"""

from __future__ import annotations

import dataclasses
import heapq
import random
import threading
import time
from typing import Any, Callable, Sequence

from .. import const
from ..cluster import pods as P
from ..topology import shape_size
from ..utils.lockrank import make_lock
from . import logic

# Heterogeneous node classes: (topology label, chips). The mix mirrors a
# real fleet growing over hardware generations — small 4-chip hosts
# through 16-chip slabs — so slice enumeration and gang scoring see
# genuinely different grids, not 1k copies of one node.
NODE_CLASSES: tuple[tuple[str, int], ...] = (
    ("2x2x1", 4),
    ("2x2x2", 8),
    ("4x2x2", 16),
)

DEFAULT_CHIP_UNITS = 32  # HBM units per chip (the bench's GiB stand-in)


def synth_node(
    name: str, shape: str, chips: int, chip_units: int = DEFAULT_CHIP_UNITS
) -> dict:
    """One synthetic node JSON: per-chip capacity ``chip_units``, chip
    count ``chips``, and the topology label the slice enumerator reads."""
    total = chips * chip_units
    cap = {
        const.RESOURCE_MEM: str(total),
        const.RESOURCE_COUNT: str(chips),
    }
    return {
        "metadata": {
            "name": name,
            "labels": {const.LABEL_NODE_TOPOLOGY: shape},
            "resourceVersion": "1",
        },
        "status": {"capacity": dict(cap), "allocatable": dict(cap)},
    }


def make_cluster(
    n_nodes: int,
    seed: int = 0,
    chip_units: int = DEFAULT_CHIP_UNITS,
    prefix: str = "sim",
) -> list[dict]:
    """A deterministic heterogeneous catalog of ``n_nodes`` nodes."""
    rng = random.Random(seed)
    nodes = []
    for i in range(n_nodes):
        shape, chips = NODE_CLASSES[rng.randrange(len(NODE_CLASSES))]
        nodes.append(
            synth_node(f"{prefix}-{i:04d}", shape, chips, chip_units)
        )
    return nodes


@dataclasses.dataclass
class ChurnStats:
    """One churn run's outcome."""

    arrivals: int = 0
    admitted: int = 0
    rejected: int = 0
    retried: int = 0
    deleted: int = 0
    gang_groups: int = 0
    gang_members: int = 0
    gang_failed: int = 0
    degraded_consultations: int = 0
    admit_wall_s: float = 0.0  # summed per-admission time (utilization)
    wall_s: float = 0.0  # the whole run's wall span (throughput base)
    latencies_ms: list[float] = dataclasses.field(default_factory=list)

    def admissions_per_s(self) -> float:
        base = self.wall_s or self.admit_wall_s
        if base <= 0:
            return 0.0
        return self.admitted / base

    def latency_ms(self, q: float) -> float:
        if not self.latencies_ms:
            return 0.0
        ordered = sorted(self.latencies_ms)
        i = min(len(ordered) - 1, int(q * len(ordered)))
        return ordered[i]


class ChurnDriver:
    """Poisson churn against an admission function.

    ``admit_fn(pod) -> {"node": str, "error": str, ...}`` places one
    pod (the router's :meth:`ShardRouter.admit`, or an adapter over a
    single core); ``admit_gang_fn(pods) -> {"error": str, ...}`` places
    a gang group all-or-nothing (None disables gang bursts).
    ``create_pod_fn``/``delete_pod_fn`` mutate the (fake) apiserver the
    admission path reads — creation happens BEFORE admission, like a
    real scheduler seeing a Pending pod.

    Every ``gang_every``-th arrival becomes a burst: a gang group of
    ``gang_members`` pods, each requesting ``gang_shape``. Lifetimes are
    exponential with mean ``mean_lifetime`` in virtual seconds; a
    deleted gang group leaves whole.
    """

    def __init__(
        self,
        create_pod_fn: Callable[[dict], None],
        delete_pod_fn: Callable[[str, str], None],
        admit_fn: Callable[[dict], dict],
        admit_gang_fn: Callable[[Sequence[dict]], dict] | None = None,
        seed: int = 0,
        sizes: Sequence[int] = (2, 4, 6, 8, 12, 16),
        arrival_rate: float = 50.0,
        mean_lifetime: float = 30.0,
        gang_every: int = 0,
        gang_members: int = 2,
        gang_shape: str = "2x1",
        retry_once: bool = True,
        namespace: str = "default",
        workers: int = 1,
    ) -> None:
        self._create = create_pod_fn
        self._delete = delete_pod_fn
        self._admit = admit_fn
        self._admit_gang = admit_gang_fn
        self._rng = random.Random(seed)
        self._sizes = tuple(sizes)
        self._rate = arrival_rate
        self._lifetime = mean_lifetime
        self._gang_every = gang_every
        self._gang_members = gang_members
        self._gang_shape = gang_shape
        self._retry_once = retry_once
        self._ns = namespace
        self._workers = max(1, workers)
        self._seq = 0
        # virtual-clock deletion heap: (death time, tiebreak, [pod names])
        self._deaths: list[tuple[float, int, list[str]]] = []
        self._now = 0.0
        # stats/heap guard for the worker pool (pod NAMES and sizes stay
        # deterministic per seed — drawn by the single generator thread —
        # only the admission interleaving varies across runs)
        self._stats_lock = make_lock("extender.simchurn")

    def _make_pod(self, name: str, units: int, extra_ann: dict | None = None) -> dict:
        return {
            "metadata": {
                "name": name,
                "namespace": self._ns,
                "uid": f"sim-{name}",
                "creationTimestamp": "2026-01-01T00:00:00Z",
                "annotations": dict(extra_ann or {}),
                "labels": {},
            },
            "spec": {
                "nodeName": "",
                "containers": [{
                    "name": "c0",
                    "image": "sim",
                    "resources": {
                        "limits": {const.RESOURCE_MEM: str(units)}
                    },
                }],
            },
            "status": {"phase": "Pending"},
        }

    def _process_deaths(self, stats: ChurnStats) -> None:
        due: list[str] = []
        with self._stats_lock:
            while self._deaths and self._deaths[0][0] <= self._now:
                _t, _tb, names = heapq.heappop(self._deaths)
                due.extend(names)
        for name in due:
            self._delete(self._ns, name)
        with self._stats_lock:
            stats.deleted += len(due)

    def _schedule_death(self, names: list[str], delta: float) -> None:
        with self._stats_lock:
            self._seq += 1
            heapq.heappush(
                self._deaths, (self._now + delta, self._seq, names)
            )

    def _admit_one(self, pod: dict, stats: ChurnStats) -> bool:
        t0 = time.perf_counter()
        result = self._admit(pod)
        retried = False
        if result.get("error") and self._retry_once:
            retried = True
            result = self._admit(pod)
        dt = time.perf_counter() - t0
        with self._stats_lock:
            if retried:
                stats.retried += 1
            stats.latencies_ms.append(dt * 1e3)
            stats.admit_wall_s += dt
            stats.degraded_consultations += len(
                result.get("degraded_shards") or ()
            )
        return not result.get("error")

    def run(self, events: int) -> ChurnStats:
        """Drive ``events`` arrival events (a gang burst counts as one
        event but creates ``gang_members`` pods); -> stats. With
        ``workers > 1`` admissions run on a thread pool (the storm's
        concurrency — HTTP round-trips to the apiserver overlap while
        the GIL serializes scoring, exactly the production shape)."""
        stats = ChurnStats()
        t_run = time.perf_counter()
        if self._workers <= 1:
            for item in self._generate(events, stats):
                self._execute(item, stats)
        else:
            import queue

            work: "queue.Queue" = queue.Queue(maxsize=self._workers * 4)

            def worker() -> None:
                while True:
                    item = work.get()
                    if item is None:
                        return
                    try:
                        self._execute(item, stats)
                    finally:
                        work.task_done()

            threads = [
                threading.Thread(target=worker, daemon=True)
                for _ in range(self._workers)
            ]
            for t in threads:
                t.start()
            for item in self._generate(events, stats):
                work.put(item)
            work.join()
            for _ in threads:
                work.put(None)
            for t in threads:
                t.join()
        # scheduled-but-not-due deletions stay: the run ends with a
        # populated cluster for the caller's audit pass
        stats.wall_s = time.perf_counter() - t_run
        return stats

    def _generate(self, events: int, stats: ChurnStats):
        """The single-threaded event source: draws every name, size, and
        death delta from ONE seeded generator (deterministic per seed),
        advances the virtual clock, and fires due deletions."""
        for i in range(events):
            self._now += self._rng.expovariate(self._rate)
            self._process_deaths(stats)
            with self._stats_lock:
                stats.arrivals += 1
                self._seq += 1
                seq = self._seq
            death = self._rng.expovariate(1.0 / self._lifetime)
            is_burst = (
                self._admit_gang is not None
                and self._gang_every > 0
                and (i + 1) % self._gang_every == 0
            )
            if is_burst:
                group = f"simgang-{seq}"
                per_chip = self._rng.choice(self._sizes[:3])
                size = shape_size(self._gang_shape)
                members = []
                for m in range(self._gang_members):
                    members.append(self._make_pod(
                        f"{group}-m{m}", per_chip * size,
                        extra_ann={
                            const.ANN_GANG_SHAPE: self._gang_shape,
                            const.ANN_GANG_GROUP: group,
                        },
                    ))
                yield ("gang", members, death)
            else:
                units = self._rng.choice(self._sizes)
                yield ("pod", self._make_pod(f"simpod-{seq}", units), death)

    def _execute(self, item: tuple, stats: ChurnStats) -> None:
        kind, payload, death = item
        if kind == "pod":
            pod = payload
            name = pod["metadata"]["name"]
            self._create(pod)
            if self._admit_one(pod, stats):
                with self._stats_lock:
                    stats.admitted += 1
                self._schedule_death([name], death)
            else:
                with self._stats_lock:
                    stats.rejected += 1
                self._delete(self._ns, name)
            return
        members = payload
        with self._stats_lock:
            stats.gang_groups += 1
            stats.gang_members += len(members)
        for pod in members:
            self._create(pod)
        t0 = time.perf_counter()
        result = self._admit_gang(members)
        dt = time.perf_counter() - t0
        with self._stats_lock:
            stats.admit_wall_s += dt
        if result.get("error"):
            with self._stats_lock:
                stats.gang_failed += 1
            for pod in members:
                self._delete(self._ns, pod["metadata"]["name"])
        else:
            with self._stats_lock:
                stats.admitted += len(members)
            self._schedule_death(
                [p["metadata"]["name"] for p in members], death,
            )


def audit_cluster(nodes: list[dict], pods: list[dict]) -> list[str]:
    """Invariant audit over (fake) apiserver state; -> violations.

    - no chip on any node holds more annotated units than its capacity
      (the cross-shard double-booking check), and no annotation names a
      chip the node does not have;
    - every granted share pod is bound to a KNOWN node — a pod carrying
      chip annotations but no (or an unknown) nodeName is counted
      nowhere, the under-count that masks a double-booking;
    - every gang GROUP is whole: all members carry their gang grant or
      none do (no partial gang visible).
    """
    violations: list[str] = []
    active = [p for p in pods if P.is_active(p)]
    known = {n.get("metadata", {}).get("name", "") for n in nodes}
    for pod in active:
        ann = P.annotations(pod)
        granted = (
            const.ENV_MEM_IDX in ann or const.ENV_GANG_CHIPS in ann
        )
        if granted and P.node_name(pod) not in known:
            violations.append(
                f"{pod.get('metadata', {}).get('name', '?')}: granted "
                f"chips but bound to unknown node "
                f"{P.node_name(pod)!r} — counted nowhere"
            )
    by_node = logic.group_pods_by_node(active)
    for node in nodes:
        name = node.get("metadata", {}).get("name", "")
        capacity = logic.node_capacity(node, const.RESOURCE_MEM)
        if not capacity:
            continue
        used = logic.node_usage(by_node.get(name, []), const.RESOURCE_MEM)
        for chip, units in used.items():
            cap = capacity.get(chip)
            if cap is None:
                violations.append(
                    f"{name}: annotated chip {chip} does not exist"
                )
            elif units > cap:
                violations.append(
                    f"{name}: chip {chip} overcommitted ({units} > {cap})"
                )
    groups: dict[str, list[dict]] = {}
    for pod in pods:
        gid = P.gang_group(pod)
        if gid:
            groups.setdefault(gid, []).append(pod)
    for gid, members in groups.items():
        granted = [
            bool(P.gang_chips_from_annotation(p)) for p in members
        ]
        if any(granted) and not all(granted):
            violations.append(
                f"gang group {gid}: partial grant "
                f"({sum(granted)}/{len(granted)} members bound)"
            )
    return violations


def audit_no_cross_shard_double_booking(
    nodes: list[dict], pods: list[dict]
) -> list[str]:
    """Alias with the acceptance criterion's name: overcommit on any
    chip IS a double-booking — two admissions (from any shards) were
    granted overlapping capacity."""
    return [v for v in audit_cluster(nodes, pods) if "overcommit" in v
            or "does not exist" in v]


def pending_share_pods(pods: list[dict]) -> list[dict]:
    """Share pods still awaiting placement (diagnostics for drivers)."""
    out = []
    for pod in pods:
        if not P.is_active(pod):
            continue
        if P.mem_units_of_pod(pod) <= 0:
            continue
        ann = P.annotations(pod)
        if const.ENV_MEM_IDX in ann or const.ENV_GANG_CHIPS in ann:
            continue
        out.append(pod)
    return out


def summarize(stats: ChurnStats) -> dict[str, Any]:
    """JSON-ready stats block for bench reports."""
    return {
        "arrivals": stats.arrivals,
        "admitted": stats.admitted,
        "rejected": stats.rejected,
        "retried": stats.retried,
        "deleted": stats.deleted,
        "gang_groups": stats.gang_groups,
        "gang_failed": stats.gang_failed,
        "degraded_consultations": stats.degraded_consultations,
        "admissions_per_s": round(stats.admissions_per_s(), 1),
        "admit_p50_ms": round(stats.latency_ms(0.50), 3),
        "admit_p99_ms": round(stats.latency_ms(0.99), 3),
    }
