"""Pluggable placement policies behind one registration point.

The extender's scoring seam (PR 12's :class:`ScoreVector` refactor) made
every placement decision a structured breakdown; this module makes the
FORMULA that produces it swappable. A policy sees one candidate's
placement evidence (:class:`PolicyView` — the decisive chip's free
units, the node's whole free vector, and, for gang slices, the topology
objective components) and answers with a :class:`ScoreVector`. The 0-10
webhook wire projection, the decision records, and ``inspect why``'s
margins all flow from that one answer, so a swapped policy is fully
introspectable for free.

Three policies ship:

- ``greedy-binpack`` — the classic slack-minimizing binpack (the default
  the repo has always run: raw = 10*(1-slack) on the tightest feasible
  chip). Also the implementation behind the legacy ``best-fit``/
  ``first-fit``/``spread`` names, so resolving those through the
  registry is bit-identical to the pre-registry scorer.
- ``multi-objective`` — a weighted composite over packing slack, node
  balance, and the gang topology objectives (ICI hops, stranded
  slivers, broken whole chips), in the spirit of the multi-objective
  MIG placement of PAPERS.md 2502.01909: one scalar the scheduler can
  rank, components preserved in the vector for provenance.
- ``learned`` — a stub for an RL-trained policy (PAPERS.md 2601.13579's
  custom scheduler is the reference): a fixed linear model over the
  same feature vector a trained policy would consume. It exists to pin
  the registration point and the feature contract, not to be smart.

Deployments select a policy with ``--placement-policy`` (extender and
shard router); ``register_policy`` is the one extension point.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

from ..utils.decisions import ScoreVector, chip_breakdown


@dataclasses.dataclass(frozen=True)
class PolicyView:
    """One placement candidate, as a policy sees it.

    ``free_units``/``chip`` describe the decisive chip (the tightest or
    roomiest feasible one, or the concretely chosen one at bind time);
    ``free_vector`` is every feasible chip's free units on the node (for
    policies that weigh balance, not just the decisive chip). Gang
    candidates add the winning slice's topology objective components;
    single-chip candidates leave them None.
    """

    free_units: int
    capacity: int
    request_units: int
    free_vector: tuple[int, ...] = ()
    chip: int | None = None
    ici_hops: int | None = None
    stranded: int | None = None
    broken: int | None = None
    tie_break: int | None = None
    # Fleet routing candidates (serving.router) add the number of radix
    # pages the candidate engine already holds for the request's prompt
    # prefix; placement candidates leave it None.
    affinity_pages: int | None = None

    def slack(self) -> float:
        """Leftover fraction on the decisive chip after placement."""
        if self.capacity <= 0:
            return 0.0
        return (self.free_units - self.request_units) / self.capacity


class PlacementPolicy:
    """One placement policy: ``score(view) -> ScoreVector``.

    ``chip_policy`` names the chip-SELECTION semantics reused from the
    binpack allocator ("best-fit" | "first-fit" | "spread") — which chip
    on a feasible node is decisive, and which chip a bind concretely
    takes. Scoring (this class) ranks candidates; selection stays with
    ``allocator.binpack.assign_chip`` so the extender's decisions and
    the device plugin's re-validation never disagree about which chip a
    score was about.
    """

    name = "base"
    chip_policy = "best-fit"

    def score(self, view: PolicyView) -> ScoreVector:
        raise NotImplementedError

    def _infeasible(self, view: PolicyView) -> ScoreVector:
        return ScoreVector(
            policy=self.name, raw=0.0,
            free_units=max(0, view.free_units),
            request_units=view.request_units, binpack=0.0,
            ici_hops=view.ici_hops, stranded=view.stranded,
            broken=view.broken, tie_break=view.tie_break,
        )


class GreedyBinpackPolicy(PlacementPolicy):
    """Slack-minimizing binpack — the repo's historical scorer.

    Delegates to :func:`chip_breakdown` (THE shared formula the
    allocator's provenance records also use) and carries the gang slice
    components through unchanged, so ``greedy-binpack`` — and the legacy
    ``best-fit``/``first-fit``/``spread`` names, which are this class
    with a different ``chip_policy`` — project bit-identical wire scores
    to the pre-registry code."""

    name = "greedy-binpack"

    def __init__(self, chip_policy: str = "best-fit") -> None:
        self.chip_policy = chip_policy

    def score(self, view: PolicyView) -> ScoreVector:
        base = chip_breakdown(
            view.free_units, view.capacity, view.chip,
            view.request_units, self.chip_policy,
        )
        if (
            base.policy == self.name
            and view.ici_hops is None
            and view.stranded is None
            and view.broken is None
            and view.tie_break is None
        ):
            return base  # the 1k-nodes-per-verb hot path: no copy
        return dataclasses.replace(
            base, policy=self.name,
            ici_hops=view.ici_hops, stranded=view.stranded,
            broken=view.broken,
            tie_break=(view.tie_break if view.tie_break is not None
                       else base.tie_break),
        )


class _LegacyPolicy(GreedyBinpackPolicy):
    """The pre-registry policy names. ``ScoreVector.policy`` keeps the
    legacy name (pinned by the existing verb and provenance tests)."""

    def __init__(self, chip_policy: str) -> None:
        super().__init__(chip_policy)
        self.name = chip_policy


class MultiObjectivePolicy(PlacementPolicy):
    """Weighted composite: packing slack + node balance + gang topology
    objectives, normalized to the same 0-10 raw scale.

    The gang terms convert the lexicographic ``topology.best_slice``
    objective into graded penalties so two nodes whose best slices
    differ only in ICI diameter or stranded slivers rank apart instead
    of tying at the wire scale. Weights are constructor arguments — a
    deployment tunes them, the vector records the outcome."""

    name = "multi-objective"

    def __init__(
        self,
        w_pack: float = 0.55,
        w_balance: float = 0.15,
        w_hops: float = 0.15,
        w_stranded: float = 0.1,
        w_broken: float = 0.05,
    ) -> None:
        self._w = (w_pack, w_balance, w_hops, w_stranded, w_broken)

    def score(self, view: PolicyView) -> ScoreVector:
        if view.capacity <= 0 or view.free_units < view.request_units:
            return self._infeasible(view)
        w_pack, w_balance, w_hops, w_stranded, w_broken = self._w
        slack = view.slack()
        pack = 1.0 - slack
        # balance: how evenly the REST of the node's feasible chips sit —
        # a node whose other chips are near-full is a better consolidation
        # target than one we would newly fragment.
        vec = view.free_vector or (view.free_units,)
        cap = float(view.capacity)
        balance = 1.0 - (sum(vec) / (cap * len(vec)))
        hops = view.ici_hops if view.ici_hops is not None else 0
        stranded = view.stranded if view.stranded is not None else 0
        broken = view.broken if view.broken is not None else 0
        hop_term = 1.0 / (1.0 + hops)
        stranded_term = 1.0 - min(1.0, stranded / cap)
        broken_term = 1.0 / (1.0 + broken)
        raw = 10.0 * (
            w_pack * pack
            + w_balance * balance
            + w_hops * hop_term
            + w_stranded * stranded_term
            + w_broken * broken_term
        )
        return ScoreVector(
            policy=self.name, raw=max(0.0, min(10.0, raw)),
            free_units=view.free_units, request_units=view.request_units,
            binpack=slack, ici_hops=view.ici_hops, stranded=view.stranded,
            broken=view.broken, tie_break=view.tie_break,
        )


class LearnedStubPolicy(PlacementPolicy):
    """Registration-point stub for a trained placement policy.

    Scores with a fixed linear model over the feature vector a real
    RL policy (PAPERS.md 2601.13579) would consume — (pack, balance,
    hop, stranded, broken), the same features ``multi-objective``
    weighs — so swapping in trained weights is a constructor argument,
    not a refactor. Deterministic by construction: same view, same
    score, no randomness."""

    name = "learned"

    # Stand-in "weights" (bias + 5 features). A trained policy replaces
    # these via the ``weights=`` ctor arg or a subclass registered under
    # its own name.
    DEFAULT_WEIGHTS = (0.5, 6.0, 1.0, 1.5, 0.7, 0.3)

    def __init__(self, weights: tuple[float, ...] | None = None) -> None:
        self._weights = tuple(weights or self.DEFAULT_WEIGHTS)
        if len(self._weights) != 6:
            raise ValueError("learned policy expects 6 weights (bias + 5)")

    def features(self, view: PolicyView) -> tuple[float, ...]:
        """The feature contract a trained policy consumes."""
        cap = float(view.capacity or 1)
        vec = view.free_vector or (view.free_units,)
        return (
            1.0 - view.slack(),
            1.0 - (sum(vec) / (cap * len(vec))),
            1.0 / (1.0 + (view.ici_hops or 0)),
            1.0 - min(1.0, (view.stranded or 0) / cap),
            1.0 / (1.0 + (view.broken or 0)),
        )

    def score(self, view: PolicyView) -> ScoreVector:
        if view.capacity <= 0 or view.free_units < view.request_units:
            return self._infeasible(view)
        bias, *ws = self._weights
        raw = bias + sum(w * f for w, f in zip(ws, self.features(view)))
        return ScoreVector(
            policy=self.name, raw=max(0.0, min(10.0, raw)),
            free_units=view.free_units, request_units=view.request_units,
            binpack=view.slack(), ici_hops=view.ici_hops,
            stranded=view.stranded, broken=view.broken,
            tie_break=view.tie_break,
        )


class PrefixAffinityPolicy(PlacementPolicy):
    """Fleet-router scorer: prefer the engine already holding the
    request's prompt prefix in its radix cache, tempered by headroom.

    ``view.affinity_pages`` carries how many prefix pages the candidate
    engine's exported fingerprint set matched; ``free_units``/``capacity``
    carry its admission headroom (free concurrency slots). The affinity
    term saturates (one long cached prefix should not outvote a nearly
    full engine forever) and the headroom term breaks ties among equally
    warm candidates, so the policy degrades to load balancing when no
    candidate holds the prefix — exactly the fall-back the router needs
    when fingerprints are stale or a scrape failed (affinity_pages=None
    scores the same as 0)."""

    name = "prefix-affinity"

    def __init__(self, w_affinity: float = 0.7, w_headroom: float = 0.3,
                 saturation_pages: int = 8) -> None:
        self._w_affinity = w_affinity
        self._w_headroom = w_headroom
        self._sat = max(1, saturation_pages)

    def score(self, view: PolicyView) -> ScoreVector:
        if view.capacity <= 0 or view.free_units < view.request_units:
            return self._infeasible(view)
        pages = view.affinity_pages or 0
        aff = min(1.0, pages / float(self._sat))
        headroom = view.free_units / float(view.capacity)
        raw = 10.0 * (self._w_affinity * aff + self._w_headroom * headroom)
        return ScoreVector(
            policy=self.name, raw=max(0.0, min(10.0, raw)),
            free_units=view.free_units, request_units=view.request_units,
            binpack=headroom, ici_hops=view.ici_hops,
            stranded=view.stranded, broken=view.broken,
            tie_break=(view.tie_break if view.tie_break is not None
                       else pages),
        )


# --- registry ---------------------------------------------------------------

_REGISTRY: dict[str, Callable[[], PlacementPolicy]] = {}


def register_policy(name: str, factory: Callable[[], PlacementPolicy]) -> None:
    """Register ``factory`` under ``name`` (``--placement-policy`` values
    resolve here). Re-registration replaces — tests and downstream
    deployments may override the stubs."""
    _REGISTRY[name] = factory


def policy_names() -> list[str]:
    """Registered policy names (stable order for --help/docs)."""
    return sorted(_REGISTRY)


def get_policy(name: str) -> PlacementPolicy:
    """Resolve a policy name to an instance. Legacy chip-policy names
    (``best-fit``/``first-fit``/``spread``) resolve to the binpack
    scorer with matching selection semantics — bit-identical to the
    pre-registry behavior."""
    factory = _REGISTRY.get(name)
    if factory is None:
        raise KeyError(
            f"unknown placement policy {name!r} (known: {policy_names()})"
        )
    return factory()


def resolve(policy: "str | PlacementPolicy") -> PlacementPolicy:
    """The seam the scoring call sites use: pass-through for an already-
    constructed policy, registry lookup for a name."""
    if isinstance(policy, PlacementPolicy):
        return policy
    return get_policy(policy)


register_policy("greedy-binpack", GreedyBinpackPolicy)
register_policy("multi-objective", MultiObjectivePolicy)
register_policy("learned", LearnedStubPolicy)
register_policy("prefix-affinity", PrefixAffinityPolicy)
for _legacy in ("best-fit", "first-fit", "spread"):
    register_policy(_legacy, lambda n=_legacy: _LegacyPolicy(n))
