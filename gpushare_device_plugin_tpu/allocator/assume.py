"""In-flight reservation ledger for the node's allocators (the assume-cache).

The reference serializes the *entire* Allocate flow — match, placement,
and the apiserver PATCH — behind one mutex (``allocate.go:42-43``), so N
concurrent kubelet admission workers pay N sequential apiserver
round-trips. This ledger is what lets the lock be sharded away: the only
state that truly needs cross-worker atomicity is "which pods are mid-
admission and what did we promise them", and that is pure memory.

Design (mirrors the scheduler extender's bind reservation, which solved
the same problem one layer up):

- **claim**: a pod matched by one worker is claimed by key, so a
  concurrent same-size Allocate matches the *next* oldest candidate
  instead of racing for the same pod. Claims are what keep the documented
  oldest-first same-size match semantics intact under concurrency.
- **reserve**: the chip decision is recorded (mem units on a chip index /
  exclusively-held chip set) *before* the PATCH goes out. Every other
  worker's placement overlays these reservations on top of the pod
  source's usage snapshot, so two in-flight placements cannot double-book
  a chip even though neither is visible in the apiserver yet.
- **transaction**: snapshot-overlay-decide-reserve must be one atomic
  step against other reservations; ``transaction()`` scopes it. The lock
  is an RLock held only for in-memory work — network I/O (PATCH, LIST)
  never runs under it on the warm informer path (the one cold exception:
  a never-synced cache refreshes inside ``chip_state()``) — and the wait
  for it is exported as a histogram so contention regressions are
  observable.
- **release**: after the PATCH persists (and ``note_pod_update`` has fed
  the result back into the pod source), the reservation is redundant —
  the source itself now counts the pod — and is dropped. The overlay
  skips reservations the source already counts (``visible_fn``), so the
  persist→release window cannot double-count either.

Failure semantics: any error path releases the claim and reservations, so
a failed admission never leaks phantom usage.
"""

from __future__ import annotations

import contextlib
import time
from typing import Callable, Iterator, Sequence

from ..utils.metrics import REGISTRY, timed_acquire
from ..utils.lockrank import make_rlock
from ..utils.metric_catalog import (
    ALLOCATOR_LOCK_WAIT_SECONDS as LOCK_WAIT_METRIC,
    ASSUME_EXPIRED_TOTAL as EXPIRED_METRIC,
)

PodKey = tuple[str, str]  # (namespace, name)

LOCK_WAIT_HELP = (
    "Time Allocate workers spend waiting for allocator locks "
    "(match stripes and the reservation ledger); mass above ~1ms means "
    "I/O crept back under a lock"
)

EXPIRED_HELP = (
    "Claims/reservations released by TTL expiry — an owner (a hung PATCH, "
    "a crashed worker) held them past the deadline; capacity was unstranded"
)

# An admission that has not finished inside this window is dead or wedged
# far beyond every retry deadline on the persist path (PATCH retries top
# out in single-digit seconds); releasing then can free capacity the owner
# still thinks it holds only if that owner later persists *without*
# re-checking — and both allocators re-place from a fresh transaction on
# every attempt, so expiry is safe and strictly better than stranding.
DEFAULT_TTL_S = 300.0


class AssumeCache:
    """Shared between the node's mem and core allocators: the two
    resources share one physical-chip ledger, and reservations from one
    must exclude chips from the other (the same reason they used to share
    one mutex).

    Every claim/reservation carries a monotonic stamp and a TTL
    (``ttl_s``): an entry whose owner died mid-admission — crashed worker
    thread, PATCH hung past all deadlines — is released by
    ``expire_stale`` (run lazily on every overlay read and by the drift
    reconciler) instead of stranding capacity forever. Entries are
    re-stamped on re-reservation, so a live retry loop never expires.
    """

    def __init__(
        self,
        ttl_s: float = DEFAULT_TTL_S,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self._lock = make_rlock("allocator.ledger")
        self._ttl = ttl_s
        self._clock = clock
        self._claimed: dict[PodKey, float] = {}  # key -> stamp
        self._mem: dict[PodKey, tuple[int, int]] = {}  # key -> (chip, units)
        self._core: dict[PodKey, tuple[int, ...]] = {}  # key -> chip indices
        # key -> ((chip, units), ...): one multi-chip gang reservation.
        # A gang is ONE ledger entry by construction — reserve, release,
        # and TTL expiry are atomic over every member chip, so no code
        # path can ever observe (or leave behind) a partial gang.
        self._gang: dict[PodKey, tuple[tuple[int, int], ...]] = {}
        self._stamps: dict[PodKey, float] = {}  # reservation stamps
        # Legacy full-serialization lock for list-backed pod sources: they
        # expose no get_pod, so a worker cannot re-verify a candidate
        # against live state at claim time — without that check the
        # sharded flow could re-match a pod whose PATCH landed after the
        # matcher's LIST snapshot. Those sources keep the reference's
        # one-admission-at-a-time semantics; the informer (the default)
        # takes the sharded path. Shared mem/core like everything here.
        self.serial_lock = make_rlock("allocator.serial")

    # --- claims -----------------------------------------------------------

    def claim(self, key: PodKey) -> bool:
        """Mark ``key`` as mid-admission; False if already claimed (by a
        live owner — an expired claim is reaped and re-claimable)."""
        with self._lock:
            now = self._clock()
            stamp = self._claimed.get(key)
            if stamp is not None:
                if now - stamp <= self._ttl:
                    return False
                self._release_expired(key, "claim")
            self._claimed[key] = now
            return True

    def renew(self, key: PodKey) -> bool:
        """Re-stamp a held claim's TTL clock; False when the claim is
        gone (expired and reaped, or never taken). A long-running
        protocol (a defrag move whose drain outlasts the TTL) renews
        before its commit point — an expired claim reaps the key's
        reservations with it, dropping the protocol's capacity
        protection mid-flight."""
        with self._lock:
            if key in self._claimed:
                self._claimed[key] = self._clock()
                return True
            return False

    def is_claimed(self, key: PodKey) -> bool:
        with self._lock:
            stamp = self._claimed.get(key)
            return stamp is not None and self._clock() - stamp <= self._ttl

    def release(self, key: PodKey) -> None:
        """Drop the claim and any reservations for ``key`` (success — the
        pod source counts the pod now — or failure — nothing was placed)."""
        with self._lock:
            self._claimed.pop(key, None)
            self._mem.pop(key, None)
            self._core.pop(key, None)
            self._gang.pop(key, None)
            self._stamps.pop(key, None)

    def release_if_unclaimed(self, key: PodKey) -> bool:
        """Atomic check-and-release for the reconciler: a claimed key is a
        live admission mid-flow and must keep its reservation — releasing
        on a stale pre-network-round-trip claim check would strip a live
        worker's protection. True when released."""
        with self._lock:
            if self.is_claimed(key):
                return False
            self.release(key)
            return True

    def _release_expired(self, key: PodKey, kind: str) -> None:
        """Caller must hold self._lock. A gang entry drops ALL member
        chips here in one pass — expiry can never strand a single-chip
        sliver of a partially-admitted gang."""
        self._claimed.pop(key, None)
        self._mem.pop(key, None)
        self._core.pop(key, None)
        self._gang.pop(key, None)
        self._stamps.pop(key, None)
        REGISTRY.counter_inc(EXPIRED_METRIC, EXPIRED_HELP, kind=kind)

    def expire_stale(self, now: float | None = None) -> list[PodKey]:
        """Release every claim/reservation older than the TTL; -> released
        keys. O(in-flight entries) — a handful at worst."""
        released: list[PodKey] = []
        with self._lock:
            if now is None:
                now = self._clock()
            for key, stamp in list(self._claimed.items()):
                if now - stamp > self._ttl:
                    self._release_expired(key, "claim")
                    released.append(key)
            for key, stamp in list(self._stamps.items()):
                if now - stamp > self._ttl:
                    if key in self._mem:
                        kind = "mem"
                    elif key in self._gang:
                        kind = "gang"
                    else:
                        kind = "core"
                    self._release_expired(key, kind)
                    released.append(key)
        return released

    def snapshot(self) -> tuple[dict[PodKey, float], dict, dict]:
        """Introspection for the drift reconciler: (claims with stamps,
        mem reservations, core reservations) — copies. Gang reservations
        are a separate family; see :meth:`gang_snapshot`."""
        with self._lock:
            return dict(self._claimed), dict(self._mem), dict(self._core)

    def gang_snapshot(self) -> dict[PodKey, tuple[tuple[int, int], ...]]:
        """Copies of the in-flight gang reservations
        (key -> ((chip, units), ...)) for the reconciler/CLI."""
        with self._lock:
            return dict(self._gang)

    # --- reservations (call within transaction()) -------------------------

    @contextlib.contextmanager
    def transaction(self) -> Iterator["AssumeCache"]:
        """Scope one atomic snapshot-overlay-decide-reserve step. In-memory
        work only; the wait is recorded in the lock-wait histogram."""
        with timed_acquire(
            self._lock, LOCK_WAIT_METRIC, LOCK_WAIT_HELP, lock="ledger"
        ):
            yield self

    def reserve_mem(self, key: PodKey, chip_idx: int, units: int) -> None:
        with self._lock:
            self._mem[key] = (chip_idx, units)
            self._stamps[key] = self._clock()

    def reserve_core(self, key: PodKey, chip_indices: list[int]) -> None:
        with self._lock:
            self._core[key] = tuple(chip_indices)
            self._stamps[key] = self._clock()

    def reserve_gang(
        self, key: PodKey, members: Sequence[tuple[int, int]]
    ) -> None:
        """Reserve ``members`` ((chip, units) per gang member) as ONE
        atomic entry: a concurrent placement overlaying the ledger sees
        either every member chip claimed or none — the all-or-nothing
        half of the gang protocol that the PATCH (one write of all member
        annotations) completes on the persist side."""
        if not members:
            raise ValueError("gang reservation needs at least one member")
        with self._lock:
            self._gang[key] = tuple((int(c), int(u)) for c, u in members)
            self._stamps[key] = self._clock()

    def overlaid_state(
        self,
        state_fn: Callable[[], tuple[dict[int, int], set[int]]],
        visible_fn: Callable[[PodKey], bool] | None = None,
    ) -> tuple[dict[int, int], set[int]]:
        """One usage snapshot with in-flight reservations folded in:
        ``state_fn() -> (mem_used, core_held)`` caller-owned copies.

        ``visible_fn(key) -> bool`` reports whether the pod source already
        counts the pod (its PATCHed copy landed in the cache) — those
        reservations are skipped to avoid double-counting in the window
        between ``note_pod_update`` and ``release``. Ordering is the
        correctness core: visibility is decided BEFORE ``state_fn`` reads
        the snapshot. Visibility only ever flips invisible -> visible (a
        deleted pod stops being visible, but then holds nothing), so a
        reservation judged visible is provably in any snapshot read
        afterwards — every in-flight pod is counted at least once, never
        zero times. The reverse order would let a pod land in the cache
        between an older snapshot and the visibility check and be counted
        nowhere. Without ``visible_fn`` every reservation counts, which is
        conservative (can only over-count, never double-book).
        """
        with self._lock:
            self.expire_stale()  # lazy TTL reaping on every overlay read
            mem = list(self._mem.items())
            core = list(self._core.items())
            gang = list(self._gang.items())
        if visible_fn is not None:
            mem = [(k, v) for k, v in mem if not visible_fn(k)]
            core = [(k, v) for k, v in core if not visible_fn(k)]
            gang = [(k, v) for k, v in gang if not visible_fn(k)]
        mem_used, core_held = state_fn()
        for _key, (idx, units) in mem:
            mem_used[idx] = mem_used.get(idx, 0) + units
        for _key, members in gang:
            for idx, units in members:
                mem_used[idx] = mem_used.get(idx, 0) + units
        for _key, indices in core:
            core_held.update(indices)
        return mem_used, core_held
