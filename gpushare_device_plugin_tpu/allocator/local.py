"""Standalone allocator: binpack with in-process accounting, no Kubernetes.

Used by unit tests, the bench harness, and single-node standalone mode
(``--standalone``). The production path (``ClusterAllocator``) instead
derives usage from the apiserver every call — restart-safe because the
cluster is the database; this one trades that for zero dependencies.

Frees are driven by ``release(pod_key)`` (bench/tests call it on pod end).
"""

from __future__ import annotations

from typing import Sequence

from ..device.fanout import DeviceInventory
from .binpack import assign_chip
from .env import ContainerAllocation, build_mem_allocation
from ..utils.lockrank import make_lock


class LocalAllocator:
    def __init__(
        self,
        inventory: DeviceInventory,
        policy: str = "first-fit",
        disable_isolation: bool = False,
    ) -> None:
        self._inv = inventory
        self._policy = policy
        self._disable_isolation = disable_isolation
        self._lock = make_lock("allocator.local")
        self._used: dict[int, int] = {}  # chip index -> units
        self._by_pod: dict[str, tuple[int, int]] = {}  # pod key -> (chip, units)
        self._unhealthy: set[int] = set()
        self._core_held: set[int] = set()  # whole-chip (tpu-core) holds

    def set_chip_health(self, chip_index: int, healthy: bool) -> None:
        with self._lock:
            if healthy:
                self._unhealthy.discard(chip_index)
            else:
                self._unhealthy.add(chip_index)

    def hold_chips(self, chip_indices: Sequence[int]) -> None:
        """Exclusively hold whole chips for a tpu-core pod; fails if any
        chip has fractional usage, an existing hold, or is unhealthy."""
        with self._lock:
            for idx in chip_indices:
                if idx in self._core_held:
                    raise RuntimeError(f"chip {idx} already exclusively held")
                if self._used.get(idx, 0) > 0:
                    raise RuntimeError(
                        f"chip {idx} has {self._used[idx]} fractional units in use"
                    )
                if idx in self._unhealthy:
                    raise RuntimeError(f"chip {idx} is unhealthy")
            self._core_held.update(chip_indices)

    def release_chips(self, chip_indices: Sequence[int]) -> None:
        with self._lock:
            self._core_held.difference_update(chip_indices)

    def core_held(self) -> set[int]:
        with self._lock:
            return set(self._core_held)

    def allocate(
        self, container_counts: Sequence[int], pod_key: str | None = None
    ) -> list[ContainerAllocation]:
        """Place one pod: ``container_counts`` = granted fake-IDs per container.

        Mirrors the Allocate contract: the request total is the pod's demand;
        which fake IDs kubelet picked is irrelevant (``allocate.go:37-39``).
        """
        pod_units = sum(container_counts)
        with self._lock:
            idx = assign_chip(
                pod_units,
                self._inv.units_by_index(),
                self._used,
                unhealthy=sorted(self._unhealthy | self._core_held),
                policy=self._policy,
            )
            self._used[idx] = self._used.get(idx, 0) + pod_units
            if pod_key is not None:
                self._by_pod[pod_key] = (idx, pod_units)
        chip = self._inv.chip_by_id(self._inv.id_of_index(idx))
        total = self._inv.units_of(chip.id)
        return [
            build_mem_allocation(
                chip=chip,
                chip_total_units=total,
                pod_units=pod_units,
                container_units=n,
                disable_isolation=self._disable_isolation,
            )
            for n in container_counts
        ]

    def release(self, pod_key: str) -> None:
        with self._lock:
            entry = self._by_pod.pop(pod_key, None)
            if entry is None:
                return
            idx, units = entry
            self._used[idx] = max(0, self._used.get(idx, 0) - units)

    def used_by_chip(self) -> dict[int, int]:
        with self._lock:
            return dict(self._used)

    def utilization(self) -> float:
        """Fraction of advertised HBM units currently allocated."""
        total = self._inv.total_units()
        if total == 0:
            return 0.0
        with self._lock:
            return sum(self._used.values()) / total
