"""Live slice defragmentation: stranded-HBM planner + crash-safe move protocol.

Long-running clusters fragment (ROADMAP open item 5): after churn, chips
hold free-HBM *slivers* no pending pod fits, and the allocator can only
refuse admission even though total free HBM is ample. This module turns
the WAL + reconciler + fencing substrate (PRs 3-4) into a defragmenter:

- **Stranded accounting** (:func:`stranded_units` / :func:`stranded_pct`):
  free units on a partially-used chip that cannot host a ``quantum``-sized
  request are stranded — the ParvaGPU-style repacking objective
  (PAPERS.md 2409.14447) restricted to one node's chips. A wholly-free
  chip is never stranded (it hosts anything up to its capacity).
- **Planner** (:func:`plan_moves` / :class:`DefragPlanner`): greedy
  repacking over the node's single-chip fractional pods, scored like
  ``topology.best_slice`` — lexicographically minimize (total stranded
  units after the move, whole chips broken open, destination index) and
  accept only strictly-improving moves, so the plan terminates and the
  bench's before/after comparison can never regress. Gangs stay whole
  (multi-chip pods are never planned; moving one is a gang re-grant, not
  a repack) and core-held/unhealthy chips are excluded.
- **Mover** (:class:`SliceMover`): one move = a journaled state machine
  ``plan -> drain -> copy -> switch -> resume`` riding the allocation WAL
  as record kind ``"move"``. Each phase record is fsync'd durable
  *before* that phase's side effect (the same begin-before-PATCH
  discipline admissions follow), the destination units are reserved
  through the shared :class:`~.assume.AssumeCache` ledger for the whole
  move (so source and destination can never be double-booked mid-move,
  and concurrent admissions route around the in-flight move via the
  ordinary reservation overlay), and the ``switch`` record is the commit
  point: a daemon SIGKILLed at any instruction leaves an entry the
  restarted incarnation replays (destination protected) and the drift
  reconciler resolves — **roll forward** past ``switch`` (re-issue the
  PATCH if it never landed, restore the drained engine snapshot on the
  destination), **roll back** before it (release the reservation, abort;
  the workload never stopped). Fencing rides the WAL: a stale daemon's
  next phase journal raises :class:`~.checkpoint.StaleDaemonError`, so
  it can never finish a move the newer incarnation now owns.

Engine hand-off: ``drain_fn(pod_key) -> snapshot`` quiesces the pod's
serving engine and checkpoints its in-flight requests
(``serving.engine.SlotEngine.drain_snapshot``); the snapshot is journaled
with the ``copy`` record so a crash after the drain can still deliver it
to the destination (``restore_fn(pod_key, snapshot)``) during roll
forward — zero lost requests, greedy tokens bit-identical to an unmoved
run (``tests/test_defrag.py``, ``make chaos-move``).
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
from typing import Any, Callable, Mapping

from .. import const
from ..cluster import pods as P
from ..utils.decisions import DECISIONS
from ..utils.faults import FAULTS
from ..utils.lockrank import make_lock
from ..utils.log import get_logger
from ..utils.metrics import REGISTRY
from ..utils.tracing import TRACER
from .assume import AssumeCache, PodKey
from .checkpoint import AllocationCheckpoint, StaleDaemonError
from ..utils.metric_catalog import (
    DEFRAG_MOVES_TOTAL as MOVES_METRIC,
    DEFRAG_MOVE_SECONDS as MOVE_SECONDS,
    DEFRAG_STRANDED_PCT as STRANDED_PCT_GAUGE,
    DEFRAG_STRANDED_UNITS as STRANDED_GAUGE,
)

log = get_logger("allocator.defrag")

# The journaled move state machine, in order. Each phase's WAL record is
# durable BEFORE its side effect; "switch" is the roll-forward boundary.
MOVE_PHASES = ("plan", "drain", "copy", "switch", "resume")
MOVE_KIND = "move"

# Synthetic namespace for move journal/ledger keys: a move protects the
# DESTINATION chip under a key no real pod owns, so the reservation
# overlay counts it unconditionally (the moving pod's own annotation
# keeps counting the source until the switch PATCH lands).
DEFRAG_NS = "tpushare-defrag"

MOVES_HELP = "Defragmentation moves by outcome (completed/aborted/failed)"
MOVE_SECONDS_HELP = "Wall time of one completed slice move, all phases"
STRANDED_GAUGE_HELP = (
    "HBM units stranded on partially-used chips (free slivers smaller "
    "than the defrag quantum) at the last planner scan"
)
STRANDED_PCT_GAUGE_HELP = "Stranded HBM as a percentage of node capacity"


class MoveError(RuntimeError):
    """A move could not proceed (planning raced reality, PATCH refused)."""


def move_key(pod: PodKey) -> PodKey:
    """The journal/ledger key for one pod's move: a synthetic namespace
    so the reservation is never mistaken for (or hidden by) the real
    pod's own accounting."""
    return (DEFRAG_NS, f"{pod[0]}.{pod[1]}")


def pod_of_move(data: Mapping[str, Any]) -> PodKey | None:
    """The real pod a journaled move record concerns, or None when the
    record is garbled."""
    ref = data.get("pod") or []
    if isinstance(ref, (list, tuple)) and len(ref) == 2:
        return (str(ref[0]), str(ref[1]))
    return None


# ---------------------------------------------------------------------------
# stranded accounting
# ---------------------------------------------------------------------------


def stranded_units(
    capacity: Mapping[int, int],
    used: Mapping[int, int],
    quantum: int,
) -> dict[int, int]:
    """Free units per chip that are stranded: the chip is partially used
    and its free sliver is smaller than ``quantum`` (the request size the
    node should stay able to admit). Wholly-free chips are never
    stranded; full chips have nothing free."""
    if quantum < 1:
        return {}
    out: dict[int, int] = {}
    for idx, cap in capacity.items():
        u = used.get(idx, 0)
        free = cap - u
        if u > 0 and 0 < free < quantum:
            out[idx] = free
    return out


def stranded_pct(
    capacity: Mapping[int, int],
    used: Mapping[int, int],
    quantum: int,
) -> float:
    """Stranded HBM as a percentage of total node capacity."""
    total = sum(capacity.values())
    if total <= 0:
        return 0.0
    return 100.0 * sum(stranded_units(capacity, used, quantum).values()) / total


def movable_placements(pods: list[dict]) -> dict[PodKey, tuple[int, int]]:
    """``{pod key: (chip index, units)}`` for every pod a repack may move:
    assigned, active, fractional tpu-mem, single-chip. Gangs are skipped
    whole (moving one is a topology re-grant, not a repack) and core
    holds are exclusive by definition."""
    out: dict[PodKey, tuple[int, int]] = {}
    for pod in pods:
        if not P.is_active(pod) or not P.is_assigned(pod):
            continue
        if P.labels(pod).get(const.LABEL_RESOURCE_KEY) != const.LABEL_RESOURCE_VALUE:
            continue
        if P.gang_usage_by_chip(pod):
            continue  # keep gangs whole
        idx = P.chip_idx_from_annotation(pod)
        units = P.mem_units_of_pod(pod)
        if idx < 0 or units <= 0:
            continue
        out[(P.namespace(pod), P.name(pod))] = (idx, units)
    return out


# ---------------------------------------------------------------------------
# planner
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class MovePlan:
    """One planned repacking move: relocate ``pod``'s ``units`` from chip
    ``src`` to chip ``dst``."""

    pod: PodKey
    src: int
    dst: int
    units: int


@dataclasses.dataclass(frozen=True)
class DefragReport:
    """One planner scan: the stranded picture and the moves that improve it."""

    quantum: int
    stranded_by_chip: dict[int, int]
    stranded_pct: float
    moves: tuple[MovePlan, ...]


def plan_moves(
    capacity: Mapping[int, int],
    placements: Mapping[PodKey, tuple[int, int]],
    quantum: int,
    *,
    excluded: Mapping[int, Any] | set[int] | tuple[int, ...] = (),
    max_moves: int = 8,  # matches ManagerConfig.defrag_max_moves
    used: Mapping[int, int] | None = None,
) -> list[MovePlan]:
    """Greedy strictly-improving repack plan over single-chip placements.

    Each step considers every (pod, destination chip) pair and picks the
    move whose simulated result lexicographically minimizes — the same
    objective order ``topology.best_slice`` uses for gang placement —

    1. total stranded units after the move (the repack objective);
    2. whole chips broken open (a move into an untouched chip fragments
       the node it is meant to heal);
    3. destination index, then pod key (determinism).

    Only strictly-improving moves are accepted, so the plan terminates
    and applying it can never make the stranded picture worse. Chips in
    ``excluded`` (core-held, unhealthy, mid-move) are neither drained
    nor filled. ``used`` is the AUTHORITATIVE per-chip usage the
    simulation starts from — it must include pods the repack may not
    move (gang members, anything non-fractional), or the planner sees
    their chips as free and plans moves the execute-time capacity check
    can only abort; it defaults to the placements' own sum for callers
    with no other usage.
    """
    banned = set(excluded)
    if used is None:
        base: dict[int, int] = {}
        for _key, (idx, units) in placements.items():
            base[idx] = base.get(idx, 0) + units
        used = base
    else:
        used = {idx: int(n) for idx, n in used.items() if n}
    work = dict(placements)
    moves: list[MovePlan] = []
    while len(moves) < max_moves:
        current = sum(stranded_units(capacity, used, quantum).values())
        if current == 0:
            break
        best_score: tuple | None = None
        best: tuple[MovePlan, dict[int, int]] | None = None
        for key, (src, units) in sorted(work.items()):
            if src in banned:
                continue
            for dst in sorted(capacity):
                if dst == src or dst in banned:
                    continue
                if capacity[dst] - used.get(dst, 0) < units:
                    continue
                trial = dict(used)
                trial[src] = trial.get(src, 0) - units
                if trial[src] <= 0:
                    trial.pop(src, None)
                trial[dst] = trial.get(dst, 0) + units
                after = sum(stranded_units(capacity, trial, quantum).values())
                broken = 1 if used.get(dst, 0) == 0 else 0
                score = (after, broken, dst, key)
                if best_score is None or score < best_score:
                    best_score = score
                    best = (MovePlan(pod=key, src=src, dst=dst, units=units), trial)
        if best is None or best_score is None or best_score[0] >= current:
            break  # nothing strictly improves: done
        plan, used = best
        moves.append(plan)
        work[plan.pod] = (plan.dst, plan.units)
    return moves


class DefragPlanner:
    """Scans a node's usage (``NodeChipUsage`` snapshot semantics: the
    pod source's chip state) for stranded HBM and plans repacking moves.

    ``quantum=0`` auto-derives the sliver threshold from the workload:
    the largest single-chip fractional request currently on the node —
    a sliver is free HBM that cannot host the biggest pod class the
    node actually serves.
    """

    def __init__(
        self,
        units_by_index: Callable[[], dict[int, int]],
        pod_source: Any,
        *,
        quantum: int = 0,
        excluded_fn: Callable[[], set[int]] | None = None,
        max_moves: int = 8,  # matches ManagerConfig.defrag_max_moves
        node: str = "",  # decision-record attribution only
    ) -> None:
        self._units_by_index = units_by_index
        self._pods = pod_source
        self._quantum = quantum
        self._excluded_fn = excluded_fn or (lambda: set())
        self._max_moves = max_moves
        self._node = node
        # guards the cached last-scan report (read by the CLI/status
        # publisher while the loop thread scans)
        self._lock = make_lock("defrag.planner")
        self._last: DefragReport | None = None

    def _auto_quantum(self, pods: list[dict]) -> int:
        sizes = [
            P.mem_units_of_pod(p)
            for p in pods
            if P.is_active(p) and P.mem_units_of_pod(p) > 0
            and not P.gang_usage_by_chip(p)
        ]
        return max(sizes) if sizes else 0

    def scan(self) -> DefragReport:
        """One planning pass; publishes the stranded gauges and caches
        the report (:meth:`last_report`)."""
        capacity = self._units_by_index()
        pods_readable = True
        try:
            pods = list(self._pods.labeled_pods())
        except Exception as e:  # noqa: BLE001 — outage: plan nothing
            log.v(4, "defrag scan: pod read failed (%s)", e)
            pods = []
            pods_readable = False
        quantum = self._quantum or self._auto_quantum(pods)
        placements = movable_placements(pods)
        # authoritative per-chip usage — includes what the repack may NOT
        # move (gang members, non-fractional pods): without it the
        # planner sees gang-hosting chips as free, under-reports their
        # stranded slivers, and plans moves the execute-time capacity
        # check can only abort, forever. Core holds + unhealthy chips
        # never participate at all.
        try:
            mem_used, core_held = self._pods.chip_state()
            used = {idx: int(n) for idx, n in mem_used.items()}
        except Exception:  # noqa: BLE001 — outage: fall back to the
            # movable placements' own sum (plan conservatively rather
            # than not at all; placements came from the same read)
            core_held = set()
            used = {}
            for _key, (idx, units) in placements.items():
                used[idx] = used.get(idx, 0) + units
        excluded = set(core_held) | self._excluded_fn()
        by_chip = stranded_units(capacity, used, quantum)
        pct = stranded_pct(capacity, used, quantum)
        moves = plan_moves(
            capacity, placements, quantum,
            excluded=excluded, max_moves=self._max_moves, used=used,
        )
        report = DefragReport(
            quantum=quantum,
            stranded_by_chip=by_chip,
            stranded_pct=pct,
            moves=tuple(moves),
        )
        if pods_readable:
            # an outage pass computed stranded=0 from an EMPTY pod list —
            # publishing that would paint a fragmented node as healed for
            # the outage's duration; keep the last honest value instead
            # (the documented signal is "the gauge stops updating")
            REGISTRY.gauge_set(
                STRANDED_GAUGE, float(sum(by_chip.values())), STRANDED_GAUGE_HELP
            )
            REGISTRY.gauge_set(STRANDED_PCT_GAUGE, pct, STRANDED_PCT_GAUGE_HELP)
        with self._lock:
            self._last = report
        # Decision provenance: one record per planning pass — what the
        # planner saw (stranded picture) and what it decided to move,
        # queryable by any affected pod (``inspect why`` matches records
        # whose moves touch the pod). Values all computed above.
        DECISIONS.emit(
            "", "defrag_plan",
            outcome="ok" if pods_readable else "error",
            node=self._node,
            reason="" if pods_readable else "pod source unreadable; planned nothing",
            candidates=len(capacity),
            placement={
                "quantum": quantum,
                "stranded_units": sum(by_chip.values()),
                "stranded_pct": round(pct, 2),
                "planned_moves": [
                    {
                        "pod": f"{m.pod[0]}/{m.pod[1]}",
                        "src": m.src, "dst": m.dst, "units": m.units,
                    }
                    for m in moves
                ],
            },
            moves=[f"{m.pod[0]}/{m.pod[1]}" for m in moves],
        )
        return report

    def last_report(self) -> DefragReport | None:
        with self._lock:
            return self._last


# ---------------------------------------------------------------------------
# the journaled mover
# ---------------------------------------------------------------------------


def _journal_phase(
    ckpt: AllocationCheckpoint | None, key: PodKey, data: dict
) -> int | None:
    """Journal one move phase durable (a fresh ``begin`` for the move key
    — the loader keeps the newest record per key, so the entry always
    names the furthest phase reached). ``StaleDaemonError`` propagates:
    a fenced daemon must not advance a move the newer incarnation owns.
    ``None`` = journal degraded (sick disk): the move continues
    unjournaled, exactly like admissions do. (tpulint's wal-protocol
    rule knows this helper as a ``begin`` form, like ``_journal_begin``
    on the admission path — every call site must be dominated by
    :func:`_journal_resolve` on its handled paths.)"""
    if ckpt is None:
        return None
    return ckpt.begin(key, data)


def _journal_resolve(
    ckpt: AllocationCheckpoint | None,
    op: str,
    key: PodKey,
    seq: int | None,
) -> bool:
    """Resolve the move's journal entry (``op`` = ``"commit"`` roll the
    move in, ``"abort"`` roll it back); the thin delegation form the
    wal-protocol rule recognizes. False = degraded/unjournaled or a
    newer begin owns the key."""
    if ckpt is None:
        return False
    if op == "commit":
        return ckpt.commit(key, seq=seq)
    return ckpt.abort(key, seq=seq)


@dataclasses.dataclass
class MoveStats:
    """Cumulative move counters for one mover (CLI/status surface)."""

    planned: int = 0
    active: int = 0
    completed: int = 0
    failed: int = 0
    last_move_ms: float = 0.0


class SliceMover:
    """Executes one :class:`MovePlan` through the journaled move protocol.

    ``drain_fn(pod_key) -> dict | None`` quiesces the pod's engine and
    returns its JSON-safe in-flight snapshot (journaled with the ``copy``
    record); ``restore_fn(pod_key, snapshot)`` re-admits it on the
    destination. Both default to None for workloads that checkpoint
    themselves (the move is then just the annotation flip plus the
    double-booking protection).
    """

    def __init__(
        self,
        api: Any,
        pod_source: Any,
        assume: AssumeCache,
        checkpoint: AllocationCheckpoint | None,
        node_name: str,
        units_by_index: Callable[[], dict[int, int]],
        *,
        drain_fn: Callable[[PodKey], dict | None] | None = None,
        restore_fn: Callable[[PodKey, dict | None], None] | None = None,
        patch_fn: Callable[[str, str, dict], dict] | None = None,
    ) -> None:
        self._api = api
        self._pods = pod_source
        self._assume = assume
        self._ckpt = checkpoint
        self._node = node_name
        self._units_by_index = units_by_index
        self._drain_fn = drain_fn
        self._restore_fn = restore_fn
        self._patch_fn = patch_fn
        # guards the move counters only — never held across journal
        # fsyncs or the switch PATCH (io_ok=False by declaration)
        self._stats_lock = make_lock("defrag.moves")
        self._stats = MoveStats()

    # --- introspection ----------------------------------------------------

    def stats(self) -> MoveStats:
        with self._stats_lock:
            return dataclasses.replace(self._stats)

    def note_failed(self) -> None:
        """Count a move that died with a propagating exception — the
        loop's accounting hook. Clean aborts and fenced moves count
        themselves inside :meth:`execute`."""
        self._note(failed=1)

    def _note(self, **delta: float) -> None:
        with self._stats_lock:
            for name, value in delta.items():
                if name == "last_move_ms":
                    self._stats.last_move_ms = float(value)
                else:
                    setattr(
                        self._stats, name,
                        getattr(self._stats, name) + int(value),
                    )

    # --- the protocol -----------------------------------------------------

    def _dst_fits(self, plan: MovePlan) -> bool:
        """Execute-time re-validation of the destination: with every
        in-flight reservation (this move's included) overlaid on the pod
        source's usage, the destination chip must not exceed capacity. A
        plan is computed against a scan snapshot — a concurrent admission
        can land on the destination between scan and reserve, and an
        earlier move in the same pass may have aborted without freeing
        the capacity the simulation assumed. Because this move's own
        reservation is already in the ledger, and admissions decide+
        reserve atomically under the same ledger lock, any conflicting
        booking is visible to exactly one of the two sides — so failing
        this check aborts the move instead of over-booking. Conservative
        on purpose: no visibility filter, so a reservation whose PATCH
        already landed may double-count — that can only abort a move
        spuriously (the planner re-plans next pass), never double-book."""
        capacity = self._units_by_index().get(plan.dst, 0)
        with self._assume.transaction():
            mem_used, core_held = self._assume.overlaid_state(self._pods.chip_state)
        if plan.dst in core_held:
            # a tpu-core pod took an exclusive hold on the destination
            # since the scan: an exclusively held chip has mem_used 0,
            # so the capacity check alone would happily flip a
            # fractional pod onto it — the same skip the mem admission
            # path applies to core-held chips
            return False
        return mem_used.get(plan.dst, 0) <= capacity

    def _live_pod(self, plan: MovePlan) -> dict | None:
        """The pod as the apiserver sees it now, still matching the plan
        (on ``src`` with ``units``); None when planning raced reality."""
        from ..cluster.apiserver import ApiError

        try:
            pod = self._api.get_pod(*plan.pod)
        except ApiError as e:
            if e.status == 404:
                return None
            raise
        if pod is None or not P.is_active(pod) or not P.is_assigned(pod):
            return None
        if P.gang_usage_by_chip(pod):
            return None
        if P.chip_idx_from_annotation(pod) != plan.src:
            return None
        if P.mem_units_of_pod(pod) != plan.units:
            return None
        return pod

    def _switch_annotations(self, plan: MovePlan, pod: dict) -> dict[str, str]:
        total = self._units_by_index().get(plan.dst, 0)
        ann = {
            const.ENV_MEM_IDX: str(plan.dst),
            const.ENV_MEM_POD: str(plan.units),
            const.ENV_MEM_DEV: str(total),
            const.ENV_ASSIGNED_FLAG: "true",
            const.ENV_ASSUME_TIME: str(time.time_ns()),
        }
        # An extender-bound pod also carries the per-container allocation
        # map, and the inspect CLI PREFERS it for per-chip attribution —
        # left untouched it would pin the pod to src forever, and the
        # post-move stranded gauges built from it would report the node
        # as still fragmented after a successful repack. Movable
        # placements are single-chip, so every container's units land on
        # dst.
        raw = P.annotations(pod).get(const.ANN_EXTENDER_ALLOCATION)
        if raw:
            try:
                per_container = json.loads(raw)
                moved = {
                    name: {str(plan.dst): sum(int(u) for u in chips.values())}
                    for name, chips in per_container.items()
                }
                ann[const.ANN_EXTENDER_ALLOCATION] = json.dumps(moved)
            except (ValueError, AttributeError, TypeError):
                pass  # garbled map: the CLI already falls back to MEM_IDX
        return ann

    def _patch_switch(self, plan: MovePlan, annotations: dict[str, str]) -> None:
        """The authoritative flip: one strategic-merge PATCH moves the
        pod's accounting from src to dst. 404 = pod deleted mid-move
        (raised as MoveError for the abort path); other transport
        failures propagate — the entry stays pending and the reconciler
        rolls the move forward once the apiserver answers."""
        from ..cluster.apiserver import ApiError

        patch_fn = self._patch_fn or self._api.patch_pod
        try:
            updated = patch_fn(
                plan.pod[0], plan.pod[1], {"metadata": {"annotations": annotations}}
            )
        except ApiError as e:
            if e.status == 404:
                raise MoveError(f"pod {plan.pod} deleted mid-move") from e
            raise
        note = getattr(self._pods, "note_pod_update", None)
        if note is not None:
            note(updated)

    def execute(self, plan: MovePlan) -> bool:
        """Run one move end to end. True = the pod now lives on ``dst``;
        False = the move was aborted cleanly (planning raced reality,
        pod deleted). Exceptions leave the journal entry pending for the
        reconciler — deliberately, that IS the crash-safety story — and
        ``StaleDaemonError`` additionally means a newer daemon owns the
        node (this instance must stop moving)."""
        self._note(planned=1, active=1)
        try:
            return self._execute(plan)
        finally:
            self._note(active=-1)

    def _execute(self, plan: MovePlan) -> bool:
        t0 = time.perf_counter()
        pod = self._live_pod(plan)
        if pod is None:
            log.v(4, "defrag: plan for %s/%s raced reality; skipped", *plan.pod)
            self._note(failed=1)
            REGISTRY.counter_inc(MOVES_METRIC, MOVES_HELP, outcome="aborted")
            return False
        key = move_key(plan.pod)
        # Claim the move key for the whole protocol, exactly like an
        # admission claims its pod key: the reconciler skips claimed
        # entries, so a concurrent reconcile pass can never resolve (and
        # release the destination reservation of) a move this thread is
        # still executing. An abandoned move (a propagating transport
        # error below) keeps claim + reservation until the ledger TTL,
        # then the reconciler resolves the pending entry — identical to
        # a hung admission's backstop.
        if not self._assume.claim(key):
            log.v(4, "defrag: move for %s/%s already in flight; skipped", *plan.pod)
            self._note(failed=1)
            REGISTRY.counter_inc(MOVES_METRIC, MOVES_HELP, outcome="aborted")
            return False
        annotations = self._switch_annotations(plan, pod)
        base = {
            "kind": MOVE_KIND,
            "pod": list(plan.pod),
            "src": plan.src,
            "dst": plan.dst,
            "units": plan.units,
            "node": self._node,
            "annotations": annotations,
        }
        with TRACER.span(
            "defrag.move",
            attributes={
                "pod": f"{plan.pod[0]}/{plan.pod[1]}",
                "src": plan.src, "dst": plan.dst, "units": plan.units,
            },
        ):
            # plan: the decision is durable, then the destination is
            # reserved — from here no concurrent admission can book dst
            # past capacity even though the PATCH is minutes away.
            seq = _journal_phase(self._ckpt, key, {**base, "phase": "plan"})
            FAULTS.fire("defrag.plan")
            self._assume.reserve_mem(key, plan.dst, plan.units)
            try:
                if not self._dst_fits(plan):
                    # the destination filled up since the scan: abort
                    # cleanly before anything drains or flips
                    _journal_resolve(self._ckpt, "abort", key, seq)
                    self._assume.release(key)
                    log.v(
                        4, "defrag: destination chip %d filled since "
                        "planning; move for %s/%s aborted",
                        plan.dst, *plan.pod,
                    )
                    self._note(failed=1)
                    REGISTRY.counter_inc(MOVES_METRIC, MOVES_HELP, outcome="aborted")
                    return False
                # drain: quiesce the engine, checkpoint its in-flight
                # requests (prompt + generated tokens + tier/SLO; radix
                # prefixes re-resolve on restore).
                seq = _journal_phase(self._ckpt, key, {**base, "phase": "drain"})
                FAULTS.fire("defrag.drain")
                snapshot: dict | None = None
                if self._drain_fn is not None:
                    with TRACER.span("move.drain", child_only=True):
                        snapshot = self._drain_fn(plan.pod)
                if snapshot is not None:
                    # Stamped identity, unique to this move attempt (the
                    # drain-phase WAL seq): the destination engine dedups
                    # restore deliveries on it, so the at-least-once
                    # re-delivery across the resume/commit crash window
                    # can never serve the drained requests twice. With
                    # the journal degraded (seq None) there is no record
                    # to re-deliver FROM, so no stamp — a constant
                    # `#None` id would wrongly dedup a later legitimate
                    # move of the same pod.
                    if seq is not None:
                        snapshot = {
                            **snapshot,
                            "snapshot_id": f"{self._node}/{key[1]}#{seq}",
                        }
                    base = {**base, "snapshot": snapshot}
                # copy: the snapshot travels inside the journal record —
                # durable before anything depends on it, so a crash from
                # here on can still deliver it to the destination.
                seq = _journal_phase(self._ckpt, key, {**base, "phase": "copy"})
                FAULTS.fire("defrag.copy")
                # Last clean-abort gate before the commit point: a drain
                # can outlast the ledger TTL (300 s), expiring the
                # destination reservation — a concurrent admission could
                # then book dst to capacity unseen. RENEW the claim
                # (re-stamp its TTL clock — is_claimed alone would leave
                # a near-TTL stamp to expire in the switch window, and
                # an EXPIRED claim reaps the whole key, fresh
                # reservation included, on the next overlay read), then
                # re-stamp the reservation and re-verify; after the
                # switch record is durable a crash rolls FORWARD, so
                # this must happen before it.
                if not (self._assume.renew(key) or self._assume.claim(key)):
                    # defensive: the reaped key was re-claimed by someone
                    # else in the gap — this incarnation's move is over
                    _journal_resolve(self._ckpt, "abort", key, seq)
                    self._assume.release(key)
                    log.warning(
                        "defrag: move claim for %s/%s lost mid-drain; "
                        "move aborted", *plan.pod,
                    )
                    self._note(failed=1)
                    REGISTRY.counter_inc(MOVES_METRIC, MOVES_HELP, outcome="aborted")
                    return False
                self._assume.reserve_mem(key, plan.dst, plan.units)
                if not self._dst_fits(plan):
                    _journal_resolve(self._ckpt, "abort", key, seq)
                    self._assume.release(key)
                    log.v(
                        4, "defrag: destination chip %d filled while the "
                        "drain ran; move for %s/%s aborted",
                        plan.dst, *plan.pod,
                    )
                    self._note(failed=1)
                    REGISTRY.counter_inc(MOVES_METRIC, MOVES_HELP, outcome="aborted")
                    return False
                # switch: the commit point. The record is durable before
                # the PATCH is on the wire (begin-before-PATCH, as ever);
                # a crash between the two rolls FORWARD — the reconciler
                # re-issues the PATCH from the journaled annotations.
                seq = _journal_phase(self._ckpt, key, {**base, "phase": "switch"})
                FAULTS.fire("defrag.switch")
                try:
                    with TRACER.span("move.switch", child_only=True):
                        self._patch_switch(plan, annotations)
                except MoveError:
                    # pod deleted mid-move: nothing persisted, nothing to
                    # finish — roll the whole move back cleanly.
                    _journal_resolve(self._ckpt, "abort", key, seq)
                    self._assume.release(key)
                    self._note(failed=1)
                    REGISTRY.counter_inc(MOVES_METRIC, MOVES_HELP, outcome="aborted")
                    return False
                seq = _journal_phase(self._ckpt, key, {**base, "phase": "resume"})
                FAULTS.fire("defrag.resume")
                if self._restore_fn is not None:
                    with TRACER.span("move.resume", child_only=True):
                        self._restore_fn(plan.pod, snapshot)
                _journal_resolve(self._ckpt, "commit", key, seq)
                self._assume.release(key)
            except StaleDaemonError:
                # A newer daemon fenced us mid-move: the journal entry
                # stays for the owner's reconciler; only our in-memory
                # reservation is dropped (the entry's replay re-creates
                # it in the owning process).
                self._assume.release(key)
                log.error(
                    "defrag: fenced mid-move for %s/%s; move left for the "
                    "owning daemon", *plan.pod,
                )
                self._note(failed=1)
                REGISTRY.counter_inc(MOVES_METRIC, MOVES_HELP, outcome="failed")
                raise
        wall_ms = (time.perf_counter() - t0) * 1e3
        self._note(completed=1, last_move_ms=round(wall_ms, 3))
        REGISTRY.counter_inc(MOVES_METRIC, MOVES_HELP, outcome="completed")
        REGISTRY.observe(MOVE_SECONDS, wall_ms / 1e3, MOVE_SECONDS_HELP)
        log.info(
            "defrag: moved %s/%s chip %d -> %d (%d units, %.1f ms)",
            plan.pod[0], plan.pod[1], plan.src, plan.dst, plan.units, wall_ms,
        )
        return True


# ---------------------------------------------------------------------------
# restart resolution (called by cluster.reconciler)
# ---------------------------------------------------------------------------


def resolve_move(
    ckpt: AllocationCheckpoint,
    assume: AssumeCache,
    api: Any,
    key: PodKey,
    data: Mapping[str, Any],
    *,
    restore_fn: Callable[[PodKey, dict | None], None] | None = None,
) -> str | None:
    """Resolve one journaled move found after a restart (any phase).

    Roll **forward** when the entry reached ``switch``: the decision was
    committed — re-issue the switch PATCH if it never landed, hand the
    journaled engine snapshot to ``restore_fn`` (the destination slice),
    then commit and release. Roll **back** before ``switch``: nothing
    authoritative changed — abort and release; the workload never
    stopped (drain's side effect, if it ran, is re-delivered to the
    SOURCE by the workload's own supervisor). A deleted pod aborts in
    any phase — both reservations (the synthetic destination key and
    whatever the annotation counted) end released.

    Returns ``"rollforward"`` / ``"rollback"`` when resolved this pass,
    None when the apiserver would not answer authoritatively or a
    roll-forward side effect (the re-PATCH, the engine restore) failed —
    the entry and its destination reservation stay protective until the
    next pass.
    """
    from ..cluster.apiserver import ApiError

    pod_key = pod_of_move(data)
    seq = data.get("_seq")
    phase = str(data.get("phase") or "plan")
    if pod_key is None:
        log.warning("defrag resolve: garbled move record for %s", key)
        if ckpt.abort(key, seq=seq):
            assume.release_if_unclaimed(key)
            return "rollback"
        return None
    try:
        pod = api.get_pod(*pod_key)
    except ApiError as e:
        if e.status != 404:
            return None  # not authoritative; resolve next pass
        pod = None
    except Exception:  # noqa: BLE001 — outage
        return None
    if pod is None or not P.is_active(pod):
        if ckpt.abort(key, seq=seq):
            assume.release_if_unclaimed(key)
            REGISTRY.counter_inc(MOVES_METRIC, MOVES_HELP, outcome="aborted")
            log.info(
                "defrag resolve: move for deleted pod %s/%s aborted", *pod_key
            )
            return "rollback"
        return None
    if phase not in ("switch", "resume"):
        # before the commit point: nothing authoritative changed
        if ckpt.abort(key, seq=seq):
            assume.release_if_unclaimed(key)
            REGISTRY.counter_inc(MOVES_METRIC, MOVES_HELP, outcome="aborted")
            log.info(
                "defrag resolve: move for %s/%s rolled back (died in %s)",
                pod_key[0], pod_key[1], phase,
            )
            return "rollback"
        return None
    # at or past switch: roll forward
    annotations = dict(data.get("annotations") or {})
    try:
        dst = int(data["dst"])
    except (KeyError, TypeError, ValueError):
        dst = -1
    if dst >= 0 and P.chip_idx_from_annotation(pod) != dst and annotations:
        # the switch PATCH never landed (or lost a race): re-issue it
        try:
            api.patch_pod(
                pod_key[0], pod_key[1], {"metadata": {"annotations": annotations}}
            )
        except Exception as e:  # noqa: BLE001 — transient: next pass retries
            log.v(4, "defrag resolve: switch re-PATCH failed (%s)", e)
            return None
    snapshot = data.get("snapshot")
    if restore_fn is None and isinstance(snapshot, dict):
        # the record carries a drained engine snapshot but no restore
        # hook is registered (yet): committing would delete the only
        # copy and lose every request it holds — stay pending until the
        # serving integration (re)registers its hooks
        log.warning(
            "defrag resolve: move for %s/%s carries a drained snapshot "
            "but no restore hook is registered; left pending", *pod_key,
        )
        return None
    if restore_fn is not None:
        try:
            restore_fn(pod_key, snapshot if isinstance(snapshot, dict) else None)
        except Exception as e:  # noqa: BLE001 — leave pending, like a
            # failed re-PATCH: committing here would delete the journal's
            # only copy of the drained snapshot and silently lose every
            # request it carries. The entry (and its protective
            # destination reservation) stays for the next pass — the
            # destination engine may simply not be rebuilt yet after the
            # restart that got us here.
            log.warning(
                "defrag resolve: engine restore for %s/%s failed (%s); "
                "move left pending for retry", pod_key[0], pod_key[1], e,
            )
            return None
    if ckpt.commit(key, seq=seq):
        assume.release_if_unclaimed(key)
        REGISTRY.counter_inc(MOVES_METRIC, MOVES_HELP, outcome="completed")
        log.info(
            "defrag resolve: move for %s/%s rolled forward (died in %s)",
            pod_key[0], pod_key[1], phase,
        )
        return "rollforward"
    return None


# ---------------------------------------------------------------------------
# the loop: scan -> move -> publish (owned by the manager)
# ---------------------------------------------------------------------------


# The numeric surface of the defrag-status annotation, coerced on read so
# a half-garbled annotation (a null counter, a stringly duration) degrades
# to zeros instead of crashing every CLI invocation against that node.
_STATUS_INT_FIELDS = (
    "planned", "active", "completed", "failed", "quantum", "stranded_units",
)
_STATUS_FLOAT_FIELDS = ("last_move_ms", "stranded_pct")


def status_from_node(node: Mapping[str, Any] | None) -> dict[str, Any] | None:
    """Parse the daemon's defrag-status node annotation
    (:data:`~..const.ANN_DEFRAG_STATUS`), or None when absent/garbled —
    the inspect CLI's read side of :meth:`DefragLoop.publish_status`.
    Numeric fields are coerced (garbled values read as 0), so callers can
    format them without re-validating."""
    if not node:
        return None
    raw = ((node.get("metadata") or {}).get("annotations") or {}).get(
        const.ANN_DEFRAG_STATUS
    )
    if not raw:
        return None
    try:
        doc = json.loads(raw)
    except (TypeError, ValueError):
        return None
    if not isinstance(doc, dict):
        return None
    out: dict[str, Any] = {}
    for k, v in doc.items():
        try:
            if k in _STATUS_INT_FIELDS:
                out[k] = int(v)
            elif k in _STATUS_FLOAT_FIELDS:
                out[k] = float(v)
            else:
                out[k] = v
        except (TypeError, ValueError):
            out[k] = 0.0 if k in _STATUS_FLOAT_FIELDS else 0
    return out


class DefragLoop:
    """The daemon's defragmentation driver: every ``interval_s`` it scans
    (:class:`DefragPlanner`), executes the planned moves one at a time
    (:class:`SliceMover` — serial on purpose: each move re-validates
    against the live apiserver, and one in-flight move's destination
    reservation already routes concurrent admissions around it), and
    publishes the node's defrag-status annotation for the inspect CLI.

    The first pass runs one full interval after :meth:`start`, never at
    startup — the reconciler's first pass must resolve any move the
    previous incarnation died holding before this instance plans new
    ones. A :class:`~.checkpoint.StaleDaemonError` stops the loop for
    good: a superseded daemon must not move pods the newer one owns.
    """

    def __init__(
        self,
        planner: DefragPlanner,
        mover: SliceMover,
        api: Any,
        node_name: str,
        *,
        interval_s: float = 300.0,
    ) -> None:
        self._planner = planner
        self._mover = mover
        self._api = api
        self._node = node_name
        self._interval = interval_s
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> "DefragLoop":
        self._thread = threading.Thread(
            target=self._run, name="defrag-loop", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self._interval):
            try:
                self.run_once()
            except StaleDaemonError:
                log.error(
                    "defrag: fenced mid-pass; loop stopping (a newer "
                    "daemon owns this node's moves)"
                )
                return
            except Exception as e:  # noqa: BLE001 — never kill the loop
                log.warning("defrag pass failed: %s", e)

    def run_once(self) -> DefragReport:
        """One scan-move-publish pass (the loop body, callable directly
        in tests/benches). ``StaleDaemonError`` propagates — the caller
        must stop driving moves."""
        report = self._planner.scan()
        for plan in report.moves:
            if self._stop.is_set():
                break
            try:
                self._mover.execute(plan)
            except StaleDaemonError:
                # fenced: a newer daemon owns this node's moves — do NOT
                # publish status (an unfenced node PATCH would overwrite
                # the owner's published counters with this superseded
                # incarnation's stale picture)
                raise
            except Exception as e:  # noqa: BLE001 — entry stays pending
                # for the reconciler (that IS the crash-safety story);
                # later moves may still apply
                log.warning(
                    "defrag: move for %s/%s failed (%s); journal entry "
                    "left for the reconciler", plan.pod[0], plan.pod[1], e,
                )
                # keep the published annotation's failed counter in step
                # with the metric — the mover's own accounting runs only
                # on its clean-abort/fenced paths, not when the
                # exception propagates out of execute()
                self._mover.note_failed()
                REGISTRY.counter_inc(MOVES_METRIC, MOVES_HELP, outcome="failed")
        self.publish_status(report)
        return report

    def publish_status(self, report: DefragReport | None) -> None:
        """Write the defrag-status node annotation (best effort — the
        apiserver is the database, so the CLI needs no extra endpoint)."""
        stats = self._mover.stats()
        doc: dict[str, Any] = {
            "planned": stats.planned,
            "active": stats.active,
            "completed": stats.completed,
            "failed": stats.failed,
            "last_move_ms": stats.last_move_ms,
        }
        if report is not None:
            doc.update(
                quantum=report.quantum,
                stranded_units=sum(report.stranded_by_chip.values()),
                stranded_pct=round(report.stranded_pct, 2),
            )
        try:
            self._api.patch_node(
                self._node,
                {"metadata": {"annotations": {
                    const.ANN_DEFRAG_STATUS: json.dumps(doc, sort_keys=True)
                }}},
            )
        except Exception as e:  # noqa: BLE001 — status is observability
            log.v(4, "defrag: status annotation publish failed (%s)", e)
