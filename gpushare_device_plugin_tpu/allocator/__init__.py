from .binpack import AssignmentError, assign_chip, available_units

__all__ = ["AssignmentError", "assign_chip", "available_units"]
