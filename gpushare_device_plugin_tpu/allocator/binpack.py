"""Pure HBM binpack policy.

Reference behavior: ``assignDevice`` first-fit over ascending chip index
(``server.go:249-264``) against the availability vector from
``getAvailableGPUs`` = per-chip capacity minus annotation-declared usage of
running pods (``server.go:268-289``). Kept pure (no I/O) so it stays
table-testable — the property the reference had but never tested.

Additions over the reference:
- ``policy="best-fit"``: picks the feasible chip with the least free space,
  which strictly improves worst-case fragmentation for mixed request sizes
  (the north-star metric is binpack utilization %).
- ``policy="spread"``: picks the feasible chip with the MOST free space —
  the anti-affinity choice for latency-sensitive fleets, minimizing HBM
  bandwidth contention between co-resident pods at the cost of packing
  density (ties break to the lowest index, so it stays deterministic).
- unhealthy chips are excluded (reference TODO at ``server.go:267``).
"""

from __future__ import annotations

from typing import Mapping, Sequence


class AssignmentError(RuntimeError):
    """No chip has enough free HBM units for the request."""


def available_units(
    capacity: Mapping[int, int],
    used: Mapping[int, int],
    unhealthy: Sequence[int] = (),
) -> dict[int, int]:
    """Free units per chip index: capacity - used, unhealthy chips removed.

    ``used`` entries for unknown or out-of-range chip indices are ignored
    (defensive: annotations are client-writable).
    """
    avail: dict[int, int] = {}
    bad = set(unhealthy)
    for idx in sorted(capacity):
        if idx in bad:
            continue
        avail[idx] = max(0, capacity[idx] - used.get(idx, 0))
    return avail


def assign_chip(
    request_units: int,
    capacity: Mapping[int, int],
    used: Mapping[int, int],
    unhealthy: Sequence[int] = (),
    policy: str = "first-fit",
) -> int:
    """Pick the chip index to host a request of ``request_units``.

    Raises ``AssignmentError`` when nothing fits (the caller turns this into
    a gRPC error -> kubelet UnexpectedAdmissionError, ``allocate.go:99-105``).
    """
    if request_units <= 0:
        raise AssignmentError(f"invalid request of {request_units} units")
    avail = available_units(capacity, used, unhealthy)
    if policy == "first-fit":
        # ascending chip index, first chip that fits (server.go:250-264)
        for idx in sorted(avail):
            if avail[idx] >= request_units:
                return idx
    elif policy in ("best-fit", "spread"):
        # best-fit: least free space among feasible chips (densest packing);
        # spread: most free space (least contention). Ties -> lowest index.
        prefer_less = policy == "best-fit"
        best = None
        for idx in sorted(avail):
            if avail[idx] >= request_units:
                if best is None or (
                    avail[idx] < avail[best]
                    if prefer_less
                    else avail[idx] > avail[best]
                ):
                    best = idx
        if best is not None:
            return best
    else:
        raise ValueError(f"unknown binpack policy {policy!r}")
    raise AssignmentError(
        f"no chip can fit {request_units} units (available: {avail})"
    )
