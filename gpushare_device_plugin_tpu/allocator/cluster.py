"""The production Allocate() flow: kubelet grant -> pod match -> chip pick
-> apiserver persistence -> env/device payload.

Faithful to the reference's critical path (``allocate.go:27-134``, traced in
SURVEY.md section 3.2) with its failure semantics:

1. The granted fake-ID lists are only *counted* (which IDs kubelet picked is
   meaningless by design).
2. The pending pod being admitted is identified by matching the request
   total against candidate pods' summed limits, oldest first. Two
   same-size pods admitted concurrently can swap allocations — a design
   hazard inherited from the reference (``allocate.go:51-61``); harmless
   for fungible HBM slices since both pods get *a* valid placement, and
   the annotation write is what the rest of the system trusts.
3. Placement: the scheduler-extender's annotation wins if the pod was
   assumed (branch A, ``allocate.go:75-84``); otherwise first-fit binpack
   over apiserver-derived usage (branch B, ``allocate.go:85-98``).
4. The decision is persisted as pod annotations + the tpushare label via
   strategic-merge patch, retried once on optimistic-lock conflicts
   (``allocate.go:136-150``). The apiserver is the only database; restart
   re-derives everything.
"""

from __future__ import annotations

import threading
import time
from typing import Sequence

from .. import const
from ..cluster import pods as P
from ..cluster.apiserver import ApiError, ApiServerClient
from ..cluster.events import REASON_ALLOC_FAILED, emit_pod_event
from ..cluster.podsource import PodSource
from ..device.fanout import DeviceInventory
from ..utils.log import get_logger
from .binpack import assign_chip
from .env import ContainerAllocation, build_core_allocation, build_mem_allocation

log = get_logger("allocator.cluster")


class AllocationFailure(RuntimeError):
    """Raised to fail pod admission (gRPC error -> UnexpectedAdmissionError)."""


class _PodGone(RuntimeError):
    """The matched pod 404ed on PATCH: deleted while its cache entry or
    DELETED watch event was in flight. Internal signal — the allocator
    evicts the stale entry and re-matches once."""


def persist_pod_assignment(
    api: ApiServerClient,
    pod_source: PodSource,
    pod,
    annotations: dict[str, str],
    label_value: str,
) -> None:
    """Label + annotation strategic-merge patch with one conflict retry
    (``allocate.go:126,136-150``); feeds the result back into the pod
    source so the next Allocate cannot re-match this pod."""
    patch = {
        "metadata": {
            "annotations": annotations,
            "labels": {const.LABEL_RESOURCE_KEY: label_value},
        }
    }
    ns, name = P.namespace(pod), P.name(pod)
    try:
        updated = api.patch_pod(ns, name, patch)
    except ApiError as e:
        if e.status == 404:
            raise _PodGone(f"{ns}/{name}") from e
        if const.OPTIMISTIC_LOCK_ERROR_MSG not in e.body and e.status != 409:
            raise AllocationFailure(f"pod patch failed: {e}") from e
        log.warning("patch conflict for %s/%s; retrying once", ns, name)
        try:
            updated = api.patch_pod(ns, name, patch)
        except ApiError as e2:
            if e2.status == 404:
                raise _PodGone(f"{ns}/{name}") from e2
            raise AllocationFailure(f"pod patch failed twice: {e2}") from e2
    pod_source.note_pod_update(updated)


class ClusterAllocator:
    def __init__(
        self,
        inventory: DeviceInventory,
        api: ApiServerClient,
        pod_source: PodSource,
        node_name: str,
        policy: str = "first-fit",
        disable_isolation: bool = False,
        unhealthy_chips_fn=None,
        lock: threading.Lock | None = None,
    ):
        self._inv = inventory
        self._api = api
        self._pods = pod_source
        self._node = node_name
        self._policy = policy
        self._disable_isolation = disable_isolation
        self._unhealthy_fn = unhealthy_chips_fn or (lambda: [])
        # Serializes the whole allocate path (reference: allocate.go:42-43).
        # MUST be shared with the node's ClusterCoreAllocator: the two
        # resources share one physical-chip ledger, and independent locks
        # would let concurrent mem/core Allocates each read a snapshot
        # before the other persists — double-booking the same chip.
        self._lock = lock if lock is not None else threading.Lock()

    # ------------------------------------------------------------------

    def allocate(self, granted: Sequence[Sequence[str]]) -> list[ContainerAllocation]:
        pod_units = sum(len(ids) for ids in granted)
        container_units = [len(ids) for ids in granted]
        log.v(4, "Allocate: pod_units=%d per-container=%s", pod_units, container_units)
        with self._lock:
            pod = self._match_pending_pod(pod_units)
            if pod is None:
                # Cached sources may lag the scheduler's bind by a watch
                # event; one synchronous refresh closes the window before
                # we fail the admission.
                self._pods.refresh()
                pod = self._match_pending_pod(pod_units)
            if pod is None:
                raise AllocationFailure(
                    f"invalid allocation request: no pending pod on {self._node} "
                    f"requesting {pod_units} {const.RESOURCE_MEM}"
                )
            try:
                for attempt in (0, 1):
                    idx, annotations = self._place(pod, pod_units)
                    try:
                        self._persist(pod, annotations)
                        break
                    except _PodGone:
                        # The matched pod was deleted with its cache entry
                        # still live — evict it and re-match so a live
                        # same-size pod is not failed for a ghost's sake.
                        log.warning(
                            "pod %s/%s vanished during persist; re-matching",
                            P.namespace(pod), P.name(pod),
                        )
                        self._pods.evict(pod)
                        pod = None
                        if attempt:
                            raise AllocationFailure(
                                f"no live pending pod on {self._node} "
                                f"requesting {pod_units} {const.RESOURCE_MEM}"
                            ) from None
                        self._pods.refresh()
                        pod = self._match_pending_pod(pod_units)
                        if pod is None:
                            raise AllocationFailure(
                                f"invalid allocation request: no pending pod "
                                f"on {self._node} requesting {pod_units} "
                                f"{const.RESOURCE_MEM}"
                            ) from None
            except AllocationFailure as e:
                # kubelet only logs the gRPC error; a Warning event on the
                # pod makes `kubectl describe pod` show why admission failed
                if pod is not None:
                    emit_pod_event(
                        self._api, pod, REASON_ALLOC_FAILED, str(e), host=self._node
                    )
                raise
        chip = self._inv.chip_by_id(self._inv.id_of_index(idx))
        total = self._chip_total(idx)
        log.info(
            "allocated pod %s/%s: %d units on chip %d (%s)",
            P.namespace(pod), P.name(pod), pod_units, idx, chip.id,
        )
        return [
            build_mem_allocation(
                chip=chip,
                chip_total_units=total,
                pod_units=pod_units,
                container_units=n,
                disable_isolation=self._disable_isolation,
            )
            for n in container_units
        ]

    # ------------------------------------------------------------------

    def _chip_total(self, idx: int) -> int:
        return self._inv.units_of(self._inv.id_of_index(idx))

    def _match_pending_pod(self, pod_units: int):
        """Oldest pending share pod whose total limits equal the request
        (``allocate.go:51-61``)."""
        candidates = P.candidate_pods(self._pods.pending_pods(), self._node)
        log.v(4, "candidates: %s", [P.name(p) for p in candidates])
        for pod in candidates:
            if P.mem_units_of_pod(pod) == pod_units:
                return pod
        return None

    def _place(self, pod, pod_units: int) -> tuple[int, dict[str, str]]:
        """Decide the chip and the annotations to persist for one pod.

        One ``chip_state()`` read serves both the usage accounting and the
        core-hold exclusion — O(chips) per placement with the informer's
        incremental index (the reference rescans every labeled pod per
        admission, ``podmanager.go:102-115``)."""
        if P.core_chips_of_pod(pod) > 0:
            raise AllocationFailure(
                f"pod {P.name(pod)} requests both {const.RESOURCE_MEM} and "
                f"{const.RESOURCE_CORE}; dual-resource pods are unsupported "
                "(the two allocators would race each other's assigned flag)"
            )
        mem_used, core_held = self._pods.chip_state()
        if P.is_assumed(pod) and not P.is_assigned(pod):
            idx = self._assumed_chip(pod, core_held)
            annotations = {const.ENV_ASSIGNED_FLAG: "true"}
        else:
            idx = self._binpack_chip(pod_units, mem_used, core_held)
            annotations = {
                const.ENV_MEM_IDX: str(idx),
                const.ENV_MEM_POD: str(pod_units),
                const.ENV_MEM_DEV: str(self._chip_total(idx)),
                const.ENV_ASSIGNED_FLAG: "true",
            }
        annotations[const.ENV_ASSUME_TIME] = str(time.time_ns())
        return idx, annotations

    def _assumed_chip(self, pod, core_held: set[int]) -> int:
        """Branch A: trust the scheduler extender's placement."""
        idx = P.chip_idx_from_annotation(pod)
        if idx < 0 or idx not in self._inv.units_by_index():
            raise AllocationFailure(
                f"pod {P.name(pod)} assumed by extender but its "
                f"{const.ENV_MEM_IDX} annotation is invalid: {idx}"
            )
        if idx in core_held:
            raise AllocationFailure(
                f"pod {P.name(pod)} assumed onto chip {idx}, but that chip "
                f"is exclusively held by a {const.RESOURCE_CORE} pod"
            )
        log.v(4, "extender placement for %s: chip %d", P.name(pod), idx)
        return idx

    def _binpack_chip(
        self, pod_units: int, used: dict[int, int], core_held: set[int]
    ) -> int:
        """Branch B: first-fit over capacity minus apiserver-declared usage.

        Chips exclusively held by assigned tpu-core pods are excluded along
        with unhealthy ones — the two resources share one physical chip
        accounting (the reference's single-resource model, server.go:268-289,
        extended across both).
        """
        excluded = sorted(set(self._unhealthy_fn()) | core_held)
        try:
            return assign_chip(
                pod_units,
                self._inv.units_by_index(),
                used,
                unhealthy=excluded,
                policy=self._policy,
            )
        except Exception as e:
            raise AllocationFailure(str(e)) from e

    def _persist(self, pod, annotations: dict[str, str]) -> None:
        persist_pod_assignment(
            self._api, self._pods, pod, annotations, const.LABEL_RESOURCE_VALUE
        )


class ClusterCoreAllocator:
    """Allocate() flow for the whole-chip ``tpu-core`` resource.

    Unlike tpu-mem, the granted device IDs *are* real chip ids (kubelet
    picks which chips, steered by GetPreferredAllocation), so placement is
    validation rather than binpack: every granted chip must be healthy,
    free of fractional-HBM usage, and not already core-held. The decision
    is persisted as the ``ENV_CORE_IDS`` annotation + the tpu-core label so
    restart re-derives exclusive holds from the apiserver and the mem
    binpack can exclude these chips (accounting model: ``server.go:268-289``
    extended across both resources).
    """

    def __init__(
        self,
        inventory: DeviceInventory,
        api: ApiServerClient,
        pod_source: PodSource,
        node_name: str,
        topology=None,
        unhealthy_chips_fn=None,
        lock: threading.Lock | None = None,
    ):
        self._inv = inventory
        self._api = api
        self._pods = pod_source
        self._node = node_name
        self._topo = topology
        self._unhealthy_fn = unhealthy_chips_fn or (lambda: [])
        # shared with the mem allocator — see ClusterAllocator.__init__
        self._lock = lock if lock is not None else threading.Lock()

    def allocate(self, granted: Sequence[Sequence[str]]) -> list[ContainerAllocation]:
        total = sum(len(ids) for ids in granted)
        try:
            per_container = [
                sorted(self._inv.index_of(cid) for cid in ids) for ids in granted
            ]
        except KeyError as e:
            raise AllocationFailure(f"granted unknown chip id: {e}") from e
        indices = sorted(i for ids in per_container for i in ids)
        log.v(4, "core Allocate: chips %s", indices)
        with self._lock:
            pod = self._match_pending_pod(total)
            if pod is None:
                self._pods.refresh()
                pod = self._match_pending_pod(total)
            if pod is None:
                raise AllocationFailure(
                    f"invalid allocation request: no pending pod on {self._node} "
                    f"requesting {total} {const.RESOURCE_CORE}"
                )
            try:
                # Validation runs per attempt: a pod re-matched after
                # _PodGone is a different pod and must clear the
                # dual-resource guard and the chip-conflict check itself
                # (mirrors the mem path re-running _place per attempt).
                for attempt in (0, 1):
                    if P.mem_units_of_pod(pod) > 0:
                        raise AllocationFailure(
                            f"pod {P.name(pod)} requests both "
                            f"{const.RESOURCE_MEM} and {const.RESOURCE_CORE}; "
                            "dual-resource pods are unsupported"
                        )
                    self._check_conflicts(indices)
                    annotations = {
                        const.ENV_CORE_IDS: ",".join(str(i) for i in indices),
                        const.ENV_CORE_POD: str(total),
                        const.ENV_ASSIGNED_FLAG: "true",
                        const.ENV_ASSUME_TIME: str(time.time_ns()),
                    }
                    try:
                        persist_pod_assignment(
                            self._api, self._pods, pod, annotations,
                            const.LABEL_CORE_VALUE,
                        )
                        break
                    except _PodGone:
                        log.warning(
                            "core pod %s/%s vanished during persist; re-matching",
                            P.namespace(pod), P.name(pod),
                        )
                        self._pods.evict(pod)
                        pod = None
                        if attempt:
                            # final attempt: no point refreshing a result
                            # we would discard (mirrors the mem path)
                            raise AllocationFailure(
                                f"no live pending pod on {self._node} requesting "
                                f"{total} {const.RESOURCE_CORE}"
                            ) from None
                        self._pods.refresh()
                        pod = self._match_pending_pod(total)
                        if pod is None:
                            raise AllocationFailure(
                                f"no live pending pod on {self._node} requesting "
                                f"{total} {const.RESOURCE_CORE}"
                            ) from None
            except AllocationFailure as e:
                if pod is not None:
                    emit_pod_event(
                        self._api, pod, REASON_ALLOC_FAILED, str(e), host=self._node
                    )
                raise
        log.info(
            "allocated core pod %s/%s: chips %s",
            P.namespace(pod), P.name(pod), indices,
        )
        chips_by_id = {c.id: c for c in self._inv.chips()}
        return [
            build_core_allocation(
                chips=[chips_by_id[self._inv.id_of_index(i)] for i in ids],
                process_bounds=getattr(self._topo, "process_bounds", ""),
                chips_per_process_bounds=getattr(
                    self._topo, "chips_per_process_bounds", ""
                ),
            )
            for ids in per_container
        ]

    def _match_pending_pod(self, total: int):
        candidates = P.candidate_pods(
            self._pods.pending_pods(), self._node, resource=const.RESOURCE_CORE
        )
        for pod in candidates:
            if P.core_chips_of_pod(pod) == total:
                return pod
        return None

    def _check_conflicts(self, indices: list[int]) -> None:
        """Every granted chip must be free of other holds and healthy."""
        mem_used, core_held = self._pods.chip_state()
        unhealthy = set(self._unhealthy_fn())
        for idx in indices:
            if idx in core_held:
                raise AllocationFailure(
                    f"chip {idx} is already exclusively held by another "
                    f"{const.RESOURCE_CORE} pod"
                )
            if mem_used.get(idx, 0) > 0:
                raise AllocationFailure(
                    f"chip {idx} has {mem_used[idx]} {const.RESOURCE_MEM} units "
                    "in use by fractional pods; cannot grant exclusively"
                )
            if idx in unhealthy:
                raise AllocationFailure(f"chip {idx} is unhealthy")


def cluster_chip_state(pod_source: PodSource):
    """() -> (mem_used_by_chip, core_held_chips) from one source read."""
    return pod_source.chip_state


def preferred_core_chips(inventory: DeviceInventory, state_fn):
    """GetPreferredAllocation hook for the core plugin: steer kubelet toward
    chips with no fractional-HBM usage and no existing exclusive hold, so
    core grants rarely conflict with the mem binpack.

    ``state_fn() -> (mem_used_by_chip, core_held_chips)`` — cluster mode
    passes ``cluster_chip_state(pod_source)``, standalone mode the
    LocalAllocator's in-process view; the ranking policy lives here once.
    """

    def prefer(available_ids: list[str], size: int) -> list[str]:
        try:
            mem_used, core_held = state_fn()
        except Exception as e:  # noqa: BLE001 — preference only, never fail
            log.warning("preferred-allocation state read failed: %s", e)
            mem_used, core_held = {}, set()

        def rank(cid: str):
            idx = inventory.index_of(cid)
            return (idx in core_held, mem_used.get(idx, 0), idx)

        return sorted(available_ids, key=rank)[:size]

    return prefer
