"""The production Allocate() flow: kubelet grant -> pod match -> chip pick
-> apiserver persistence -> env/device payload.

Faithful to the reference's critical path (``allocate.go:27-134``, traced in
SURVEY.md section 3.2) with its failure semantics:

1. The granted fake-ID lists are only *counted* (which IDs kubelet picked is
   meaningless by design).
2. The pending pod being admitted is identified by matching the request
   total against candidate pods' summed limits, oldest first. Two
   same-size pods admitted concurrently can swap allocations — a design
   hazard inherited from the reference (``allocate.go:51-61``); harmless
   for fungible HBM slices since both pods get *a* valid placement, and
   the annotation write is what the rest of the system trusts.
3. Placement: the scheduler-extender's annotation wins if the pod was
   assumed (branch A, ``allocate.go:75-84``); otherwise first-fit binpack
   over apiserver-derived usage (branch B, ``allocate.go:85-98``).
4. The decision is persisted as pod annotations + the tpushare label via
   strategic-merge patch, retried once on optimistic-lock conflicts
   (``allocate.go:136-150``). The apiserver is the only database; restart
   re-derives everything.
"""

from __future__ import annotations

import threading
import time
from typing import Sequence

from .. import const
from ..cluster import pods as P
from ..cluster.apiserver import ApiError, ApiServerClient
from ..cluster.podsource import PodSource
from ..device.fanout import DeviceInventory
from ..utils.log import get_logger
from .binpack import assign_chip
from .env import ContainerAllocation, build_mem_allocation

log = get_logger("allocator.cluster")


class AllocationFailure(RuntimeError):
    """Raised to fail pod admission (gRPC error -> UnexpectedAdmissionError)."""


class _PodGone(RuntimeError):
    """The matched pod 404ed on PATCH: deleted while its cache entry or
    DELETED watch event was in flight. Internal signal — the allocator
    evicts the stale entry and re-matches once."""


class ClusterAllocator:
    def __init__(
        self,
        inventory: DeviceInventory,
        api: ApiServerClient,
        pod_source: PodSource,
        node_name: str,
        policy: str = "first-fit",
        disable_isolation: bool = False,
        unhealthy_chips_fn=None,
    ):
        self._inv = inventory
        self._api = api
        self._pods = pod_source
        self._node = node_name
        self._policy = policy
        self._disable_isolation = disable_isolation
        self._unhealthy_fn = unhealthy_chips_fn or (lambda: [])
        # serializes the whole allocate path (reference: allocate.go:42-43)
        self._lock = threading.Lock()

    # ------------------------------------------------------------------

    def allocate(self, granted: Sequence[Sequence[str]]) -> list[ContainerAllocation]:
        pod_units = sum(len(ids) for ids in granted)
        container_units = [len(ids) for ids in granted]
        log.v(4, "Allocate: pod_units=%d per-container=%s", pod_units, container_units)
        with self._lock:
            pod = self._match_pending_pod(pod_units)
            if pod is None:
                # Cached sources may lag the scheduler's bind by a watch
                # event; one synchronous refresh closes the window before
                # we fail the admission.
                self._pods.refresh()
                pod = self._match_pending_pod(pod_units)
            if pod is None:
                raise AllocationFailure(
                    f"invalid allocation request: no pending pod on {self._node} "
                    f"requesting {pod_units} {const.RESOURCE_MEM}"
                )
            for attempt in (0, 1):
                idx, annotations = self._place(pod, pod_units)
                try:
                    self._persist(pod, annotations)
                    break
                except _PodGone:
                    # The matched pod was deleted with its cache entry still
                    # live — evict it and re-match so a live same-size pod
                    # is not failed for a ghost's sake.
                    log.warning(
                        "pod %s/%s vanished during persist; re-matching",
                        P.namespace(pod), P.name(pod),
                    )
                    self._pods.evict(pod)
                    if attempt:
                        raise AllocationFailure(
                            f"no live pending pod on {self._node} requesting "
                            f"{pod_units} {const.RESOURCE_MEM}"
                        ) from None
                    self._pods.refresh()
                    pod = self._match_pending_pod(pod_units)
                    if pod is None:
                        raise AllocationFailure(
                            f"invalid allocation request: no pending pod on "
                            f"{self._node} requesting {pod_units} "
                            f"{const.RESOURCE_MEM}"
                        ) from None
        chip = self._inv.chip_by_id(self._inv.id_of_index(idx))
        total = self._chip_total(idx)
        log.info(
            "allocated pod %s/%s: %d units on chip %d (%s)",
            P.namespace(pod), P.name(pod), pod_units, idx, chip.id,
        )
        return [
            build_mem_allocation(
                chip=chip,
                chip_total_units=total,
                pod_units=pod_units,
                container_units=n,
                disable_isolation=self._disable_isolation,
            )
            for n in container_units
        ]

    # ------------------------------------------------------------------

    def _chip_total(self, idx: int) -> int:
        return self._inv.units_of(self._inv.id_of_index(idx))

    def _match_pending_pod(self, pod_units: int):
        """Oldest pending share pod whose total limits equal the request
        (``allocate.go:51-61``)."""
        candidates = P.candidate_pods(self._pods.pending_pods(), self._node)
        log.v(4, "candidates: %s", [P.name(p) for p in candidates])
        for pod in candidates:
            if P.mem_units_of_pod(pod) == pod_units:
                return pod
        return None

    def _place(self, pod, pod_units: int) -> tuple[int, dict[str, str]]:
        """Decide the chip and the annotations to persist for one pod."""
        if P.is_assumed(pod) and not P.is_assigned(pod):
            idx = self._assumed_chip(pod)
            annotations = {const.ENV_ASSIGNED_FLAG: "true"}
        else:
            idx = self._binpack_chip(pod_units)
            annotations = {
                const.ENV_MEM_IDX: str(idx),
                const.ENV_MEM_POD: str(pod_units),
                const.ENV_MEM_DEV: str(self._chip_total(idx)),
                const.ENV_ASSIGNED_FLAG: "true",
            }
        annotations[const.ENV_ASSUME_TIME] = str(time.time_ns())
        return idx, annotations

    def _assumed_chip(self, pod) -> int:
        """Branch A: trust the scheduler extender's placement."""
        idx = P.chip_idx_from_annotation(pod)
        if idx < 0 or idx not in self._inv.units_by_index():
            raise AllocationFailure(
                f"pod {P.name(pod)} assumed by extender but its "
                f"{const.ENV_MEM_IDX} annotation is invalid: {idx}"
            )
        log.v(4, "extender placement for %s: chip %d", P.name(pod), idx)
        return idx

    def _binpack_chip(self, pod_units: int) -> int:
        """Branch B: first-fit over capacity minus apiserver-declared usage."""
        used = P.used_units_by_chip(self._pods.running_share_pods())
        try:
            return assign_chip(
                pod_units,
                self._inv.units_by_index(),
                used,
                unhealthy=self._unhealthy_fn(),
                policy=self._policy,
            )
        except Exception as e:
            raise AllocationFailure(str(e)) from e

    def _persist(self, pod, annotations: dict[str, str]) -> None:
        """Label + annotation patch with one conflict retry
        (``allocate.go:126,136-150``)."""
        patch = {
            "metadata": {
                "annotations": annotations,
                "labels": {const.LABEL_RESOURCE_KEY: const.LABEL_RESOURCE_VALUE},
            }
        }
        ns, name = P.namespace(pod), P.name(pod)
        try:
            updated = self._api.patch_pod(ns, name, patch)
        except ApiError as e:
            if e.status == 404:
                raise _PodGone(f"{ns}/{name}") from e
            if const.OPTIMISTIC_LOCK_ERROR_MSG not in e.body and e.status != 409:
                raise AllocationFailure(f"pod patch failed: {e}") from e
            log.warning("patch conflict for %s/%s; retrying once", ns, name)
            try:
                updated = self._api.patch_pod(ns, name, patch)
            except ApiError as e2:
                if e2.status == 404:
                    raise _PodGone(f"{ns}/{name}") from e2
                raise AllocationFailure(f"pod patch failed twice: {e2}") from e2
        # Cached sources must see the assignment before the MODIFIED event
        # arrives, or the next Allocate could re-match this pod.
        self._pods.note_pod_update(updated)
