"""The production Allocate() flow: kubelet grant -> pod match -> chip pick
-> apiserver persistence -> env/device payload.

Faithful to the reference's critical path (``allocate.go:27-134``, traced in
SURVEY.md section 3.2) with its failure semantics:

1. The granted fake-ID lists are only *counted* (which IDs kubelet picked is
   meaningless by design).
2. The pending pod being admitted is identified by matching the request
   total against candidate pods' summed limits, oldest first. Two
   same-size pods admitted concurrently can swap allocations — a design
   hazard inherited from the reference (``allocate.go:51-61``); harmless
   for fungible HBM slices since both pods get *a* valid placement, and
   the annotation write is what the rest of the system trusts.
3. Placement: the scheduler-extender's annotation wins if the pod was
   assumed (branch A, ``allocate.go:75-84``); otherwise first-fit binpack
   over apiserver-derived usage (branch B, ``allocate.go:85-98``).
4. The decision is persisted as pod annotations + the tpushare label via
   strategic-merge patch, retried once on optimistic-lock conflicts
   (``allocate.go:136-150``). The apiserver is the only database; restart
   re-derives everything.

Concurrency design (replaces the reference's single mutex,
``allocate.go:42-43``): the flow is sharded so concurrent kubelet
admission workers for different pods proceed in parallel —

- *match* is serialized per request size only (striped locks): two
  same-size pods admitted concurrently keep the documented oldest-first
  semantics because the first worker *claims* its match in the shared
  ``AssumeCache`` and the second matches the next oldest candidate;
- *placement* is one atomic in-memory transaction against the ledger:
  usage snapshot + in-flight reservation overlay + chip decision +
  reservation, so two in-flight placements cannot double-book a chip;
- *persist* (the apiserver PATCH — the dominant wall-clock cost) runs
  under no lock at all; the reservation covers the pod until its PATCHed
  copy is visible in the pod source.

The same-size-pod match hazard documented above (point 2) is unchanged:
two same-size pods can still swap allocations — each still gets *a*
valid placement, never the same one.
"""

from __future__ import annotations

import contextlib
import dataclasses
import time
from typing import Any, Callable, Sequence

from .. import const
from ..cluster import pods as P
from ..cluster.apiserver import ApiError, ApiServerClient
from ..cluster.events import REASON_ALLOC_FAILED, emit_pod_event
from ..cluster.podsource import PodSource
from ..cluster.usage import pod_counts_toward_usage
from ..device.fanout import DeviceInventory
from ..topology import ChipTopology, format_shape, pad3, parse_shape, shape_size
from ..utils.decisions import DECISIONS, chip_breakdown
from ..utils.faults import FAULTS
from ..utils.log import get_logger
from ..utils.metrics import timed_acquire
from ..utils.tracing import TRACER, parse_context
from .assume import LOCK_WAIT_HELP, LOCK_WAIT_METRIC, AssumeCache, PodKey
from .checkpoint import AllocationCheckpoint, StaleDaemonError
from .binpack import assign_chip
from ..utils.lockrank import make_lock
from .env import (
    ContainerAllocation,
    build_core_allocation,
    build_gang_allocation,
    build_mem_allocation,
)

log = get_logger("allocator.cluster")

# Match stripes: same-size matches must serialize (they compete for the
# same oldest candidate); different sizes never do. 8 stripes is plenty —
# the stripe is held only for the in-memory match, not the PATCH.
NUM_MATCH_STRIPES = 8


def _pod_key(pod) -> PodKey:
    return P.namespace(pod), P.name(pod)


def _adopt_pod_trace(pod) -> None:
    """Stitch this Allocate into the extender's admission trace: the pod
    identity is only known after the match, so the open span stack is
    re-parented under the bind-span context the extender recorded in the
    ``tpushare.aliyun.com/trace-id`` annotation (no-op for branch-B pods
    the extender never touched, and for garbled annotations)."""
    TRACER.adopt_current_trace(
        parse_context(P.annotations(pod).get(const.ANN_TRACE_ID))
    )


def _current_trace_id() -> str:
    """The stitched admission trace id for decision records (after
    ``_adopt_pod_trace`` this is the SAME trace the extender's bind
    record carries — the join key between the two processes' "why"s)."""
    ctx = TRACER.current_context()
    return ctx.trace_id if ctx is not None else ""


def _counted_by_source(pod_source, key: PodKey) -> bool:
    """True when the pod source's own accounting already covers the
    reserved pod (its PATCHed copy landed in the cache) — the reservation
    overlay skips it to avoid double-counting. List-backed sources expose
    no ``get_pod``; their reservations count until released, which is
    conservative (over-counts briefly, never double-books)."""
    get_pod = getattr(pod_source, "get_pod", None)
    if get_pod is None:
        return False
    pod = get_pod(*key)
    return pod is not None and pod_counts_toward_usage(pod)


def _live_candidate(pod_source, pod, node: str, units: int, resource: str):
    """Re-evaluate a matched candidate against the source's *current*
    state. The match snapshot can predate a concurrent worker's
    note_pod_update: its claim is released only after the PATCHed copy is
    in the cache, so a candidate that is (a) unclaimed and (b) still a
    candidate in the live copy is genuinely unowned. Returns the live pod
    (the copy to place/persist) or None. Sources without ``get_pod`` run
    fully serialized (``_serial_guard``) and skip this check."""
    get_pod = getattr(pod_source, "get_pod", None)
    if get_pod is None:
        return pod
    live = get_pod(P.namespace(pod), P.name(pod))
    if live is None:
        return None
    if P.phase(live) != "Pending" or P.node_name(live) != node:
        return None
    if P.mem_units_of_pod(live, resource=resource) != units:
        return None
    if P.is_assumed(live) and P.is_assigned(live):
        return None
    return live


def _journal_begin(ckpt, key: PodKey, data: dict) -> None:
    """WAL begin before the PATCH. Fencing refusal is a hard admission
    failure (two writers double-book); journal I/O trouble is handled
    inside the checkpoint (degrade to unjournaled, never block admission).
    """
    if ckpt is None:
        return
    try:
        ckpt.begin(key, data)
    except StaleDaemonError as e:
        raise AllocationFailure(
            f"stale daemon instance refuses to allocate: {e}"
        ) from e


def _journal_resolve(ckpt, key: PodKey, op: str) -> None:
    if ckpt is None:
        return
    (ckpt.commit if op == "commit" else ckpt.abort)(key)


def _serial_guard(pod_source, assume: AssumeCache):
    """The sharded flow is safe only when a matcher can re-verify a stale
    candidate against live state (``get_pod``). List-backed sources can't
    offer that — a LIST snapshot taken before a concurrent PATCH would
    happily re-match the just-assigned pod — so they keep the reference's
    one-admission-at-a-time lock; the informer path returns a no-op
    guard and admissions overlap."""
    if getattr(pod_source, "get_pod", None) is None:
        return timed_acquire(
            assume.serial_lock, LOCK_WAIT_METRIC, LOCK_WAIT_HELP, lock="serial"
        )
    return contextlib.nullcontext()


class AllocationFailure(RuntimeError):
    """Raised to fail pod admission (gRPC error -> UnexpectedAdmissionError)."""


@dataclasses.dataclass(frozen=True)
class GangPlacement:
    """One gang decision: the member chips, the realized grid shape, and
    the HBM units claimed on EACH member (``_place`` returns this instead
    of a bare chip index for gang pods)."""

    chips: tuple[int, ...]
    shape: tuple[int, int, int]
    per_chip: int


class _PodGone(RuntimeError):
    """The matched pod 404ed on PATCH: deleted while its cache entry or
    DELETED watch event was in flight. Internal signal — the allocator
    evicts the stale entry and re-matches once."""


def persist_pod_assignment(
    api: ApiServerClient,
    pod_source: PodSource,
    pod: dict,
    annotations: dict[str, str],
    label_value: str,
    patch_fn: Callable[[str, str, dict], dict] | None = None,
) -> None:
    """Label + annotation strategic-merge patch with one conflict retry
    (``allocate.go:126,136-150``); feeds the result back into the pod
    source so the next Allocate cannot re-match this pod.

    ``patch_fn(ns, name, patch) -> pod`` overrides the write transport —
    the manager passes the coalesced ``PodPatchPipeline.patch_pod`` so
    concurrently-committed admissions batch their PATCHes; semantics
    (response, ApiError statuses, conflict retry) are identical."""
    patch_fn = patch_fn or api.patch_pod
    patch = {
        "metadata": {
            "annotations": annotations,
            "labels": {const.LABEL_RESOURCE_KEY: label_value},
        }
    }
    ns, name = P.namespace(pod), P.name(pod)
    try:
        updated = patch_fn(ns, name, patch)
    except ApiError as e:
        if e.status == 404:
            raise _PodGone(f"{ns}/{name}") from e
        if const.OPTIMISTIC_LOCK_ERROR_MSG not in e.body and e.status != 409:
            raise AllocationFailure(f"pod patch failed: {e}") from e
        log.warning("patch conflict for %s/%s; retrying once", ns, name)
        try:
            updated = patch_fn(ns, name, patch)
        except ApiError as e2:
            if e2.status == 404:
                raise _PodGone(f"{ns}/{name}") from e2
            raise AllocationFailure(f"pod patch failed twice: {e2}") from e2
    pod_source.note_pod_update(updated)


class ClusterAllocator:
    def __init__(
        self,
        inventory: DeviceInventory,
        api: ApiServerClient,
        pod_source: PodSource,
        node_name: str,
        policy: str = "first-fit",
        disable_isolation: bool = False,
        unhealthy_chips_fn: Callable[[], list[int]] | None = None,
        assume: AssumeCache | None = None,
        checkpoint: AllocationCheckpoint | None = None,
        patcher: Callable[[str, str, dict], dict] | None = None,
        chip_topology: ChipTopology | None = None,
    ) -> None:
        self._inv = inventory
        self._api = api
        self._pods = pod_source
        self._node = node_name
        self._policy = policy
        self._disable_isolation = disable_isolation
        self._unhealthy_fn = unhealthy_chips_fn or (lambda: [])
        # This node's chip grid for gang placement; defaults to the
        # standard grid for the inventory's chip count (the same rule the
        # extender applies from the node's topology label).
        self._chip_topo = chip_topology or ChipTopology.default_for(
            max(1, len(inventory.units_by_index()))
        )
        # Optional coalesced PATCH transport (PodPatchPipeline.patch_pod):
        # concurrently-committed admissions batch their apiserver writes.
        self._patcher = patcher
        # Write-ahead journal (allocator.checkpoint): the decision is made
        # durable before the PATCH leaves the node, so a daemon killed
        # mid-persist replays the reservation instead of double-assigning.
        self._ckpt = checkpoint
        # The in-flight claim/reservation ledger (see allocator.assume).
        # MUST be shared with the node's ClusterCoreAllocator: the two
        # resources share one physical-chip ledger, and independent
        # ledgers would let concurrent mem/core Allocates each read a
        # snapshot before the other persists — double-booking the chip.
        self._assume = assume if assume is not None else AssumeCache()
        self._match_locks = [make_lock("allocator.match") for _ in range(NUM_MATCH_STRIPES)]

    # ------------------------------------------------------------------

    def allocate(self, granted: Sequence[Sequence[str]]) -> list[ContainerAllocation]:
        pod_units = sum(len(ids) for ids in granted)
        container_units = [len(ids) for ids in granted]
        log.v(4, "Allocate: pod_units=%d per-container=%s", pod_units, container_units)
        # The allocator's admission span: match through env injection.
        # Nests under the plugin server's gRPC-entry span when driven by
        # kubelet; once the pod is matched, _admit adopts the extender's
        # trace context off the pod annotation and the whole open stack
        # re-parents under the bind span — one stitched trace across the
        # two processes.
        with TRACER.span(
            "allocator.admit",
            attributes={"resource": const.RESOURCE_MEM, "pod_units": pod_units},
        ) as asp:
            with _serial_guard(self._pods, self._assume):
                placement, pod = self._admit(pod_units)
            asp.set_attribute("pod", f"{P.namespace(pod)}/{P.name(pod)}")
            workload_class = P.workload_class(pod)
            asp.set_attribute("workload_class", workload_class)
            lora_adapter = P.lora_adapter(pod)
            if lora_adapter:
                asp.set_attribute("lora_adapter", lora_adapter)
            with TRACER.span("allocator.env", child_only=True):
                if isinstance(placement, GangPlacement):
                    asp.set_attribute("chips", list(placement.chips))
                    chips_by_id = {c.id: c for c in self._inv.chips()}
                    members = [
                        chips_by_id[self._inv.id_of_index(i)]
                        for i in placement.chips
                    ]
                    log.info(
                        "allocated gang pod %s/%s: %d units/chip on chips %s (shape %s)",
                        P.namespace(pod), P.name(pod), placement.per_chip,
                        list(placement.chips), placement.shape,
                    )
                    return [
                        build_gang_allocation(
                            chips=members,
                            shape=placement.shape,
                            per_chip_units=placement.per_chip,
                            chip_total_units=self._chip_total(placement.chips[0]),
                            pod_units=pod_units,
                            container_units=n,
                            disable_isolation=self._disable_isolation,
                            workload_class=workload_class,
                            lora_adapter=lora_adapter,
                        )
                        for n in container_units
                    ]
                idx = placement
                asp.set_attribute("chip", idx)
                chip = self._inv.chip_by_id(self._inv.id_of_index(idx))
                total = self._chip_total(idx)
                log.info(
                    "allocated pod %s/%s: %d units on chip %d (%s)",
                    P.namespace(pod), P.name(pod), pod_units, idx, chip.id,
                )
                return [
                    build_mem_allocation(
                        chip=chip,
                        chip_total_units=total,
                        pod_units=pod_units,
                        container_units=n,
                        disable_isolation=self._disable_isolation,
                        workload_class=workload_class,
                        lora_adapter=lora_adapter,
                    )
                    for n in container_units
                ]

    def _admit(self, pod_units: int):
        """Match, place, journal, persist; -> (chip index, the matched pod).

        WAL ordering per attempt: the chip decision is journaled durable
        (``begin``) before the PATCH goes out, ``commit`` lands only after
        the PATCHed copy is back in the pod source, and every failure path
        that provably persisted nothing journals ``abort``. A crash at any
        instruction leaves either no entry (nothing happened), or an
        unresolved entry the restarted daemon replays as a reservation and
        the reconciler resolves against the apiserver.
        """
        pod = self._claim_pod(pod_units)
        _adopt_pod_trace(pod)
        try:
            try:
                for attempt in (0, 1):
                    with TRACER.span("allocator.place", child_only=True):
                        placement, annotations = self._place(pod, pod_units)
                    key = _pod_key(pod)
                    if isinstance(placement, GangPlacement):
                        journal = {
                            "kind": "gang",
                            "chips": list(placement.chips),
                            "shape": list(placement.shape),
                            "per_chip": placement.per_chip,
                            "annotations": annotations,
                        }
                    else:
                        journal = {
                            "kind": "mem",
                            "idx": placement,
                            "units": pod_units,
                            "annotations": annotations,
                        }
                    with TRACER.span("wal.begin", child_only=True):
                        _journal_begin(self._ckpt, key, journal)
                    try:
                        with TRACER.span("pod.patch", child_only=True):
                            self._persist(pod, annotations)
                        FAULTS.fire("allocator.post_persist")
                        with TRACER.span("wal.commit", child_only=True):
                            _journal_resolve(self._ckpt, key, "commit")
                        break
                    except _PodGone:
                        # The matched pod was deleted with its cache entry
                        # still live — evict it and re-match so a live
                        # same-size pod is not failed for a ghost's sake.
                        with TRACER.span("wal.abort", child_only=True):
                            _journal_resolve(self._ckpt, key, "abort")
                        log.warning(
                            "pod %s/%s vanished during persist; re-matching",
                            P.namespace(pod), P.name(pod),
                        )
                        self._pods.evict(pod)
                        self._assume.release(_pod_key(pod))
                        pod = None
                        if attempt:
                            raise AllocationFailure(
                                f"no live pending pod on {self._node} "
                                f"requesting {pod_units} {const.RESOURCE_MEM}"
                            ) from None
                        pod = self._claim_pod(pod_units, refresh_first=True)
                        _adopt_pod_trace(pod)
                    except AllocationFailure:
                        # the PATCH conclusively failed — nothing persisted
                        with TRACER.span("wal.abort", child_only=True):
                            _journal_resolve(self._ckpt, key, "abort")
                        raise
            except AllocationFailure as e:
                # kubelet only logs the gRPC error; a Warning event on the
                # pod makes `kubectl describe pod` show why admission failed
                if pod is not None:
                    emit_pod_event(
                        self._api, pod, REASON_ALLOC_FAILED, str(e), host=self._node
                    )
                raise
        finally:
            # Success: the PATCHed copy is in the pod source (counted by
            # its own accounting, and ``note_pod_update`` landed before
            # this release — matchers re-verify candidates against the
            # live copy, so the released claim cannot re-open a re-match).
            # Failure: nothing was placed. Either way the claim must not
            # outlive this admission.
            if pod is not None:
                self._assume.release(_pod_key(pod))
        return placement, pod

    # ------------------------------------------------------------------

    def _chip_total(self, idx: int) -> int:
        return self._inv.units_of(self._inv.id_of_index(idx))

    def _claim_pod(self, pod_units: int, refresh_first: bool = False):
        """Match + claim the oldest unclaimed same-size pending pod, under
        this size's stripe lock (two same-size admissions serialize their
        match; different sizes proceed in parallel). Raises when nothing
        matches even after a refresh."""
        stripe = self._match_locks[pod_units % NUM_MATCH_STRIPES]
        with timed_acquire(
            stripe, LOCK_WAIT_METRIC, LOCK_WAIT_HELP, lock="match"
        ):
            refreshed = refresh_first
            if refresh_first:
                self._pods.refresh()
            while True:
                pod = self._match_pending_pod(pod_units)
                if pod is None and not refreshed:
                    # Cached sources may lag the scheduler's bind by a
                    # watch event; one synchronous refresh closes the
                    # window before we fail the admission.
                    refreshed = True
                    self._pods.refresh()
                    pod = self._match_pending_pod(pod_units)
                if pod is None:
                    raise AllocationFailure(
                        f"invalid allocation request: no pending pod on "
                        f"{self._node} requesting {pod_units} {const.RESOURCE_MEM}"
                    )
                # The stripe serializes same-size matches within this
                # allocator, but another instance sharing the ledger (the
                # core allocator on a dual-labeled ghost, or a rebuilt
                # plugin's allocator) can win the claim between our match
                # and here — losing means rescan, never proceed unowned.
                if self._assume.claim(_pod_key(pod)):
                    return pod

    def _match_pending_pod(self, pod_units: int):
        """Oldest pending share pod whose total limits equal the request
        (``allocate.go:51-61``), skipping pods another worker has claimed
        mid-admission. Candidates that pass the claim check are re-verified
        against the live cache copy (see ``_live_candidate``) — the
        snapshot may predate a concurrent worker's just-persisted
        assignment."""
        candidates = P.candidate_pods(
            self._pods.pending_share_pods(const.RESOURCE_MEM), self._node
        )
        log.v(4, "candidates: %s", [P.name(p) for p in candidates])
        for pod in candidates:
            if P.mem_units_of_pod(pod) == pod_units and not self._assume.is_claimed(
                _pod_key(pod)
            ):
                live = _live_candidate(
                    self._pods, pod, self._node, pod_units, const.RESOURCE_MEM
                )
                if live is not None:
                    return live
        return None

    def _place(self, pod, pod_units: int) -> tuple[int, dict[str, str]]:
        """Decide the chip (or gang slice) and the annotations to persist
        for one pod — dispatch only; the emitting verbs are
        :meth:`_place_mem` and :meth:`_place_gang`, each of which records
        a decision-provenance "why" on every outcome path."""
        if P.gang_shape_request(pod):
            return self._place_gang(pod, pod_units)
        return self._place_mem(pod, pod_units)

    def _dual_resource_guard(self, pod) -> None:
        if P.core_chips_of_pod(pod) > 0:
            raise AllocationFailure(
                f"pod {P.name(pod)} requests both {const.RESOURCE_MEM} and "
                f"{const.RESOURCE_CORE}; dual-resource pods are unsupported "
                "(the two allocators would race each other's assigned flag)"
            )

    def _place_mem(self, pod, pod_units: int) -> tuple[int, dict[str, str]]:
        """Single-chip placement.

        One ``chip_state()`` read serves both the usage accounting and the
        core-hold exclusion — O(chips) per placement with the informer's
        incremental index (the reference rescans every labeled pod per
        admission, ``podmanager.go:102-115``). Snapshot, overlay of
        in-flight reservations, decision, and this pod's own reservation
        are one ledger transaction, so the chip is protected the moment it
        is chosen — before the PATCH leaves the building."""
        pod_key = f"{P.namespace(pod)}/{P.name(pod)}"
        try:
            self._dual_resource_guard(pod)
            with self._assume.transaction():
                mem_used, core_held = self._assume.overlaid_state(
                    self._pods.chip_state,
                    visible_fn=lambda key: _counted_by_source(self._pods, key),
                )
                if P.is_assumed(pod) and not P.is_assigned(pod):
                    idx = self._assumed_chip(pod, core_held)
                    annotations = {const.ENV_ASSIGNED_FLAG: "true"}
                    assumed = True
                else:
                    idx = self._binpack_chip(pod_units, mem_used, core_held)
                    annotations = {
                        const.ENV_MEM_IDX: str(idx),
                        const.ENV_MEM_POD: str(pod_units),
                        const.ENV_MEM_DEV: str(self._chip_total(idx)),
                        const.ENV_ASSIGNED_FLAG: "true",
                    }
                    assumed = False
                self._assume.reserve_mem(_pod_key(pod), idx, pod_units)
        except AllocationFailure as e:
            # a refused admission deserves a "why" as much as a grant
            DECISIONS.emit(
                pod_key, "allocate", outcome="error",
                node=self._node, reason=str(e),
                trace_id=_current_trace_id(),
            )
            raise
        annotations[const.ENV_ASSUME_TIME] = str(time.time_ns())
        # Persist the NORMALIZED workload class with the decision: every
        # downstream reader (informer indexes, interference detector,
        # inspect CLI) then sees one canonical value even when the pod
        # declared nothing or garbage.
        annotations[const.ANN_WORKLOAD_CLASS] = P.workload_class(pod)
        if P.lora_adapter(pod):
            # Persist the stripped adapter id alongside the class so the
            # same PATCH carries the full serving identity of the pod.
            annotations[const.ANN_LORA_ADAPTER] = P.lora_adapter(pod)
        # Decision provenance: built from values the placement already
        # computed (the ledger snapshot and the chosen chip) — the
        # breakdown re-derives one chip's slack from numbers in hand.
        total = self._chip_total(idx)
        DECISIONS.emit(
            pod_key, "allocate",
            node=self._node,
            scores={f"chip{idx}": chip_breakdown(
                total - mem_used.get(idx, 0), total, idx, pod_units,
                self._policy,
            )},
            placement={
                "chip": idx, "units": pod_units,
                "source": "extender-assumed" if assumed else "binpack",
            },
            trace_id=_current_trace_id(),
        )
        return idx, annotations

    def _place_gang(self, pod, pod_units: int) -> tuple[GangPlacement, dict[str, str]]:
        """Gang placement: decide (or honor) the member chip set for a
        multi-chip pod and reserve EVERY member atomically.

        Branch A trusts the extender's persisted gang annotations (like
        the single-chip assumed path) after re-validating them against
        the live overlay — a core pod may have grabbed a member chip in
        the window. Branch B re-runs the topology scorer over the
        overlaid free vector. Either way the decision enters the ledger
        as one gang entry inside one transaction: a concurrent placement
        sees all member chips claimed or none, never a partial gang.
        """
        pod_key = f"{P.namespace(pod)}/{P.name(pod)}"
        slice_score = None
        free = {}
        try:
            self._dual_resource_guard(pod)
            shape_raw = P.gang_shape_request(pod)
            try:
                size = shape_size(shape_raw)
            except ValueError as e:
                raise AllocationFailure(
                    f"pod {P.name(pod)} has invalid gang shape "
                    f"{shape_raw!r}: {e}"
                ) from e
            if size < 1 or pod_units % size != 0:
                raise AllocationFailure(
                    f"pod {P.name(pod)}: {pod_units} {const.RESOURCE_MEM} units "
                    f"do not divide evenly over gang shape {shape_raw!r} "
                    f"({size} chips)"
                )
            per_chip = pod_units // size
            units_by_index = self._inv.units_by_index()
            with self._assume.transaction():
                mem_used, core_held = self._assume.overlaid_state(
                    self._pods.chip_state,
                    visible_fn=lambda key: _counted_by_source(self._pods, key),
                )
                excluded = set(self._unhealthy_fn()) | core_held
                assumed_chips = (
                    P.gang_chips_from_annotation(pod)
                    if P.is_assumed(pod) and not P.is_assigned(pod)
                    else []
                )
                if assumed_chips:
                    placement = self._assumed_gang(
                        pod, assumed_chips, per_chip, units_by_index,
                        mem_used, excluded,
                    )
                    annotations = {const.ENV_ASSIGNED_FLAG: "true"}
                else:
                    free = {
                        i: cap - mem_used.get(i, 0)
                        for i, cap in units_by_index.items()
                    }
                    scored = self._chip_topo.best_slice_scored(
                        shape_raw, free, per_chip,
                        capacity=units_by_index, excluded=excluded,
                    )
                    if scored is None:
                        raise AllocationFailure(
                            f"no {shape_raw} sub-slice with {per_chip} free "
                            f"units per chip on {self._node} "
                            f"(free: {free}, excluded: {sorted(excluded)})"
                        )
                    cand, slice_score = scored
                    placement = GangPlacement(
                        chips=cand.chips, shape=cand.shape, per_chip=per_chip
                    )
                    annotations = {
                        const.ENV_GANG_CHIPS: ",".join(str(i) for i in cand.chips),
                        const.ENV_GANG_SHAPE: format_shape(cand.shape),
                        const.ENV_GANG_PER_CHIP: str(per_chip),
                        const.ENV_MEM_POD: str(pod_units),
                        const.ENV_MEM_DEV: str(self._chip_total(cand.chips[0])),
                        const.ENV_ASSIGNED_FLAG: "true",
                    }
                self._assume.reserve_gang(
                    _pod_key(pod), [(i, per_chip) for i in placement.chips]
                )
        except AllocationFailure as e:
            DECISIONS.emit(
                pod_key, "allocate_gang", outcome="error",
                node=self._node, reason=str(e),
                trace_id=_current_trace_id(),
            )
            raise
        annotations[const.ENV_ASSUME_TIME] = str(time.time_ns())
        annotations[const.ANN_WORKLOAD_CLASS] = P.workload_class(pod)
        if P.lora_adapter(pod):
            annotations[const.ANN_LORA_ADAPTER] = P.lora_adapter(pod)
        # Decision provenance: branch B carries the winning slice's full
        # multi-objective breakdown (ICI hops, stranded slivers, broken
        # chips); branch A honors the extender's persisted decision, so
        # the slice score lives in the extender's own bind record.
        scores = {}
        if slice_score is not None:
            base = chip_breakdown(
                min(free[i] for i in placement.chips),
                max(units_by_index.values(), default=0),
                placement.chips[0], per_chip, "topology",
            )
            scores["slice"] = dataclasses.replace(
                base,
                ici_hops=slice_score.hops,
                stranded=slice_score.stranded,
                broken=slice_score.broken,
                tie_break=slice_score.tie_break,
            )
        DECISIONS.emit(
            pod_key, "allocate_gang",
            node=self._node,
            scores=scores,
            placement={
                "chips": list(placement.chips),
                "shape": format_shape(placement.shape),
                "per_chip": placement.per_chip,
                "source": "binpack" if slice_score is not None
                else "extender-assumed",
            },
            trace_id=_current_trace_id(),
        )
        return placement, annotations

    def _assumed_gang(
        self, pod, chips, per_chip, units_by_index, mem_used, excluded
    ) -> GangPlacement:
        """Branch A for gangs: honor the extender's member set, but
        re-validate every chip against the live overlay — all-or-nothing,
        so ONE bad member fails the whole gang (the kubelet retry re-runs
        placement from scratch)."""
        size = P.mem_units_of_pod(pod) // per_chip if per_chip else 0
        if len(chips) != size or len(set(chips)) != len(chips):
            # The annotation is user-writable: a truncated or duplicated
            # member list would book per_chip over the WRONG set (under-
            # reserving the claim, or stacking one chip twice) — reject
            # the whole gang rather than trust a garbled grant.
            raise AllocationFailure(
                f"pod {P.name(pod)} gang annotation lists chips {chips} "
                f"but the {P.mem_units_of_pod(pod)}-unit request at "
                f"{per_chip} units/chip needs {size} distinct members"
            )
        for idx in chips:
            if idx not in units_by_index:
                raise AllocationFailure(
                    f"pod {P.name(pod)} assumed onto unknown gang chip {idx}"
                )
            if idx in excluded:
                raise AllocationFailure(
                    f"pod {P.name(pod)} assumed onto gang chip {idx}, which "
                    "is core-held or unhealthy"
                )
            if mem_used.get(idx, 0) + per_chip > units_by_index[idx]:
                raise AllocationFailure(
                    f"pod {P.name(pod)} assumed onto gang chip {idx}, which "
                    f"no longer has {per_chip} free units"
                )
        try:
            shape = parse_shape(
                P.annotations(pod).get(const.ENV_GANG_SHAPE, "")
            )
            size_of_shape = 1
            for d in shape:
                size_of_shape *= d
            if size_of_shape != len(chips):
                # stale/tampered shape annotation: a carve-out that does
                # not match the member count would misconfigure libtpu at
                # container startup — degrade to a line over the members
                shape = (len(chips),)
        except ValueError:
            shape = (len(chips),)
        shape3 = pad3(shape)
        log.v(4, "extender gang placement for %s: chips %s", P.name(pod), chips)
        return GangPlacement(
            chips=tuple(sorted(chips)), shape=shape3, per_chip=per_chip
        )

    def _assumed_chip(self, pod, core_held: set[int]) -> int:
        """Branch A: trust the scheduler extender's placement."""
        idx = P.chip_idx_from_annotation(pod)
        if idx < 0 or idx not in self._inv.units_by_index():
            raise AllocationFailure(
                f"pod {P.name(pod)} assumed by extender but its "
                f"{const.ENV_MEM_IDX} annotation is invalid: {idx}"
            )
        if idx in core_held:
            raise AllocationFailure(
                f"pod {P.name(pod)} assumed onto chip {idx}, but that chip "
                f"is exclusively held by a {const.RESOURCE_CORE} pod"
            )
        log.v(4, "extender placement for %s: chip %d", P.name(pod), idx)
        return idx

    def _binpack_chip(
        self, pod_units: int, used: dict[int, int], core_held: set[int]
    ) -> int:
        """Branch B: first-fit over capacity minus apiserver-declared usage.

        Chips exclusively held by assigned tpu-core pods are excluded along
        with unhealthy ones — the two resources share one physical chip
        accounting (the reference's single-resource model, server.go:268-289,
        extended across both).
        """
        excluded = sorted(set(self._unhealthy_fn()) | core_held)
        try:
            return assign_chip(
                pod_units,
                self._inv.units_by_index(),
                used,
                unhealthy=excluded,
                policy=self._policy,
            )
        except Exception as e:
            raise AllocationFailure(str(e)) from e

    def _persist(self, pod, annotations: dict[str, str]) -> None:
        persist_pod_assignment(
            self._api, self._pods, pod, annotations,
            const.LABEL_RESOURCE_VALUE, patch_fn=self._patcher,
        )


class ClusterCoreAllocator:
    """Allocate() flow for the whole-chip ``tpu-core`` resource.

    Unlike tpu-mem, the granted device IDs *are* real chip ids (kubelet
    picks which chips, steered by GetPreferredAllocation), so placement is
    validation rather than binpack: every granted chip must be healthy,
    free of fractional-HBM usage, and not already core-held. The decision
    is persisted as the ``ENV_CORE_IDS`` annotation + the tpu-core label so
    restart re-derives exclusive holds from the apiserver and the mem
    binpack can exclude these chips (accounting model: ``server.go:268-289``
    extended across both resources).
    """

    def __init__(
        self,
        inventory: DeviceInventory,
        api: ApiServerClient,
        pod_source: PodSource,
        node_name: str,
        topology: Any = None,
        unhealthy_chips_fn: Callable[[], list[int]] | None = None,
        assume: AssumeCache | None = None,
        checkpoint: AllocationCheckpoint | None = None,
        patcher: Callable[[str, str, dict], dict] | None = None,
    ) -> None:
        self._inv = inventory
        self._api = api
        self._pods = pod_source
        self._node = node_name
        self._topo = topology
        self._unhealthy_fn = unhealthy_chips_fn or (lambda: [])
        # shared coalesced PATCH transport — see ClusterAllocator.__init__
        self._patcher = patcher
        # shared WAL with the mem allocator — see ClusterAllocator.__init__
        self._ckpt = checkpoint
        # shared with the mem allocator — see ClusterAllocator.__init__
        self._assume = assume if assume is not None else AssumeCache()
        self._match_locks = [make_lock("allocator.match") for _ in range(NUM_MATCH_STRIPES)]

    def allocate(self, granted: Sequence[Sequence[str]]) -> list[ContainerAllocation]:
        total = sum(len(ids) for ids in granted)
        try:
            per_container = [
                sorted(self._inv.index_of(cid) for cid in ids) for ids in granted
            ]
        except KeyError as e:
            raise AllocationFailure(f"granted unknown chip id: {e}") from e
        indices = sorted(i for ids in per_container for i in ids)
        log.v(4, "core Allocate: chips %s", indices)
        with TRACER.span(
            "allocator.admit",
            attributes={"resource": const.RESOURCE_CORE, "chips": indices},
        ) as asp:
            with _serial_guard(self._pods, self._assume):
                pod = self._admit(total, indices)
            asp.set_attribute("pod", f"{P.namespace(pod)}/{P.name(pod)}")
            log.info(
                "allocated core pod %s/%s: chips %s",
                P.namespace(pod), P.name(pod), indices,
            )
            with TRACER.span("allocator.env", child_only=True):
                chips_by_id = {c.id: c for c in self._inv.chips()}
                return [
                    build_core_allocation(
                        chips=[chips_by_id[self._inv.id_of_index(i)] for i in ids],
                        process_bounds=getattr(self._topo, "process_bounds", ""),
                        chips_per_process_bounds=getattr(
                            self._topo, "chips_per_process_bounds", ""
                        ),
                    )
                    for ids in per_container
                ]

    def _admit(self, total: int, indices: list[int]):
        """Match, validate+reserve, persist; -> the matched pod."""
        pod = self._claim_pod(total)
        _adopt_pod_trace(pod)
        try:
            try:
                # Validation runs per attempt: a pod re-matched after
                # _PodGone is a different pod and must clear the
                # dual-resource guard and the chip-conflict check itself
                # (mirrors the mem path re-running _place per attempt).
                for attempt in (0, 1):
                    if P.mem_units_of_pod(pod) > 0:
                        raise AllocationFailure(
                            f"pod {P.name(pod)} requests both "
                            f"{const.RESOURCE_MEM} and {const.RESOURCE_CORE}; "
                            "dual-resource pods are unsupported"
                        )
                    self._check_and_reserve(pod, indices)
                    annotations = {
                        const.ENV_CORE_IDS: ",".join(str(i) for i in indices),
                        const.ENV_CORE_POD: str(total),
                        const.ENV_ASSIGNED_FLAG: "true",
                        const.ENV_ASSUME_TIME: str(time.time_ns()),
                    }
                    key = _pod_key(pod)
                    with TRACER.span("wal.begin", child_only=True):
                        _journal_begin(self._ckpt, key, {
                            "kind": "core",
                            "ids": list(indices),
                            "units": total,
                            "annotations": annotations,
                        })
                    try:
                        with TRACER.span("pod.patch", child_only=True):
                            persist_pod_assignment(
                                self._api, self._pods, pod, annotations,
                                const.LABEL_CORE_VALUE, patch_fn=self._patcher,
                            )
                        FAULTS.fire("allocator.post_persist")
                        with TRACER.span("wal.commit", child_only=True):
                            _journal_resolve(self._ckpt, key, "commit")
                        break
                    except AllocationFailure:
                        with TRACER.span("wal.abort", child_only=True):
                            _journal_resolve(self._ckpt, key, "abort")
                        raise
                    except _PodGone:
                        with TRACER.span("wal.abort", child_only=True):
                            _journal_resolve(self._ckpt, key, "abort")
                        log.warning(
                            "core pod %s/%s vanished during persist; re-matching",
                            P.namespace(pod), P.name(pod),
                        )
                        self._pods.evict(pod)
                        self._assume.release(_pod_key(pod))
                        pod = None
                        if attempt:
                            # final attempt: no point refreshing a result
                            # we would discard (mirrors the mem path)
                            raise AllocationFailure(
                                f"no live pending pod on {self._node} requesting "
                                f"{total} {const.RESOURCE_CORE}"
                            ) from None
                        pod = self._claim_pod(total, refresh_first=True)
                        _adopt_pod_trace(pod)
            except AllocationFailure as e:
                if pod is not None:
                    emit_pod_event(
                        self._api, pod, REASON_ALLOC_FAILED, str(e), host=self._node
                    )
                raise
        finally:
            if pod is not None:
                self._assume.release(_pod_key(pod))
        return pod

    def _claim_pod(self, total: int, refresh_first: bool = False):
        """Match + claim under the size stripe (see ClusterAllocator)."""
        stripe = self._match_locks[total % NUM_MATCH_STRIPES]
        with timed_acquire(
            stripe, LOCK_WAIT_METRIC, LOCK_WAIT_HELP, lock="match"
        ):
            refreshed = refresh_first
            if refresh_first:
                self._pods.refresh()
            while True:
                pod = self._match_pending_pod(total)
                if pod is None and not refreshed:
                    refreshed = True
                    self._pods.refresh()
                    pod = self._match_pending_pod(total)
                if pod is None:
                    raise AllocationFailure(
                        f"invalid allocation request: no pending pod on "
                        f"{self._node} requesting {total} {const.RESOURCE_CORE}"
                    )
                # lost claim race to another instance -> rescan, see
                # ClusterAllocator._claim_pod
                if self._assume.claim(_pod_key(pod)):
                    return pod

    def _match_pending_pod(self, total: int):
        candidates = P.candidate_pods(
            self._pods.pending_share_pods(const.RESOURCE_CORE),
            self._node,
            resource=const.RESOURCE_CORE,
        )
        for pod in candidates:
            if P.core_chips_of_pod(pod) == total and not self._assume.is_claimed(
                _pod_key(pod)
            ):
                live = _live_candidate(
                    self._pods, pod, self._node, total, const.RESOURCE_CORE
                )
                if live is not None:
                    return live
        return None

    def _check_and_reserve(self, pod, indices: list[int]) -> None:
        """Every granted chip must be free of other holds (in-flight
        reservations included) and healthy; passing chips are reserved in
        the same ledger transaction so a concurrent mem binpack excludes
        them before this pod's PATCH lands."""
        with self._assume.transaction():
            mem_used, core_held = self._assume.overlaid_state(
                self._pods.chip_state,
                visible_fn=lambda key: _counted_by_source(self._pods, key),
            )
            unhealthy = set(self._unhealthy_fn())
            for idx in indices:
                if idx in core_held:
                    raise AllocationFailure(
                        f"chip {idx} is already exclusively held by another "
                        f"{const.RESOURCE_CORE} pod"
                    )
                if mem_used.get(idx, 0) > 0:
                    raise AllocationFailure(
                        f"chip {idx} has {mem_used[idx]} {const.RESOURCE_MEM} units "
                        "in use by fractional pods; cannot grant exclusively"
                    )
                if idx in unhealthy:
                    raise AllocationFailure(f"chip {idx} is unhealthy")
            self._assume.reserve_core(_pod_key(pod), indices)


def cluster_chip_state(
    pod_source: PodSource, assume: AssumeCache | None = None
) -> Callable[[], tuple[dict[int, int], set[int]]]:
    """() -> (mem_used_by_chip, core_held_chips) from one source read,
    with in-flight reservations folded in when the allocators' shared
    ledger is supplied (GetPreferredAllocation should steer kubelet away
    from chips a concurrent Allocate is mid-claiming, too)."""
    if assume is None:
        return pod_source.chip_state

    def state():
        return assume.overlaid_state(
            pod_source.chip_state,
            visible_fn=lambda key: _counted_by_source(pod_source, key),
        )

    return state


def preferred_core_chips(
    inventory: DeviceInventory,
    state_fn: Callable[[], tuple[dict[int, int], set[int]]],
) -> Callable[[list[str], int], list[str]]:
    """GetPreferredAllocation hook for the core plugin: steer kubelet toward
    chips with no fractional-HBM usage and no existing exclusive hold, so
    core grants rarely conflict with the mem binpack.

    ``state_fn() -> (mem_used_by_chip, core_held_chips)`` — cluster mode
    passes ``cluster_chip_state(pod_source)``, standalone mode the
    LocalAllocator's in-process view; the ranking policy lives here once.
    """

    def prefer(available_ids: list[str], size: int) -> list[str]:
        try:
            mem_used, core_held = state_fn()
        except Exception as e:  # noqa: BLE001 — preference only, never fail
            log.warning("preferred-allocation state read failed: %s", e)
            mem_used, core_held = {}, set()

        def rank(cid: str):
            idx = inventory.index_of(cid)
            return (idx in core_held, mem_used.get(idx, 0), idx)

        return sorted(available_ids, key=rank)[:size]

    return prefer
