"""Write-ahead allocation journal: crash-safe record of in-flight PATCHes.

The apiserver is the database (SURVEY.md section 5) — but the Allocate
flow has a window where the truth is *in flight*: the chip decision is
made and the annotation PATCH is on the wire, yet nothing durable on this
node records it. A daemon killed inside that window restarts with a cold
ledger; until the informer resyncs, a concurrent admission can binpack
onto state that silently omits the just-persisted pod. This journal
closes the window with classic WAL discipline:

1. **begin** — appended and fsync'd *before* the PATCH leaves the node:
   pod key + the exact decision (chip index / chip ids, units, the
   annotations about to be written).
2. **commit** — appended after the PATCH response was processed and the
   pod source counts the pod.
3. **abort** — appended when the admission fails before persisting
   anything (binpack conflict, pod deleted, PATCH refused).

A restarted daemon replays every begun-but-unresolved entry as a ledger
reservation (``replay_checkpoint``) — conservative: the chip is protected
whether or not the PATCH landed — and the drift reconciler
(``cluster/reconciler.py``) then resolves each entry against the
apiserver: annotation present -> the PATCH won, retro-commit; absent ->
nothing persisted, retro-abort. Either way the reservation is released
and capacity converges to exactly what annotations say.

File format: JSON lines (``{"op": "header"|"begin"|"commit"|"abort", ...}``),
append-only between compactions. A torn final line (crash mid-append) is
detected and ignored on load. Compaction rewrites the file to a header
plus the live ``begin`` records via atomic rename.

Fencing: the header carries a **generation**, bumped on every open. In
cluster mode the daemon stamps its generation into a node annotation
(``acquire_fence``); a stale duplicate daemon — two instances racing
during a botched DaemonSet rollout — observes a higher generation on the
node (``verify_fence``, run by the reconciler each pass) and refuses
further journal begins, which the allocator maps to admission failure.
The newest daemon always wins; the loser can only read.

Durability modes (``fsync=``, the daemon's ``--wal-fsync`` flag):

- ``always`` — the original discipline: every record is appended and
  fsync'd synchronously under the journal lock before the call returns.
- ``batch`` (default) — **group commit**: records are handed to a
  dedicated writer thread; one ``flush+fsync`` covers everything queued
  since the last sync, and each ``begin``/``commit``/``abort`` caller
  blocks on a per-batch ticket until *its* bytes are durable. The
  durability invariant is unchanged — no caller proceeds past ``begin``
  until its record is on disk — only the fsync count is amortized across
  concurrent admissions. A record that was batched but never fsync'd when
  the process died is simply absent (or a torn tail) at the next load,
  which the torn-tail-tolerant loader already replays correctly: the
  caller never acted on it, so nothing was lost.

Record kinds: ``"mem"`` / ``"core"`` / ``"gang"`` journal one admission's
chip decision; ``"move"`` journals a live-defragmentation move
(``allocator/defrag.py``) — the same key carries a fresh ``begin`` per
protocol phase (``plan -> drain -> copy -> switch -> resume``, the loader
keeps the newest record), replays as a destination-chip reservation, and
resolves by phase (roll forward past ``switch``, roll back before it).

Fault points ``checkpoint.begin|commit|abort`` fire immediately *after*
each record is durable, giving the restart-recovery suite its
``crash_after:<site>`` boundaries (see utils/faults.py). Two more sit at
the group-commit batch boundaries: ``checkpoint.wal_queue`` fires after a
record is queued but *before* its durability wait (a crash there = the
batched-but-never-fsynced record, which must replay as absent), and
``checkpoint.batch_fsync`` fires in the writer immediately after a batch
becomes durable (a crash there kills every caller of that batch with the
records already on disk).
"""

from __future__ import annotations

import json
import os
import time
from typing import Any

from ..utils.batch import GroupBatcher
from ..utils.faults import FAULTS
from ..utils.log import get_logger
from ..utils.metrics import REGISTRY
from ..utils.tracing import TRACER
from .assume import AssumeCache, PodKey
from ..utils.lockrank import make_lock, make_rlock
from ..utils.metric_catalog import CHECKPOINT_REPLAYED_TOTAL
from ..utils.metric_catalog import (
    CHECKPOINT_APPENDS_TOTAL as JOURNAL_APPENDS,
    CHECKPOINT_ERRORS_TOTAL as JOURNAL_ERRORS,
    CHECKPOINT_FENCED as FENCE_GAUGE,
    CHECKPOINT_FSYNC_SECONDS as FSYNC_SECONDS,
    CHECKPOINT_WAL_BATCH_RECORDS as BATCH_RECORDS,
)

log = get_logger("allocator.checkpoint")

JOURNAL_APPENDS_HELP = "Checkpoint journal records appended, by op"
JOURNAL_ERRORS_HELP = (
    "Checkpoint journal I/O failures (the daemon degrades to unjournaled "
    "operation rather than refusing admissions on a sick disk)"
)
FENCE_GAUGE_HELP = (
    "1 when this daemon observed a newer generation on the node and "
    "refuses journal writes (a stale duplicate instance)"
)
FSYNC_SECONDS_HELP = (
    "WAL flush+fsync latency; the count is the fsync count — divide by "
    "admissions for fsyncs-per-admission (group commit drives it below 1)"
)
BATCH_RECORDS_HELP = (
    "Journal records made durable per fsync (group-commit batch-size "
    "distribution; always-mode fsyncs observe 1)"
)
# Batch-size buckets (records per fsync), not latencies.
BATCH_RECORDS_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0)
# Default group-commit gather window. Callers see at most this much added
# latency per record (typically window/4 — the writer drains early once
# arrivals go quiet); a 16-way admission storm fills it and amortizes one
# fsync across the whole batch.
DEFAULT_BATCH_WINDOW_S = 0.002
WAL_FSYNC_MODES = ("always", "batch")

# Resolved (committed/aborted) records tolerated in the file before the
# journal is rewritten down to header + live begins.
COMPACT_EVERY = 512


class StaleDaemonError(RuntimeError):
    """This daemon's fencing generation was superseded on the node: a newer
    instance owns allocation now. Writes must be refused — two writers
    journaling against one node double-book chips."""


class AllocationCheckpoint:
    def __init__(
        self,
        path: str,
        fsync: str = "batch",
        batch_window_s: float = DEFAULT_BATCH_WINDOW_S,
    ) -> None:
        if fsync not in WAL_FSYNC_MODES:
            raise ValueError(f"unknown wal fsync mode: {fsync!r}")
        self._path = path
        self._fsync_mode = fsync
        self._lock = make_rlock("checkpoint.journal")
        # File-handle discipline: the group-commit writer thread appends
        # while callers mutate in-memory state under self._lock, and
        # compaction swaps the file out from under both — every open/
        # write/fsync/swap happens under this dedicated I/O lock (never
        # held while waiting for self._lock, so no ordering cycle).
        self._io_lock = make_lock("checkpoint.io")
        self._writer: GroupBatcher | None = None
        if fsync == "batch":
            self._writer = GroupBatcher(
                self._write_batch,
                window_s=batch_window_s,
                name="wal-writer",
                on_batch=self._note_batch,
            )
        self._entries: dict[PodKey, dict] = {}  # begun, unresolved
        self._generation = 0
        # Incarnation token: the fencing tie-breaker. Two daemons racing a
        # rollout can GET-then-PATCH the same generation onto the node
        # (the PATCH carries no resourceVersion precondition); with equal
        # generations neither would fence on the number alone. The token
        # makes the node annotation name one exact incarnation — last
        # writer wins, the other observes a foreign token and fences.
        self._token = os.urandom(6).hex()
        self._fenced = False
        self._resolved_since_compact = 0
        self._compactions = 0  # guards resolve-record-vs-compaction races
        self._seq = 0  # monotonically stamps each begin (see begin())
        self._f = None
        self._lockf = None
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        self._acquire_file_lock()
        self._load()
        # every open is a new incarnation: bump the generation and compact
        # so the header on disk names it before any new begin rides on it
        self._generation += 1
        self._compact()
        REGISTRY.gauge_set(FENCE_GAUGE, 0.0, FENCE_GAUGE_HELP)

    def _acquire_file_lock(self) -> None:
        """Best-effort flock on a sidecar: two live processes appending and
        compacting one WAL would corrupt it. Advisory only — the fencing
        token is the correctness mechanism for allocation writes; this
        just makes the shared-file mistake loud instead of silent."""
        try:
            import fcntl

            self._lockf = open(self._path + ".lock", "wb")
            fcntl.flock(self._lockf.fileno(), fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            log.error(
                "checkpoint %s is locked by another live process — two "
                "daemon instances sharing one WAL file will corrupt it; "
                "continuing, relying on the fencing token", self._path,
            )
            if self._lockf is not None:
                try:
                    self._lockf.close()
                except OSError:
                    pass
                self._lockf = None
        except ImportError:
            self._lockf = None

    # --- introspection ----------------------------------------------------

    @property
    def path(self) -> str:
        return self._path

    @property
    def generation(self) -> int:
        with self._lock:
            return self._generation

    @property
    def fenced(self) -> bool:
        with self._lock:
            return self._fenced

    @property
    def last_seq(self) -> int:
        """The newest begin's sequence stamp (0 before any begin). The
        shard-map CLI reports it per shard as the cheapest 'how far has
        this WAL advanced' signal."""
        with self._lock:
            return self._seq

    def pending(self) -> dict[PodKey, dict]:
        """Begun-but-unresolved entries (the replay set)."""
        with self._lock:
            return {k: dict(v) for k, v in self._entries.items()}

    # --- load / persist ---------------------------------------------------

    def _load(self) -> None:
        if not os.path.exists(self._path):
            return
        try:
            with open(self._path, "rb") as f:
                raw = f.read()
        except OSError as e:
            log.warning("checkpoint read failed (%s); starting empty", e)
            return
        lines = raw.split(b"\n")
        for i, line in enumerate(lines):
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                # a torn final line is the expected crash artifact; a torn
                # middle line means external corruption — skip either way,
                # the WAL invariant (begin precedes PATCH) still holds for
                # every record that did land intact
                log.warning(
                    "checkpoint: dropping unparseable line %d%s",
                    i + 1,
                    " (torn tail)" if i == len(lines) - 1 else "",
                )
                continue
            op = rec.get("op")
            if op == "header":
                try:
                    self._generation = max(
                        self._generation, int(rec.get("generation", 0))
                    )
                except (TypeError, ValueError):
                    pass
            elif op == "begin":
                key = rec.get("key") or []
                if len(key) == 2:
                    data = dict(rec.get("data") or {})
                    try:
                        self._seq = max(self._seq, int(data.get("_seq", 0)))
                    except (TypeError, ValueError):
                        data.pop("_seq", None)
                    self._entries[(str(key[0]), str(key[1]))] = data
            elif op in ("commit", "abort"):
                key = rec.get("key") or []
                if len(key) == 2:
                    self._entries.pop((str(key[0]), str(key[1])), None)

    def _open_append(self):
        """Caller must hold self._io_lock."""
        if self._f is None:
            self._f = open(self._path, "ab")
        return self._f

    @staticmethod
    def _encode(rec: dict) -> bytes:
        return json.dumps(rec, separators=(",", ":")).encode() + b"\n"

    def _fsync_observe(self, seconds: float) -> None:
        REGISTRY.observe(
            FSYNC_SECONDS, seconds, FSYNC_SECONDS_HELP, mode=self._fsync_mode
        )

    def _note_batch(self, n: int) -> None:
        REGISTRY.observe(
            BATCH_RECORDS, float(n), BATCH_RECORDS_HELP,
            buckets=BATCH_RECORDS_BUCKETS, mode=self._fsync_mode,
        )

    def _write_batch(self, payloads: list[bytes]) -> None:
        """Group-commit flush (writer thread): one write + one fsync for
        every record queued since the last sync. Compaction may have
        swapped the file meanwhile — the append handle is (re)opened under
        the I/O lock, so the batch always lands in the live journal,
        *after* the compacted snapshot (a duplicate begin or an
        already-resolved commit replays as a no-op)."""
        with self._io_lock:
            f = self._open_append()
            t0 = time.perf_counter()
            f.write(b"".join(payloads))
            f.flush()
            os.fsync(f.fileno())
        self._fsync_observe(time.perf_counter() - t0)
        FAULTS.fire("checkpoint.batch_fsync")

    def _append_always(self, payload: bytes) -> None:
        """Synchronous per-record append (``always`` mode). Caller must
        hold self._lock; durable before return."""
        with self._io_lock:
            f = self._open_append()
            t0 = time.perf_counter()
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        self._fsync_observe(time.perf_counter() - t0)
        self._note_batch(1)

    def _compact(self) -> None:
        """Caller must hold self._lock (or be the constructor). Rewrite the
        journal to header + live begins via atomic rename, so a crash
        mid-compaction leaves the old file intact. Safe to run while the
        group-commit writer has a batch queued: the snapshot covers every
        entry the queued records would establish, and the writer appends
        them after the swap — harmless duplicates on replay."""
        tmp = self._path + ".tmp"
        with self._io_lock:
            with open(tmp, "wb") as f:
                f.write(
                    self._encode(
                        {"op": "header", "generation": self._generation}
                    )
                )
                for key, data in self._entries.items():
                    f.write(
                        self._encode(
                            {"op": "begin", "key": list(key), "data": data}
                        )
                    )
                f.flush()
                os.fsync(f.fileno())
            if self._f is not None:
                try:
                    self._f.close()
                except OSError:
                    pass
                self._f = None
            os.replace(tmp, self._path)
        parent = os.path.dirname(self._path) or "."
        try:
            dirfd = os.open(parent, os.O_RDONLY)
            try:
                os.fsync(dirfd)
            finally:
                os.close(dirfd)
        except OSError:
            pass  # platform without dir fsync — rename is still atomic
        self._resolved_since_compact = 0
        self._compactions += 1

    def compact(self) -> None:
        """Rewrite the journal down to header + live begins now."""
        with self._lock:
            self._compact()

    # --- journal ops ------------------------------------------------------

    def begin(self, key: PodKey, data: dict) -> int | None:
        """Journal an in-flight decision; MUST precede the PATCH. Raises
        ``StaleDaemonError`` when fenced; I/O failures degrade to
        unjournaled operation (logged + counted, ``None`` returned) — a
        full disk must not take pod admission down with it.

        Each begin gets a monotonic ``_seq`` stamp (persisted with the
        record, returned to the caller): ``commit``/``abort`` with
        ``seq`` only resolve the exact incarnation of the entry the
        caller saw, so a resolver racing a fresh same-key begin — the
        reconciler, or the extender's deferred expired-overlay aborts —
        cannot pop the new entry."""
        ticket = None
        with self._lock:
            if self._fenced:
                raise StaleDaemonError(
                    f"generation {self._generation} superseded on the node"
                )
            self._seq += 1
            seq = self._seq
            data = dict(data)
            data["_seq"] = seq
            payload = self._encode({"op": "begin", "key": list(key), "data": data})
            if self._writer is None:
                try:
                    self._append_always(payload)
                except OSError as e:
                    log.warning("checkpoint begin append failed: %s", e)
                    REGISTRY.counter_inc(
                        JOURNAL_ERRORS, JOURNAL_ERRORS_HELP, op="begin"
                    )
                    return None
                self._entries[key] = data
            else:
                try:
                    ticket = self._writer.submit(payload)
                except RuntimeError as e:  # writer stopped (shutdown race)
                    log.warning("checkpoint begin submit failed: %s", e)
                    REGISTRY.counter_inc(
                        JOURNAL_ERRORS, JOURNAL_ERRORS_HELP, op="begin"
                    )
                    return None
                self._entries[key] = data
        if ticket is not None:
            # crash site: the record is queued but NOT yet durable — a
            # death here must replay as if begin never happened
            FAULTS.fire("checkpoint.wal_queue")
            try:
                # The group-commit gather window as a child span of the
                # admission's wal.begin: a trace shows exactly how much
                # of an admission's latency was spent waiting for its
                # batch's fsync (no-op outside a sampled trace).
                with TRACER.span("wal.batch_wait", child_only=True):
                    ticket.wait()
            except (OSError, RuntimeError) as e:
                # the batch fsync failed (sick disk): degrade to
                # unjournaled operation like the always path does
                log.warning("checkpoint begin group-commit failed: %s", e)
                REGISTRY.counter_inc(
                    JOURNAL_ERRORS, JOURNAL_ERRORS_HELP, op="begin"
                )
                with self._lock:
                    if self._entries.get(key) is data:
                        self._entries.pop(key, None)
                return None
        REGISTRY.counter_inc(JOURNAL_APPENDS, JOURNAL_APPENDS_HELP, op="begin")
        FAULTS.fire("checkpoint.begin")
        return seq

    def commit(self, key: PodKey, seq: int | None = None) -> bool:
        resolved = self._resolve("commit", key, seq)
        FAULTS.fire("checkpoint.commit")
        return resolved

    def abort(self, key: PodKey, seq: int | None = None) -> bool:
        resolved = self._resolve("abort", key, seq)
        FAULTS.fire("checkpoint.abort")
        return resolved

    def _resolve(self, op: str, key: PodKey, seq: int | None = None) -> bool:
        """The entry leaves ``pending()`` only once its resolve record is
        durable — exactly the ``always``-mode ordering — so a reader that
        observes the entry gone can rely on the record surviving a crash."""
        ticket = None
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                return False  # unjournaled admission (degraded mode)
            if seq is not None and entry.get("_seq") != seq:
                return False  # a newer begin owns this key now
            payload = self._encode({"op": op, "key": list(key)})
            if self._writer is None:
                try:
                    self._append_always(payload)
                except OSError as e:
                    log.warning("checkpoint %s append failed: %s", op, e)
                    REGISTRY.counter_inc(JOURNAL_ERRORS, JOURNAL_ERRORS_HELP, op=op)
                    return False
                self._entries.pop(key, None)
                self._resolved_since_compact += 1
                if self._resolved_since_compact >= COMPACT_EVERY:
                    try:
                        self._compact()
                    except OSError as e:
                        log.warning("checkpoint compaction failed: %s", e)
            else:
                try:
                    ticket = self._writer.submit(payload)
                except RuntimeError as e:
                    log.warning("checkpoint %s submit failed: %s", op, e)
                    REGISTRY.counter_inc(JOURNAL_ERRORS, JOURNAL_ERRORS_HELP, op=op)
                    return False
                compactions_at_submit = self._compactions
        if ticket is not None:
            while True:
                try:
                    with TRACER.span("wal.batch_wait", child_only=True):
                        ticket.wait()
                except (OSError, RuntimeError) as e:
                    # The resolve record may never hit disk: the entry
                    # stays pending, replays as unresolved at restart, and
                    # the reconciler re-resolves it — conservative, never
                    # lossy.
                    log.warning("checkpoint %s group-commit failed: %s", op, e)
                    REGISTRY.counter_inc(JOURNAL_ERRORS, JOURNAL_ERRORS_HELP, op=op)
                    return False
                with self._lock:
                    if self._entries.get(key) is entry:
                        self._entries.pop(key, None)
                    if self._compactions == compactions_at_submit:
                        self._resolved_since_compact += 1
                        compact_due = (
                            self._resolved_since_compact >= COMPACT_EVERY
                        )
                        break
                    # A compaction ran while our durable resolve record was
                    # in flight: its snapshot still carried the entry (the
                    # pop above is what excludes it from future snapshots)
                    # and os.replace dropped the record with the old file.
                    # Re-append it after the compacted snapshot so "gone
                    # from pending()" keeps implying "resolve survives a
                    # crash". The entry is popped now, so one more pass
                    # converges.
                    compactions_at_submit = self._compactions
                    try:
                        ticket = self._writer.submit(payload)
                    except RuntimeError as e:
                        log.warning(
                            "checkpoint %s re-append failed: %s", op, e
                        )
                        REGISTRY.counter_inc(
                            JOURNAL_ERRORS, JOURNAL_ERRORS_HELP, op=op
                        )
                        return False
            if compact_due:
                try:
                    self.compact()
                except OSError as e:
                    log.warning("checkpoint compaction failed: %s", e)
        REGISTRY.counter_inc(JOURNAL_APPENDS, JOURNAL_APPENDS_HELP, op=op)
        return True

    def flush(self, timeout_s: float | None = 5.0) -> bool:
        """Durability barrier: every record handed to the journal so far is
        on disk when this returns True. One path for both modes —
        ``always`` already fsyncs per record (nothing to do), ``batch``
        drains the group-commit writer. This is the writer's own flush;
        there is no side-channel file flush for callers to bypass its
        locking with. False (logged + counted) when the writer could not
        drain within ``timeout_s`` — a wedged disk at shutdown must not
        masquerade as a clean flush."""
        if self._writer is None:
            return True
        drained = self._writer.flush(timeout=timeout_s)
        if not drained:
            log.error(
                "checkpoint flush did not drain within %.1fs — queued "
                "records may not be durable", timeout_s or 0.0,
            )
            REGISTRY.counter_inc(
                JOURNAL_ERRORS, JOURNAL_ERRORS_HELP, op="flush"
            )
        return drained

    def close(self) -> None:
        self.flush()
        if self._writer is not None:
            self._writer.stop()
        with self._lock:
            with self._io_lock:
                if self._f is not None:
                    try:
                        self._f.close()
                    except OSError:
                        pass
                    self._f = None
            if self._lockf is not None:
                try:
                    self._lockf.close()  # releases the flock
                except OSError:
                    pass
                self._lockf = None

    def abandon(self) -> None:
        """Test hook: simulate SIGKILL. Queued-but-unfsynced records are
        discarded (exactly what process death does to them) and the file
        handles drop without any flush."""
        if self._writer is not None:
            self._writer.kill()
        with self._lock, self._io_lock:
            if self._f is not None:
                try:
                    self._f.close()
                except OSError:
                    pass
                self._f = None
            if self._lockf is not None:
                try:
                    self._lockf.close()
                except OSError:
                    pass
                self._lockf = None

    # --- fencing ----------------------------------------------------------

    def acquire_fence(self, api: Any, node_name: str) -> int:
        """Claim allocation ownership of the node: ensure our generation
        exceeds whatever the node annotation records, then stamp
        ``<generation>:<incarnation token>``. Called at every (re)build;
        any instance that acquires later gets a generation at least as
        high and — PATCH order being the tie-breaker via the token — the
        last writer owns the node and everyone else fences."""
        from .. import const

        node = api.get_node(node_name)
        node_gen, _tok = _node_fence(node)
        with self._lock:
            if node_gen >= self._generation:
                self._generation = node_gen + 1
                self._compact()  # the header must name the new generation
            gen = self._generation
            self._fenced = False
        # coalesced node PATCH when the client offers it: two plugins
        # (mem + core) re-acquiring on one rebuild merge into one request
        patch_node = getattr(api, "patch_node_merged", None) or api.patch_node
        patch_node(
            node_name,
            {"metadata": {"annotations": {
                const.ANN_FENCE_GENERATION: f"{gen}:{self._token}"
            }}},
        )
        REGISTRY.gauge_set(FENCE_GAUGE, 0.0, FENCE_GAUGE_HELP)
        log.info(
            "fence acquired: node %s generation=%d token=%s",
            node_name, gen, self._token,
        )
        return gen

    def verify_fence(self, api: Any, node_name: str) -> bool:
        """True while this daemon still owns the node. Fences on a newer
        generation OR an equal generation under a foreign token (two
        instances raced the non-CAS acquire to the same number; the last
        PATCH writer owns it) — run by the reconciler every pass."""
        node = api.get_node(node_name)
        node_gen, node_tok = _node_fence(node)
        with self._lock:
            superseded = node_gen > self._generation or (
                node_gen == self._generation
                and node_tok not in ("", self._token)
            )
            if superseded:
                if not self._fenced:
                    log.error(
                        "FENCED: node fence %d:%s vs ours %d:%s — another "
                        "daemon instance owns allocation; refusing writes",
                        node_gen, node_tok, self._generation, self._token,
                    )
                self._fenced = True
            ok = not self._fenced
        REGISTRY.gauge_set(
            FENCE_GAUGE, 0.0 if ok else 1.0, FENCE_GAUGE_HELP
        )
        return ok


def _node_fence(node: dict) -> tuple[int, str]:
    """Parse the ``<generation>[:<token>]`` node annotation."""
    from .. import const

    ann = node.get("metadata", {}).get("annotations") or {}
    raw = str(ann.get(const.ANN_FENCE_GENERATION, "0"))
    gen_s, _, token = raw.partition(":")
    try:
        return int(gen_s), token
    except (TypeError, ValueError):
        return 0, token


def replay_checkpoint(ckpt: AllocationCheckpoint, assume: AssumeCache) -> int:
    """Re-install every unresolved journal entry as a ledger reservation.

    Conservative by design: whether the crashed PATCH landed or not, the
    chip is protected until the reconciler resolves the entry against the
    apiserver. An admission placed during the replay-to-reconcile window
    sees the reservation through the usual overlay and routes around it —
    it can under-pack briefly, never double-book. No claims are taken:
    the crashed admission's kubelet RPC died with the old process, and a
    retried Allocate for the same pod must be free to re-match it.
    """
    n = 0
    for key, data in ckpt.pending().items():
        kind = data.get("kind")
        if kind == "mem":
            try:
                assume.reserve_mem(key, int(data["idx"]), int(data["units"]))
            except (KeyError, TypeError, ValueError):
                log.warning("checkpoint replay: malformed mem entry for %s", key)
                continue
        elif kind == "core":
            ids = data.get("ids") or []
            try:
                assume.reserve_core(key, [int(i) for i in ids])
            except (TypeError, ValueError):
                log.warning("checkpoint replay: malformed core entry for %s", key)
                continue
        elif kind == "gang":
            # one atomic gang entry: every member chip replays protected
            # together (a partial replay would be exactly the stranded
            # sliver the gang protocol forbids)
            try:
                per = int(data["per_chip"])
                members = [(int(i), per) for i in (data.get("chips") or [])]
                assume.reserve_gang(key, members)
            except (KeyError, TypeError, ValueError):
                log.warning("checkpoint replay: malformed gang entry for %s", key)
                continue
        elif kind == "move":
            # a defragmentation move died mid-protocol: protect the
            # DESTINATION chip until the reconciler rolls the move forward
            # or back (allocator/defrag.py). The source stays protected by
            # the moving pod's own annotation — before the switch PATCH it
            # still names the source chip; after it, counting the
            # destination twice is conservative over-reservation, never a
            # double-booking.
            try:
                assume.reserve_mem(key, int(data["dst"]), int(data["units"]))
            except (KeyError, TypeError, ValueError):
                log.warning("checkpoint replay: malformed move entry for %s", key)
                continue
        elif kind == "handoff":
            # a prefill->decode KV handoff died mid-protocol
            # (serving/handoffproto.py). Nothing to re-install in the
            # chip ledger: the destination pages live inside the decode
            # engine's own refcounted page pool (its import ledger holds
            # or releases them), not in per-chip HBM accounting. The
            # entry itself stays pending — that IS the protection — and
            # the reconciler resolves it by phase: roll forward
            # (re-deliver idempotently by handoff id) at or past
            # "import", roll back to a local re-prefill before it.
            pass
        elif kind == "scale":
            # a fleet scale-down died mid-protocol (serving/router.py).
            # Nothing to re-install in the chip ledger: the drained
            # requests and snapshot live inside the journal record
            # itself and the engines' own refcounted page pools. The
            # entry stays pending — that IS the protection — and the
            # reconciler resolves it by phase: roll forward (re-deliver
            # the snapshot to a survivor, idempotent by snapshot_id) at
            # or past "migrate", roll back (un-cordon or re-queue the
            # journaled rows) before it.
            pass
        else:
            log.warning("checkpoint replay: unknown entry kind %r for %s", kind, key)
            continue
        n += 1
        log.info("replayed in-flight %s reservation for %s/%s", kind, *key)
    if n:
        REGISTRY.counter_inc(
            CHECKPOINT_REPLAYED_TOTAL,
            "In-flight journal entries re-installed as ledger reservations "
            "at daemon (re)start",
            value=float(n),
        )
    return n
