"""Container payload builder: the env/devices a granted pod receives.

TPU analog of the reference's response assembly (``allocate.go:109-124``):
where the reference injects ``NVIDIA_VISIBLE_DEVICES=<idx>`` plus the
``ALIYUN_COM_GPU_MEM_*`` family, a TPU pod needs

- ``TPU_VISIBLE_CHIPS``            — which local chip(s) the process may use
- ``TPU_PROCESS_BOUNDS`` /
  ``TPU_CHIPS_PER_PROCESS_BOUNDS`` — single-process topology carve-out
- the ``ALIYUN_COM_TPU_MEM_*`` bookkeeping family (idx/pod/container/dev)
- a cooperative HBM cap (``XLA_PYTHON_CLIENT_MEM_FRACTION``) because TPU
  HBM, like GPU memory in the reference, has no hardware fence; disabled
  via the node label analog of cGPU's toggle (``podmanager.go:59-72``)

and, unlike the reference (which never used the proto's ``devices`` field),
an explicit ``DeviceSpec`` for ``/dev/accel<idx>`` so the container can open
the chip without privileged mode.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

from .. import const
from ..discovery.base import TpuChip


@dataclasses.dataclass
class DeviceMount:
    container_path: str
    host_path: str
    permissions: str = "rw"


@dataclasses.dataclass
class ContainerAllocation:
    """One container's allocation payload (maps 1:1 onto the proto)."""

    envs: dict[str, str] = dataclasses.field(default_factory=dict)
    devices: list[DeviceMount] = dataclasses.field(default_factory=list)
    annotations: dict[str, str] = dataclasses.field(default_factory=dict)


def visible_chips_value(chip_indices: Sequence[int]) -> str:
    return ",".join(str(i) for i in sorted(chip_indices))


def build_mem_allocation(
    *,
    chip: TpuChip,
    chip_total_units: int,
    pod_units: int,
    container_units: int,
    disable_isolation: bool = False,
    workload_class: str = "",
    lora_adapter: str = "",
) -> ContainerAllocation:
    """Payload for a fractional-HBM container pinned to one chip.

    ``workload_class`` (the pod's normalized QoS class) is mirrored into
    the container env so the workload inside — the serving engine's
    governor, a training loop deciding to self-pace — knows which side
    of the interference plane it is on. ``lora_adapter`` (the pod's
    requested adapter id, empty for the base model) rides along the same
    way so the serving engine can tag its requests without re-reading
    pod annotations."""
    envs = {
        const.ENV_TPU_VISIBLE_CHIPS: str(chip.index),
        # one process, one chip: the standard TPU-VM carve-out
        const.ENV_TPU_PROCESS_BOUNDS: "1,1,1",
        const.ENV_TPU_CHIPS_PER_PROCESS_BOUNDS: "1,1,1",
        const.ENV_MEM_IDX: str(chip.index),
        const.ENV_MEM_POD: str(pod_units),
        const.ENV_MEM_CONTAINER: str(container_units),
        const.ENV_MEM_DEV: str(chip_total_units),
    }
    if workload_class:
        envs[const.ENV_WORKLOAD_CLASS] = workload_class
    if lora_adapter:
        envs[const.ENV_LORA_ADAPTER] = lora_adapter
    if disable_isolation:
        envs["CTPU_DISABLE"] = "true"
    elif chip_total_units > 0:
        # Per-container, not per-pod: each container is its own XLA client
        # process; capping every container at the pod's total fraction would
        # let a 2-container pod preallocate double its entitlement.
        units = container_units if container_units > 0 else pod_units
        frac = min(1.0, units / chip_total_units)
        envs[const.ENV_XLA_MEM_FRACTION] = f"{frac:.4f}"
        envs[const.ENV_XLA_PYTHON_MEM_FRACTION] = f"{frac:.4f}"
    alloc = ContainerAllocation(envs=envs)
    if chip.device_path:
        alloc.devices.append(
            DeviceMount(container_path=chip.device_path, host_path=chip.device_path)
        )
    return alloc


def build_core_allocation(
    *, chips: Sequence[TpuChip], process_bounds: str = "", chips_per_process_bounds: str = ""
) -> ContainerAllocation:
    """Payload for a whole-chip (``tpu-core``) container: exclusive chips,
    no HBM cap."""
    envs = {
        const.ENV_TPU_VISIBLE_CHIPS: visible_chips_value([c.index for c in chips]),
    }
    if process_bounds:
        envs[const.ENV_TPU_PROCESS_BOUNDS] = process_bounds
    if chips_per_process_bounds:
        envs[const.ENV_TPU_CHIPS_PER_PROCESS_BOUNDS] = chips_per_process_bounds
    alloc = ContainerAllocation(envs=envs)
    for c in chips:
        if c.device_path:
            alloc.devices.append(
                DeviceMount(container_path=c.device_path, host_path=c.device_path)
            )
    return alloc


def build_gang_allocation(
    *,
    chips: Sequence[TpuChip],
    shape: Sequence[int],
    per_chip_units: int,
    chip_total_units: int,
    pod_units: int,
    container_units: int,
    disable_isolation: bool = False,
    workload_class: str = "",
    lora_adapter: str = "",
) -> ContainerAllocation:
    """Payload for a topology-aware multi-chip gang container: every
    member chip visible, the granted slice shape as the single-process
    topology carve-out, and a PER-CHIP cooperative HBM cap (each chip of
    the gang holds ``per_chip_units`` of ``chip_total_units``).

    ``container_units`` is this container's share of the pod's TOTAL
    (cross-chip) request; its per-chip fraction scales accordingly so a
    two-container gang pod cannot double-claim a chip's slice.
    ``workload_class`` and ``lora_adapter`` mirror the pod's QoS class
    and requested adapter id into the env (see
    :func:`build_mem_allocation`).
    """
    from ..topology import format_shape, pad3

    shape3 = pad3(tuple(shape))
    envs = {
        const.ENV_TPU_VISIBLE_CHIPS: visible_chips_value([c.index for c in chips]),
        # one process owning the whole granted sub-slice: libtpu forms the
        # per-process mesh from the shape carve-out
        const.ENV_TPU_PROCESS_BOUNDS: "1,1,1",
        const.ENV_TPU_CHIPS_PER_PROCESS_BOUNDS: ",".join(str(d) for d in shape3),
        const.ENV_GANG_CHIPS: ",".join(str(c.index) for c in chips),
        const.ENV_GANG_SHAPE: format_shape(shape3),
        const.ENV_GANG_PER_CHIP: str(per_chip_units),
        const.ENV_MEM_POD: str(pod_units),
        const.ENV_MEM_CONTAINER: str(container_units),
        const.ENV_MEM_DEV: str(chip_total_units),
    }
    if workload_class:
        envs[const.ENV_WORKLOAD_CLASS] = workload_class
    if lora_adapter:
        envs[const.ENV_LORA_ADAPTER] = lora_adapter
    if disable_isolation:
        envs["CTPU_DISABLE"] = "true"
    elif chip_total_units > 0 and chips:
        units = container_units if container_units > 0 else pod_units
        per_chip = units / len(chips)
        frac = min(1.0, per_chip / chip_total_units)
        envs[const.ENV_XLA_MEM_FRACTION] = f"{frac:.4f}"
        envs[const.ENV_XLA_PYTHON_MEM_FRACTION] = f"{frac:.4f}"
    alloc = ContainerAllocation(envs=envs)
    for c in chips:
        if c.device_path:
            alloc.devices.append(
                DeviceMount(container_path=c.device_path, host_path=c.device_path)
            )
    return alloc
