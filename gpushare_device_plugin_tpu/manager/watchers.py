"""Kubelet-socket watcher.

Kubelet forgets all device plugins on restart, recreating its socket; the
plugin must detect that and re-register (reference: fsnotify Create event on
``kubelet.sock`` -> full rebuild, ``gpumanager.go:83-87``). No fsnotify
binding is available here, so we watch the socket's inode: a new inode (or
fresh existence) at the same path means kubelet restarted.
"""

from __future__ import annotations

import os
import threading
from typing import Callable


class SocketWatcher:
    def __init__(self, path: str, poll_interval_s: float = 0.5):
        self._path = path
        self._interval = poll_interval_s
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def _signature(self) -> tuple[int, int] | None:
        """(inode, ctime_ns): inode alone is unreliable — filesystems reuse
        inodes immediately after unlink+create."""
        try:
            st = os.stat(self._path)
            return (st.st_ino, st.st_ctime_ns)
        except OSError:
            return None

    def start(self, on_recreate: Callable[[], None]) -> None:
        """Invoke ``on_recreate`` whenever the socket is recreated (new
        signature or fresh appearance) — the kubelet-restart signal."""
        last = self._signature()

        def run():
            nonlocal last
            while not self._stop.wait(self._interval):
                cur = self._signature()
                if cur is not None and cur != last:
                    on_recreate()
                last = cur

        self._thread = threading.Thread(target=run, daemon=True, name="sock-watch")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
