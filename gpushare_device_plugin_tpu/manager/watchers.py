"""Kubelet-socket and device-plugin-dir watchers.

Kubelet forgets all device plugins on restart, recreating its socket; the
plugin must detect that and re-register (reference: fsnotify Create event on
``kubelet.sock`` -> full rebuild, ``gpumanager.go:83-87``). No fsnotify
binding is available here, so we watch inodes: a new inode (or fresh
existence) at the same path means the file was recreated.

``SocketWatcher`` watches one path (the original kubelet.sock check).
``PluginDirWatcher`` extends detection across the whole device-plugin dir:
besides the kubelet.sock signature it also notices *our own* plugin
sockets vanishing while kubelet.sock is alive — some kubelet restarts and
node-agent cleanups wipe plugin sockets without recreating kubelet.sock
in a way the inode check can see (same inode number reused, coarse
ctime), and a plugin whose socket is gone is silently unregistered: no
more ListAndWatch, no more Allocate, forever. Either signal triggers the
same full rebuild + re-registration + device-state replay.
"""

from __future__ import annotations

import os
import threading
from typing import Callable, Iterable


def _signature(path: str) -> tuple[int, int] | None:
    """(inode, ctime_ns): inode alone is unreliable — filesystems reuse
    inodes immediately after unlink+create."""
    try:
        st = os.stat(path)
        return (st.st_ino, st.st_ctime_ns)
    except OSError:
        return None


class SocketWatcher:
    def __init__(self, path: str, poll_interval_s: float = 0.5):
        self._path = path
        self._interval = poll_interval_s
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def _signature(self) -> tuple[int, int] | None:
        return _signature(self._path)

    def start(self, on_recreate: Callable[[], None]) -> None:
        """Invoke ``on_recreate`` whenever the socket is recreated (new
        signature or fresh appearance) — the kubelet-restart signal."""
        last = self._signature()

        def run():
            nonlocal last
            while not self._stop.wait(self._interval):
                cur = self._signature()
                if cur is not None and cur != last:
                    on_recreate()
                last = cur

        self._thread = threading.Thread(target=run, daemon=True, name="sock-watch")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)


class PluginDirWatcher:
    """Watch kubelet.sock recreation AND our plugin sockets' disappearance.

    The manager suspends the watcher around its own rebuilds (it unlinks
    and recreates the plugin sockets itself — that churn must not read as
    a kubelet restart and loop the rebuild forever) and resumes once the
    new sockets are serving.

    A plugin socket must be missing for two consecutive polls before it
    fires: an atomic-ish external recreate (unlink+bind by somebody else)
    is not a gap we need to chase, and the debounce makes the check immune
    to sub-poll races with legitimate churn.
    """

    def __init__(
        self,
        kubelet_sock_path: str,
        plugin_sockets_fn: Callable[[], Iterable[str]],
        poll_interval_s: float = 0.5,
    ):
        self._kubelet_path = kubelet_sock_path
        self._plugins_fn = plugin_sockets_fn
        self._interval = poll_interval_s
        self._stop = threading.Event()
        self._suspended = threading.Event()
        self._thread: threading.Thread | None = None
        self._last_kubelet = _signature(kubelet_sock_path)
        self._missing_streak: dict[str, int] = {}

    def suspend(self) -> None:
        """Stop triggering while the manager rebuilds the plugins."""
        self._suspended.set()

    def resume(self) -> None:
        """Watch again after a rebuild. Only the plugin-socket streaks are
        reset — the rebuild's socket churn was ours. The kubelet.sock
        signature is deliberately NOT re-seeded: we never touch that file,
        so a change observed across the suspended window is a real kubelet
        restart (possibly after our register() call, which the new kubelet
        has forgotten) and must still fire on the next poll."""
        self._missing_streak.clear()
        self._suspended.clear()

    def start(self, on_recreate: Callable[[str], None]) -> None:
        """``on_recreate(reason)`` fires on either restart signal."""

        def run():
            while not self._stop.wait(self._interval):
                if self._suspended.is_set():
                    continue
                cur = _signature(self._kubelet_path)
                if cur is not None and cur != self._last_kubelet:
                    self._last_kubelet = cur
                    self._missing_streak.clear()
                    on_recreate("kubelet.sock recreated")
                    continue
                self._last_kubelet = cur
                if cur is None:
                    # kubelet itself is down: re-registering is pointless
                    # until its socket returns (which the check above sees)
                    continue
                fired = False
                for path in list(self._plugins_fn()):
                    if os.path.exists(path):
                        self._missing_streak.pop(path, None)
                        continue
                    streak = self._missing_streak.get(path, 0) + 1
                    self._missing_streak[path] = streak
                    if streak >= 2 and not fired:
                        fired = True
                        self._missing_streak.clear()
                        on_recreate(f"plugin socket {os.path.basename(path)} vanished")
                        break

        self._thread = threading.Thread(target=run, daemon=True, name="plugindir-watch")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
