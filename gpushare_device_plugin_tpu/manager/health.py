"""Health watcher thread: discovery backend events -> plugin streams.

Reference: the ``watchXIDs`` goroutine feeding the unhealthy channel
(``nvidia.go:102-154`` -> ``server.go:207-225``), opt-in via
``--health-check``. Differences by design: transitions flow in both
directions (recovery supported) and also update the allocator's
unhealthy-chip set so binpack stops targeting sick chips
(closing the reference's TODO at ``server.go:267``).
"""

from __future__ import annotations

import threading
from typing import Callable, Iterable

from ..discovery.base import ChipHealth, DiscoveryBackend, HealthEvent
from ..utils.faults import FAULTS
from ..utils.log import get_logger
from ..utils.retry import Backoff
from ..utils.lockrank import make_lock
from ..utils.metric_catalog import (
    HEALTH_EVENTS_TOTAL,
    HEALTH_WATCHER_RESTARTS_TOTAL,
    UNHEALTHY_CHIPS,
)

log = get_logger("manager.health")


class HealthWatcher:
    def __init__(
        self,
        backend: DiscoveryBackend,
        sinks: Iterable[Callable[[str | None, ChipHealth], None]],
        on_event: Callable[[HealthEvent], None] | None = None,
    ):
        """``sinks``: callables like ``plugin.set_chip_health`` invoked per
        hard event. ``on_event`` (optional) receives EVERY event including
        ``"app"``-severity ones — the hook the lifecycle uses to surface
        transitions as Kubernetes node events (``kubectl describe node``)."""
        self._backend = backend
        self._sinks = list(sinks)
        self._on_event = on_event
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._unhealthy_ids: set[str] = set()
        self._lock = make_lock("manager.health")
        self._restarts = 0

    @property
    def restarts(self) -> int:
        """How many times the supervisor revived a dead watch loop."""
        return self._restarts

    @property
    def alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def unhealthy_ids(self) -> set[str]:
        with self._lock:
            return set(self._unhealthy_ids)

    def _handle(self, event: HealthEvent) -> None:
        log.info(
            "health: chip=%s -> %s (%s, %s)",
            event.chip_id or "ALL", event.health.value, event.reason,
            event.severity,
        )
        from ..utils.metrics import REGISTRY

        REGISTRY.counter_inc(
            HEALTH_EVENTS_TOTAL,
            "Classified health transitions",
            severity=event.severity, health=event.health.value,
        )
        if self._on_event is not None:
            try:
                self._on_event(event)
            except Exception as e:  # noqa: BLE001 — events are best-effort
                log.warning("health on_event hook failed: %s", e)
        if event.severity != "hard":
            # "app" (reference skips XIDs 31/43/45, nvidia.go:133-137) and
            # "transient" (self-healed blip): visible, never de-advertise.
            return
        with self._lock:
            if event.chip_id is None:
                if event.health == ChipHealth.UNHEALTHY:
                    self._unhealthy_ids.update(
                        c.id for c in self._backend.chips()
                    )
                else:
                    self._unhealthy_ids.clear()
            elif event.health == ChipHealth.UNHEALTHY:
                self._unhealthy_ids.add(event.chip_id)
            else:
                self._unhealthy_ids.discard(event.chip_id)
        REGISTRY.gauge_set(
            UNHEALTHY_CHIPS,
            len(self._unhealthy_ids),
            "Chips currently excluded from placement",
        )
        for sink in self._sinks:
            try:
                sink(event.chip_id, event.health)
            except Exception as e:  # a dead sink must not kill the watcher
                log.warning("health sink failed: %s", e)

    def start(self) -> None:
        """Run the watch loop under a supervisor: a backend that raises (a
        wedged driver poll, a flaky metadata server) gets restarted with
        jittered backoff instead of silently ending health monitoring for
        the life of the daemon — the chips would otherwise stay advertised
        Healthy forever on a node whose watcher died at hour one."""

        def run():
            from ..utils.metrics import REGISTRY

            backoff = Backoff(base_s=0.1, max_s=5.0)
            while not self._stop.is_set():
                try:
                    FAULTS.fire("discovery.watch_health")
                    for event in self._backend.watch_health(self._stop.is_set):
                        if self._stop.is_set():
                            return
                        backoff.reset()
                        self._handle(event)
                    if self._stop.is_set():
                        return
                    # Generator exhausted without stop: the backend gave up
                    # on its own — treat it exactly like a crash.
                    raise RuntimeError("watch_health stream ended early")
                except Exception as e:  # noqa: BLE001 — supervised
                    if self._stop.is_set():
                        return
                    self._restarts += 1
                    REGISTRY.counter_inc(
                        HEALTH_WATCHER_RESTARTS_TOTAL,
                        "Health watch loop crashes revived by the supervisor",
                    )
                    delay = backoff.next()
                    log.error(
                        "health watcher died (%s); restart #%d in %.2fs",
                        e, self._restarts, delay,
                    )
                    self._stop.wait(delay)

        self._thread = threading.Thread(target=run, daemon=True, name="health-watch")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
