from .lifecycle import ManagerConfig, TpuShareManager

__all__ = ["ManagerConfig", "TpuShareManager"]
