"""Daemon lifecycle manager.

Reference: ``sharedGPUManager.Run`` (``gpumanager.go:33-108``):
- park forever (never crash-loop) on nodes without accelerators,
- serve the device plugin(s),
- rebuild + re-register when kubelet restarts (socket watcher) or on SIGHUP,
- SIGQUIT dumps all-thread stacks to a file,
- other signals stop the plugins and exit.

This manager owns both resource plugins (tpu-mem fan-out and whole-chip
tpu-core), the health watcher, and — in cluster mode — the node-capacity
patch at (re)build time.
"""

from __future__ import annotations

import dataclasses
import signal
import threading
from typing import Sequence

from .. import const
from ..utils.metric_catalog import SLO_BURN_RATE
from ..allocator.env import build_core_allocation
from ..allocator.local import LocalAllocator
from ..device.fanout import DeviceInventory
from ..discovery.base import DiscoveryBackend
from ..plugin.server import PluginConfig, TpuSharePlugin
from ..utils.faults import FAULTS
from ..utils.log import get_logger
from ..utils.stacktrace import coredump
from .health import HealthWatcher
from .watchers import PluginDirWatcher

log = get_logger("manager")


@dataclasses.dataclass
class ManagerConfig:
    plugin_dir: str = const.DEVICE_PLUGIN_PATH
    node_name: str = ""
    memory_unit: const.MemoryUnit = const.MemoryUnit.GiB
    policy: str = "first-fit"
    health_check: bool = False
    # No apiserver: LocalAllocator accounting. Dev/bench only — without the
    # apiserver there is no pod-lifecycle feed, so standalone allocations
    # are never reclaimed until the daemon restarts.
    standalone: bool = False
    serve_core_resource: bool = True
    disable_isolation: bool = False
    coredump_dir: str = "/etc/kubernetes"
    # Write-ahead allocation journal (allocator/checkpoint.py). Empty
    # disables it; cluster mode should point it at a path that survives
    # container restarts (the device-plugin dir is the natural hostPath).
    checkpoint_path: str = ""
    # WAL durability mode: "batch" (group commit — one fsync covers every
    # record queued within the gather window) or "always" (fsync per
    # record). Durability semantics are identical; see --wal-fsync.
    wal_fsync: str = "batch"
    wal_batch_window_s: float = 0.002
    # Coalesce concurrently-committed pod-annotation PATCHes through one
    # pipelined dispatcher (cluster/apiserver.py PodPatchPipeline).
    patch_coalesce: bool = True
    # Drift-reconciler cadence (cluster/reconciler.py); <= 0 disables.
    reconcile_interval_s: float = 30.0
    # How long graceful shutdown waits for in-flight Allocate calls.
    drain_timeout_s: float = 5.0
    # Flight-recorder dump directory (utils/flightrec.py): SIGUSR1, fatal
    # exit, and injected-crash postmortems land here. Empty disables (the
    # daemon defaults it to the coredump dir). flightrecord_keep bounds
    # the directory to the newest K dump files (0 = unbounded).
    flightrecord_dir: str = ""
    flightrecord_keep: int = 16
    # Interference detector cadence (cluster/interference.py): every
    # interval the daemon correlates per-chip co-residency with step-p99
    # inflation and publishes the interference node annotation + ratio
    # gauges; <= 0 disables (pure observability, but opt-in like defrag
    # so fleets without serving engines pay nothing).
    interference_interval_s: float = 0.0
    interference_threshold: float = 1.25
    # Serving pods' /metrics endpoints to scrape for the engines' step
    # p99 gauges. Empty: the loop reads the shared in-process registry —
    # which only works when the engines feed it (bench/test/co-located
    # integrations); real per-pod engines need their endpoints listed.
    interference_scrape_urls: tuple[str, ...] = ()
    # Live slice defragmentation (allocator/defrag.py): scan cadence in
    # seconds, <= 0 disables (the default — repacking moves workloads and
    # should be an explicit operator opt-in). quantum=0 auto-derives the
    # stranded-sliver threshold from the node's own pod sizes.
    defrag_interval_s: float = 0.0
    defrag_quantum: int = 0
    defrag_max_moves: int = 8
    # Cluster-state timeline recorder (utils/timeline.py): every interval
    # the daemon folds utilization / stranded% / pending depth / SLO burn
    # into the bounded timeline ring (served on /timeline, embedded in
    # flight-recorder dumps). Cheap (one chip_state read per tick), so
    # on by default; <= 0 disables.
    timeline_interval_s: float = 10.0
    # Decision-provenance ring (utils/decisions.py): per-verb "why"
    # records served on /decisions. 0 disables emission.
    decisions_ring: int = 512
    decisions_log_path: str = ""


class TpuShareManager:
    def __init__(
        self,
        backend: DiscoveryBackend,
        config: ManagerConfig,
        api_client=None,
        pod_source=None,
    ):
        self._backend = backend
        self._cfg = config
        self._api = api_client
        self._pod_source = pod_source
        self._plugins: list[TpuSharePlugin] = []
        self._health: HealthWatcher | None = None
        self._events = None  # NodeEventEmitter in cluster mode w/ health
        self._local: LocalAllocator | None = None  # standalone accounting
        # effective isolation toggle: config flag OR node label, re-read at
        # every plugin (re)build (reference: podmanager.go:59-72 read at
        # server.go:60-74)
        self._disable_isolation = config.disable_isolation
        # one reservation ledger across the mem and core allocators: both
        # resources share one physical-chip ledger, so their in-flight
        # claims/reservations must be mutually visible (allocator.assume)
        from ..allocator.assume import AssumeCache

        self._alloc_assume = AssumeCache()
        # Crash-safe state layer (cluster mode): the WAL checkpoint the
        # allocators journal through, and the drift reconciler that keeps
        # annotations / ledger / checkpoint / kubelet coherent.
        self._ckpt = None
        if config.checkpoint_path and api_client is not None and not config.standalone:
            from ..allocator.checkpoint import AllocationCheckpoint

            try:
                self._ckpt = AllocationCheckpoint(
                    config.checkpoint_path,
                    fsync=config.wal_fsync,
                    batch_window_s=config.wal_batch_window_s,
                )
            except OSError as e:
                log.warning(
                    "allocation checkpoint unavailable (%s); running "
                    "unjournaled — restart recovery degraded", e,
                )
        # Coalesced admission writes: both allocators route their pod
        # PATCHes through one group-commit dispatcher so a storm of
        # concurrent admissions batches its apiserver round-trips.
        self._patch_pipeline = None
        if (
            config.patch_coalesce
            and api_client is not None
            and not config.standalone
        ):
            from ..cluster.apiserver import PodPatchPipeline

            self._patch_pipeline = PodPatchPipeline(api_client)
        self._reconciler = None
        # Live defragmentation (allocator/defrag.py): the loop itself, and
        # the engine hand-off hooks a serving integration registers via
        # set_move_hooks() — None means moves skip the drain/restore
        # phases (workloads that checkpoint themselves).
        self._defrag = None
        self._interference = None  # InterferenceLoop (cluster/interference.py)
        self._timeline_loop = None  # TimelineLoop (utils/timeline.py)
        # Decision-provenance configuration applies process-wide (the
        # allocators emit through the module singleton).
        from ..utils.decisions import DECISIONS

        DECISIONS.configure(
            enabled=config.decisions_ring > 0,
            max_records=max(1, config.decisions_ring),
            segment_path=config.decisions_log_path,
        )
        self._move_drain_fn = None
        self._move_restore_fn = None
        self._restart = threading.Event()
        self._stop = threading.Event()
        self._park = threading.Event()
        self._parked = False  # no-TPU node: healthy, serving nothing

    def ready(self) -> bool:
        """Daemon readiness for the metrics server's ``/readyz``: every
        served plugin has completed kubelet registration (an unregistered
        plugin serves no pods, whatever its socket says). A parked daemon
        (no TPUs on the node) reads ready — it is healthy and
        intentionally serving nothing."""
        if self._parked:
            return True
        plugins = list(self._plugins)
        return bool(plugins) and all(p.registered for p in plugins)

    def set_move_hooks(self, drain_fn=None, restore_fn=None) -> None:
        """Register the defragmenter's engine hand-off: ``drain_fn(pod_key)
        -> snapshot dict | None`` quiesces the pod's serving engine
        (``PagedSlotEngine.drain_snapshot``), ``restore_fn(pod_key,
        snapshot)`` re-admits it on the destination slice. Takes effect
        immediately: the reconciler and mover dispatch through the
        manager and read the current hooks at call time — registering
        after the build (the natural order; the engine exists only once
        a pod is served) still covers in-flight move resolution."""
        self._move_drain_fn = drain_fn
        self._move_restore_fn = restore_fn

    def _move_drain_dispatch(self, pod_key):
        fn = self._move_drain_fn
        return None if fn is None else fn(pod_key)

    def _move_restore_dispatch(self, pod_key, snapshot) -> None:
        fn = self._move_restore_fn
        if fn is None:
            if snapshot:
                # A drained snapshot with no registered restore hook must
                # NOT be dropped: raising maps to retry-next-pass in both
                # resolve_move and SliceMover, so the journaled requests
                # survive until the serving integration (re)registers.
                raise RuntimeError(
                    "drained engine snapshot present but no restore hook "
                    "registered (set_move_hooks)"
                )
            return
        fn(pod_key, snapshot)

    # ------------------------------------------------------------------

    def _build_inventory(self) -> DeviceInventory | None:
        FAULTS.fire("discovery.probe")
        if not self._backend.probe():
            return None
        chips = self._backend.chips()
        if not chips:
            return None
        return DeviceInventory(chips, unit=self._cfg.memory_unit)

    def _build_allocator(self, inventory: DeviceInventory, unhealthy_fn):
        if self._cfg.standalone or self._api is None:
            log.warning(
                "standalone mode: allocations are accounted in-process and "
                "never reclaimed on pod deletion (dev/bench only)"
            )
            self._local = LocalAllocator(
                inventory,
                policy=self._cfg.policy,
                disable_isolation=self._disable_isolation,
            )
            local = self._local
            return lambda granted: local.allocate([len(g) for g in granted])
        from ..allocator.cluster import ClusterAllocator

        cluster = ClusterAllocator(
            inventory,
            self._api,
            self._pod_source,
            self._cfg.node_name,
            policy=self._cfg.policy,
            disable_isolation=self._disable_isolation,
            unhealthy_chips_fn=unhealthy_fn,
            assume=self._alloc_assume,
            checkpoint=self._ckpt,
            patcher=(
                self._patch_pipeline.patch_pod
                if self._patch_pipeline is not None else None
            ),
            chip_topology=self._node_chip_topology(inventory),
        )
        return cluster.allocate

    def _node_chip_topology(self, inventory: DeviceInventory):
        """This node's chip grid for gang placement: the same
        ``tpushare.aliyun.com/topology`` label rule the extender and the
        inspect CLI apply, so branch-B gang decisions agree with the
        extender's grid. An unreachable apiserver or missing label falls
        back to the default grid (None -> ClusterAllocator derives it)."""
        from ..topology import ChipTopology

        n_chips = len(inventory.units_by_index())
        node: dict = {}
        try:
            node = self._api.get_node(self._cfg.node_name)
        except Exception as e:  # noqa: BLE001 — degrade to the default grid
            log.v(4, "node topology label read failed (%s); using default", e)
        return ChipTopology.from_node(node, max(1, n_chips))

    def _build_core_allocate_fn(self, inventory: DeviceInventory, unhealthy_fn):
        """Whole-chip allocator for the tpu-core resource.

        Unlike tpu-mem, core device IDs *are* the real chip ids. Cluster
        mode validates them against fractional usage / existing holds and
        persists the decision (ClusterCoreAllocator); standalone mode
        accounts the hold in-process so mem binpack and core grants cannot
        double-book a chip.
        """
        topo = self._backend.topology()
        if self._cfg.standalone or self._api is None:
            local = self._local

            def allocate(granted: Sequence[Sequence[str]]):
                # One atomic hold for the whole pod: hold_chips validates
                # every chip before recording any, so a conflict on one
                # container cannot leak the others' holds.
                all_indices = [
                    inventory.index_of(cid) for ids in granted for cid in ids
                ]
                if local is not None:
                    local.hold_chips(all_indices)  # raises on conflict
                return [
                    build_core_allocation(
                        chips=[inventory.chip_by_id(cid) for cid in ids],
                        process_bounds=topo.process_bounds,
                        chips_per_process_bounds=topo.chips_per_process_bounds,
                    )
                    for ids in granted
                ]

            return allocate
        from ..allocator.cluster import ClusterCoreAllocator

        core = ClusterCoreAllocator(
            inventory,
            self._api,
            self._pod_source,
            self._cfg.node_name,
            topology=topo,
            unhealthy_chips_fn=unhealthy_fn,
            assume=self._alloc_assume,
            checkpoint=self._ckpt,
            patcher=(
                self._patch_pipeline.patch_pod
                if self._patch_pipeline is not None else None
            ),
        )
        return core.allocate

    def _build_plugins(self, inventory: DeviceInventory) -> list[TpuSharePlugin]:
        plugins: list[TpuSharePlugin] = []
        mem_plugin = TpuSharePlugin(
            inventory,
            allocate_fn=None,  # late-bound: the allocator reads this
            # plugin's live health view for unhealthy-chip exclusion
            config=PluginConfig(
                resource_name=const.RESOURCE_MEM,
                socket_name=const.MEM_SOCKET_NAME,
                plugin_dir=self._cfg.plugin_dir,
            ),
        )
        mem_plugin.set_allocate_fn(
            self._build_allocator(
                inventory, unhealthy_fn=mem_plugin.unhealthy_chip_indices
            )
        )
        plugins.append(mem_plugin)
        if self._cfg.serve_core_resource:
            from ..allocator.cluster import cluster_chip_state, preferred_core_chips

            if not (self._cfg.standalone or self._api is None):
                state_fn = cluster_chip_state(
                    self._pod_source, assume=self._alloc_assume
                )
            else:
                local = self._local

                def state_fn():
                    if local is None:
                        return {}, set()
                    return local.used_by_chip(), local.core_held()

            preferred_fn = preferred_core_chips(inventory, state_fn)

            core_plugin = TpuSharePlugin(
                inventory,
                allocate_fn=None,
                config=PluginConfig(
                    resource_name=const.RESOURCE_CORE,
                    socket_name=const.CORE_SOCKET_NAME,
                    plugin_dir=self._cfg.plugin_dir,
                ),
                devices_fn=inventory.core_devices,
                preferred_fn=preferred_fn,
            )
            core_plugin.set_allocate_fn(
                self._build_core_allocate_fn(
                    inventory, unhealthy_fn=core_plugin.unhealthy_chip_indices
                )
            )
            plugins.append(core_plugin)
        return plugins

    # ------------------------------------------------------------------

    def _serve_all(self) -> None:
        inventory = self._build_inventory()
        assert inventory is not None
        if self._api is not None and self._cfg.node_name:
            from ..cluster.node import isolation_disabled, patch_chip_count

            try:
                patch_chip_count(self._api, self._cfg.node_name, inventory.chip_count)
            except Exception as e:
                log.warning("node capacity patch failed: %s", e)
            # Node label as feature flag, re-read at every (re)build
            # (reference: podmanager.go:59-72 read at server.go:60-74).
            self._disable_isolation = self._cfg.disable_isolation or isolation_disabled(
                self._api, self._cfg.node_name
            )
            if self._disable_isolation:
                log.info("HBM isolation disabled (config flag or node label)")
        # Crash recovery BEFORE the plugins serve: claim the fencing
        # generation (a stale duplicate instance observes it and refuses)
        # and replay unresolved journal entries into the ledger, so the
        # first Allocate after a restart already sees every in-flight
        # reservation the previous incarnation died holding.
        if self._ckpt is not None and self._api is not None:
            if self._cfg.node_name:
                try:
                    self._ckpt.acquire_fence(self._api, self._cfg.node_name)
                except Exception as e:
                    log.warning(
                        "fence acquire failed (%s); continuing unfenced", e
                    )
            from ..allocator.checkpoint import replay_checkpoint

            n = replay_checkpoint(self._ckpt, self._alloc_assume)
            if n:
                log.info(
                    "device-state replay: %d in-flight allocation(s) "
                    "restored from checkpoint", n,
                )
        self._plugins = self._build_plugins(inventory)
        for plugin in self._plugins:
            plugin.serve()
        if self._cfg.health_check:
            sinks = [p.set_chip_health for p in self._plugins]
            if self._local is not None:
                local, inv = self._local, inventory

                def local_sink(chip_id, health):
                    from ..discovery.base import ChipHealth

                    ok = health == ChipHealth.HEALTHY
                    ids = [c.id for c in inv.chips()] if chip_id is None else [chip_id]
                    for cid in ids:
                        local.set_chip_health(inv.index_of(cid), ok)

                sinks.append(local_sink)
            on_event = None
            if self._api is not None and self._cfg.node_name:
                # Rate limit per (chip, reason-class): a continuously
                # ticking correctable-error counter must not write a fresh
                # Event into etcd every 5 s poll. Hard transitions are rare
                # (state-edge-triggered in the backend) and pass through.
                last_emit: dict[tuple, float] = {}
                min_interval_s = 300.0

                from ..cluster.events import (
                    REASON_CHIP_APP_FAULT,
                    REASON_CHIP_RECOVERED,
                    REASON_CHIP_TRANSIENT,
                    REASON_CHIP_UNHEALTHY,
                    NodeEventEmitter,
                )
                from ..discovery.base import ChipHealth

                # One worker + bounded queue instead of a daemon thread per
                # event: an unreachable apiserver must neither stall
                # hard-health propagation nor grow a thread per poll tick
                # for the whole outage. Overflow drops are counted.
                self._events = NodeEventEmitter(
                    self._api, self._cfg.node_name
                ).start()
                emitter = self._events

                def on_event(event):  # noqa: F811 — the cluster-mode hook
                    import time as _time

                    if event.severity == "app":
                        reason, etype = REASON_CHIP_APP_FAULT, "Warning"
                    elif event.severity == "transient":
                        reason, etype = REASON_CHIP_TRANSIENT, "Normal"
                    elif event.health == ChipHealth.UNHEALTHY:
                        reason, etype = REASON_CHIP_UNHEALTHY, "Warning"
                    else:
                        reason, etype = REASON_CHIP_RECOVERED, "Normal"
                    if event.severity != "hard":
                        key = (event.chip_id, reason)
                        now = _time.monotonic()
                        if now - last_emit.get(key, -min_interval_s) < min_interval_s:
                            return
                        last_emit[key] = now
                    emitter.emit(
                        reason,
                        f"chip {event.chip_id or 'ALL'}: {event.reason}",
                        event_type=etype,
                    )

            self._health = HealthWatcher(
                self._backend, sinks=sinks, on_event=on_event
            )
            self._health.start()
        # The drift reconciler runs for the lifetime of this build; its
        # first pass resolves whatever the replay above re-reserved.
        if (
            self._api is not None
            and self._pod_source is not None
            and not self._cfg.standalone
            and self._cfg.reconcile_interval_s > 0
        ):
            from ..cluster.reconciler import DriftReconciler

            self._reconciler = DriftReconciler(
                api=self._api,
                pod_source=self._pod_source,
                assume=self._alloc_assume,
                checkpoint=self._ckpt,
                node_name=self._cfg.node_name,
                inventory=inventory,
                interval_s=self._cfg.reconcile_interval_s,
                move_restore_fn=self._move_restore_dispatch,
            ).start()
        # Live defragmentation rides the same substrate: planner over the
        # pod source, mover through the shared ledger + WAL + patch
        # pipeline. Starts one full interval in — the reconciler's first
        # pass resolves any move the previous incarnation died holding
        # before this one plans new work.
        if (
            self._api is not None
            and self._pod_source is not None
            and not self._cfg.standalone
            and self._cfg.defrag_interval_s > 0
            and self._cfg.node_name
        ):
            from ..allocator.defrag import DefragLoop, DefragPlanner, SliceMover

            # the mem plugin's live health view: unhealthy chips are
            # excluded from planning (never drained, never filled) just
            # as the admission allocator refuses to place on them
            unhealthy_fns = [
                p.unhealthy_chip_indices
                for p in self._plugins
                if p.resource_name == const.RESOURCE_MEM
            ]

            def _excluded() -> set[int]:
                return {i for fn in unhealthy_fns for i in fn()}

            planner = DefragPlanner(
                inventory.units_by_index,
                self._pod_source,
                quantum=self._cfg.defrag_quantum,
                excluded_fn=_excluded,
                max_moves=self._cfg.defrag_max_moves,
                node=self._cfg.node_name,
            )
            mover = SliceMover(
                self._api,
                self._pod_source,
                self._alloc_assume,
                self._ckpt,
                self._cfg.node_name,
                inventory.units_by_index,
                drain_fn=self._move_drain_dispatch,
                restore_fn=self._move_restore_dispatch,
                patch_fn=(
                    self._patch_pipeline.patch_pod
                    if self._patch_pipeline is not None else None
                ),
            )
            self._defrag = DefragLoop(
                planner, mover, self._api, self._cfg.node_name,
                interval_s=self._cfg.defrag_interval_s,
            ).start()
        # Interference observability plane (cluster/interference.py):
        # residency from the pod source, step-p99 signal from the shared
        # metrics registry, verdicts onto the interference node
        # annotation for the inspect CLI's `top` view.
        if (
            self._api is not None
            and self._pod_source is not None
            and not self._cfg.standalone
            and self._cfg.interference_interval_s > 0
            and self._cfg.node_name
        ):
            from ..cluster.interference import (
                InterferenceDetector,
                InterferenceLoop,
            )

            self._interference = InterferenceLoop(
                InterferenceDetector(
                    threshold=self._cfg.interference_threshold
                ),
                self._api,
                self._cfg.node_name,
                self._pod_source,
                interval_s=self._cfg.interference_interval_s,
                scrape_urls=self._cfg.interference_scrape_urls,
            ).start()
        # Cluster-state timeline recorder (utils/timeline.py): fold
        # utilization / fragmentation / queue depth / SLO burn into the
        # bounded ring every tick — read-only sources, each best-effort.
        if (
            self._pod_source is not None
            and not self._cfg.standalone
            and self._cfg.timeline_interval_s > 0
        ):
            from ..allocator.defrag import STRANDED_PCT_GAUGE
            from ..cluster import pods as PODS
            from ..utils.metrics import REGISTRY
            from ..utils.timeline import TIMELINE, TimelineLoop

            total_units = sum(inventory.units_by_index().values())
            pod_source = self._pod_source

            def _util_pct():
                if not total_units:
                    return None
                mem_used, _held = pod_source.chip_state()
                return 100.0 * sum(mem_used.values()) / total_units

            def _queue_depth():
                # ONE pending-pod read feeds both series (a second LIST
                # per tick would double the control-plane read load on
                # list-backed sources, from two different snapshots)
                pending = pod_source.pending_share_pods(const.RESOURCE_MEM)
                return {
                    "pending_pods": float(len(pending)),
                    "pending_gangs": float(sum(
                        1 for p in pending if PODS.gang_shape_request(p)
                    )),
                }

            def _stranded_pct():
                return REGISTRY.gauge_value(STRANDED_PCT_GAUGE)

            def _slo_burn_5m():
                series = REGISTRY.gauge_series(SLO_BURN_RATE)
                vals = [
                    v for labels, v in series.items()
                    if dict(labels).get("window") == "5m"
                ]
                return max(vals) if vals else None

            self._timeline_loop = TimelineLoop(
                TIMELINE,
                {
                    "util_pct": _util_pct,
                    "queue_depth": _queue_depth,
                    "stranded_pct": _stranded_pct,
                    "slo_burn_5m": _slo_burn_5m,
                },
                interval_s=self._cfg.timeline_interval_s,
            ).start()

    def _stop_all(self) -> None:
        if self._timeline_loop is not None:
            self._timeline_loop.stop()
            self._timeline_loop = None
        if self._interference is not None:
            self._interference.stop()
            self._interference = None
        if self._defrag is not None:
            # before the reconciler: a mid-shutdown move must not lose its
            # resolver while still journaling phases
            self._defrag.stop()
            self._defrag = None
        if self._reconciler is not None:
            self._reconciler.stop()
            self._reconciler = None
        if self._health is not None:
            self._health.stop()
            self._health = None
        if self._events is not None:
            self._events.stop()
            self._events = None
        # Graceful drain first: refuse new Allocate RPCs on EVERY plugin
        # at once (quiesce), then wait for in-flight ones to finish their
        # PATCH + journal commit against one shared deadline — the
        # checkpoint covers a hard cut, but a clean flush beats replaying
        # one, and the total drain must fit one grace budget, not N.
        import time as _time

        for plugin in self._plugins:
            try:
                plugin.quiesce()
            except Exception as e:
                log.warning("plugin quiesce failed: %s", e)
        deadline = _time.monotonic() + self._cfg.drain_timeout_s
        for plugin in self._plugins:
            try:
                remaining = max(0.0, deadline - _time.monotonic())
                if not plugin.drain(remaining):
                    log.warning(
                        "plugin %s did not drain within %.1fs; stopping "
                        "anyway (checkpoint covers the cut)",
                        plugin.resource_name, self._cfg.drain_timeout_s,
                    )
            except Exception as e:
                log.warning("plugin drain failed: %s", e)
        for plugin in self._plugins:
            try:
                plugin.stop()
            except Exception as e:
                log.warning("plugin stop failed: %s", e)
        self._plugins = []
        if self._ckpt is not None:
            self._ckpt.flush()

    # ------------------------------------------------------------------

    def install_signal_handlers(self) -> None:
        signal.signal(signal.SIGHUP, lambda *_: self.trigger_restart("SIGHUP"))
        signal.signal(signal.SIGINT, lambda *_: self.trigger_stop("SIGINT"))
        signal.signal(signal.SIGTERM, lambda *_: self.trigger_stop("SIGTERM"))
        try:
            signal.signal(
                signal.SIGQUIT,
                lambda *_: log.info("stack dump: %s", coredump(self._cfg.coredump_dir)),
            )
        except (OSError, ValueError):
            pass
        # SIGUSR1: live postmortem — dump the flight recorder (last N
        # admission traces + recent log ring) without disturbing the
        # daemon, the trace analog of SIGQUIT's stack dump.
        if self._cfg.flightrecord_dir:
            from ..utils.flightrec import FLIGHT

            FLIGHT.install_signal_handler()

    def trigger_restart(self, reason: str = "") -> None:
        log.info("restart requested (%s)", reason or "socket watcher")
        self._restart.set()
        self._park.set()

    def trigger_stop(self, reason: str = "") -> None:
        log.info("stop requested (%s)", reason)
        self._stop.set()
        self._restart.set()
        self._park.set()

    def run(self) -> None:
        """Blocking main loop; returns only on stop."""
        # Flight recorder first: from here on a fatal exit or an injected
        # crash leaves a postmortem (traces + recent logs) on disk.
        if self._cfg.flightrecord_dir:
            from ..utils.flightrec import FLIGHT

            FLIGHT.install(
                self._cfg.flightrecord_dir,
                max_dumps=self._cfg.flightrecord_keep,
            )
        if self._build_inventory() is None:
            # No TPUs here: park forever instead of crash-looping, so the
            # DaemonSet stays green on heterogenous fleets
            # (gpumanager.go:36-47 semantics).
            log.info("no TPU chips found on this node; parking")
            self._parked = True
            self._park.wait()
            return
        # Restart detection across the whole device-plugin dir: kubelet.sock
        # recreation (kubelet restart) or our own plugin sockets vanishing
        # (kubelet cleanup that silently unregisters us). Suspended around
        # our own rebuilds so self-inflicted socket churn never loops.
        watcher = PluginDirWatcher(
            kubelet_sock_path=f"{self._cfg.plugin_dir.rstrip('/')}/kubelet.sock",
            plugin_sockets_fn=lambda: [p.socket_path for p in self._plugins],
        )
        watcher.start(
            on_recreate=lambda reason: self.trigger_restart(reason)
        )
        try:
            while not self._stop.is_set():
                self._restart.clear()
                watcher.suspend()
                try:
                    self._serve_all()
                except Exception as e:
                    log.error("serve failed: %s; retrying in 5s", e)
                    self._stop_all()
                    if self._stop.wait(5.0):
                        break
                    continue
                watcher.resume()
                self._restart.wait()
                watcher.suspend()
                self._stop_all()
        finally:
            watcher.stop()
            self._stop_all()
            if self._patch_pipeline is not None:
                # after the drain: in-flight admissions have finished their
                # PATCHes, so stopping the dispatcher strands nothing
                self._patch_pipeline.stop()
            if self._ckpt is not None:
                # graceful shutdown: the journal is flushed and closed so
                # the next incarnation loads a clean file
                self._ckpt.close()
