"""Table rendering for the inspect CLI (reference: ``cmd/inspect/display.go``).

Summary: per-node per-chip ``used/total`` plus the cluster utilization
total — the north-star metric line (``display.go:231-241``). Details adds
per-pod rows with chip attribution.
"""

from __future__ import annotations

from io import StringIO

from .nodeinfo import PENDING_IDX, NodeInfo, infer_unit


def _table(rows: list[list[str]]) -> str:
    if not rows:
        return ""
    widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
    out = []
    for r in rows:
        out.append("  ".join(cell.ljust(w) for cell, w in zip(r, widths)).rstrip())
    return "\n".join(out)


def render_summary(infos: list[NodeInfo]) -> str:
    unit = infer_unit(infos)
    buf = StringIO()
    rows = [["NAME", "IPADDRESS", f"TPU Memory ({unit})"]]
    for info in infos:
        chips = ", ".join(
            f"chip{d.index}: {d.used_units}/{d.total_units}"
            for d in sorted(info.devices.values(), key=lambda d: d.index)
        )
        rows.append([info.name, info.address, chips])
    buf.write(_table(rows))
    buf.write("\n")
    total = sum(i.total_units for i in infos)
    used = sum(i.used_units for i in infos)
    pct = (100.0 * used / total) if total else 0.0
    buf.write("-" * 40 + "\n")
    buf.write(
        f"Allocated/Total TPU Memory ({unit}) In Cluster:\n{used}/{total} ({pct:.0f}%)\n"
    )
    pending = sum(i.pending_units for i in infos)
    if pending:
        buf.write(f"Pending (unattributed) TPU Memory ({unit}): {pending}\n")
    return buf.getvalue()


def render_details(infos: list[NodeInfo]) -> str:
    unit = infer_unit(infos)
    buf = StringIO()
    for info in infos:
        buf.write(f"NAME: {info.name} ({info.address})\n")
        rows = [["NAMESPACE", "NAME", f"TPU MEMORY ({unit})", "CHIPS"]]
        for pod in sorted(info.pods, key=lambda p: (p.namespace, p.name)):
            chips = ", ".join(
                ("pending" if idx == PENDING_IDX else f"chip{idx}") + f":{units}"
                for idx, units in sorted(pod.units_by_chip.items())
            )
            rows.append([pod.namespace, pod.name, str(pod.total_units), chips])
        buf.write(_table(rows))
        buf.write("\n")
        buf.write(
            f"Allocated : {info.used_units} ({(100.0 * info.used_units / info.total_units) if info.total_units else 0:.0f}%)\n"
        )
        buf.write(f"Total     : {info.total_units}\n")
        buf.write("\n")
    buf.write(render_summary(infos))
    return buf.getvalue()
