"""Table rendering for the inspect CLI (reference: ``cmd/inspect/display.go``).

Summary: per-node per-chip ``used/total`` plus the cluster utilization
total — the north-star metric line (``display.go:231-241``). Details adds
per-pod rows with chip attribution.
"""

from __future__ import annotations

from io import StringIO

from .. import const
from .nodeinfo import PENDING_IDX, NodeInfo, infer_unit


def _table(rows: list[list[str]]) -> str:
    if not rows:
        return ""
    widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
    out = []
    for r in rows:
        out.append("  ".join(cell.ljust(w) for cell, w in zip(r, widths)).rstrip())
    return "\n".join(out)


def _chip_cell(info: NodeInfo, d, held: set[int]) -> str:
    """One chip's ``used/total`` summary, marking exclusive holds and —
    when the node publishes defrag status — stranded slivers (free HBM
    below the defragmenter's quantum)."""
    if d.index in held:
        body = "exclusive"
    else:
        body = f"{d.used_units}/{d.total_units}"
        stranded = info.stranded_by_chip.get(d.index, 0)
        if stranded:
            body += f" ({stranded} stranded)"
    return f"chip{d.index}: {body}"


def _moves_cell(status: dict | None) -> str:
    """The MOVES column: planned/active/completed move counters plus the
    last move's duration, from the defrag-status node annotation."""
    if not status:
        return "-"
    cell = (
        f"{int(status.get('planned', 0))} planned · "
        f"{int(status.get('active', 0))} active · "
        f"{int(status.get('completed', 0))} done"
    )
    last = status.get("last_move_ms")
    if last:
        cell += f" · last {float(last):.1f}ms"
    return cell


def render_summary(infos: list[NodeInfo]) -> str:
    unit = infer_unit(infos)
    buf = StringIO()
    any_core = any(i.core_holds for i in infos)
    any_defrag = any(i.defrag is not None for i in infos)
    header = ["NAME", "IPADDRESS", f"TPU Memory ({unit})"]
    if any_core:
        header.append("EXCLUSIVE CHIPS (tpu-core)")
    if any_defrag:
        header.append("MOVES (defrag)")
    rows = [header]
    for info in infos:
        held = set(info.core_held_chips)
        chips = ", ".join(
            _chip_cell(info, d, held)
            for d in sorted(info.devices.values(), key=lambda d: d.index)
        )
        row = [info.name, info.address, chips]
        if any_core:
            pending_holds = sum(1 for h in info.core_holds if not h.chips)
            cell = ",".join(str(i) for i in info.core_held_chips) or "-"
            if pending_holds:
                cell += f" (+{pending_holds} pending)"
            row.append(cell)
        if any_defrag:
            row.append(_moves_cell(info.defrag))
        rows.append(row)
    buf.write(_table(rows))
    buf.write("\n")
    total = sum(i.total_units for i in infos)
    used = sum(i.used_units for i in infos)
    pct = (100.0 * used / total) if total else 0.0
    buf.write("-" * 40 + "\n")
    buf.write(
        f"Allocated/Total TPU Memory ({unit}) In Cluster:\n{used}/{total} ({pct:.0f}%)\n"
    )
    pending = sum(i.pending_units for i in infos)
    if pending:
        buf.write(f"Pending (unattributed) TPU Memory ({unit}): {pending}\n")
    if any_core:
        n_held = sum(len(i.core_held_chips) for i in infos)
        n_pods = sum(len(i.core_holds) for i in infos)
        buf.write(
            f"Exclusively held TPU chips (tpu-core): {n_held} across {n_pods} pod(s)\n"
        )
    if any_defrag:
        stranded = sum(sum(i.stranded_by_chip.values()) for i in infos)
        buf.write(
            f"Stranded (sub-quantum sliver) TPU Memory ({unit}): {stranded}\n"
        )
    return buf.getvalue()


def _gang_cell(pod, info: NodeInfo, unit: str) -> str:
    """One gang pod's grant, rendered with grid coordinates: e.g.
    ``2x2x1 @ (0,0,0)(1,0,0)(0,1,0)(1,1,0) · 8 GiB/chip``. Falls back to
    bare indices when the node's grid is unknown."""
    members = sorted(i for i in pod.units_by_chip if i != PENDING_IDX)
    if info.topology is not None:
        try:
            coords = "".join(
                "({},{},{})".format(*info.topology.coords(i)) for i in members
            )
        except ValueError:  # annotation points off the grid
            coords = ",".join(f"chip{i}" for i in members)
    else:
        coords = ",".join(f"chip{i}" for i in members)
    return f"{pod.gang_shape} @ {coords} · {pod.gang_per_chip} {unit}/chip"


def _engine_cell(row: dict[str, float]) -> str:
    """One serving pod's cache telemetry as a compact cell: KV page
    occupancy, radix prefix-cache hit ratio, and the preemption count —
    the ``tpushare_engine_*`` families scraped from the pod's
    ``/metrics`` endpoint (``inspect.parse_engine_metrics`` keys, prefix
    already stripped). A disaggregated pod's ``tpushare_handoff_*``
    counters (folded into the row under ``handoff_*`` keys) append the
    KV-handoff story: transfers delivered, re-prefill fallbacks, pages
    still staged in flight. A speculative engine
    (``tpushare_engine_spec_*`` families) appends its summary — draft
    length, mean tokens emitted per verify dispatch, rollback pages —
    e.g. ``spec k=4 · acc 2.7/step · rb 12``; pods that export no spec
    families show nothing extra."""
    parts = []
    total = row.get("kv_pages_total")
    if total is not None:
        used = row.get("kv_pages_used")
        if used is None:
            used = total - row.get("kv_pages_free", 0.0)
        parts.append(f"pages {int(used)}/{int(total)}")
    hit = row.get("prefix_hit_ratio")
    if hit is not None:
        parts.append(f"prefix {100.0 * hit:.0f}%")
    pre = row.get("preemptions_total", row.get("preemptions"))
    if pre is not None:
        parts.append(f"preempt {int(pre)}")
    if any(k.startswith("handoff_") for k in row):
        parts.append(
            f"handoff {int(row.get('handoff_transfers_total_delivered', 0))}"
        )
        reprefill = sum(
            v for k, v in row.items()
            if k.startswith("handoff_fallback_reprefill_total")
        )
        if reprefill:
            parts.append(f"reprefill {int(reprefill)}")
        inflight = row.get("handoff_pages_in_flight", 0.0)
        if inflight:
            parts.append(f"inflight {int(inflight)}")
    if row.get("spec_enabled"):
        spec = f"spec k={int(row.get('spec_k', 0))}"
        cnt = row.get("spec_accepted_tokens_per_step_count", 0.0)
        if cnt:
            mean = row.get("spec_accepted_tokens_per_step_sum", 0.0) / cnt
            spec += f" · acc {mean:.1f}/step"
        spec += f" · rb {int(row.get('spec_rollback_pages_total', 0.0))}"
        parts.append(spec)
    return " · ".join(parts) or "-"


def _adapter_cell(row: dict[str, float] | None) -> str:
    """One multi-LoRA pod's ADAPTERS cell: resident adapters and the
    pool pages they hold, the admission hit ratio, and the eviction
    count — the ``tpushare_engine_adapter_*`` families. "-" when the
    pod's engine serves only the base model."""
    if not row or not row.get("adapter_enabled"):
        return "-"
    parts = [
        f"{int(row.get('adapter_resident', 0.0))} resident",
        f"{int(row.get('adapter_cache_pages', 0.0))} pages",
    ]
    hits = row.get("adapter_hits_total", 0.0)
    misses = row.get("adapter_misses_total", 0.0)
    if hits + misses:
        parts.append(f"hit {100.0 * hits / (hits + misses):.0f}%")
    ev = row.get("adapter_evictions_total", 0.0)
    if ev:
        parts.append(f"evict {int(ev)}")
    return " · ".join(parts)


def adapter_row_for(row: dict[str, float] | None) -> dict | None:
    """The ``adapters`` JSON sub-document for one scraped engine row
    (``-o json``): residency gauges, hit/miss/eviction counters with the
    recovered hit ratio, and the mean adapter-miss stall from the
    histogram's ``_sum``/``_count`` samples. ``None`` when the pod
    exports no adapter families — a base-model-only reference document
    gains no key (the ``speculative`` precedent)."""
    if not row or not row.get("adapter_enabled"):
        return None
    out: dict = {
        "enabled": True,
        "resident": int(row.get("adapter_resident", 0.0)),
        "cache_pages": int(row.get("adapter_cache_pages", 0.0)),
        "hits": int(row.get("adapter_hits_total", 0.0)),
        "misses": int(row.get("adapter_misses_total", 0.0)),
        "evictions": int(row.get("adapter_evictions_total", 0.0)),
    }
    total = out["hits"] + out["misses"]
    if total:
        out["hit_ratio"] = round(out["hits"] / total, 3)
    cnt = row.get("adapter_miss_stall_seconds_count", 0.0)
    if cnt:
        out["miss_stall_mean_s"] = round(
            row.get("adapter_miss_stall_seconds_sum", 0.0) / cnt, 6
        )
    return out


def spec_row_for(row: dict[str, float] | None) -> dict | None:
    """The ``speculative`` JSON sub-document for one scraped engine row
    (``-o json``): draft length, dispatch/rollback counters, and the
    acceptance means recovered from the histograms' ``_sum``/``_count``
    samples. ``None`` when the pod exports no spec families — the
    no-speculation reference document gains no key."""
    if not row or not row.get("spec_enabled"):
        return None
    out: dict = {
        "enabled": True,
        "k": int(row.get("spec_k", 0.0)),
        "draft_steps": int(row.get("spec_draft_steps_total", 0.0)),
        "rollback_pages": int(row.get("spec_rollback_pages_total", 0.0)),
    }
    cnt = row.get("spec_acceptance_len_count", 0.0)
    if cnt:
        out["acceptance_len_mean"] = round(
            row.get("spec_acceptance_len_sum", 0.0) / cnt, 3
        )
    cnt = row.get("spec_accepted_tokens_per_step_count", 0.0)
    if cnt:
        out["accepted_tokens_per_step_mean"] = round(
            row.get("spec_accepted_tokens_per_step_sum", 0.0) / cnt, 3
        )
    return out


def engine_row_for(pod, engine: dict[str, dict[str, float]] | None):
    """The scraped telemetry row for ``pod``, matched by the engine's
    ``pod`` metrics label: ``namespace/name`` first, then the bare pod
    name (what a pod that only knows its own name exports). ``None``
    when the pod runs no serving engine (or none was scraped)."""
    if not engine:
        return None
    return engine.get(f"{pod.namespace}/{pod.name}") or engine.get(pod.name)


def _interference_lines(doc: dict, indent: str = "") -> str:
    """Per-chip interference verdicts (the parsed
    ``tpushare.aliyun.com/interference`` annotation), one line per chip:
    victim, ratio vs its solo baseline, aggressors, FLAGGED marker."""
    def _chip_key(kv):
        # numeric order like every other per-chip listing (chip10 must
        # not sort before chip2); non-numeric keys sort after, by name
        try:
            return (0, int(kv[0]), "")
        except (TypeError, ValueError):
            return (1, 0, str(kv[0]))

    out = []
    for chip, row in sorted((doc.get("chips") or {}).items(), key=_chip_key):
        aggs = ", ".join(row.get("aggressors") or []) or "?"
        flag = "  FLAGGED" if row.get("flagged") else ""
        out.append(
            f"{indent}chip{chip}: {row.get('victim', '?')} "
            f"{row.get('ratio', 0.0):.2f}x vs solo "
            f"(aggressors: {aggs}){flag}\n"
        )
    return "".join(out)


def _fmt_step(row: dict[str, float] | None) -> str:
    """A pod's rolling step p50/p99 cell from its scraped
    ``tpushare_engine_step_p{50,99}_seconds`` gauges; "-" when the pod
    exports no step profile."""
    if not row:
        return "-"
    p50 = row.get("step_p50_seconds")
    p99 = row.get("step_p99_seconds")
    if p50 is None and p99 is None:
        return "-"

    def ms(v):
        return f"{v * 1e3:.1f}ms" if v is not None else "?"

    return f"{ms(p50)}/{ms(p99)}"


def render_top(
    infos: list[NodeInfo],
    obs: dict | None = None,
    *,
    now_label: str = "",
) -> str:
    """One refresh of the ``kubectl-inspect-tpushare top`` live view:
    per-chip co-residency (with workload classes), each resident's
    rolling step p50/p99, the chip's interference verdict, and the
    scraped SLO burn-rate + governor state. Deterministic for a given
    input set (golden-tested like ``render_trace``).

    ``obs`` is ``inspect.fetch_observability_metrics`` output:
    ``{"engine": {pod: {...}}, "slo": {tier: {...}}, "governor":
    {pod: {...}}}`` — any part may be missing (partial scrape)."""
    engine = (obs or {}).get("engine") or {}
    slo = (obs or {}).get("slo") or {}
    governor = (obs or {}).get("governor") or {}
    build = (obs or {}).get("build") or {}
    buf = StringIO()
    title = "tpushare top"
    if now_label:
        title += f" — {now_label}"
    buf.write(title + "\n")
    if build:
        buf.write(f"build: {_fmt_build(build)}\n")
    rows = [["NODE", "CHIP", "RESIDENTS (class)", "STEP p50/p99",
             "INTERFERENCE"]]
    for info in infos:
        held = set(info.core_held_chips)
        idoc = (info.interference or {}).get("chips") or {}
        for d in sorted(info.devices.values(), key=lambda d: d.index):
            residents = [
                p for p in info.pods if d.index in p.units_by_chip
            ]
            if d.index in held:
                res_cell = "exclusive (tpu-core)"
            elif residents:
                res_cell = " ".join(
                    f"{p.namespace}/{p.name}"
                    + ("(BE)" if p.workload_class
                       == const.WORKLOAD_BEST_EFFORT else "(LC)")
                    for p in sorted(
                        residents, key=lambda p: (p.namespace, p.name)
                    )
                )
            else:
                res_cell = "-"
            step_cells = []
            for p in sorted(residents, key=lambda p: (p.namespace, p.name)):
                cell = _fmt_step(engine_row_for(p, engine))
                if cell != "-":
                    step_cells.append(cell)
            irow = idoc.get(str(d.index))
            if irow:
                icell = (
                    f"{irow.get('ratio', 0.0):.2f}x {irow.get('victim', '?')}"
                    + (" FLAGGED" if irow.get("flagged") else "")
                )
            else:
                icell = "-"
            rows.append([
                info.name, f"chip{d.index}", res_cell,
                " ".join(step_cells) or "-", icell,
            ])
    buf.write(_table(rows))
    buf.write("\n")
    if slo:
        buf.write("SLO BURN\n")
        sev_names = {0.0: "ok", 1.0: "warn", 2.0: "page"}
        for tier, row in sorted(slo.items()):
            sev = sev_names.get(row.get("severity", 0.0), "?")
            line = (
                f"  {tier:<12} 5m={row.get('burn_5m', 0.0):.2f} "
                f"1h={row.get('burn_1h', 0.0):.2f} "
                f"6h={row.get('burn_6h', 0.0):.2f}"
            )
            remaining = row.get("error_budget_remaining")
            if remaining is not None:
                line += f" budget={remaining * 100:.1f}%"
            buf.write(f"{line} [{sev}]\n")
    if governor:
        buf.write("GOVERNOR\n")
        for pod, row in sorted(governor.items()):
            engaged = "ENGAGED" if row.get("engaged") else "released"
            buf.write(
                f"  {pod or '(unlabeled)':<20} {engaged} "
                f"engagements={int(row.get('engagements_total', 0))} "
                f"throttled={int(row.get('throttled_steps_total', 0))}\n"
            )
    return buf.getvalue()


def _fmt_build(build: dict | None) -> str:
    """One-line build identity from the scraped ``tpushare_build_info``
    labels: ``daemon v0.1.0 (rev abc123, py 3.10, jax 0.4.37)`` per
    component, joined by ``·``."""
    if not build:
        return ""
    parts = []
    for component, labels in sorted(build.items()):
        parts.append(
            f"{component} v{labels.get('version', '?')} "
            f"(rev {labels.get('git_rev', '?')}, "
            f"py {labels.get('python', '?')}, "
            f"jax {labels.get('jax', '?')})"
        )
    return " · ".join(parts)


def _score_cell(sv: dict) -> str:
    """One ScoreVector dict as a compact breakdown cell: the raw
    full-resolution score, its 0-10 wire projection, and the terms
    behind it (gang records add the topology objective components)."""
    parts = [
        f"raw={sv.get('raw', 0.0):.4f}",
        f"wire={sv.get('projected', 0)}/10",
        f"free={int(sv.get('free_units', 0))}",
        f"req={int(sv.get('request_units', 0))}",
        f"binpack={sv.get('binpack', 0.0):.3f}",
    ]
    for key in ("ici_hops", "stranded", "broken", "tie_break"):
        if key in sv:
            parts.append(f"{key}={sv[key]}")
    return " ".join(parts)


def _placement_cell(placement: dict) -> str:
    parts = []
    if placement.get("group"):
        cell = f"group {placement['group']}"
        if placement.get("members"):
            cell += f" ({placement['members']} members)"
        parts.append(cell)
    if "chip" in placement:
        parts.append(f"chip {placement['chip']}")
    if "chips" in placement:
        parts.append(
            "chips " + ",".join(str(i) for i in placement["chips"])
        )
    if placement.get("shape"):
        parts.append(f"shape {placement['shape']}")
    if "units" in placement:
        parts.append(f"{placement['units']} units")
    if "per_chip" in placement:
        parts.append(f"{placement['per_chip']} units/chip")
    if placement.get("tier"):
        parts.append(f"tier {placement['tier']}")
    if placement.get("tiers"):
        # a disaggregated two-tier slice: the group's composition,
        # prefill first (the catalog order), then anything else by name
        order = {t: i for i, t in enumerate(const.SERVING_TIERS)}
        names = sorted(
            placement["tiers"], key=lambda t: (order.get(t, len(order)), t)
        )
        parts.append(
            "tiers " + " + ".join(
                f"{placement['tiers'][t]} {t}" for t in names
            )
        )
    if placement.get("source"):
        parts.append(f"[{placement['source']}]")
    return " · ".join(parts) or "-"


def render_why(pod: str, records: list[dict]) -> str:
    """Render one pod's decision tree (``kubectl-inspect-tpushare why``):
    every verb's record in order — each rejected node with its reason,
    the score breakdowns ranked best-first by the RAW fractional score
    (winner vs runner-up with the margin the 0-10 wire scale cannot
    resolve), the chosen placement, and the WAL seq / trace id that tie
    the record to the journal and the PR 8 admission trace.
    Deterministic for a given record set (golden-tested like
    ``render_trace``)."""
    buf = StringIO()
    buf.write(f"pod {pod} — {len(records)} decision record(s)\n")
    if not records:
        buf.write(
            "(no decision records: admitted before provenance, emission "
            "disabled, or the ring already evicted it)\n"
        )
        return buf.getvalue()
    for rec in records:
        verb = rec.get("verb", "?")
        head = f"[#{rec.get('id', '?')}] {verb}"
        if rec.get("shard"):
            head += f" @{rec['shard']}"
        if rec.get("node"):
            head += f" -> {rec['node']}"
        if rec.get("outcome", "ok") != "ok":
            head += "  FAILED"
        buf.write(head + "\n")
        if rec.get("reason"):
            buf.write(f"   reason: {rec['reason']}\n")
        degraded = rec.get("degraded_shards") or []
        if degraded:
            # "not consulted" is a different fact than "rejected": these
            # shards' nodes were never scored at all
            buf.write(
                f"   ! not consulted (degraded shards): "
                f"{', '.join(degraded)}\n"
            )
        if rec.get("candidates"):
            line = f"   candidates: {rec['candidates']}"
            if rec.get("rejected"):
                line += f" ({len(rec['rejected'])} rejected)"
            buf.write(line + "\n")
        for node, why in sorted((rec.get("rejected") or {}).items()):
            buf.write(f"   x {node}: {why}\n")
        scores = rec.get("scores") or {}
        ranked = sorted(
            scores.items(),
            key=lambda kv: (-float(kv[1].get("raw", 0.0)), kv[0]),
        )
        for i, (name, sv) in enumerate(ranked):
            tag = "> " if i == 0 else "  "
            buf.write(f"   {tag}{name}  {_score_cell(sv)}\n")
        if len(ranked) >= 2:
            margin = float(ranked[0][1].get("raw", 0.0)) - float(
                ranked[1][1].get("raw", 0.0)
            )
            buf.write(
                f"   margin: {ranked[0][0]} leads {ranked[1][0]} by "
                f"{margin:.4f} raw\n"
            )
        if rec.get("placement"):
            buf.write(f"   placement: {_placement_cell(rec['placement'])}\n")
        moves = rec.get("moves") or []
        if moves:
            buf.write(f"   moves: {', '.join(moves)}\n")
        tail = []
        if rec.get("seq") is not None:
            tail.append(f"wal seq {rec['seq']}")
        if rec.get("trace_id"):
            tail.append(f"trace {rec['trace_id']}")
        if tail:
            buf.write(f"   {' · '.join(tail)}\n")
    return buf.getvalue()


SPARK_LEVELS = "▁▂▃▄▅▆▇█"


def sparkline(values: list[float], width: int = 48) -> str:
    """A unicode sparkline over the LAST ``width`` values, scaled to the
    rendered window's min/max (a flat series renders mid-level)."""
    if not values:
        return ""
    window = values[-width:]
    lo, hi = min(window), max(window)
    if hi - lo < 1e-12:
        return SPARK_LEVELS[3] * len(window)
    out = []
    for v in window:
        i = int((v - lo) / (hi - lo) * (len(SPARK_LEVELS) - 1))
        out.append(SPARK_LEVELS[max(0, min(len(SPARK_LEVELS) - 1, i))])
    return "".join(out)


def render_timeline(doc: dict, width: int = 48) -> str:
    """Render a ``/timeline`` document (``utils.timeline.to_doc``) as
    per-field sparklines with last/min/max annotations. Deterministic
    for a given document (golden-tested)."""
    series = (doc or {}).get("series") or {}
    buf = StringIO()
    buf.write(
        f"cluster timeline — bucket {doc.get('bucket_s', '?')}s, "
        f"span {doc.get('span_s', '?')}s\n"
    )
    populated = {
        # stats cover the SAME trailing window the sparkline renders —
        # a spike older than `width` buckets must not print a max= the
        # glyphs never show
        name: [float(v) for _t, v in points][-width:]
        for name, points in sorted(series.items())
        if points
    }
    if not populated:
        buf.write("(no samples yet)\n")
        return buf.getvalue()
    name_w = max(len(n) for n in populated)
    for name, values in populated.items():
        buf.write(
            f"{name.ljust(name_w)}  {sparkline(values, width=width)}  "
            f"last={values[-1]:g} min={min(values):g} "
            f"max={max(values):g} n={len(values)}\n"
        )
    return buf.getvalue()


def render_shards(doc: dict) -> str:
    """Render a ``/shards`` document (``ShardRouter.shards_doc``): the
    hash-ring ownership spread, one row per shard with its node count,
    WAL seq, journal queue depth, and in-flight 2PC gangs, then the
    pending gang2pc entries. Deterministic for a given document
    (golden-tested like ``render_why``/``render_top``)."""
    buf = StringIO()
    ring = (doc or {}).get("ring") or {}
    rows = (doc or {}).get("shards") or []
    buf.write(
        f"shard map — {ring.get('shards', len(rows))} shard(s), "
        f"{ring.get('vnodes', '?')} vnodes/shard, "
        f"fanout {doc.get('fanout', '?')}\n"
    )
    if not rows:
        buf.write("(no shards)\n")
        return buf.getvalue()
    per = ring.get("nodes_per_shard") or {}
    name_w = max(len(str(r.get("shard", "?"))) for r in rows)
    header = (
        f"{'SHARD'.ljust(name_w)}  NODES  WAL-SEQ  QUEUE  2PC  STATE"
    )
    buf.write(header + "\n")
    for r in rows:
        sid = str(r.get("shard", "?"))
        nodes = r.get("nodes", per.get(sid, 0))
        state = "PARTITIONED" if r.get("partitioned") else "ok"
        buf.write(
            f"{sid.ljust(name_w)}  {str(nodes).rjust(5)}  "
            f"{str(r.get('wal_seq', 0)).rjust(7)}  "
            f"{str(r.get('wal_pending', 0)).rjust(5)}  "
            f"{str(r.get('gangs_inflight', 0)).rjust(3)}  {state}\n"
        )
    gangs = (doc or {}).get("gangs_2pc") or []
    if gangs:
        buf.write("gang 2PC in flight:\n")
        for g in sorted(
            gangs, key=lambda g: (g.get("group", ""), g.get("pod", ""))
        ):
            buf.write(
                f"   {g.get('group', '?')} [{g.get('phase', '?')}] "
                f"pod={g.get('pod', '') or '-'} "
                f"node={g.get('node', '') or '-'} "
                f"shard={g.get('shard', '?')}\n"
            )
    return buf.getvalue()


def render_fleet(doc: dict) -> str:
    """Render a ``/fleet`` document (``FleetServer.fleet_doc``): one
    row per replica with its lifecycle state, health (consecutive
    scrape misses), headroom, queue depth and fingerprint count, then
    the router's routing/shed outcomes and the scale-down drain status.
    Deterministic for a given document (golden-tested like
    ``render_shards``)."""
    buf = StringIO()
    replicas = (doc or {}).get("replicas") or {}
    router = (doc or {}).get("router") or {}
    scale = (doc or {}).get("scale") or {}
    ratio = (doc or {}).get("prefix_hit_ratio")
    buf.write(
        f"fleet — {len(replicas)} replica(s), policy "
        f"{router.get('policy', '?')}, global prefix-hit ratio "
        f"{ratio if ratio is not None else '?'}\n"
    )
    if not replicas:
        buf.write("(no replicas)\n")
        return buf.getvalue()
    name_w = max(len("REPLICA"), max(len(str(n)) for n in replicas))
    header = (
        f"{'REPLICA'.ljust(name_w)}  STATE      MISSES  FREE  CAP  "
        f"QUEUE  PREFIXES"
    )
    buf.write(header + "\n")
    for name in sorted(replicas):
        r = replicas[name] or {}
        buf.write(
            f"{str(name).ljust(name_w)}  "
            f"{str(r.get('state', '?')).ljust(9)}  "
            f"{str(r.get('misses', 0)).rjust(6)}  "
            f"{str(r.get('free_slots', 0)).rjust(4)}  "
            f"{str(r.get('capacity', 0)).rjust(3)}  "
            f"{str(r.get('queue_depth', 0)).rjust(5)}  "
            f"{str(r.get('fingerprints', 0)).rjust(8)}\n"
        )
    outcomes = router.get("outcomes") or {}
    if outcomes:
        parts = [f"{k}={outcomes[k]}" for k in sorted(outcomes)]
        buf.write(
            f"router: {' '.join(parts)} "
            f"inflight={router.get('inflight', 0)} "
            f"affinity_hit_ratio="
            f"{round(router.get('affinity_hit_ratio', 0.0) or 0.0, 4)}\n"
        )
    buf.write(
        f"scale: ops={scale.get('ops', 0)} "
        f"migrated_requests={scale.get('migrated_requests', 0)}\n"
    )
    return buf.getvalue()


def render_trace(spans: list[dict]) -> str:
    """Render one admission/serving trace as an offset/duration tree.

    ``spans`` are flat span dicts (``utils.tracing.spans_from_otlp`` /
    ``Span.to_dict``); offsets are milliseconds from the earliest span's
    start. Orphans (parent span not in the set — e.g. only one process's
    ``/traces`` endpoint was reachable) render as extra roots, so a
    partial fetch still shows a timeline. Deterministic for a given span
    set (golden-tested)."""
    if not spans:
        return "(no spans)\n"
    by_id = {s["span_id"]: s for s in spans if s.get("span_id")}
    children: dict[str, list[dict]] = {}
    roots: list[dict] = []
    for s in spans:
        parent = s.get("parent_id", "")
        if parent and parent in by_id:
            children.setdefault(parent, []).append(s)
        else:
            roots.append(s)
    t0 = min(s["start_ns"] for s in spans)
    buf = StringIO()
    trace_ids = sorted({s.get("trace_id", "") for s in spans})
    buf.write(f"trace {', '.join(t for t in trace_ids if t)}\n")

    def attr_note(s: dict) -> str:
        attrs = s.get("attributes") or {}
        parts = []
        for key in ("pod", "node", "chip", "chips", "rid", "error"):
            if key in attrs:
                parts.append(f"{key}={attrs[key]}")
        if s.get("status") not in (None, "ok"):
            parts.append(f"status={s['status']}")
        return ("  " + " ".join(parts)) if parts else ""

    def emit(s: dict, prefix: str, tail: str, child_prefix: str) -> None:
        start_ms = (s["start_ns"] - t0) / 1e6
        dur_ms = max(0, s.get("end_ns", 0) - s["start_ns"]) / 1e6
        name = f"{prefix}{tail}{s.get('name', '?')}"
        buf.write(
            f"{name:<44} +{start_ms:9.3f}ms {dur_ms:9.3f}ms{attr_note(s)}\n"
        )
        kids = sorted(
            children.get(s.get("span_id", ""), ()),
            key=lambda c: (c["start_ns"], c.get("name", "")),
        )
        for i, kid in enumerate(kids):
            last = i == len(kids) - 1
            emit(
                kid,
                prefix + child_prefix,
                "└─ " if last else "├─ ",
                "   " if last else "│  ",
            )

    for root in sorted(roots, key=lambda s: (s["start_ns"], s.get("name", ""))):
        emit(root, "", "", "")
    return buf.getvalue()


def render_flightrecord(doc: dict, max_traces: int = 5, max_logs: int = 20) -> str:
    """Human summary of a flight-record dump (utils/flightrec.py): the
    header, the most recent traces as timeline trees, and the tail of
    the log ring with trace correlation."""
    import datetime

    from ..utils.tracing import spans_from_otlp

    buf = StringIO()
    when = datetime.datetime.fromtimestamp(
        doc.get("time_unix", 0), tz=datetime.timezone.utc
    ).strftime("%Y-%m-%d %H:%M:%S UTC")
    buf.write(f"flight record: reason={doc.get('reason', '?')}\n")
    buf.write(f"captured     : {when} (pid {doc.get('pid', '?')})\n")
    buf.write(
        f"traces       : {doc.get('trace_count', 0)} retained, "
        f"{doc.get('dropped_traces', 0)} older evicted\n"
    )
    timeline = doc.get("timeline") or {}
    tl_series = {
        k: v for k, v in (timeline.get("series") or {}).items() if v
    }
    if tl_series:
        buf.write(
            f"timeline     : {len(tl_series)} series over the last "
            f"{timeline.get('span_s', '?')}s (render with "
            "`inspect timeline`)\n"
        )
        buf.write(render_timeline(timeline))
    spans = spans_from_otlp(doc.get("traces") or {})
    by_trace: dict[str, list[dict]] = {}
    for s in spans:
        by_trace.setdefault(s["trace_id"], []).append(s)
    # newest last in store order; show the most recent max_traces
    shown = list(by_trace.items())[-max_traces:]
    if len(by_trace) > len(shown):
        buf.write(f"(showing the last {len(shown)} of {len(by_trace)} traces)\n")
    for _tid, tspans in shown:
        buf.write("\n")
        buf.write(render_trace(tspans))
    logs = doc.get("logs") or []
    if logs:
        buf.write(f"\nlast {min(max_logs, len(logs))} log records:\n")
        for entry in logs[-max_logs:]:
            trace = (
                f" [{entry['trace_id'][:8]}/{entry['span_id'][:8]}]"
                if entry.get("trace_id")
                else ""
            )
            buf.write(
                f"  {entry.get('level', '?'):<8} {entry.get('logger', '?')}"
                f"{trace} {entry.get('message', '')}\n"
            )
    return buf.getvalue()


def render_details(
    infos: list[NodeInfo],
    engine: dict[str, dict[str, float]] | None = None,
    build: dict | None = None,
) -> str:
    unit = infer_unit(infos)
    buf = StringIO()
    if build:
        buf.write(f"build: {_fmt_build(build)}\n")
    for info in infos:
        buf.write(f"NAME: {info.name} ({info.address})\n")
        any_gang = any(p.is_gang for p in info.pods)
        any_engine = engine is not None and any(
            engine_row_for(p, engine) for p in info.pods
        )
        # the ADAPTERS column appears only when some pod's engine serves
        # LoRA tenants — base-model fleets keep the reference layout
        any_adapter = engine is not None and any(
            (engine_row_for(p, engine) or {}).get("adapter_enabled")
            for p in info.pods
        )
        # the CLASS column appears only when a non-default class is
        # present, preserving the reference layout for fleets that never
        # declare workload classes
        any_class = any(
            p.workload_class != const.WORKLOAD_LATENCY_CRITICAL
            for p in info.pods
        )
        # likewise the TIER column: only when some pod declares a
        # disaggregated-serving tier (serving/handoff.py) — unified
        # fleets keep the reference layout
        any_tier = any(p.serving_tier for p in info.pods)
        header = ["NAMESPACE", "NAME", f"TPU MEMORY ({unit})", "CHIPS"]
        if any_class:
            header.append("CLASS")
        if any_tier:
            header.append("TIER")
        if any_gang:
            header.append("GANG (shape @ coords)")
        if any_engine:
            header.append("SERVING CACHE")
        if any_adapter:
            header.append("ADAPTERS")
        rows = [header]
        for pod in sorted(info.pods, key=lambda p: (p.namespace, p.name)):
            chips = ", ".join(
                ("pending" if idx == PENDING_IDX else f"chip{idx}") + f":{units}"
                for idx, units in sorted(pod.units_by_chip.items())
            )
            row = [pod.namespace, pod.name, str(pod.total_units), chips]
            if any_class:
                row.append(pod.workload_class)
            if any_tier:
                row.append(pod.serving_tier or "-")
            if any_gang:
                row.append(_gang_cell(pod, info, unit) if pod.is_gang else "-")
            if any_engine:
                erow = engine_row_for(pod, engine)
                row.append(_engine_cell(erow) if erow else "-")
            if any_adapter:
                row.append(_adapter_cell(engine_row_for(pod, engine)))
            rows.append(row)
        buf.write(_table(rows))
        buf.write("\n")
        if info.core_holds:
            crows = [["NAMESPACE", "NAME", "EXCLUSIVE CHIPS"]]
            for hold in sorted(info.core_holds, key=lambda h: (h.namespace, h.name)):
                chips = ",".join(f"chip{i}" for i in hold.chips) or (
                    f"pending ({hold.requested} chip"
                    + ("s" if hold.requested != 1 else "")
                    + ")"
                )
                crows.append([hold.namespace, hold.name, chips])
            buf.write(_table(crows))
            buf.write("\n")
        buf.write(
            f"Allocated : {info.used_units} ({(100.0 * info.used_units / info.total_units) if info.total_units else 0:.0f}%)\n"
        )
        buf.write(f"Total     : {info.total_units}\n")
        if info.defrag is not None:
            slivers = " ".join(
                f"chip{i}:{u}"
                for i, u in sorted(info.stranded_by_chip.items())
            ) or "none"
            buf.write(
                f"Stranded  : {sum(info.stranded_by_chip.values())} "
                f"({unit}, sub-quantum slivers: {slivers}, "
                f"quantum {int(info.defrag.get('quantum') or 0)})\n"
            )
            buf.write(f"Moves     : {_moves_cell(info.defrag)}\n")
        if info.interference and info.interference.get("chips"):
            buf.write(
                "Interference:\n"
                + _interference_lines(info.interference, indent="  ")
            )
        buf.write("\n")
    buf.write(render_summary(infos))
    return buf.getvalue()
