"""``kubectl-inspect-tpushare``: cluster TPU-share utilization report.

Reference: ``cmd/inspect/main.go:31-74`` — optional node-name argument
narrows the report; ``-d`` shows per-pod details. Reads only the apiserver
(kubeconfig from ``$KUBECONFIG``/``~/.kube/config``, else in-cluster), with
the reference CLI's 5 x 100 ms list retry budget (``podinfo.go:24,64-69``).
"""

from __future__ import annotations

import argparse
import sys

from ..cluster.apiserver import ApiServerClient
from ..utils.retry import retry
from .display import render_details, render_summary
from .nodeinfo import build_all_node_infos

LIST_RETRIES = 5
LIST_DELAY_S = 0.1


def _client(timeout_s: float = 10.0) -> ApiServerClient:
    return ApiServerClient.from_env(timeout_s=timeout_s)


def gather(client: ApiServerClient, node_name: str = "") -> tuple[list, list]:
    nodes = retry(client.list_nodes, attempts=LIST_RETRIES, delay_s=LIST_DELAY_S)
    if node_name:
        nodes = [n for n in nodes if n.get("metadata", {}).get("name") == node_name]
        if not nodes:
            raise SystemExit(f"error: node {node_name!r} not found")
    pods = retry(client.list_pods, attempts=LIST_RETRIES, delay_s=LIST_DELAY_S)
    return nodes, pods


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="kubectl-inspect-tpushare",
        description="Display TPU-share HBM utilization across the cluster",
    )
    p.add_argument("node", nargs="?", default="", help="restrict to one node")
    p.add_argument("-d", "--details", action="store_true", help="per-pod rows")
    p.add_argument("-o", "--output", default="table", choices=["table", "json"],
                   help="output format (json: machine-readable, for "
                   "dashboards/automation)")
    args = p.parse_args(argv)

    try:
        client = _client()
        nodes, pods = gather(client, args.node)
    except SystemExit:
        raise
    except Exception as e:  # config errors or exhausted list retries
        print(f"error: cannot reach the cluster: {e}", file=sys.stderr)
        return 1
    infos = build_all_node_infos(nodes, pods)
    if args.output == "json":
        sys.stdout.write(render_json(infos))
        return 0
    if not infos:
        print("no shared-TPU nodes found (allocatable aliyun.com/tpu-mem is 0 everywhere)")
        return 0
    out = render_details(infos) if args.details else render_summary(infos)
    sys.stdout.write(out)
    return 0


def render_json(infos: list) -> str:
    """Machine-readable report: the same numbers the tables show,
    including the north-star cluster utilization line."""
    import json

    from .nodeinfo import infer_unit

    total = sum(n.total_units for n in infos)
    used = sum(n.used_units for n in infos)

    def node_doc(n):
        held = set(n.core_held_chips)
        return {
            "name": n.name,
            "address": n.address,
            "total_units": n.total_units,
            "used_units": n.used_units,
            "pending_units": n.pending_units,
            "chips": [
                {
                    "index": d.index,
                    "total_units": d.total_units,
                    "used_units": d.used_units,
                    "core_held": d.index in held,
                }
                for d in sorted(n.devices.values(), key=lambda d: d.index)
            ],
            "pods": [
                {
                    "namespace": p.namespace,
                    "name": p.name,
                    "units_by_chip": {str(k): v for k, v in p.units_by_chip.items()},
                    **(
                        {
                            "gang_shape": p.gang_shape,
                            "gang_per_chip": p.gang_per_chip,
                            "gang_coords": {
                                str(i): list(n.topology.coords(i))
                                for i in sorted(p.units_by_chip)
                                if n.topology is not None
                                and 0 <= i < n.topology.n_chips
                            },
                        }
                        if p.is_gang
                        else {}
                    ),
                }
                for p in n.pods
            ],
            "core_holds": [
                {
                    "namespace": h.namespace,
                    "name": h.name,
                    "chips": h.chips,
                    "requested": h.requested,
                }
                for h in n.core_holds
            ],
        }

    doc = {
        # same MiB/GiB heuristic the table headers use; without it a
        # consumer cannot compare unit counts across clusters
        "unit": infer_unit(infos),
        "nodes": [node_doc(n) for n in infos],
        "cluster": {
            "total_units": total,
            "used_units": used,
            "utilization_pct": round(100.0 * used / total, 1) if total else 0.0,
        },
    }
    return json.dumps(doc, indent=2) + "\n"


if __name__ == "__main__":
    sys.exit(main())
