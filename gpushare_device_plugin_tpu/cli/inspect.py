"""``kubectl-inspect-tpushare``: cluster TPU-share utilization report,
admission-trace timelines, and flight-record postmortems.

Reference: ``cmd/inspect/main.go:31-74`` — optional node-name argument
narrows the report; ``-d`` shows per-pod details. Reads only the apiserver
(kubeconfig from ``$KUBECONFIG``/``~/.kube/config``, else in-cluster), with
the reference CLI's 5 x 100 ms list retry budget (``podinfo.go:24,64-69``).

Observability subcommands (docs/observability.md):

- ``inspect trace [ns/]pod --traces-url http://node:PORT [...]`` — read
  the pod's ``tpushare.aliyun.com/trace-id`` annotation, fetch the trace
  from each given ``/traces`` endpoint (the extender's and the node
  daemon's metrics ports), merge, and render the admission timeline.
- ``inspect why [ns/]pod --decisions-url http://node:PORT [...]`` —
  fetch the pod's decision-provenance records from each ``/decisions``
  endpoint, merge, and render the decision tree: every rejected node
  with its reason, winner-vs-runner-up score breakdowns, the chosen
  placement, WAL seq, and the stitched trace id.
- ``inspect timeline --timeline-url http://node:PORT [...]`` — render
  the cluster-state timeline ring (utilization / stranded % / queue
  depth / SLO burn) as sparklines.
- ``inspect flightrecord <file>`` — summarize a flight-recorder dump.
"""

from __future__ import annotations

import argparse
import json
import sys

from .. import const
from ..cluster.apiserver import ApiServerClient
from ..utils.metric_catalog import (
    BUILD_INFO,
    PREFIX_ENGINE,
    PREFIX_FLEET,
    PREFIX_GOVERNOR,
    PREFIX_HANDOFF,
    PREFIX_ROUTER,
    PREFIX_SLO,
)
from ..utils.retry import retry
from .display import (
    render_details,
    render_flightrecord,
    render_summary,
    render_trace,
)
from .nodeinfo import build_all_node_infos

LIST_RETRIES = 5
LIST_DELAY_S = 0.1


def _client(timeout_s: float = 10.0) -> ApiServerClient:
    return ApiServerClient.from_env(timeout_s=timeout_s)


def gather(client: ApiServerClient, node_name: str = "") -> tuple[list, list]:
    nodes = retry(client.list_nodes, attempts=LIST_RETRIES, delay_s=LIST_DELAY_S)
    if node_name:
        nodes = [n for n in nodes if n.get("metadata", {}).get("name") == node_name]
        if not nodes:
            raise SystemExit(f"error: node {node_name!r} not found")
    pods = retry(client.list_pods, attempts=LIST_RETRIES, delay_s=LIST_DELAY_S)
    return nodes, pods


def _fetch_json_docs(urls: list[str], suffix: str, params=None):
    """Yield one parsed JSON document per reachable endpoint — THE
    fetch-and-merge boilerplate (URL suffix normalization, 10 s timeout,
    warn-on-stderr partial-merge policy) shared by every JSON endpoint
    reader (``/traces``, ``/decisions``, ``/timeline``); a partial
    answer beats none."""
    import requests

    for url in urls:
        full = url.rstrip("/")
        if not full.endswith(suffix):
            full += suffix
        try:
            resp = requests.get(full, params=params, timeout=10)
            resp.raise_for_status()
            yield resp.json()
        except Exception as e:  # noqa: BLE001 — partial merge by design
            print(f"warning: {full} unreachable: {e}", file=sys.stderr)


def fetch_trace_spans(urls: list[str], trace_id: str) -> list[dict]:
    """Fetch + merge one trace from every ``/traces`` endpoint given
    (extender and node daemon each hold their process's half; spans are
    deduped by span id). Unreachable endpoints are reported but do not
    fail the merge — a partial timeline beats none."""
    from ..utils.tracing import spans_from_otlp

    spans: dict[str, dict] = {}
    for doc in _fetch_json_docs(urls, "/traces", {"trace_id": trace_id}):
        for span in spans_from_otlp(doc):
            spans.setdefault(span["span_id"], span)
    return sorted(spans.values(), key=lambda s: (s["start_ns"], s["name"]))


def parse_engine_metrics(text: str) -> dict[str, dict[str, float]]:
    """Pull the serving engine's cache telemetry out of a ``/metrics``
    exposition: ``tpushare_engine_*`` samples keyed by their ``pod``
    label (``""`` for unlabeled engines). Families: KV page occupancy
    (``kv_pages_total/used/free``), ``prefix_hit_ratio``,
    ``prefix_cached_pages``, the ``preemptions`` gauge /
    ``preemptions_total`` counter, and the speculative-decoding
    ``spec_*`` group (``spec_enabled``/``spec_k`` gauges,
    ``spec_draft_steps_total``/``spec_rollback_pages_total`` counters,
    plus the ``_sum``/``_count`` of the acceptance histograms), and the
    multi-LoRA ``adapter_*`` group (``adapter_enabled``/``resident``/
    ``cache_pages`` gauges, hit/miss/eviction counters, plus the
    ``_sum``/``_count`` of the miss-stall histogram).

    The disaggregated-serving ``tpushare_handoff_*`` families
    (utils/metric_catalog.py) fold into the same per-pod row under
    ``handoff_*`` keys — an ``outcome``/``reason`` label folds into the
    key (``handoff_transfers_total_delivered``); the fleet router's
    ``tpushare_fleet_*`` / ``tpushare_router_*`` families fold the same
    way under ``fleet_*`` / ``router_*`` keys (``engine``/``tier``/
    ``state`` labels fold too: ``router_shed_total_best_effort``,
    ``fleet_replicas_ready``); histogram buckets are skipped, the
    ``_sum``/``_count`` samples carry what the CLI shows."""
    out: dict[str, dict[str, float]] = {}
    for line in text.splitlines():
        if line.startswith("#"):
            continue
        if line.startswith(PREFIX_ENGINE):
            prefix, fold = PREFIX_ENGINE, ""
        elif line.startswith(PREFIX_HANDOFF):
            prefix, fold = PREFIX_HANDOFF, "handoff_"
        elif line.startswith(PREFIX_FLEET):
            prefix, fold = PREFIX_FLEET, "fleet_"
        elif line.startswith(PREFIX_ROUTER):
            prefix, fold = PREFIX_ROUTER, "router_"
        else:
            continue
        try:
            metric, value = line.rsplit(None, 1)
            val = float(value)
        except ValueError:
            continue
        pod = ""
        name = metric
        labels: dict[str, str] = {}
        if "{" in metric:
            name, raw = metric.split("{", 1)
            labels = _parse_prom_labels(raw.rstrip("}"))
            pod = labels.get("pod", "")
        if name.endswith("_bucket") or "le" in labels:
            continue
        short = fold + name[len(prefix):]
        for extra in ("outcome", "reason", "tier", "state", "engine"):
            if labels.get(extra):
                short += f"_{labels[extra]}"
        out.setdefault(pod, {})[short] = val
    return out


def _parse_prom_labels(raw: str) -> dict[str, str]:
    """Minimal label-block parse ('k="v",k2="v2"'); same tolerance as
    ``parse_engine_metrics`` (label values containing commas are beyond
    this CLI's needs)."""
    out: dict[str, str] = {}
    for part in raw.split(","):
        if "=" not in part:
            continue
        k, v = part.split("=", 1)
        out[k.strip()] = v.strip().strip('"').replace('\\"', '"')
    return out


def parse_observability_metrics(text: str) -> dict:
    """Pull the interference plane's families out of a ``/metrics``
    exposition for the ``top`` view:

    - ``engine``: :func:`parse_engine_metrics` rows (now including the
      ``step_p50_seconds``/``step_p99_seconds`` profiler gauges), keyed
      by ``pod`` label;
    - ``slo``: per-tier burn rates / budget remaining / severity from
      the ``tpushare_slo_*`` gauges;
    - ``governor``: per-pod engage state + counters from the
      ``tpushare_governor_*`` families;
    - ``build``: per-component version labels from
      ``tpushare_build_info`` (the inspect header line).
    """
    out: dict = {
        "engine": parse_engine_metrics(text), "slo": {}, "governor": {},
        "build": {},
    }
    for line in text.splitlines():
        if line.startswith("#"):
            continue
        if not line.startswith(
            (PREFIX_SLO, PREFIX_GOVERNOR, BUILD_INFO)
        ):
            continue
        try:
            metric, value = line.rsplit(None, 1)
            val = float(value)
        except ValueError:
            continue
        labels: dict[str, str] = {}
        name = metric
        if "{" in metric:
            name, raw = metric.split("{", 1)
            labels = _parse_prom_labels(raw.rstrip("}"))
        if name == BUILD_INFO:
            component = labels.pop("component", "") or "?"
            out["build"][component] = labels
        elif name.startswith(PREFIX_SLO):
            tier = labels.get("tier", "")
            if not tier:
                continue
            row = out["slo"].setdefault(tier, {})
            short = name[len(PREFIX_SLO):]
            if short == "burn_rate":
                row[f"burn_{labels.get('window', '?')}"] = val
            else:
                row[short] = val
        else:
            pod = labels.get("pod", "")
            row = out["governor"].setdefault(pod, {})
            row[name[len(PREFIX_GOVERNOR):]] = val
    return out


def fetch_observability_metrics(urls: list[str]) -> dict:
    """Scrape + merge the ``top`` view's telemetry from every
    ``/metrics`` endpoint given (same partial-scrape policy as
    :func:`fetch_engine_metrics`)."""
    import requests

    out: dict = {"engine": {}, "slo": {}, "governor": {}, "build": {}}
    for url in urls:
        full = url.rstrip("/")
        if not full.endswith("/metrics"):
            full += "/metrics"
        try:
            resp = requests.get(full, timeout=10)
            resp.raise_for_status()
            text = resp.text
        except Exception as e:  # noqa: BLE001 — partial scrape by design
            print(f"warning: {full} unreachable: {e}", file=sys.stderr)
            continue
        parsed = parse_observability_metrics(text)
        for section in ("engine", "slo", "governor", "build"):
            for key, row in parsed[section].items():
                out[section].setdefault(key, {}).update(row)
    return out


def fetch_engine_metrics(urls: list[str]) -> dict[str, dict[str, float]]:
    """Scrape serving-cache telemetry from every ``/metrics`` endpoint
    given (each serving pod's engine exports under its own ``pod``
    label). Unreachable endpoints warn but do not fail — partial
    telemetry beats none (same policy as :func:`fetch_trace_spans`)."""
    import requests

    out: dict[str, dict[str, float]] = {}
    for url in urls:
        full = url.rstrip("/")
        if not full.endswith("/metrics"):
            full += "/metrics"
        try:
            resp = requests.get(full, timeout=10)
            resp.raise_for_status()
            text = resp.text
        except Exception as e:  # noqa: BLE001 — partial scrape by design
            print(f"warning: {full} unreachable: {e}", file=sys.stderr)
            continue
        for pod, row in parse_engine_metrics(text).items():
            out.setdefault(pod, {}).update(row)
    return out


def fetch_decisions(urls: list[str], pod: str) -> list[dict]:
    """Fetch + merge one pod's decision records from every
    ``/decisions`` endpoint given (the extender's and the node daemon's
    metrics ports each hold their process's half of the admission
    story). Records are deduped by (verb, id, time) — ids are
    per-process — and ordered by emission time. Unreachable endpoints
    warn but do not fail: a partial "why" beats none (same policy as
    :func:`fetch_trace_spans`)."""
    merged: dict[tuple, dict] = {}
    for doc in _fetch_json_docs(urls, "/decisions", {"pod": pod}):
        for rec in doc.get("records") or []:
            key = (rec.get("verb"), rec.get("id"), rec.get("time_unix"))
            merged.setdefault(key, rec)
    return sorted(
        merged.values(),
        key=lambda r: (r.get("time_unix", 0.0), r.get("id", 0)),
    )


def why_main(argv: list[str]) -> int:
    """``kubectl-inspect-tpushare why [ns/]pod``: render the pod's full
    admission decision tree — every rejected node with its reason, the
    score breakdowns (winner vs runner-up at raw resolution), the chosen
    placement, WAL seq, and the stitched trace id
    (docs/observability.md)."""
    from .display import render_why

    p = argparse.ArgumentParser(
        prog="kubectl-inspect-tpushare why",
        description="Explain one pod's admission decisions",
    )
    p.add_argument("pod", help="[namespace/]name of a share pod")
    p.add_argument("--decisions-url", action="append", default=[],
                   metavar="URL",
                   help="a /decisions endpoint to fetch records from "
                   "(the extender's and/or node daemon's --metrics-"
                   "port); repeatable — records from all endpoints are "
                   "merged into one story")
    p.add_argument("-o", "--output", default="tree", choices=["tree", "json"])
    args = p.parse_args(argv)
    ns, _, name = args.pod.rpartition("/")
    pod_key = f"{ns or 'default'}/{name}"
    if not args.decisions_url:
        print(
            "error: no --decisions-url given — point me at the "
            "extender's and/or node daemon's metrics port (e.g. "
            "--decisions-url http://node:9114)",
            file=sys.stderr,
        )
        return 1
    records = fetch_decisions(args.decisions_url, pod_key)
    if args.output == "json":
        json.dump(records, sys.stdout, indent=2)
        print()
        return 0
    if not records:
        print(
            f"error: no decision records for {pod_key} (admitted before "
            "provenance, emission disabled, or the ring already evicted "
            "it)",
            file=sys.stderr,
        )
        return 1
    sys.stdout.write(render_why(pod_key, records))
    return 0


def fetch_timeline(urls: list[str]) -> dict:
    """Fetch + merge ``/timeline`` documents (per-field union; the same
    field from several endpoints merges by bucket time, later endpoints
    winning ties). Unreachable endpoints warn but do not fail."""
    merged: dict = {"bucket_s": None, "span_s": None, "series": {}}
    for doc in _fetch_json_docs(urls, "/timeline"):
        if merged["bucket_s"] is None:
            merged["bucket_s"] = doc.get("bucket_s")
            merged["span_s"] = doc.get("span_s")
        for field, points in (doc.get("series") or {}).items():
            byt = {t: v for t, v in merged["series"].get(field, [])}
            byt.update({t: v for t, v in points})
            merged["series"][field] = [
                [t, byt[t]] for t in sorted(byt)
            ]
    return merged


def timeline_main(argv: list[str]) -> int:
    """``kubectl-inspect-tpushare timeline``: sparkline view of the
    cluster-state timeline ring (utilization, stranded %, pending/gang
    queue depth, SLO burn) served on ``/timeline``."""
    from .display import render_timeline

    p = argparse.ArgumentParser(
        prog="kubectl-inspect-tpushare timeline",
        description="Cluster-state timeline sparklines",
    )
    p.add_argument("--timeline-url", action="append", default=[],
                   metavar="URL",
                   help="a /timeline endpoint (a daemon's --metrics-"
                   "port); repeatable — series are merged")
    p.add_argument("--width", type=int, default=48,
                   help="sparkline width in buckets")
    p.add_argument("-o", "--output", default="spark",
                   choices=["spark", "json"])
    args = p.parse_args(argv)
    if not args.timeline_url:
        print(
            "error: no --timeline-url given — point me at a node "
            "daemon's metrics port (e.g. --timeline-url "
            "http://node:9114)",
            file=sys.stderr,
        )
        return 1
    doc = fetch_timeline(args.timeline_url)
    if args.output == "json":
        json.dump(doc, sys.stdout, indent=2)
        print()
        return 0
    sys.stdout.write(render_timeline(doc, width=args.width))
    return 0


def fetch_shards(urls: list[str]) -> dict:
    """Fetch + merge ``/shards`` documents (several router replicas
    serve the same ring; rows merge by shard id, later endpoints
    winning ties). Unreachable endpoints warn but do not fail."""
    merged: dict = {"ring": None, "fanout": None, "shards": {}, "gangs": {}}
    for doc in _fetch_json_docs(urls, "/shards"):
        if merged["ring"] is None:
            merged["ring"] = doc.get("ring")
            merged["fanout"] = doc.get("fanout")
        for row in doc.get("shards") or []:
            merged["shards"][row.get("shard", "?")] = row
        for g in doc.get("gangs_2pc") or []:
            # replicas fronting the same shards report the same gangs —
            # dedupe like the shard rows, not extend
            key = (g.get("group"), g.get("pod"), g.get("shard"),
                   g.get("phase"))
            merged["gangs"].setdefault(key, g)
    return {
        "ring": merged["ring"] or {},
        "fanout": merged["fanout"],
        "shards": [merged["shards"][k] for k in sorted(merged["shards"])],
        "gangs_2pc": list(merged["gangs"].values()),
    }


def shards_main(argv: list[str]) -> int:
    """``kubectl-inspect-tpushare shards``: render the sharded
    extender's shard map — hash-ring ownership, per-shard WAL seq and
    journal queue depth, and cross-shard 2PC gangs in flight
    (docs/scheduling.md)."""
    from .display import render_shards

    p = argparse.ArgumentParser(
        prog="kubectl-inspect-tpushare shards",
        description="Sharded-extender shard map",
    )
    p.add_argument("--shards-url", action="append", default=[],
                   metavar="URL",
                   help="a /shards endpoint (the shard router's "
                   "--metrics-port); repeatable — rows are merged by "
                   "shard id")
    p.add_argument("-o", "--output", default="table",
                   choices=["table", "json"])
    args = p.parse_args(argv)
    if not args.shards_url:
        print(
            "error: no --shards-url given — point me at the shard "
            "router's metrics port (e.g. --shards-url "
            "http://router:9114)",
            file=sys.stderr,
        )
        return 1
    doc = fetch_shards(args.shards_url)
    if args.output == "json":
        json.dump(doc, sys.stdout, indent=2)
        print()
        return 0
    sys.stdout.write(render_shards(doc))
    return 0


def fetch_fleet(urls: list[str]) -> dict:
    """Fetch + merge ``/fleet`` documents (a fleet may front several
    router replicas; replica rows merge by name, later endpoints
    winning ties). Unreachable endpoints warn but do not fail."""
    merged: dict = {
        "replicas": {}, "router": None, "scale": None,
        "prefix_hit_ratio": None,
    }
    for doc in _fetch_json_docs(urls, "/fleet"):
        for name, row in (doc.get("replicas") or {}).items():
            merged["replicas"][name] = row
        if merged["router"] is None:
            merged["router"] = doc.get("router")
            merged["scale"] = doc.get("scale")
            merged["prefix_hit_ratio"] = doc.get("prefix_hit_ratio")
    return {
        "replicas": {
            name: merged["replicas"][name]
            for name in sorted(merged["replicas"])
        },
        "router": merged["router"] or {},
        "scale": merged["scale"] or {},
        "prefix_hit_ratio": merged["prefix_hit_ratio"],
    }


def fleet_main(argv: list[str]) -> int:
    """``kubectl-inspect-tpushare fleet``: render the fleet router's
    replica map — per-replica health/state/queue depth, router routing
    outcomes and shed counts, scale-down drain status, and the global
    prefix-hit ratio (docs/serving.md, fleet section)."""
    from .display import render_fleet

    p = argparse.ArgumentParser(
        prog="kubectl-inspect-tpushare fleet",
        description="Fleet router replica map + routing outcomes",
    )
    p.add_argument("--fleet-url", action="append", default=[],
                   metavar="URL",
                   help="a /fleet endpoint (the fleet router's "
                   "--metrics-port); repeatable — replica rows are "
                   "merged by name")
    p.add_argument("-o", "--output", default="table",
                   choices=["table", "json"])
    args = p.parse_args(argv)
    if not args.fleet_url:
        print(
            "error: no --fleet-url given — point me at the fleet "
            "router's metrics port (e.g. --fleet-url "
            "http://router:9114)",
            file=sys.stderr,
        )
        return 1
    doc = fetch_fleet(args.fleet_url)
    if args.output == "json":
        json.dump(doc, sys.stdout, indent=2)
        print()
        return 0
    sys.stdout.write(render_fleet(doc))
    return 0


def trace_main(argv: list[str]) -> int:
    p = argparse.ArgumentParser(
        prog="kubectl-inspect-tpushare trace",
        description="Render one pod's admission trace timeline",
    )
    p.add_argument("pod", help="[namespace/]name of an admitted share pod")
    p.add_argument("--traces-url", action="append", default=[],
                   help="a /traces endpoint to fetch spans from (the "
                   "extender's and/or node daemon's --metrics-port); "
                   "repeatable — spans from all endpoints are merged")
    p.add_argument("-o", "--output", default="tree", choices=["tree", "json"])
    args = p.parse_args(argv)
    ns, _, name = args.pod.rpartition("/")
    ns = ns or "default"
    try:
        pod = _client().get_pod(ns, name)
    except Exception as e:  # config errors / 404
        print(f"error: cannot read pod {ns}/{name}: {e}", file=sys.stderr)
        return 1
    raw = (pod.get("metadata", {}).get("annotations") or {}).get(
        const.ANN_TRACE_ID
    )
    if not raw:
        print(
            f"error: pod {ns}/{name} carries no {const.ANN_TRACE_ID} "
            "annotation (admitted before tracing, branch-B placement "
            "without the extender, or the trace was not sampled)",
            file=sys.stderr,
        )
        return 1
    trace_id = raw.split(":", 1)[0]
    if not args.traces_url:
        print(
            f"trace id: {trace_id}\n"
            "error: no --traces-url given — point me at the extender's "
            "and/or node daemon's metrics port (e.g. "
            "--traces-url http://node:9114)",
            file=sys.stderr,
        )
        return 1
    spans = fetch_trace_spans(args.traces_url, trace_id)
    if not spans:
        print(f"error: no spans found for trace {trace_id}", file=sys.stderr)
        return 1
    if args.output == "json":
        json.dump(spans, sys.stdout, indent=2)
        print()
        return 0
    sys.stdout.write(f"pod {ns}/{name}\n")
    sys.stdout.write(render_trace(spans))
    return 0


def top_main(argv: list[str]) -> int:
    """``kubectl-inspect-tpushare top``: periodically refreshed live view
    of per-chip co-residency, step p50/p99, interference verdicts, and
    SLO burn-rate / governor state (docs/observability.md)."""
    import time as _time

    from .display import render_top

    p = argparse.ArgumentParser(
        prog="kubectl-inspect-tpushare top",
        description="Live per-chip co-residency / interference view",
    )
    p.add_argument("node", nargs="?", default="", help="restrict to one node")
    p.add_argument("--metrics-url", action="append", default=[],
                   metavar="URL",
                   help="a /metrics endpoint to scrape for step-profile, "
                   "SLO burn-rate, and governor telemetry (repeatable)")
    p.add_argument("--interval", type=float, default=2.0,
                   help="seconds between refreshes")
    p.add_argument("--iterations", type=int, default=0,
                   help="number of refreshes then exit (0 = until ^C)")
    args = p.parse_args(argv)
    try:
        client = _client()
    except Exception as e:  # config errors
        print(f"error: cannot reach the cluster: {e}", file=sys.stderr)
        return 1
    i = 0
    try:
        while True:
            i += 1
            try:
                nodes, pods = gather(client, args.node)
            except SystemExit:
                raise
            except Exception as e:  # config errors / exhausted retries
                print(f"error: cannot reach the cluster: {e}", file=sys.stderr)
                return 1
            infos = build_all_node_infos(nodes, pods)
            obs = (
                fetch_observability_metrics(args.metrics_url)
                if args.metrics_url else None
            )
            out = render_top(
                infos, obs,
                now_label=_time.strftime("%H:%M:%S"),
            )
            if sys.stdout.isatty():
                sys.stdout.write("\x1b[2J\x1b[H")
            sys.stdout.write(out)
            sys.stdout.flush()
            if args.iterations and i >= args.iterations:
                return 0
            _time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


def flightrecord_main(argv: list[str]) -> int:
    p = argparse.ArgumentParser(
        prog="kubectl-inspect-tpushare flightrecord",
        description="Summarize a flight-recorder dump file",
    )
    p.add_argument("path", help="a tpushare-flightrec-*.json dump")
    p.add_argument("-o", "--output", default="summary",
                   choices=["summary", "json"])
    p.add_argument("--max-traces", type=int, default=5)
    p.add_argument("--max-logs", type=int, default=20)
    args = p.parse_args(argv)
    from ..utils.flightrec import load_dump

    try:
        doc = load_dump(args.path)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"error: cannot read flight record: {e}", file=sys.stderr)
        return 1
    if args.output == "json":
        json.dump(doc, sys.stdout, indent=2)
        print()
        return 0
    sys.stdout.write(
        render_flightrecord(
            doc, max_traces=args.max_traces, max_logs=args.max_logs
        )
    )
    return 0


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    # Subcommand dispatch ahead of the legacy flat interface: the node
    # positional stays `inspect [node]`, observability verbs get words.
    if argv and argv[0] == "trace":
        return trace_main(argv[1:])
    if argv and argv[0] == "flightrecord":
        return flightrecord_main(argv[1:])
    if argv and argv[0] == "top":
        return top_main(argv[1:])
    if argv and argv[0] == "why":
        return why_main(argv[1:])
    if argv and argv[0] == "timeline":
        return timeline_main(argv[1:])
    if argv and argv[0] == "shards":
        return shards_main(argv[1:])
    if argv and argv[0] == "fleet":
        return fleet_main(argv[1:])
    p = argparse.ArgumentParser(
        prog="kubectl-inspect-tpushare",
        description="Display TPU-share HBM utilization across the cluster",
    )
    p.add_argument("node", nargs="?", default="", help="restrict to one node")
    p.add_argument("-d", "--details", action="store_true", help="per-pod rows")
    p.add_argument("-o", "--output", default="table", choices=["table", "json"],
                   help="output format (json: machine-readable, for "
                   "dashboards/automation)")
    p.add_argument("--metrics-url", action="append", default=[],
                   metavar="URL",
                   help="serving pod /metrics endpoint to scrape for KV-"
                   "page / prefix-cache / preemption telemetry (repeat "
                   "per pod; implies --details so the per-pod SERVING "
                   "CACHE column has rows to land on)")
    args = p.parse_args(argv)

    try:
        client = _client()
        nodes, pods = gather(client, args.node)
    except SystemExit:
        raise
    except Exception as e:  # config errors or exhausted list retries
        print(f"error: cannot reach the cluster: {e}", file=sys.stderr)
        return 1
    infos = build_all_node_infos(nodes, pods)
    obs = (
        fetch_observability_metrics(args.metrics_url)
        if args.metrics_url else None
    )
    engine = obs["engine"] if obs is not None else None
    build = (obs or {}).get("build") or None
    if args.output == "json":
        sys.stdout.write(render_json(infos, engine))
        return 0
    if not infos:
        print("no shared-TPU nodes found (allocatable aliyun.com/tpu-mem is 0 everywhere)")
        return 0
    out = (
        render_details(infos, engine, build=build)
        if args.details or engine is not None
        else render_summary(infos)
    )
    sys.stdout.write(out)
    return 0


def render_json(
    infos: list, engine: dict[str, dict[str, float]] | None = None
) -> str:
    """Machine-readable report: the same numbers the tables show,
    including the north-star cluster utilization line. ``engine``
    (``fetch_engine_metrics`` output) attaches each serving pod's cache
    telemetry as a ``serving_cache`` sub-document, plus a
    ``speculative`` sub-document for pods whose engine exports the
    ``tpushare_engine_spec_*`` families and an ``adapters`` sub-document
    for pods whose engine exports the multi-LoRA
    ``tpushare_engine_adapter_*`` families."""
    import json

    from .display import adapter_row_for, engine_row_for, spec_row_for
    from .nodeinfo import infer_unit

    total = sum(n.total_units for n in infos)
    used = sum(n.used_units for n in infos)

    def node_doc(n):
        held = set(n.core_held_chips)
        return {
            "name": n.name,
            "address": n.address,
            "total_units": n.total_units,
            "used_units": n.used_units,
            "pending_units": n.pending_units,
            # defrag-status annotation + per-chip stranded slivers, when
            # the node's daemon runs the defragmenter (the MOVES column's
            # machine-readable form)
            **(
                {
                    "defrag": {
                        **n.defrag,
                        "stranded_by_chip": {
                            str(i): u
                            for i, u in sorted(n.stranded_by_chip.items())
                        },
                    }
                }
                if n.defrag is not None
                else {}
            ),
            # interference verdicts (when the node's daemon runs the
            # detector): the parsed node annotation, per chip
            **(
                {"interference": n.interference}
                if n.interference is not None
                else {}
            ),
            "chips": [
                {
                    "index": d.index,
                    "total_units": d.total_units,
                    "used_units": d.used_units,
                    "core_held": d.index in held,
                    **(
                        {"stranded_units": n.stranded_by_chip.get(d.index, 0)}
                        if n.defrag is not None
                        else {}
                    ),
                }
                for d in sorted(n.devices.values(), key=lambda d: d.index)
            ],
            "pods": [
                {
                    "namespace": p.namespace,
                    "name": p.name,
                    "units_by_chip": {str(k): v for k, v in p.units_by_chip.items()},
                    "workload_class": p.workload_class,
                    # disaggregated-serving tier: emitted only when the
                    # pod declares one, preserving the no-disagg
                    # reference document
                    **(
                        {"serving_tier": p.serving_tier}
                        if p.serving_tier
                        else {}
                    ),
                    **(
                        {
                            "gang_shape": p.gang_shape,
                            "gang_per_chip": p.gang_per_chip,
                            "gang_coords": {
                                str(i): list(n.topology.coords(i))
                                for i in sorted(p.units_by_chip)
                                if n.topology is not None
                                and 0 <= i < n.topology.n_chips
                            },
                        }
                        if p.is_gang
                        else {}
                    ),
                    **(
                        {"serving_cache": engine_row_for(p, engine)}
                        if engine_row_for(p, engine)
                        else {}
                    ),
                    # speculative-decoding summary: emitted only when
                    # the pod's engine exports the spec families, so the
                    # no-speculation reference document is unchanged
                    **(
                        {
                            "speculative": spec_row_for(
                                engine_row_for(p, engine)
                            )
                        }
                        if spec_row_for(engine_row_for(p, engine))
                        else {}
                    ),
                    # multi-LoRA residency summary: same rule — only a
                    # pod whose engine exports the adapter families
                    # gains the key
                    **(
                        {
                            "adapters": adapter_row_for(
                                engine_row_for(p, engine)
                            )
                        }
                        if adapter_row_for(engine_row_for(p, engine))
                        else {}
                    ),
                }
                for p in n.pods
            ],
            "core_holds": [
                {
                    "namespace": h.namespace,
                    "name": h.name,
                    "chips": h.chips,
                    "requested": h.requested,
                }
                for h in n.core_holds
            ],
        }

    doc = {
        # same MiB/GiB heuristic the table headers use; without it a
        # consumer cannot compare unit counts across clusters
        "unit": infer_unit(infos),
        "nodes": [node_doc(n) for n in infos],
        "cluster": {
            "total_units": total,
            "used_units": used,
            "utilization_pct": round(100.0 * used / total, 1) if total else 0.0,
        },
    }
    return json.dumps(doc, indent=2) + "\n"


if __name__ == "__main__":
    sys.exit(main())
