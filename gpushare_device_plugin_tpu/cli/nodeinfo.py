"""Cluster TPU-share utilization model for the inspect CLI.

Reference: ``cmd/inspect/nodeinfo.go`` + ``podinfo.go`` — shared nodes are
those advertising allocatable ``tpu-mem`` > 0; per-chip usage is attributed
from the scheduler-extender's per-container allocation annotation when
present (``GetAllocation``, ``nodeinfo.go:244-271``), else from the
``..._IDX`` annotation with the pod's summed limits; pods whose chip can't
be determined land in a "pending" bucket (devIdx -1, ``nodeinfo.go:136-139``).
"""

from __future__ import annotations

import dataclasses
import json

from .. import const
from ..cluster import pods as P
from ..cluster.noderes import chip_capacity_vector
from ..topology import ChipTopology

PENDING_IDX = -1


@dataclasses.dataclass
class PodUsage:
    namespace: str
    name: str
    units_by_chip: dict[int, int]  # PENDING_IDX for unattributed
    # multi-chip gang grants: the granted slice shape ("2x2x1") and the
    # per-chip HBM share — the inspect CLI renders these with the member
    # chips' grid coordinates instead of a single device column
    gang_shape: str = ""
    gang_per_chip: int = 0
    # normalized QoS class (tpushare.aliyun.com/workload-class): the
    # interference plane's victim/aggressor split, rendered as a CLASS
    # column when any pod on the node is best-effort
    workload_class: str = const.WORKLOAD_LATENCY_CRITICAL
    # disaggregated-serving tier (tpushare.aliyun.com/serving-tier:
    # prefill/decode); "" for unified serving pods — the TIER column
    # appears only when some pod on the report declares one, preserving
    # the no-disagg reference layout
    serving_tier: str = ""

    @property
    def total_units(self) -> int:
        return sum(self.units_by_chip.values())

    @property
    def is_gang(self) -> bool:
        return bool(self.gang_shape) and len(self.units_by_chip) > 1


@dataclasses.dataclass
class DeviceInfo:
    index: int
    total_units: int
    used_units: int = 0


@dataclasses.dataclass
class CoreHold:
    """One tpu-core pod's exclusive chip hold (empty chips = not yet
    assigned: the hold is pending)."""

    namespace: str
    name: str
    chips: list[int]
    requested: int = 0


@dataclasses.dataclass
class NodeInfo:
    name: str
    address: str
    devices: dict[int, DeviceInfo]
    pods: list[PodUsage]
    pending_units: int = 0
    core_holds: list[CoreHold] = dataclasses.field(default_factory=list)
    # the node's chip grid (topology label or the default for its chip
    # count) — lets the report print gang member COORDINATES, not bare
    # indices
    topology: ChipTopology | None = None
    # the daemon's defrag-status annotation (allocator/defrag.py
    # DefragLoop.publish_status): move counters + stranded totals; None
    # when the node runs no defragmenter (columns stay hidden, keeping
    # the reference layout)
    defrag: dict | None = None
    # per-chip stranded-HBM units, recomputed from this report's own
    # usage attribution at the annotation's quantum
    stranded_by_chip: dict[int, int] = dataclasses.field(default_factory=dict)
    # the interference detector's node annotation (cluster/interference.py
    # interference_from_node): per-chip victim/aggressor/ratio verdicts;
    # None when the node runs no detector (rendering stays hidden)
    interference: dict | None = None

    @property
    def total_units(self) -> int:
        return sum(d.total_units for d in self.devices.values())

    @property
    def used_units(self) -> int:
        return sum(d.used_units for d in self.devices.values())

    @property
    def core_held_chips(self) -> list[int]:
        return sorted({i for h in self.core_holds for i in h.chips})


def is_shared_tpu_node(node: dict) -> bool:
    """Allocatable ``aliyun.com/tpu-mem`` > 0 (``podinfo.go:213-221``)."""
    try:
        alloc = node.get("status", {}).get("allocatable", {})
        return int(str(alloc.get(const.RESOURCE_MEM, "0"))) > 0
    except ValueError:
        return False


def node_address(node: dict) -> str:
    for addr in node.get("status", {}).get("addresses", []) or []:
        if addr.get("type") == "InternalIP":
            return addr.get("address", "")
    return ""


def pod_allocation(pod: dict) -> dict[int, int]:
    """Per-chip units for one pod.

    Priority 1: extender annotation (JSON ``{container: {chipIdx: units}}``,
    ``nodeinfo.go:244-271``). Priority 2: IDX annotation x summed limits.
    Fallback: everything pending.
    """
    ann = P.annotations(pod)
    gang = P.gang_usage_by_chip(pod)
    if gang:
        # multi-chip gang: the persisted member set IS the per-chip truth
        return dict(gang)
    raw = ann.get(const.ANN_EXTENDER_ALLOCATION)
    if raw:
        try:
            per_container = json.loads(raw)
            out: dict[int, int] = {}
            for chip_map in per_container.values():
                for idx_str, units in chip_map.items():
                    idx = int(idx_str)
                    out[idx] = out.get(idx, 0) + int(units)
            if out:
                return out
        except (ValueError, AttributeError, TypeError):
            pass  # garbled annotation: fall through to IDX
    total = P.mem_units_of_pod(pod)
    if total <= 0:
        return {}
    idx = P.chip_idx_from_annotation(pod)
    if idx < 0 or not P.is_assigned(pod):
        return {PENDING_IDX: total}
    return {idx: total}


def build_node_info(
    node: dict, pods: list[dict], core_pods: list[dict] | None = None
) -> NodeInfo:
    """Pods must already be filtered to this node's active share pods;
    ``core_pods`` to its active whole-chip (tpu-core) pods."""
    capacity = chip_capacity_vector(node, const.RESOURCE_MEM, const.RESOURCE_COUNT)
    topo = ChipTopology.from_node(node, len(capacity)) if capacity else None
    info = NodeInfo(
        name=node.get("metadata", {}).get("name", ""),
        address=node_address(node),
        devices={
            i: DeviceInfo(index=i, total_units=per) for i, per in capacity.items()
        },
        pods=[],
        topology=topo,
    )
    for pod in pods:
        usage = pod_allocation(pod)
        if not usage:
            continue
        info.pods.append(
            PodUsage(
                namespace=P.namespace(pod),
                name=P.name(pod),
                units_by_chip=usage,
                gang_shape=P.annotations(pod).get(const.ENV_GANG_SHAPE, ""),
                gang_per_chip=P.gang_per_chip_units(pod),
                workload_class=P.workload_class(pod),
                serving_tier=P.serving_tier(pod),
            )
        )
        for idx, units in usage.items():
            if idx == PENDING_IDX:
                info.pending_units += units
            elif idx in info.devices:
                info.devices[idx].used_units += units
            else:
                # annotation points at a chip the node doesn't advertise
                info.devices[idx] = DeviceInfo(
                    index=idx, total_units=0, used_units=units
                )
    for pod in core_pods or []:
        info.core_holds.append(
            CoreHold(
                namespace=P.namespace(pod),
                name=P.name(pod),
                chips=P.core_hold_chips(pod) if P.is_assigned(pod) else [],
                requested=P.core_chips_of_pod(pod),
            )
        )
    # Defrag status (when the node's daemon publishes it): the MOVES
    # column straight from the annotation, per-chip stranded slivers
    # recomputed from THIS report's usage attribution at the published
    # quantum — so the table's chips and its stranded markers can never
    # disagree with each other.
    from ..allocator.defrag import status_from_node, stranded_units

    info.defrag = status_from_node(node)
    if info.defrag is not None:
        info.stranded_by_chip = stranded_units(
            {i: d.total_units for i, d in info.devices.items()},
            {i: d.used_units for i, d in info.devices.items()},
            int(info.defrag.get("quantum") or 0),
        )
    # Interference verdicts (when the node's daemon runs the detector):
    # per-chip victim/aggressor/ratio straight from the annotation.
    from ..cluster.interference import interference_from_node

    info.interference = interference_from_node(node)
    return info


def build_all_node_infos(nodes: list[dict], pods: list[dict]) -> list[NodeInfo]:
    """Shared nodes only; active (not Succeeded/Failed) share pods grouped
    by node (``buildAllNodeInfos``, ``nodeinfo.go:46-93``)."""
    infos = []
    for node in nodes:
        if not is_shared_tpu_node(node):
            continue
        name = node.get("metadata", {}).get("name", "")
        active = [
            p
            for p in pods
            if P.node_name(p) == name and P.phase(p) not in ("Succeeded", "Failed")
        ]
        node_pods = [p for p in active if P.mem_units_of_pod(p) > 0]
        core_pods = [p for p in active if P.core_chips_of_pod(p) > 0]
        infos.append(build_node_info(node, node_pods, core_pods))
    return infos


def infer_unit(infos: list[NodeInfo]) -> str:
    """Heuristic from the reference (``setUnit``, ``nodeinfo.go:227-243``):
    per-chip capacity > 100 reads as MiB, else GiB."""
    for info in infos:
        for dev in info.devices.values():
            if dev.total_units > 100:
                return "MiB"
            if dev.total_units > 0:
                return "GiB"
    return "GiB"
