"""Daemon entrypoint: ``tpushare-device-plugin``.

Reference: ``cmd/nvidia/main.go:15-78`` — flag parsing, kubelet-client
construction with serviceaccount-token fallback, memory-unit validation,
then hand-off to the lifecycle manager. TPU additions: ``--discovery``
backend selection, ``--policy`` binpack choice, ``--standalone`` mode
(no apiserver), and ``--no-core-resource``.

Graceful shutdown: SIGTERM/SIGINT (installed via
``manager.install_signal_handlers``) triggers a drain — in-flight
Allocate calls finish their apiserver PATCH and journal commit, new ones
are refused, the allocation checkpoint is flushed and closed, and the
plugin gRPC sockets are unlinked — instead of dying mid-write. A hard
kill at any instruction is survivable too (``--checkpoint-path`` WAL +
restart replay); the drain just makes the common case not need it.
"""

from __future__ import annotations

import argparse
import os
import sys

from .. import const
from ..cluster.apiserver import ApiServerClient
from ..cluster.kubelet import KubeletClient
from ..cluster.informer import PodInformer
from ..cluster.podsource import ApiServerPodSource, KubeletPodSource
from ..discovery import from_name
from ..manager import ManagerConfig, TpuShareManager
from ..utils import log as logutil

SA_TOKEN_PATH = "/var/run/secrets/kubernetes.io/serviceaccount/token"

log = logutil.get_logger("daemon")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpushare-device-plugin",
        description="TPU-sharing Kubernetes device plugin (fractional HBM + whole chips)",
    )
    # reference flag set (cmd/nvidia/main.go:15-26)
    p.add_argument("--health-check", action="store_true",
                   help="enable chip health monitoring into ListAndWatch")
    p.add_argument("--memory-unit", default="GiB", choices=["GiB", "MiB"],
                   help="granularity of one tpu-mem unit")
    p.add_argument("--query-kubelet", action="store_true",
                   help="source pods from kubelet /pods instead of the apiserver")
    p.add_argument("--pod-source", default="informer",
                   choices=["informer", "list"],
                   help="apiserver read strategy: watch-backed cache "
                   "(informer, default) or a fresh LIST per Allocate "
                   "(the reference's behavior); ignored with --query-kubelet")
    p.add_argument("--kubelet-address", default="127.0.0.1")
    p.add_argument("--kubelet-port", type=int, default=10250)
    p.add_argument("--client-cert", default="")
    p.add_argument("--client-key", default="")
    p.add_argument("--token", default="", help="kubelet bearer token "
                   "(default: serviceaccount token file)")
    p.add_argument("--timeout", type=float, default=10.0,
                   help="kubelet/apiserver HTTP timeout seconds")
    # TPU-native flags
    p.add_argument("--discovery", default="auto",
                   choices=["auto", "mock", "jax", "tpuvm"])
    p.add_argument("--policy", default="first-fit",
                   choices=["first-fit", "best-fit", "spread"])
    p.add_argument("--standalone", action="store_true",
                   help="no apiserver: in-process accounting (dev/bench)")
    p.add_argument("--no-core-resource", action="store_true",
                   help="do not serve the whole-chip tpu-core resource")
    p.add_argument("--disable-isolation", action="store_true",
                   help="never inject the cooperative HBM cap (also "
                   "settable per-node via the ctpu.disable.isolation label)")
    p.add_argument("--plugin-dir", default=const.DEVICE_PLUGIN_PATH)
    p.add_argument("--node-name", default=os.environ.get("NODE_NAME", ""))
    p.add_argument("--coredump-dir", default="/etc/kubernetes")
    p.add_argument("--metrics-port", type=int, default=0,
                   help="serve Prometheus /metrics (+ /traces OTLP-JSON) "
                   "on this port (0 = off; the reference had no metrics "
                   "at all)")
    # observability (docs/observability.md)
    p.add_argument("--trace-sample", type=float, default=1.0,
                   help="admission-trace sample ratio in [0,1]: each "
                   "Allocate's trace is kept with this probability; 0 "
                   "disables and the unsampled hot path costs O(ns). "
                   "Run the extender at the SAME ratio — each process "
                   "samples its own half, so mismatched ratios produce "
                   "partial traces")
    p.add_argument("--trace-sample-critical", type=float, default=None,
                   help="per-tier override of --trace-sample for "
                   "critical-tier serving traces (serve.request roots); "
                   "default inherits --trace-sample")
    p.add_argument("--trace-sample-besteffort", type=float, default=None,
                   help="per-tier override of --trace-sample for "
                   "best-effort serving traces, so best-effort churn can "
                   "be down-sampled without losing critical-tier traces; "
                   "default inherits --trace-sample")
    p.add_argument("--flightrecord-dir", default="",
                   help="crash/postmortem flight-recorder directory "
                   "(last N admission traces + recent log ring, dumped "
                   "on SIGUSR1, fatal exit, and injected crash sites); "
                   "default is the coredump dir, 'none' disables")
    p.add_argument("--flightrecord-keep", type=int, default=16,
                   help="keep only the newest K flight-record dump files "
                   "in --flightrecord-dir (repeated SIGUSR1/crash dumps "
                   "rotate instead of growing unbounded; 0 = unbounded)")
    p.add_argument("--interference-interval", type=float, default=0.0,
                   help="seconds between interference-detector passes "
                   "(cluster/interference.py: per-chip co-residency vs "
                   "decode-step p99 inflation, published as the "
                   "tpushare_interference_ratio gauge + the node "
                   "interference annotation); 0 disables")
    p.add_argument("--interference-threshold", type=float, default=1.25,
                   help="step-p99 inflation ratio (current / solo "
                   "baseline) at which a co-residency verdict is flagged")
    p.add_argument("--interference-scrape-url", action="append", default=[],
                   metavar="URL",
                   help="a serving pod /metrics endpoint to scrape for "
                   "its engine's step-p99 gauge (repeatable). Without "
                   "any, the detector reads the daemon's own in-process "
                   "registry, which only sees engines co-located in "
                   "this process — per-pod engines need their "
                   "endpoints listed here")
    # degraded-mode knobs (docs/robustness.md)
    p.add_argument("--breaker-threshold", type=int, default=5,
                   help="consecutive apiserver failures before the circuit "
                   "opens and calls fail fast")
    p.add_argument("--breaker-reset-s", type=float, default=5.0,
                   help="seconds the circuit stays open before a half-open "
                   "probe")
    # crash-safe state (docs/robustness.md, allocator/checkpoint.py)
    p.add_argument("--checkpoint-path", default="",
                   help="write-ahead allocation journal file; default is "
                   "<plugin-dir>/tpushare-allocations.ckpt in cluster mode "
                   "(the device-plugin dir is already a host path, so the "
                   "journal survives container restarts); 'none' disables")
    p.add_argument("--wal-fsync", default="batch", choices=["always", "batch"],
                   help="WAL durability mode: 'batch' (group commit — one "
                   "fsync covers every record queued within the gather "
                   "window; no admission proceeds past begin until its "
                   "record is durable) or 'always' (fsync per record)")
    p.add_argument("--wal-batch-window-ms", type=float, default=2.0,
                   help="group-commit gather window in milliseconds "
                   "(--wal-fsync=batch); the writer drains early once "
                   "arrivals go quiet for a quarter window")
    p.add_argument("--no-patch-coalesce", action="store_true",
                   help="disable the coalesced pod-PATCH dispatcher and "
                   "send one apiserver PATCH per admission from the "
                   "calling thread (the pre-group-commit behavior)")
    p.add_argument("--reconcile-interval", type=float, default=30.0,
                   help="seconds between drift-reconciler passes "
                   "(annotations vs ledger vs checkpoint); 0 disables")
    p.add_argument("--drain-timeout", type=float, default=5.0,
                   help="graceful-shutdown budget for in-flight Allocate "
                   "calls before the gRPC sockets close")
    p.add_argument("--defrag-interval", type=float, default=0.0,
                   help="seconds between live slice-defragmentation "
                   "passes (journaled move protocol, allocator/defrag.py)"
                   "; 0 disables (the default — repacking moves running "
                   "workloads and is an explicit operator opt-in)")
    p.add_argument("--defrag-quantum", type=int, default=0,
                   help="stranded-sliver threshold in memory units: free "
                   "HBM on a partially-used chip below this cannot host "
                   "a request and counts as stranded; 0 auto-derives it "
                   "from the largest fractional pod on the node")
    p.add_argument("--defrag-max-moves", type=int, default=8,
                   help="upper bound on repacking moves planned per "
                   "defrag pass — each move drains and restores a "
                   "running workload, so passes stay small by default")
    p.add_argument("--timeline-interval", type=float, default=10.0,
                   help="seconds between cluster-state timeline samples "
                   "(utilization / stranded%% / pending depth / SLO "
                   "burn into the bounded /timeline ring, embedded in "
                   "flight-recorder dumps); 0 disables")
    p.add_argument("--decisions-ring", type=int, default=512,
                   help="in-memory decision-provenance ring size (per-"
                   "verb 'why' records for every admission, served on "
                   "/decisions and rendered by inspect why; 0 disables "
                   "emission)")
    p.add_argument("--decisions-log", default="",
                   help="optional on-disk decision segment log (JSON "
                   "lines, fsync-free, size-rotated — provenance, not "
                   "durability; the WAL owns that); empty disables")
    p.add_argument("-v", "--verbosity", type=int, default=0)
    return p


def build_kubelet_token(args) -> str:
    """Explicit flag, else in-cluster serviceaccount token
    (``cmd/nvidia/main.go:28-53``)."""
    if args.token:
        return args.token
    if os.path.exists(SA_TOKEN_PATH):
        with open(SA_TOKEN_PATH) as f:
            return f.read().strip()
    return ""


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    logutil.setup(args.verbosity)

    # e2e fault injection (TPUSHARE_FAULTS="apiserver.request=error:5,...")
    from ..utils.faults import FAULTS

    if FAULTS.install_from_env():
        log.warning("fault injection ACTIVE at points: %s", FAULTS.active())

    from ..utils.tracing import TRACER

    tier_ratios = {}
    if args.trace_sample_critical is not None:
        tier_ratios[const.SLO_TIER_CRITICAL] = args.trace_sample_critical
    if args.trace_sample_besteffort is not None:
        tier_ratios[const.SLO_TIER_BEST_EFFORT] = args.trace_sample_besteffort
    TRACER.configure(
        sample_ratio=args.trace_sample,
        tier_ratios=tier_ratios or None,
    )
    flightrecord_dir = args.flightrecord_dir
    if flightrecord_dir == "none":
        flightrecord_dir = ""
    elif not flightrecord_dir:
        flightrecord_dir = args.coredump_dir

    backend = from_name(args.discovery)
    # WAL default: on in cluster mode, under the plugin dir (a hostPath in
    # every real deployment, so the journal outlives the container).
    checkpoint_path = args.checkpoint_path
    if checkpoint_path == "none":
        checkpoint_path = ""
    elif not checkpoint_path and not args.standalone:
        checkpoint_path = os.path.join(
            args.plugin_dir, "tpushare-allocations.ckpt"
        )
    cfg = ManagerConfig(
        plugin_dir=args.plugin_dir,
        node_name=args.node_name,
        memory_unit=const.translate_memory_units(args.memory_unit),
        policy=args.policy,
        health_check=args.health_check,
        standalone=args.standalone,
        serve_core_resource=not args.no_core_resource,
        disable_isolation=args.disable_isolation,
        coredump_dir=args.coredump_dir,
        checkpoint_path=checkpoint_path,
        wal_fsync=args.wal_fsync,
        wal_batch_window_s=args.wal_batch_window_ms / 1000.0,
        patch_coalesce=not args.no_patch_coalesce,
        reconcile_interval_s=args.reconcile_interval,
        drain_timeout_s=args.drain_timeout,
        flightrecord_dir=flightrecord_dir,
        flightrecord_keep=args.flightrecord_keep,
        defrag_interval_s=args.defrag_interval,
        defrag_quantum=args.defrag_quantum,
        defrag_max_moves=args.defrag_max_moves,
        interference_interval_s=args.interference_interval,
        interference_threshold=args.interference_threshold,
        interference_scrape_urls=tuple(args.interference_scrape_url),
        timeline_interval_s=args.timeline_interval,
        decisions_ring=args.decisions_ring,
        decisions_log_path=args.decisions_log,
    )

    api_client = None
    pod_source = None
    if not args.standalone:
        if not args.node_name:
            log.fatal("NODE_NAME env (or --node-name) is required in cluster mode")
        try:
            api_client = ApiServerClient.from_env(timeout_s=args.timeout)
        except Exception as e:  # bad/garbled kubeconfig, missing SA, etc.
            log.fatal(f"apiserver config failed: {e} (use --standalone for no-cluster mode)")
        from ..utils.circuit import CircuitBreaker

        api_client.breaker = CircuitBreaker(
            failure_threshold=args.breaker_threshold,
            reset_timeout_s=args.breaker_reset_s,
        )
        apisrc = ApiServerPodSource(api_client, args.node_name)
        if args.query_kubelet:
            cert = None
            if args.client_cert and args.client_key:
                cert = (args.client_cert, args.client_key)
            kubelet = KubeletClient(
                host=args.kubelet_address,
                port=args.kubelet_port,
                token=build_kubelet_token(args),
                client_cert=cert,
                timeout_s=args.timeout,
            )
            pod_source = KubeletPodSource(kubelet, apisrc, args.node_name)
        elif args.pod_source == "informer":
            pod_source = PodInformer(api_client, args.node_name).start()
        else:
            pod_source = apisrc

    manager = TpuShareManager(
        backend, cfg, api_client=api_client, pod_source=pod_source
    )
    metrics_server = None
    if args.metrics_port:
        from ..utils.metrics import MetricsServer, publish_build_info

        publish_build_info(component="daemon")
        # /readyz gates on kubelet plugin registration — the DaemonSet's
        # readiness probe (a daemon whose plugins never registered serves
        # no pods, whatever its process state).
        metrics_server = MetricsServer(
            port=args.metrics_port, ready_fn=manager.ready
        ).start()
        log.info("metrics on :%d/metrics", metrics_server.port)

    try:
        manager.install_signal_handlers()
        log.info(
            "tpushare-device-plugin starting: discovery=%s policy=%s standalone=%s",
            args.discovery, args.policy, args.standalone,
        )
        manager.run()
    finally:
        if metrics_server is not None:
            metrics_server.stop()
        # The informer owns a watch thread + open HTTP stream; shut it down
        # with the manager instead of abandoning it to process teardown.
        stop = getattr(pod_source, "stop", None)
        if callable(stop):
            stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
