"""Debug CLI: dump the kubelet's local pod list.

Reference: ``cmd/podgetter/main.go`` — same client flag set as the daemon's
kubelet path; prints the raw ``/pods`` result for debugging the
``--query-kubelet`` source.
"""

from __future__ import annotations

import argparse
import json
import sys

from ..cluster.kubelet import KubeletClient
from .daemon import build_kubelet_token


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="tpushare-podgetter")
    p.add_argument("--kubelet-address", default="127.0.0.1")
    p.add_argument("--kubelet-port", type=int, default=10250)
    p.add_argument("--client-cert", default="")
    p.add_argument("--client-key", default="")
    p.add_argument("--token", default="")
    p.add_argument("--timeout", type=float, default=10.0)
    p.add_argument("--scheme", default="https", choices=["https", "http"])
    args = p.parse_args(argv)

    cert = None
    if args.client_cert and args.client_key:
        cert = (args.client_cert, args.client_key)
    client = KubeletClient(
        host=args.kubelet_address,
        port=args.kubelet_port,
        token=build_kubelet_token(args),
        client_cert=cert,
        timeout_s=args.timeout,
        scheme=args.scheme,
    )
    try:
        pods = client.get_node_running_pods()
    except Exception as e:
        print(f"error: kubelet query failed: {e}", file=sys.stderr)
        return 1
    json.dump({"items": pods}, sys.stdout, indent=2)
    print()
    return 0


if __name__ == "__main__":
    sys.exit(main())
