"""SLO error budgets with multi-window burn-rate alerting.

The serving engine already *scores* per-request SLOs (each
:class:`~..serving.engine.Request` carries tick-clock TTFT/TPOT targets
and ``RequestResult.meets_slo()`` grades them at retire). This module
turns those point verdicts into the operational object SREs actually
alert on: an **error budget** per tier — the fraction of requests the
objective *allows* to miss — consumed at a measurable **burn rate**.

Burn rate over a window = (observed miss fraction) / (allowed miss
fraction). Burn 1.0 spends the budget exactly at its sustainable pace;
burn 14.4 over an hour exhausts a 30-day budget in ~2 days. The
classic multi-window scheme (Google SRE workbook ch. 5) requires BOTH a
long and a short window to burn simultaneously, so a page means "the
budget is being consumed fast *and it is still happening*":

- **page**: burn >= ``page_burn`` (14.4) over the 1h window AND the 5m
  window — wake a human; at this pace the monthly budget dies in days.
- **warn**: burn >= ``warn_burn`` (6.0) over the 6h window AND the 1h
  window — ticket-grade; sustained would exhaust the budget in ~5 days.

Mechanics: :meth:`SloBudget.record` drops each verdict into a
fixed-granularity bucketed ring (O(1), near-leaf lock ``slo.budget``),
:meth:`SloBudget.evaluate` sums windows over the buckets, and
:meth:`SloBudget.publish` exports ``tpushare_slo_burn_rate{tier,window}``
+ ``tpushare_slo_error_budget_remaining{tier}`` +
``tpushare_slo_severity{tier}`` on ``/metrics``. Crossing INTO page
severity fires the registered hook exactly once per episode — the
daemon wires it to the flight recorder, so the postmortem of a burning
SLO captures the traces and logs of the moment it started burning.

The best-effort governor (``serving/governor.py``) consumes
:meth:`SloBudget.severity` as its engage signal: when a co-resident
latency-critical tier pages, the best-effort tenant's decode rate is
throttled until the budget stops burning (hysteresis in the governor).

Clock-injectable throughout: the windows are seconds on whatever
monotonic clock the caller provides, so tests and the deterministic
bench drive hours of budget history in microseconds.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable

from .lockrank import make_lock
from .metric_catalog import (
    SLO_BURN_RATE,
    SLO_ERROR_BUDGET_REMAINING,
    SLO_SEVERITY,
)
from .metrics import MetricsRegistry, REGISTRY

SEVERITY_PAGE = "page"
SEVERITY_WARN = "warn"

# The multi-window pairs: severity -> (long window s, short window s).
FAST_WINDOW_S = 300.0  # 5m — "is it still happening"
MID_WINDOW_S = 3600.0  # 1h — page-grade consumption
SLOW_WINDOW_S = 21600.0  # 6h — warn-grade consumption

DEFAULT_PAGE_BURN = 14.4
DEFAULT_WARN_BURN = 6.0


@dataclasses.dataclass(frozen=True)
class SloObjective:
    """One tier's objective: the attainment ``goal`` (fraction of
    requests that must meet their latency targets; the targets
    themselves ride on each request). ``1 - goal`` is the error
    budget."""

    tier: str
    goal: float = 0.99

    def __post_init__(self) -> None:
        if not 0.0 < self.goal < 1.0:
            raise ValueError(
                f"goal must be in (0, 1), got {self.goal} — 1.0 leaves "
                "zero error budget and every miss pages"
            )

    @property
    def budget_fraction(self) -> float:
        return 1.0 - self.goal


@dataclasses.dataclass
class _TierState:
    objective: SloObjective
    good: list[int]
    bad: list[int]
    newest_bucket: int  # absolute bucket index of ring position "newest"
    paging: bool = False  # hysteresis for the page hook (fire on entry)
    seq: int = 0  # bumped per record: invalidates the severity cache
    # (now_bucket, seq) -> verdict: severity() polls between records in
    # the same bucket are O(1) instead of re-summing three windows — the
    # governor polls this on the decode hot path
    cached: "tuple[int, int, TierVerdict] | None" = None


@dataclasses.dataclass(frozen=True)
class TierVerdict:
    """One tier's evaluated budget state."""

    tier: str
    severity: str | None  # SEVERITY_PAGE | SEVERITY_WARN | None
    burn_5m: float
    burn_1h: float
    burn_6h: float
    budget_remaining: float  # of the 6h window's budget, in [0, 1]
    requests_6h: int

    def to_dict(self) -> dict[str, Any]:
        return {
            "tier": self.tier,
            "severity": self.severity,
            "burn_5m": round(self.burn_5m, 3),
            "burn_1h": round(self.burn_1h, 3),
            "burn_6h": round(self.burn_6h, 3),
            "budget_remaining": round(self.budget_remaining, 4),
            "requests_6h": self.requests_6h,
        }


class SloBudget:
    """Per-tier error budgets over a bucketed ring of SLO verdicts.

    ``bucket_s`` is the counting granularity (default 10s; the slow 6h
    window then needs 2160 int pairs per tier — trivial memory, O(window
    / bucket) sums only at evaluate time, O(1) at record time).
    """

    def __init__(
        self,
        objectives: dict[str, SloObjective] | None = None,
        *,
        bucket_s: float = 10.0,
        page_burn: float = DEFAULT_PAGE_BURN,
        warn_burn: float = DEFAULT_WARN_BURN,
        clock: Callable[[], float] = time.monotonic,
        on_page: Callable[[str, TierVerdict], None] | None = None,
    ) -> None:
        if bucket_s <= 0:
            raise ValueError(f"bucket_s must be > 0, got {bucket_s}")
        self._bucket_s = bucket_s
        self._n_buckets = int(SLOW_WINDOW_S // bucket_s) + 1
        self._page_burn = page_burn
        self._warn_burn = warn_burn
        self._clock = clock
        self._on_page = on_page
        self._lock = make_lock("slo.budget")
        self._tiers: dict[str, _TierState] = {}
        # Explicitly-configured budgets track ONLY their declared tiers:
        # a verdict for a tier the operator never budgeted must not
        # invent a default objective and start paging against it.
        # A budget constructed without objectives tracks every tier it
        # sees at the default goal (the zero-config convenience mode).
        self._auto_tiers = not objectives
        for obj in (objectives or {}).values():
            self._ensure(obj.tier, obj)

    # --- recording --------------------------------------------------------

    def _ensure(
        self, tier: str, objective: SloObjective | None = None
    ) -> _TierState | None:
        state = self._tiers.get(tier)
        if state is None:
            if objective is None and not self._auto_tiers:
                return None  # undeclared tier on a configured budget
            state = _TierState(
                objective=objective or SloObjective(tier=tier),
                good=[0] * self._n_buckets,
                bad=[0] * self._n_buckets,
                newest_bucket=int(self._clock() / self._bucket_s),
            )
            self._tiers[tier] = state
        return state

    def _advance(self, state: _TierState, bucket: int) -> None:
        """Zero the ring positions between the newest seen bucket and
        ``bucket`` (lock held)."""
        gap = bucket - state.newest_bucket
        if gap <= 0:
            return
        for i in range(1, min(gap, self._n_buckets) + 1):
            pos = (state.newest_bucket + i) % self._n_buckets
            state.good[pos] = 0
            state.bad[pos] = 0
        state.newest_bucket = bucket

    def record(self, tier: str, ok: bool, now: float | None = None) -> None:
        """One request's SLO verdict (engine retire path — O(1)).
        Verdicts for tiers a configured budget never declared are
        dropped: alerting on an objective nobody set is worse than not
        alerting."""
        t = self._clock() if now is None else now
        bucket = int(t / self._bucket_s)
        with self._lock:
            state = self._ensure(tier)
            if state is None:
                return
            self._advance(state, bucket)
            pos = bucket % self._n_buckets
            if ok:
                state.good[pos] += 1
            else:
                state.bad[pos] += 1
            state.seq += 1

    # --- evaluation -------------------------------------------------------

    def _window_counts(
        self, state: _TierState, window_s: float, now_bucket: int
    ) -> tuple[int, int]:
        """(good, bad) within the trailing ``window_s`` (lock held)."""
        n = min(int(window_s // self._bucket_s) + 1, self._n_buckets)
        good = bad = 0
        for i in range(n):
            bucket = now_bucket - i
            if bucket < 0:
                break
            if bucket <= state.newest_bucket - self._n_buckets:
                break  # fell off the ring
            pos = bucket % self._n_buckets
            if bucket > state.newest_bucket:
                continue  # future position not yet advanced — stale data
            good += state.good[pos]
            bad += state.bad[pos]
        return good, bad

    @staticmethod
    def _burn(good: int, bad: int, budget_fraction: float) -> float:
        total = good + bad
        if total == 0:
            return 0.0
        return (bad / total) / budget_fraction

    def _tier_verdict(
        self, tier: str, state: _TierState, now_bucket: int
    ) -> TierVerdict:
        """One tier's verdict (lock held; ``state`` already advanced)."""
        bf = state.objective.budget_fraction
        g5, b5 = self._window_counts(state, FAST_WINDOW_S, now_bucket)
        g1, b1 = self._window_counts(state, MID_WINDOW_S, now_bucket)
        g6, b6 = self._window_counts(state, SLOW_WINDOW_S, now_bucket)
        burn_5m = self._burn(g5, b5, bf)
        burn_1h = self._burn(g1, b1, bf)
        burn_6h = self._burn(g6, b6, bf)
        severity: str | None = None
        if burn_1h >= self._page_burn and burn_5m >= self._page_burn:
            severity = SEVERITY_PAGE
        elif burn_6h >= self._warn_burn and burn_1h >= self._warn_burn:
            severity = SEVERITY_WARN
        allowed = (g6 + b6) * bf
        remaining = 1.0 if allowed <= 0 else max(0.0, 1.0 - b6 / allowed)
        return TierVerdict(
            tier=tier, severity=severity, burn_5m=burn_5m,
            burn_1h=burn_1h, burn_6h=burn_6h,
            budget_remaining=remaining, requests_6h=g6 + b6,
        )

    def _update_paging(self, state: _TierState, verdict: TierVerdict) -> bool:
        """Latch the page-episode flag (lock held); True when the tier
        just ENTERED page severity (the hook fires once per episode)."""
        if verdict.severity == SEVERITY_PAGE and not state.paging:
            state.paging = True
            return True
        if verdict.severity != SEVERITY_PAGE and state.paging:
            state.paging = False
        return False

    def evaluate(self, now: float | None = None) -> dict[str, TierVerdict]:
        """Every tier's burn rates + severity; fires the page hook for
        tiers that just ENTERED page severity (outside the lock)."""
        t = self._clock() if now is None else now
        now_bucket = int(t / self._bucket_s)
        verdicts: dict[str, TierVerdict] = {}
        newly_paging: list[TierVerdict] = []
        with self._lock:
            for tier, state in self._tiers.items():
                self._advance(state, now_bucket)
                verdict = self._tier_verdict(tier, state, now_bucket)
                state.cached = (now_bucket, state.seq, verdict)
                verdicts[tier] = verdict
                if self._update_paging(state, verdict):
                    newly_paging.append(verdict)
        if self._on_page is not None:
            for verdict in newly_paging:
                self._on_page(verdict.tier, verdict)
        return verdicts

    def severity(self, tier: str, now: float | None = None) -> str | None:
        """One tier's current severity — the governor's engage signal,
        polled from the decode hot path. Single-tier, and cached per
        (bucket, record-seq): repeated polls between retires within the
        same 10s bucket are O(1), never a three-window re-sum."""
        t = self._clock() if now is None else now
        now_bucket = int(t / self._bucket_s)
        fire: TierVerdict | None = None
        with self._lock:
            state = self._tiers.get(tier)
            if state is None:
                return None
            cached = state.cached
            if cached is not None and cached[0] == now_bucket and (
                cached[1] == state.seq
            ):
                return cached[2].severity
            self._advance(state, now_bucket)
            verdict = self._tier_verdict(tier, state, now_bucket)
            state.cached = (now_bucket, state.seq, verdict)
            if self._update_paging(state, verdict):
                fire = verdict
        if fire is not None and self._on_page is not None:
            self._on_page(fire.tier, fire)
        return verdict.severity

    def set_on_page(
        self, hook: Callable[[str, TierVerdict], None] | None
    ) -> None:
        """(Re)register the page-entry hook (the daemon wires the flight
        recorder here)."""
        self._on_page = hook

    # --- export -----------------------------------------------------------

    def publish(
        self,
        registry: MetricsRegistry | None = None,
        now: float | None = None,
        **labels: str,
    ) -> dict[str, TierVerdict]:
        """Evaluate and export every tier's budget state as gauges."""
        reg = registry if registry is not None else REGISTRY
        verdicts = self.evaluate(now)
        for tier, v in verdicts.items():
            for window, burn in (
                ("5m", v.burn_5m), ("1h", v.burn_1h), ("6h", v.burn_6h)
            ):
                reg.gauge_set(
                    SLO_BURN_RATE, burn,
                    "Error-budget burn rate (miss fraction / allowed miss "
                    "fraction) over the trailing window",
                    tier=tier, window=window, **labels,
                )
            reg.gauge_set(
                SLO_ERROR_BUDGET_REMAINING, v.budget_remaining,
                "Fraction of the 6h window's error budget still unspent",
                tier=tier, **labels,
            )
            reg.gauge_set(
                SLO_SEVERITY,
                2.0 if v.severity == SEVERITY_PAGE
                else 1.0 if v.severity == SEVERITY_WARN else 0.0,
                "Multi-window burn-rate severity (0 ok, 1 warn, 2 page)",
                tier=tier, **labels,
            )
        return verdicts
