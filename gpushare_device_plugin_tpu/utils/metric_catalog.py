"""The metric contract: every ``tpushare_*`` family, declared once.

The exporters grew one module at a time (PRs 2-14), each minting its own
metric-name literals — and the CLI parsers (`cli/inspect.py`) grew their
own copies of those names and prefixes. Nothing pinned the two sides
together: an exporter renaming a family or a label silently breaks every
dashboard and the ``top``/``shards`` views, and the scrape still returns
200. This module is the single declaration point — family name, type,
and allowed label set — and tpulint's ``metric-contract`` rule closes
the loop statically:

- a ``tpushare_*`` name literal anywhere in the package OUTSIDE this
  module is a finding (exporters and parsers import the consts);
- an emission call (``counter_inc``/``gauge_set``/``observe``/
  ``timed_acquire`` and the programmatic readers) whose family is not
  declared here, whose call kind contradicts the declared type, or
  whose explicit label keywords fall outside the declared label set is
  a finding.

Help text stays at the emission site (it is prose about the *use*, and
the registry de-duplicates it); the contract here is the machine-checked
part: name, type, labels. Keep the table sorted by family name.
"""

from __future__ import annotations

import dataclasses

COUNTER = "counter"
GAUGE = "gauge"
HISTOGRAM = "histogram"


@dataclasses.dataclass(frozen=True)
class MetricSpec:
    """One declared family: its exposition type and the full set of
    label keys any exporter may attach (a site may emit a subset —
    e.g. the ``pod`` label only when the engine is pod-scoped)."""

    name: str
    type: str
    labels: tuple[str, ...]


def _m(name: str, mtype: str, *labels: str) -> tuple[str, MetricSpec]:
    return name, MetricSpec(name, mtype, tuple(sorted(labels)))


# --- family name consts (import these; never inline the string) -------------

ALLOCATE_SECONDS = "tpushare_allocate_seconds"
ALLOCATE_TOTAL = "tpushare_allocate_total"
ALLOCATOR_LOCK_WAIT_SECONDS = "tpushare_allocator_lock_wait_seconds"
ASSUME_EXPIRED_TOTAL = "tpushare_assume_expired_total"
BUILD_INFO = "tpushare_build_info"
CHECKPOINT_APPENDS_TOTAL = "tpushare_checkpoint_appends_total"
CHECKPOINT_ERRORS_TOTAL = "tpushare_checkpoint_errors_total"
CHECKPOINT_FENCED = "tpushare_checkpoint_fenced"
CHECKPOINT_FSYNC_SECONDS = "tpushare_checkpoint_fsync_seconds"
CHECKPOINT_REPLAYED_TOTAL = "tpushare_checkpoint_replayed_total"
CHECKPOINT_WAL_BATCH_RECORDS = "tpushare_checkpoint_wal_batch_records"
CIRCUIT_FASTFAIL_TOTAL = "tpushare_circuit_fastfail_total"
CIRCUIT_STATE = "tpushare_circuit_state"
CIRCUIT_TRANSITIONS_TOTAL = "tpushare_circuit_transitions_total"
DEFRAG_MOVE_SECONDS = "tpushare_defrag_move_seconds"
DEFRAG_MOVES_TOTAL = "tpushare_defrag_moves_total"
DEFRAG_STRANDED_PCT = "tpushare_defrag_stranded_pct"
DEFRAG_STRANDED_UNITS = "tpushare_defrag_stranded_units"
ENGINE_ADAPTER_CACHE_PAGES = "tpushare_engine_adapter_cache_pages"
ENGINE_ADAPTER_ENABLED = "tpushare_engine_adapter_enabled"
ENGINE_ADAPTER_EVICTIONS_TOTAL = "tpushare_engine_adapter_evictions_total"
ENGINE_ADAPTER_HITS_TOTAL = "tpushare_engine_adapter_hits_total"
ENGINE_ADAPTER_MISS_STALL_SECONDS = "tpushare_engine_adapter_miss_stall_seconds"
ENGINE_ADAPTER_MISSES_TOTAL = "tpushare_engine_adapter_misses_total"
ENGINE_ADAPTER_RESIDENT = "tpushare_engine_adapter_resident"
ENGINE_KV_PAGES_FREE = "tpushare_engine_kv_pages_free"
ENGINE_KV_PAGES_TOTAL = "tpushare_engine_kv_pages_total"
ENGINE_KV_PAGES_USED = "tpushare_engine_kv_pages_used"
ENGINE_PREEMPTIONS = "tpushare_engine_preemptions"
ENGINE_PREEMPTIONS_TOTAL = "tpushare_engine_preemptions_total"
ENGINE_PREFIX_CACHED_PAGES = "tpushare_engine_prefix_cached_pages"
ENGINE_PREFIX_HIT_RATIO = "tpushare_engine_prefix_hit_ratio"
ENGINE_PREFIX_HIT_TOKENS = "tpushare_engine_prefix_hit_tokens"
ENGINE_SPEC_ACCEPTANCE_LEN = "tpushare_engine_spec_acceptance_len"
ENGINE_SPEC_ACCEPTED_TOKENS_PER_STEP = (
    "tpushare_engine_spec_accepted_tokens_per_step"
)
ENGINE_SPEC_DRAFT_STEPS_TOTAL = "tpushare_engine_spec_draft_steps_total"
ENGINE_SPEC_ENABLED = "tpushare_engine_spec_enabled"
ENGINE_SPEC_K = "tpushare_engine_spec_k"
ENGINE_SPEC_ROLLBACK_PAGES_TOTAL = "tpushare_engine_spec_rollback_pages_total"
ENGINE_STEP_P50_SECONDS = "tpushare_engine_step_p50_seconds"
ENGINE_STEP_P99_SECONDS = "tpushare_engine_step_p99_seconds"
ENGINE_STEP_SECONDS = "tpushare_engine_step_seconds"
EXTENDER_VERB_SECONDS = "tpushare_extender_verb_seconds"
EXTENDER_VERB_TOTAL = "tpushare_extender_verb_total"
EXTENDER_VIEW_TOTAL = "tpushare_extender_view_total"
FLEET_DRAIN_MIGRATED_REQUESTS_TOTAL = (
    "tpushare_fleet_drain_migrated_requests_total"
)
FLEET_REPLICAS = "tpushare_fleet_replicas"
FLEET_SCALE_OPS_TOTAL = "tpushare_fleet_scale_ops_total"
GANG2PC_TOTAL = "tpushare_gang2pc_total"
GOVERNOR_ENGAGED = "tpushare_governor_engaged"
GOVERNOR_ENGAGEMENTS_TOTAL = "tpushare_governor_engagements_total"
GOVERNOR_THROTTLE_SECONDS_TOTAL = "tpushare_governor_throttle_seconds_total"
GOVERNOR_THROTTLED_STEPS_TOTAL = "tpushare_governor_throttled_steps_total"
HANDOFF_BYTES = "tpushare_handoff_bytes"
HANDOFF_FALLBACK_REPREFILL_TOTAL = "tpushare_handoff_fallback_reprefill_total"
HANDOFF_PAGES_IN_FLIGHT = "tpushare_handoff_pages_in_flight"
HANDOFF_TRANSFER_SECONDS = "tpushare_handoff_transfer_seconds"
HANDOFF_TRANSFERS_TOTAL = "tpushare_handoff_transfers_total"
HEALTH_EVENTS_TOTAL = "tpushare_health_events_total"
HEALTH_WATCHER_RESTARTS_TOTAL = "tpushare_health_watcher_restarts_total"
INFORMER_APPLY_BATCH_EVENTS = "tpushare_informer_apply_batch_events"
INFORMER_INDEX_REBUILDS_TOTAL = "tpushare_informer_index_rebuilds_total"
INFORMER_STALENESS_SECONDS = "tpushare_informer_staleness_seconds"
INTERFERENCE_RATIO = "tpushare_interference_ratio"
NODE_EVENTS_DROPPED_TOTAL = "tpushare_node_events_dropped_total"
PATCH_BATCH_RECORDS = "tpushare_patch_batch_records"
PATCH_COALESCED_TOTAL = "tpushare_patch_coalesced_total"
PATCH_REQUESTS_TOTAL = "tpushare_patch_requests_total"
RECONCILE_DRIFT_TOTAL = "tpushare_reconcile_drift_total"
RECONCILE_REPAIRS_TOTAL = "tpushare_reconcile_repairs_total"
RECONCILE_RUNS_TOTAL = "tpushare_reconcile_runs_total"
RECONCILE_SECONDS = "tpushare_reconcile_seconds"
ROUTER_PREFIX_AFFINITY_HITS_TOTAL = (
    "tpushare_router_prefix_affinity_hits_total"
)
ROUTER_ROUTED_TOTAL = "tpushare_router_routed_total"
ROUTER_SHED_TOTAL = "tpushare_router_shed_total"
SLO_BURN_RATE = "tpushare_slo_burn_rate"
SLO_ERROR_BUDGET_REMAINING = "tpushare_slo_error_budget_remaining"
SLO_SEVERITY = "tpushare_slo_severity"
UNHEALTHY_CHIPS = "tpushare_unhealthy_chips"

# Family prefixes the CLI parsers slice on (`parse_engine_metrics`,
# `parse_observability_metrics`): declared here so a family rename
# breaks the parser at lint time, not on a live cluster.
PREFIX_ENGINE = "tpushare_engine_"
PREFIX_SLO = "tpushare_slo_"
PREFIX_GOVERNOR = "tpushare_governor_"
PREFIX_HANDOFF = "tpushare_handoff_"
PREFIX_FLEET = "tpushare_fleet_"
PREFIX_ROUTER = "tpushare_router_"

# --- the contract table -----------------------------------------------------

CATALOG: dict[str, MetricSpec] = dict((
    _m(ALLOCATE_SECONDS, HISTOGRAM, "resource"),
    _m(ALLOCATE_TOTAL, COUNTER, "resource", "outcome"),
    _m(ALLOCATOR_LOCK_WAIT_SECONDS, HISTOGRAM, "lock"),
    _m(ASSUME_EXPIRED_TOTAL, COUNTER, "kind"),
    _m(BUILD_INFO, GAUGE, "component", "version", "git_rev", "python", "jax"),
    _m(CHECKPOINT_APPENDS_TOTAL, COUNTER, "op"),
    _m(CHECKPOINT_ERRORS_TOTAL, COUNTER, "op"),
    _m(CHECKPOINT_FENCED, GAUGE),
    _m(CHECKPOINT_FSYNC_SECONDS, HISTOGRAM, "mode"),
    _m(CHECKPOINT_REPLAYED_TOTAL, COUNTER),
    _m(CHECKPOINT_WAL_BATCH_RECORDS, HISTOGRAM, "mode"),
    _m(CIRCUIT_FASTFAIL_TOTAL, COUNTER, "breaker"),
    _m(CIRCUIT_STATE, GAUGE, "breaker"),
    _m(CIRCUIT_TRANSITIONS_TOTAL, COUNTER, "breaker", "to"),
    _m(DEFRAG_MOVE_SECONDS, HISTOGRAM),
    _m(DEFRAG_MOVES_TOTAL, COUNTER, "outcome"),
    _m(DEFRAG_STRANDED_PCT, GAUGE),
    _m(DEFRAG_STRANDED_UNITS, GAUGE),
    _m(ENGINE_ADAPTER_CACHE_PAGES, GAUGE, "pod"),
    _m(ENGINE_ADAPTER_ENABLED, GAUGE, "pod"),
    _m(ENGINE_ADAPTER_EVICTIONS_TOTAL, COUNTER, "pod"),
    _m(ENGINE_ADAPTER_HITS_TOTAL, COUNTER, "pod"),
    _m(ENGINE_ADAPTER_MISS_STALL_SECONDS, HISTOGRAM, "pod"),
    _m(ENGINE_ADAPTER_MISSES_TOTAL, COUNTER, "pod"),
    _m(ENGINE_ADAPTER_RESIDENT, GAUGE, "pod"),
    _m(ENGINE_KV_PAGES_FREE, GAUGE, "pod"),
    _m(ENGINE_KV_PAGES_TOTAL, GAUGE, "pod"),
    _m(ENGINE_KV_PAGES_USED, GAUGE, "pod"),
    _m(ENGINE_PREEMPTIONS, GAUGE, "pod"),
    _m(ENGINE_PREEMPTIONS_TOTAL, COUNTER, "pod"),
    _m(ENGINE_PREFIX_CACHED_PAGES, GAUGE, "pod"),
    _m(ENGINE_PREFIX_HIT_RATIO, GAUGE, "pod"),
    _m(ENGINE_PREFIX_HIT_TOKENS, HISTOGRAM, "pod"),
    _m(ENGINE_SPEC_ACCEPTANCE_LEN, HISTOGRAM, "pod"),
    _m(ENGINE_SPEC_ACCEPTED_TOKENS_PER_STEP, HISTOGRAM, "pod"),
    _m(ENGINE_SPEC_DRAFT_STEPS_TOTAL, COUNTER, "pod"),
    _m(ENGINE_SPEC_ENABLED, GAUGE, "pod"),
    _m(ENGINE_SPEC_K, GAUGE, "pod"),
    _m(ENGINE_SPEC_ROLLBACK_PAGES_TOTAL, COUNTER, "pod"),
    _m(ENGINE_STEP_P50_SECONDS, GAUGE, "pod"),
    _m(ENGINE_STEP_P99_SECONDS, GAUGE, "pod"),
    _m(ENGINE_STEP_SECONDS, HISTOGRAM, "pod"),
    _m(EXTENDER_VERB_SECONDS, HISTOGRAM, "verb"),
    _m(EXTENDER_VERB_TOTAL, COUNTER, "verb", "outcome"),
    _m(EXTENDER_VIEW_TOTAL, COUNTER, "outcome"),
    _m(FLEET_DRAIN_MIGRATED_REQUESTS_TOTAL, COUNTER, "pod"),
    _m(FLEET_REPLICAS, GAUGE, "state", "pod"),
    _m(FLEET_SCALE_OPS_TOTAL, COUNTER, "outcome", "pod"),
    _m(GANG2PC_TOTAL, COUNTER, "phase", "outcome"),
    _m(GOVERNOR_ENGAGED, GAUGE, "pod"),
    _m(GOVERNOR_ENGAGEMENTS_TOTAL, COUNTER, "pod"),
    _m(GOVERNOR_THROTTLE_SECONDS_TOTAL, COUNTER, "pod"),
    _m(GOVERNOR_THROTTLED_STEPS_TOTAL, COUNTER, "pod"),
    _m(HANDOFF_BYTES, HISTOGRAM, "pod"),
    _m(HANDOFF_FALLBACK_REPREFILL_TOTAL, COUNTER, "reason", "pod"),
    _m(HANDOFF_PAGES_IN_FLIGHT, GAUGE, "pod"),
    _m(HANDOFF_TRANSFER_SECONDS, HISTOGRAM, "pod"),
    _m(HANDOFF_TRANSFERS_TOTAL, COUNTER, "outcome", "pod"),
    _m(HEALTH_EVENTS_TOTAL, COUNTER, "health", "severity"),
    _m(HEALTH_WATCHER_RESTARTS_TOTAL, COUNTER),
    _m(INFORMER_APPLY_BATCH_EVENTS, HISTOGRAM, "scope"),
    _m(INFORMER_INDEX_REBUILDS_TOTAL, COUNTER, "reason", "scope"),
    _m(INFORMER_STALENESS_SECONDS, GAUGE, "scope"),
    _m(INTERFERENCE_RATIO, GAUGE, "chip", "victim", "aggressor"),
    _m(NODE_EVENTS_DROPPED_TOTAL, COUNTER, "reason"),
    _m(PATCH_BATCH_RECORDS, HISTOGRAM, "kind"),
    _m(PATCH_COALESCED_TOTAL, COUNTER, "kind"),
    _m(PATCH_REQUESTS_TOTAL, COUNTER, "transport"),
    _m(RECONCILE_DRIFT_TOTAL, COUNTER, "kind"),
    _m(RECONCILE_REPAIRS_TOTAL, COUNTER, "kind"),
    _m(RECONCILE_RUNS_TOTAL, COUNTER, "outcome"),
    _m(RECONCILE_SECONDS, HISTOGRAM),
    _m(ROUTER_PREFIX_AFFINITY_HITS_TOTAL, COUNTER, "pod"),
    _m(ROUTER_ROUTED_TOTAL, COUNTER, "engine", "outcome", "pod"),
    _m(ROUTER_SHED_TOTAL, COUNTER, "tier", "pod"),
    _m(SLO_BURN_RATE, GAUGE, "tier", "window", "pod"),
    _m(SLO_ERROR_BUDGET_REMAINING, GAUGE, "tier", "pod"),
    _m(SLO_SEVERITY, GAUGE, "tier", "pod"),
    _m(UNHEALTHY_CHIPS, GAUGE),
))


def spec_of(name: str) -> MetricSpec:
    try:
        return CATALOG[name]
    except KeyError:
        raise ValueError(
            f"unknown metric family {name!r}; declare it in "
            "gpushare_device_plugin_tpu/utils/metric_catalog.py"
        ) from None
